// hetu_ps: host-side sharded embedding parameter store with server-side
// optimizers, versioned rows, bounded-staleness client caches, and SSP
// clocks.
//
// TPU-native counterpart of the reference's parameter-server stack:
//   * ps-lite KVServer + server optimizers  (ps-lite/include/ps/server/
//     kvserver.h:19, optimizer.h:36-205, param.h:21 — versioned CacheTable
//     rows at param.h:119)
//   * HET client cache with pull/push staleness bounds (src/hetu_cache/
//     include/cache.h:21-58, lru_cache.cc, lfu_cache.cc, lfuopt_cache.cc)
//   * SSP consistency clocks (ps-lite/include/ps/psf/ssp.h:10-32)
//
// Design differences from the reference (not a port): there is no RPC van —
// on TPU VMs the store lives in host RAM of each worker and is reached by
// direct calls from the training process (DCN sharding is layered on top in
// Python, hetu_tpu/ps/store.py). Tables are flat preallocated arrays (rows
// are hot in the embedding workloads this serves), sharded 64-way by key for
// lock granularity, with row versions driving both SSP and HET bounds.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <memory>
#include <mutex>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kShards = 64;

inline int shard_of(int64_t key) { return static_cast<int>(key & (kShards - 1)); }

enum OptType { OPT_SGD = 0, OPT_MOMENTUM = 1, OPT_ADAGRAD = 2, OPT_ADAM = 3 };
enum Policy { POLICY_LRU = 0, POLICY_LFU = 1, POLICY_LFUOPT = 2 };

struct Table {
  int64_t rows = 0, dim = 0;
  int opt = OPT_SGD;
  float lr = 0.01f, beta1 = 0.9f, beta2 = 0.999f, eps = 1e-8f, wd = 0.f;
  std::vector<float> data;
  std::vector<uint64_t> version;   // bumped on every push to a row
  std::vector<float> slot1;        // momentum / adagrad accum / adam m
  std::vector<float> slot2;        // adam v
  std::vector<uint64_t> steps;     // per-row adam step counters
  std::mutex locks[kShards];

  void ensure_slots() {
    if (opt == OPT_SGD) return;
    if (slot1.empty()) slot1.assign(data.size(), 0.f);
    if (opt == OPT_ADAM && slot2.empty()) slot2.assign(data.size(), 0.f);
    if (opt == OPT_ADAM && steps.empty()) steps.assign(rows, 0);
  }

  // server-side optimizer step for one row (reference: ps-lite server
  // optimizers optimizer.h:36-205 apply per-key updates)
  void apply_row(int64_t row, const float* grad) {
    float* w = data.data() + row * dim;
    switch (opt) {
      case OPT_SGD:
        for (int64_t j = 0; j < dim; ++j)
          w[j] -= lr * (grad[j] + wd * w[j]);
        break;
      case OPT_MOMENTUM: {
        float* v = slot1.data() + row * dim;
        for (int64_t j = 0; j < dim; ++j) {
          v[j] = beta1 * v[j] - lr * (grad[j] + wd * w[j]);
          w[j] += v[j];
        }
        break;
      }
      case OPT_ADAGRAD: {
        float* acc = slot1.data() + row * dim;
        for (int64_t j = 0; j < dim; ++j) {
          float g = grad[j] + wd * w[j];
          acc[j] += g * g;
          w[j] -= lr * g / (std::sqrt(acc[j]) + eps);
        }
        break;
      }
      case OPT_ADAM: {
        float* m = slot1.data() + row * dim;
        float* v = slot2.data() + row * dim;
        uint64_t t = ++steps[row];
        float bc1 = 1.f - std::pow(beta1, static_cast<float>(t));
        float bc2 = 1.f - std::pow(beta2, static_cast<float>(t));
        for (int64_t j = 0; j < dim; ++j) {
          float g = grad[j] + wd * w[j];
          m[j] = beta1 * m[j] + (1.f - beta1) * g;
          v[j] = beta2 * v[j] + (1.f - beta2) * g * g;
          w[j] -= lr * (m[j] / bc1) / (std::sqrt(v[j] / bc2) + eps);
        }
        break;
      }
    }
  }
};

// HET client cache: fixed-slot store of hot rows with per-row cached
// versions; hits served while version lag <= pull_bound; local gradient
// accumulation flushed to the table after push_bound updates per row.
struct Cache {
  Table* table = nullptr;
  int64_t limit = 0, dim = 0;
  int policy = POLICY_LRU;
  uint64_t pull_bound = 0, push_bound = 0;
  std::unordered_map<int64_t, int64_t> slot_of;
  std::vector<int64_t> key_of;       // slot -> key (-1 empty)
  std::vector<float> rows;           // limit x dim cached values
  std::vector<float> pending;        // limit x dim accumulated grads
  std::vector<uint32_t> pend_count;  // updates since last flush
  std::vector<uint64_t> cached_ver;
  std::vector<uint64_t> last_use;    // LRU tick
  std::vector<uint64_t> freq;        // LFU counter
  uint64_t tick = 0;
  std::mutex mu;
  // perf counters (reference cstable.py:126-187 records the same)
  std::atomic<int64_t> hits{0}, misses{0}, pushes{0}, evictions{0};

  int64_t pick_victim() {
    // all slots full: evict by policy
    int64_t best = 0;
    for (int64_t s = 1; s < limit; ++s) {
      bool better = false;
      switch (policy) {
        case POLICY_LRU: better = last_use[s] < last_use[best]; break;
        case POLICY_LFU: better = freq[s] < freq[best]; break;
        case POLICY_LFUOPT:  // LFU with LRU tiebreak + freq aging on evict
          better = freq[s] < freq[best] ||
                   (freq[s] == freq[best] && last_use[s] < last_use[best]);
          break;
      }
      if (better) best = s;
    }
    return best;
  }

  void flush_slot(int64_t s) {
    if (pend_count[s] == 0) return;
    int64_t key = key_of[s];
    auto& lock = table->locks[shard_of(key)];
    {
      std::lock_guard<std::mutex> g(lock);
      table->apply_row(key, pending.data() + s * dim);
      table->version[key] += 1;
      // refresh local copy so subsequent reads see the applied update
      std::memcpy(rows.data() + s * dim, table->data.data() + key * dim,
                  sizeof(float) * dim);
      cached_ver[s] = table->version[key];
    }
    std::memset(pending.data() + s * dim, 0, sizeof(float) * dim);
    pend_count[s] = 0;
    pushes.fetch_add(1, std::memory_order_relaxed);
  }

  // returns slot holding key, admitting (and possibly evicting) on miss
  int64_t admit(int64_t key) {
    auto it = slot_of.find(key);
    if (it != slot_of.end()) return it->second;
    int64_t s;
    if (static_cast<int64_t>(slot_of.size()) < limit) {
      s = static_cast<int64_t>(slot_of.size());
    } else {
      s = pick_victim();
      flush_slot(s);
      slot_of.erase(key_of[s]);
      evictions.fetch_add(1, std::memory_order_relaxed);
      if (policy == POLICY_LFUOPT) {  // age frequencies so old heat decays
        for (int64_t i = 0; i < limit; ++i) freq[i] >>= 1;
      }
    }
    // fetch fresh row from table
    auto& lock = table->locks[shard_of(key)];
    {
      std::lock_guard<std::mutex> g(lock);
      std::memcpy(rows.data() + s * dim, table->data.data() + key * dim,
                  sizeof(float) * dim);
      cached_ver[s] = table->version[key];
    }
    key_of[s] = key;
    slot_of[key] = s;
    freq[s] = 0;
    pend_count[s] = 0;
    std::memset(pending.data() + s * dim, 0, sizeof(float) * dim);
    return s;
  }
};

struct SSPClock {
  std::vector<std::atomic<int64_t>> clocks;
  explicit SSPClock(int n) : clocks(n) {
    for (auto& c : clocks) c.store(0);
  }
};

// Partial-reduce matchmaking (reference ps-lite/src/preduce_handler.cc,
// psf/preduce.h kPReduceGetPartner): workers arriving at a reduce key wait
// until either `target` workers showed up or the first arrival's wait_time
// expired, then all receive the same sorted member list.  One stat per
// reduce key (a pipeline stage uses a unique key).
struct PReduceStat {
  std::mutex mtx;
  std::condition_variable cv;
  std::vector<int> ready;
  std::chrono::system_clock::time_point wake_time;
  int critical = 0;  // members still copying out the current decision
};

struct PReduceScheduler {
  std::mutex map_mtx;
  std::unordered_map<int64_t, std::unique_ptr<PReduceStat>> stats;

  // blocks; returns group size, member ranks (sorted) in out
  int get_partner(int64_t key, int rank, int target, float wait_ms,
                  int* out) {
    PReduceStat* st;
    {
      std::lock_guard<std::mutex> g(map_mtx);
      auto& slot = stats[key];
      if (!slot) slot.reset(new PReduceStat());
      st = slot.get();
    }
    std::unique_lock<std::mutex> lock(st->mtx);
    // a previous decision is still being read out: wait for it to clear
    while (st->critical) st->cv.wait(lock);
    if (st->ready.empty()) {
      st->wake_time = std::chrono::system_clock::now() +
                      std::chrono::microseconds(
                          static_cast<int64_t>(wait_ms * 1000));
    }
    st->ready.push_back(rank);
    if (static_cast<int>(st->ready.size()) >= target) {
      st->cv.notify_all();
    } else {
      while (static_cast<int>(st->ready.size()) < target && !st->critical &&
             st->cv.wait_until(lock, st->wake_time) !=
                 std::cv_status::timeout) {
      }
    }
    if (!st->critical) {  // first thread awake freezes the decision
      st->critical = static_cast<int>(st->ready.size());
      std::sort(st->ready.begin(), st->ready.end());
      st->cv.notify_all();
    }
    int n = static_cast<int>(st->ready.size());
    std::copy(st->ready.begin(), st->ready.end(), out);
    if (--st->critical == 0) {
      st->ready.clear();
      st->cv.notify_all();
    }
    return n;
  }
};

std::mutex g_registry_mu;
std::unordered_map<int64_t, Table*> g_tables;
std::unordered_map<int64_t, Cache*> g_caches;
std::unordered_map<int64_t, SSPClock*> g_clocks;
std::unordered_map<int64_t, PReduceScheduler*> g_preduces;
int64_t g_next_handle = 1;

template <typename M, typename T>
int64_t register_handle(M& map, T* obj) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  int64_t h = g_next_handle++;
  map[h] = obj;
  return h;
}

Table* table_of(int64_t h) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_tables.find(h);
  return it == g_tables.end() ? nullptr : it->second;
}

Cache* cache_of(int64_t h) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_caches.find(h);
  return it == g_caches.end() ? nullptr : it->second;
}

// chunked multithreading for big batches (lookup/push are memory-bound)
void parallel_for(int64_t n, int64_t grain,
                  const std::function<void(int64_t, int64_t)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  if (n < grain * 2 || hw <= 1) {
    fn(0, n);
    return;
  }
  int64_t nthreads = std::min<int64_t>(hw, (n + grain - 1) / grain);
  std::vector<std::thread> ts;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int64_t t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(fn, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

int64_t ps_table_create(int64_t rows, int64_t dim, int opt_type, float lr,
                        float beta1, float beta2, float eps, float wd) {
  auto* t = new Table();
  t->rows = rows;
  t->dim = dim;
  t->opt = opt_type;
  t->lr = lr;
  t->beta1 = beta1;
  t->beta2 = beta2;
  t->eps = eps;
  t->wd = wd;
  t->data.assign(static_cast<size_t>(rows) * dim, 0.f);
  t->version.assign(rows, 0);
  t->ensure_slots();
  return register_handle(g_tables, t);
}

void ps_table_destroy(int64_t h) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_tables.find(h);
  if (it != g_tables.end()) {
    delete it->second;
    g_tables.erase(it);
  }
}

int64_t ps_table_rows(int64_t h) { Table* t = table_of(h); return t ? t->rows : -1; }
int64_t ps_table_dim(int64_t h) { Table* t = table_of(h); return t ? t->dim : -1; }

// uniform(-scale, scale) init, seeded (reference: init_on_ps initializers)
void ps_table_init_uniform(int64_t h, uint64_t seed, float scale) {
  Table* t = table_of(h);
  if (!t) return;
  parallel_for(t->rows, 1 << 14, [&](int64_t lo, int64_t hi) {
    std::mt19937_64 gen(seed + static_cast<uint64_t>(lo));
    std::uniform_real_distribution<float> dist(-scale, scale);
    for (int64_t r = lo; r < hi; ++r)
      for (int64_t j = 0; j < t->dim; ++j) t->data[r * t->dim + j] = dist(gen);
  });
}

void ps_table_set_rows(int64_t h, const int64_t* keys, int64_t n,
                       const float* vals) {
  Table* t = table_of(h);
  if (!t) return;
  parallel_for(n, 1 << 12, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int64_t k = keys[i];
      if (k < 0 || k >= t->rows) continue;
      std::lock_guard<std::mutex> g(t->locks[shard_of(k)]);
      std::memcpy(t->data.data() + k * t->dim, vals + i * t->dim,
                  sizeof(float) * t->dim);
      t->version[k] += 1;
    }
  });
}

void ps_table_lookup(int64_t h, const int64_t* keys, int64_t n, float* out) {
  Table* t = table_of(h);
  if (!t) return;
  parallel_for(n, 1 << 12, [&](int64_t lo, int64_t hi) {
    constexpr int64_t kAhead = 8;  // software prefetch distance: random
    // rows of a multi-GB table are DRAM-latency-bound (measured 0.63x
    // throughput at 28 GB vs 3 GB working sets before prefetching)
    for (int64_t i = lo; i < hi; ++i) {
      if (i + kAhead < hi) {
        int64_t pk = keys[i + kAhead];
        if (pk >= 0 && pk < t->rows)
          __builtin_prefetch(t->data.data() + pk * t->dim, 0, 1);
      }
      int64_t k = keys[i];
      if (k < 0 || k >= t->rows) {  // pad ids read as zero rows
        std::memset(out + i * t->dim, 0, sizeof(float) * t->dim);
        continue;
      }
      std::lock_guard<std::mutex> g(t->locks[shard_of(k)]);
      std::memcpy(out + i * t->dim, t->data.data() + k * t->dim,
                  sizeof(float) * t->dim);
    }
  });
}

void ps_table_versions(int64_t h, const int64_t* keys, int64_t n,
                       uint64_t* out) {
  Table* t = table_of(h);
  if (!t) return;
  for (int64_t i = 0; i < n; ++i)
    out[i] = (keys[i] >= 0 && keys[i] < t->rows) ? t->version[keys[i]] : 0;
}

// push gradients; server-side optimizer applies them (DensePush/SparsePush
// semantics: duplicate keys in one batch apply sequentially)
void ps_table_push(int64_t h, const int64_t* keys, const float* grads,
                   int64_t n) {
  Table* t = table_of(h);
  if (!t) return;
  parallel_for(n, 1 << 12, [&](int64_t lo, int64_t hi) {
    constexpr int64_t kAhead = 8;
    for (int64_t i = lo; i < hi; ++i) {
      if (i + kAhead < hi) {
        int64_t pk = keys[i + kAhead];
        if (pk >= 0 && pk < t->rows) {
          __builtin_prefetch(t->data.data() + pk * t->dim, 1, 1);
          if (!t->slot1.empty())
            __builtin_prefetch(t->slot1.data() + pk * t->dim, 1, 1);
          if (!t->slot2.empty())
            __builtin_prefetch(t->slot2.data() + pk * t->dim, 1, 1);
        }
      }
      int64_t k = keys[i];
      // skip padded slots from fixed-size dedup buffers + out-of-range ids
      if (k < 0 || k >= t->rows) continue;
      std::lock_guard<std::mutex> g(t->locks[shard_of(k)]);
      t->apply_row(k, grads + i * t->dim);
      t->version[k] += 1;
    }
  });
}

int ps_table_save(int64_t h, const char* path) {
  Table* t = table_of(h);
  if (!t) return -1;
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  std::fwrite(&t->rows, sizeof(int64_t), 1, f);
  std::fwrite(&t->dim, sizeof(int64_t), 1, f);
  std::fwrite(t->data.data(), sizeof(float), t->data.size(), f);
  std::fwrite(t->version.data(), sizeof(uint64_t), t->version.size(), f);
  if (!t->slot1.empty())
    std::fwrite(t->slot1.data(), sizeof(float), t->slot1.size(), f);
  if (!t->slot2.empty())
    std::fwrite(t->slot2.data(), sizeof(float), t->slot2.size(), f);
  if (!t->steps.empty())
    std::fwrite(t->steps.data(), sizeof(uint64_t), t->steps.size(), f);
  std::fclose(f);
  return 0;
}

int ps_table_load(int64_t h, const char* path) {
  Table* t = table_of(h);
  if (!t) return -1;
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t rows = 0, dim = 0;
  if (std::fread(&rows, sizeof(int64_t), 1, f) != 1 ||
      std::fread(&dim, sizeof(int64_t), 1, f) != 1 || rows != t->rows ||
      dim != t->dim) {
    std::fclose(f);
    return -2;
  }
  bool ok = std::fread(t->data.data(), sizeof(float), t->data.size(), f) ==
            t->data.size();
  ok = ok && std::fread(t->version.data(), sizeof(uint64_t),
                        t->version.size(), f) == t->version.size();
  if (ok && !t->slot1.empty())
    ok = std::fread(t->slot1.data(), sizeof(float), t->slot1.size(), f) ==
         t->slot1.size();
  if (ok && !t->slot2.empty())
    ok = std::fread(t->slot2.data(), sizeof(float), t->slot2.size(), f) ==
         t->slot2.size();
  if (ok && !t->steps.empty())
    ok = std::fread(t->steps.data(), sizeof(uint64_t), t->steps.size(), f) ==
         t->steps.size();
  std::fclose(f);
  return ok ? 0 : -3;  // -3: truncated/short file
}

// ---- HET client cache -----------------------------------------------------

int64_t ps_cache_create(int64_t table_h, int64_t limit, int policy,
                        int64_t pull_bound, int64_t push_bound) {
  Table* t = table_of(table_h);
  if (!t) return -1;
  auto* c = new Cache();
  c->table = t;
  c->limit = limit;
  c->dim = t->dim;
  c->policy = policy;
  c->pull_bound = static_cast<uint64_t>(pull_bound);
  c->push_bound = static_cast<uint64_t>(push_bound);
  c->key_of.assign(limit, -1);
  c->rows.assign(static_cast<size_t>(limit) * t->dim, 0.f);
  c->pending.assign(static_cast<size_t>(limit) * t->dim, 0.f);
  c->pend_count.assign(limit, 0);
  c->cached_ver.assign(limit, 0);
  c->last_use.assign(limit, 0);
  c->freq.assign(limit, 0);
  return register_handle(g_caches, c);
}

void ps_cache_destroy(int64_t h) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_caches.find(h);
  if (it != g_caches.end()) {
    delete it->second;
    g_caches.erase(it);
  }
}

// batched lookup through the cache (reference cache.h:54 batchedLookup):
// hit if present AND version lag <= pull_bound; else refetch.
void ps_cache_lookup(int64_t h, const int64_t* keys, int64_t n, float* out) {
  Cache* c = cache_of(h);
  if (!c) return;
  std::lock_guard<std::mutex> g(c->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t key = keys[i];
    if (key < 0 || key >= c->table->rows) {  // padding / out-of-range
      std::memset(out + i * c->dim, 0, sizeof(float) * c->dim);
      continue;
    }
    auto it = c->slot_of.find(key);
    bool hit = false;
    int64_t s = -1;
    if (it != c->slot_of.end()) {
      s = it->second;
      uint64_t cur = c->table->version[key];  // racy read is fine: bound check
      hit = (cur - c->cached_ver[s]) <= c->pull_bound;
    }
    if (hit) {
      c->hits.fetch_add(1, std::memory_order_relaxed);
    } else {
      c->misses.fetch_add(1, std::memory_order_relaxed);
      if (s >= 0) {  // stale: refetch in place
        auto& lock = c->table->locks[shard_of(key)];
        std::lock_guard<std::mutex> tg(lock);
        std::memcpy(c->rows.data() + s * c->dim,
                    c->table->data.data() + key * c->dim,
                    sizeof(float) * c->dim);
        c->cached_ver[s] = c->table->version[key];
      } else {
        s = c->admit(key);
      }
    }
    c->last_use[s] = ++c->tick;
    c->freq[s] += 1;
    std::memcpy(out + i * c->dim, c->rows.data() + s * c->dim,
                sizeof(float) * c->dim);
  }
}

// buffered sparse update: accumulate grads locally; flush a row to the
// server optimizer once it has seen push_bound updates (reference
// cache.h:25 push_bound_ write buffering)
void ps_cache_update(int64_t h, const int64_t* keys, const float* grads,
                     int64_t n) {
  Cache* c = cache_of(h);
  if (!c) return;
  std::lock_guard<std::mutex> g(c->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t key = keys[i];
    if (key < 0 || key >= c->table->rows) continue;
    int64_t s = c->admit(key);
    float* p = c->pending.data() + s * c->dim;
    const float* gr = grads + i * c->dim;
    for (int64_t j = 0; j < c->dim; ++j) p[j] += gr[j];
    c->pend_count[s] += 1;
    c->last_use[s] = ++c->tick;
    if (c->pend_count[s] >= c->push_bound) c->flush_slot(s);
  }
}

void ps_cache_flush(int64_t h) {
  Cache* c = cache_of(h);
  if (!c) return;
  std::lock_guard<std::mutex> g(c->mu);
  for (int64_t s = 0; s < c->limit; ++s)
    if (c->key_of[s] >= 0) c->flush_slot(s);
}

void ps_cache_stats(int64_t h, int64_t* hits, int64_t* misses,
                    int64_t* pushes, int64_t* evictions) {
  Cache* c = cache_of(h);
  if (!c) return;
  *hits = c->hits.load();
  *misses = c->misses.load();
  *pushes = c->pushes.load();
  *evictions = c->evictions.load();
}

// ---- SSP clocks -----------------------------------------------------------

int64_t ssp_create(int nworkers) {
  return register_handle(g_clocks, new SSPClock(nworkers));
}

void ssp_destroy(int64_t h) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_clocks.find(h);
  if (it != g_clocks.end()) {
    delete it->second;
    g_clocks.erase(it);
  }
}

void ssp_tick(int64_t h, int worker) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_clocks.find(h);
  if (it != g_clocks.end()) it->second->clocks[worker].fetch_add(1);
}

int64_t ssp_clock(int64_t h, int worker) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_clocks.find(h);
  return it == g_clocks.end() ? -1 : it->second->clocks[worker].load();
}

int64_t ssp_min(int64_t h) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_clocks.find(h);
  if (it == g_clocks.end()) return -1;
  int64_t m = INT64_MAX;
  for (auto& c : it->second->clocks) m = std::min(m, c.load());
  return m;
}

// ---- partial-reduce matchmaking -------------------------------------------

int64_t preduce_create() {
  return register_handle(g_preduces, new PReduceScheduler());
}

void preduce_destroy(int64_t h) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_preduces.find(h);
  if (it != g_preduces.end()) {
    delete it->second;
    g_preduces.erase(it);
  }
}

// Blocks until `target` workers joined `key` or the first arrival's
// wait_ms elapsed; writes the sorted member ranks to out and returns the
// group size (ctypes releases the GIL, so Python worker threads block here
// concurrently like the reference's PS RPC threads).
int preduce_get_partner(int64_t h, int64_t key, int rank, int target,
                        float wait_ms, int* out) {
  PReduceScheduler* s;
  {
    std::lock_guard<std::mutex> g(g_registry_mu);
    auto it = g_preduces.find(h);
    if (it == g_preduces.end()) return -1;
    s = it->second;
  }
  return s->get_partner(key, rank, target, wait_ms, out);
}

}  // extern "C"
