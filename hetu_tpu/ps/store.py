"""Python face of the native embedding store.

Reference mapping:
  * `EmbeddingTable`  ≈ ps-lite server Param/CacheTable rows with server-side
    optimizers (ps-lite/include/ps/server/param.h:21, optimizer.h:36-205)
  * `CacheTable`      ≈ HET client cache (src/hetu_cache/include/cache.h:21)
  * `SSPController`   ≈ SSP clock RPCs (ps-lite/include/ps/psf/ssp.h:10-32)

Multi-worker sharding: the reference shards keys across PS server processes
reached over ZMQ.  On TPU VMs every host holds a shard of each table in RAM;
`ShardedTable` routes keys by hash over shards that may be in-process
EmbeddingTables or `rpc.RemoteTable` clients reaching PSServer processes
over DCN (ps/rpc.py is the van-layer equivalent; tests/test_rpc_launch.py
exercises real server processes).
"""

from __future__ import annotations

import ctypes

import numpy as np

from .build import load

_OPT_TYPES = {"sgd": 0, "momentum": 1, "adagrad": 2, "adam": 3}
_POLICIES = {"lru": 0, "lfu": 1, "lfuopt": 2}


def _i64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class EmbeddingTable:
    """Host-RAM embedding table with a server-side optimizer."""

    def __init__(self, rows, dim, optimizer="sgd", lr=0.01, beta1=0.9,
                 beta2=0.999, eps=1e-8, weight_decay=0.0, seed=0,
                 init_scale=None):
        self._lib = load()
        self.rows, self.dim = int(rows), int(dim)
        self.optimizer = optimizer
        self.handle = self._lib.ps_table_create(
            self.rows, self.dim, _OPT_TYPES[optimizer], lr, beta1, beta2,
            eps, weight_decay)
        if init_scale is None:
            init_scale = 1.0 / np.sqrt(dim)
        if init_scale:
            self._lib.ps_table_init_uniform(self.handle, seed,
                                            float(init_scale))

    def lookup(self, keys):
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1),
                                    dtype=np.int64)
        out = np.empty((keys.size, self.dim), np.float32)
        self._lib.ps_table_lookup(self.handle, _i64p(keys), keys.size,
                                  _f32p(out))
        return out

    def push(self, keys, grads):
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1),
                                    dtype=np.int64)
        grads = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(keys.size, self.dim))
        self._lib.ps_table_push(self.handle, _i64p(keys), _f32p(grads),
                                keys.size)

    def set_rows(self, keys, values):
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1),
                                    dtype=np.int64)
        values = np.ascontiguousarray(
            np.asarray(values, np.float32).reshape(keys.size, self.dim))
        self._lib.ps_table_set_rows(self.handle, _i64p(keys), keys.size,
                                    _f32p(values))

    def versions(self, keys):
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1),
                                    dtype=np.int64)
        out = np.empty(keys.size, np.uint64)
        self._lib.ps_table_versions(
            self.handle, _i64p(keys), keys.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        return out

    def to_numpy(self):
        return self.lookup(np.arange(self.rows))

    # SaveParam / LoadParam RPC analogue (reference executor.py:589-591)
    def save(self, path):
        rc = self._lib.ps_table_save(self.handle, str(path).encode())
        if rc != 0:
            raise IOError(f"ps_table_save({path}) -> {rc}")

    def load(self, path):
        rc = self._lib.ps_table_load(self.handle, str(path).encode())
        if rc != 0:
            raise IOError(f"ps_table_load({path}) -> {rc}")

    def __del__(self):
        try:
            self._lib.ps_table_destroy(self.handle)
        except Exception:
            pass


class CacheTable:
    """Bounded-staleness client cache over an EmbeddingTable (HET)."""

    def __init__(self, table: EmbeddingTable, limit, policy="lru",
                 pull_bound=0, push_bound=1):
        self._lib = load()
        self.table = table
        self.dim = table.dim
        self.policy = policy
        self.handle = self._lib.ps_cache_create(
            table.handle, int(limit), _POLICIES[policy], int(pull_bound),
            int(push_bound))
        assert self.handle > 0

    def lookup(self, keys):
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1),
                                    dtype=np.int64)
        out = np.empty((keys.size, self.dim), np.float32)
        self._lib.ps_cache_lookup(self.handle, _i64p(keys), keys.size,
                                  _f32p(out))
        return out

    def update(self, keys, grads):
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1),
                                    dtype=np.int64)
        grads = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(keys.size, self.dim))
        self._lib.ps_cache_update(self.handle, _i64p(keys), _f32p(grads),
                                  keys.size)

    def flush(self):
        self._lib.ps_cache_flush(self.handle)

    def stats(self):
        vals = [ctypes.c_int64() for _ in range(4)]
        self._lib.ps_cache_stats(self.handle, *[ctypes.byref(v)
                                                for v in vals])
        hits, misses, pushes, evictions = [v.value for v in vals]
        total = max(hits + misses, 1)
        return {"hits": hits, "misses": misses, "pushes": pushes,
                "evictions": evictions, "hit_rate": hits / total}

    def __del__(self):
        try:
            self._lib.ps_cache_destroy(self.handle)
        except Exception:
            pass


class ShardedTable:
    """Key-hash sharding over N EmbeddingTables (the multi-host layout:
    shard s on worker s; here in-process).  Routing: shard = key % nshards,
    local key = key // nshards (matches the reference's server key
    partitioner semantics without its ranges)."""

    def __init__(self, rows, dim, nshards=1, tables=None, **kw):
        if tables is not None:
            # pre-built shards — local EmbeddingTables and/or rpc.RemoteTable
            # clients reaching server processes over DCN (the reference's
            # multi-host server layout, ps-lite postoffice key ranges)
            if kw:
                raise TypeError(
                    f"table kwargs {sorted(kw)} are ignored with tables= "
                    "(build the shards with those options instead)")
            self.shards = list(tables)
            self.nshards = len(self.shards)
            self.rows, self.dim = int(rows), int(dim)
            for s, t in enumerate(self.shards):
                if t.dim != self.dim:
                    raise ValueError(f"shard {s} dim {t.dim} != {self.dim}")
                # under key%nshards routing, shard s holds local rows for
                # keys s, s+n, s+2n, ... — exactly-sized tail shards hold
                # one row fewer than the leading ones
                need = ((self.rows - 1 - s) // self.nshards + 1
                        if s < self.rows else 0)
                if t.rows < need:
                    # undersized shards would make the native store treat
                    # tail keys as pads: pushes silently dropped
                    raise ValueError(
                        f"shard {s} has {t.rows} rows < {need} needed for "
                        f"{self.rows} rows over {self.nshards} shards")
            return
        self.nshards = nshards
        self.rows, self.dim = int(rows), int(dim)
        per = (rows + nshards - 1) // nshards
        seed = kw.pop("seed", 0)
        self.shards = [EmbeddingTable(per, dim, seed=seed + s, **kw)
                       for s in range(nshards)]

    def lookup(self, keys):
        keys = np.asarray(keys).reshape(-1).astype(np.int64)
        out = np.empty((keys.size, self.dim), np.float32)
        for s in range(self.nshards):
            m = (keys % self.nshards) == s
            if m.any():
                out[m] = self.shards[s].lookup(keys[m] // self.nshards)
        return out

    def push(self, keys, grads):
        keys = np.asarray(keys).reshape(-1).astype(np.int64)
        grads = np.asarray(grads, np.float32).reshape(keys.size, self.dim)
        for s in range(self.nshards):
            m = (keys % self.nshards) == s
            if m.any():
                self.shards[s].push(keys[m] // self.nshards, grads[m])


class SSPController:
    """Stale-synchronous-parallel clocks (reference psf/ssp.h): a worker may
    advance to step c only while c - min(all clocks) <= staleness."""

    def __init__(self, nworkers, staleness=0):
        self._lib = load()
        self.nworkers = nworkers
        self.staleness = staleness
        self.handle = self._lib.ssp_create(nworkers)

    def tick(self, worker):
        self._lib.ssp_tick(self.handle, worker)

    def clock(self, worker):
        return self._lib.ssp_clock(self.handle, worker)

    def can_advance(self, worker):
        return (self.clock(worker) - self._lib.ssp_min(self.handle)
                <= self.staleness)

    def __del__(self):
        try:
            self._lib.ssp_destroy(self.handle)
        except Exception:
            pass
