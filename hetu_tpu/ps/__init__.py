"""Parameter-server / embedding-store subsystem (reference: ps-lite +
src/hetu_cache + python/hetu/cstable.py; see SURVEY.md N8/N9/P17)."""

from .store import (EmbeddingTable, CacheTable, ShardedTable, SSPController)
from .cstable import CacheSparseTable
from .embedding import PSEmbedding, PSRowsOp
from .preduce import (PReduceScheduler, PartialReduce, partner_mask,
                      masked_mean_allreduce)
from .rpc import PSServer, RemoteTable, PartialBulkError, PSUnavailable

__all__ = ["EmbeddingTable", "CacheTable", "ShardedTable", "SSPController",
           "CacheSparseTable", "PSEmbedding", "PSRowsOp",
           "PReduceScheduler", "PartialReduce", "partner_mask",
           "masked_mean_allreduce", "PSServer", "RemoteTable",
           "PartialBulkError", "PSUnavailable"]
