"""Build + load the native PS core (g++ → libhetu_ps.so, loaded via ctypes).

The reference ships its store as prebuilt C++ (libps.so loaded by ctypes at
executor.py:100-137); here the library is compiled on first use from the
in-tree source so the repo stays self-contained.
"""

from __future__ import annotations

import ctypes
import os

from ..native_build import NativeLib

_HERE = os.path.dirname(os.path.abspath(__file__))


def _declare(lib):
        i64, f32p, i64p, u64p = (ctypes.c_int64,
                                 ctypes.POINTER(ctypes.c_float),
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.POINTER(ctypes.c_uint64))
        f = ctypes.c_float
        lib.ps_table_create.restype = i64
        lib.ps_table_create.argtypes = [i64, i64, ctypes.c_int, f, f, f, f, f]
        lib.ps_table_destroy.argtypes = [i64]
        lib.ps_table_rows.restype = i64
        lib.ps_table_rows.argtypes = [i64]
        lib.ps_table_dim.restype = i64
        lib.ps_table_dim.argtypes = [i64]
        lib.ps_table_init_uniform.argtypes = [i64, ctypes.c_uint64, f]
        lib.ps_table_set_rows.argtypes = [i64, i64p, i64, f32p]
        lib.ps_table_lookup.argtypes = [i64, i64p, i64, f32p]
        lib.ps_table_versions.argtypes = [i64, i64p, i64, u64p]
        lib.ps_table_push.argtypes = [i64, i64p, f32p, i64]
        lib.ps_table_save.restype = ctypes.c_int
        lib.ps_table_save.argtypes = [i64, ctypes.c_char_p]
        lib.ps_table_load.restype = ctypes.c_int
        lib.ps_table_load.argtypes = [i64, ctypes.c_char_p]
        lib.ps_cache_create.restype = i64
        lib.ps_cache_create.argtypes = [i64, i64, ctypes.c_int, i64, i64]
        lib.ps_cache_destroy.argtypes = [i64]
        lib.ps_cache_lookup.argtypes = [i64, i64p, i64, f32p]
        lib.ps_cache_update.argtypes = [i64, i64p, f32p, i64]
        lib.ps_cache_flush.argtypes = [i64]
        lib.ps_cache_stats.argtypes = [i64] + [ctypes.POINTER(i64)] * 4
        lib.ssp_create.restype = i64
        lib.ssp_create.argtypes = [ctypes.c_int]
        lib.ssp_destroy.argtypes = [i64]
        lib.ssp_tick.argtypes = [i64, ctypes.c_int]
        lib.ssp_clock.restype = i64
        lib.ssp_clock.argtypes = [i64, ctypes.c_int]
        lib.ssp_min.restype = i64
        lib.ssp_min.argtypes = [i64]
        lib.preduce_create.restype = i64
        lib.preduce_create.argtypes = []
        lib.preduce_destroy.argtypes = [i64]
        lib.preduce_get_partner.restype = ctypes.c_int
        lib.preduce_get_partner.argtypes = [
            i64, i64, ctypes.c_int, ctypes.c_int, ctypes.c_float,
            ctypes.POINTER(ctypes.c_int)]


_native = NativeLib(os.path.join(_HERE, "native", "hetu_ps.cpp"),
                    os.path.join(_HERE, "native", "libhetu_ps.so"),
                    declare=_declare, extra_flags=["-pthread"])


def build():
    return _native.build()


def load():
    """Compile (if needed) and load the native library, declaring arg types."""
    return _native.load()
