"""Partial reduce: reduce gradients over whichever workers show up.

Reference: python/hetu/preduce.py `PartialReduce` (get_partner via the PS
scheduler RPC kPReduceGetPartner, then an ncclAvg allreduce over a lazily
created NCCL subgroup) with server-side matchmaking in
ps-lite/src/preduce_handler.cc.  Used by HetPipe-style training to tolerate
stragglers: a slow worker simply misses the round.

TPU redesign: NCCL subcommunicators don't exist under XLA, and compiling one
program per dynamic worker subset would defeat the point (the subset changes
every round).  Instead the member set enters the compiled program as DATA —
a boolean mask — and the reduction is a masked mean over the full `dp` mesh
axis: contribution = where(member, x, 0); psum; divide by member count.  One
compiled program serves every possible group, the collective still rides ICI
at full bandwidth, and non-members simply contribute zeros.
"""

from __future__ import annotations

import ctypes

import numpy as np
import jax.numpy as jnp
from jax import lax

from .build import load


class PReduceScheduler:
    """In-process matchmaking service (native, thread-safe).

    Each training worker thread calls `get_partner`; the call blocks until
    `target` workers arrived at the same key or the first arrival's
    `wait_time` (ms) elapsed.
    """

    def __init__(self, nworkers):
        self._lib = load()
        self.nworkers = nworkers
        self.handle = self._lib.preduce_create()

    def get_partner(self, key, rank, target=-1, wait_time=1.0):
        if target < 0:
            target = self.nworkers
        buf = (ctypes.c_int * (self.nworkers + 1))()
        n = self._lib.preduce_get_partner(
            self.handle, int(key), int(rank), int(target),
            ctypes.c_float(wait_time), buf)
        assert n > 0, "preduce matchmaking failed"
        return tuple(buf[i] for i in range(n))

    def close(self):
        if getattr(self, "handle", None):
            self._lib.preduce_destroy(self.handle)
            self.handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def partner_mask(partner, nworkers):
    """Member tuple -> float mask [nworkers] feeding the compiled reduce."""
    mask = np.zeros((nworkers,), np.float32)
    mask[list(partner)] = 1.0
    return mask


def masked_mean_allreduce(x, mask, axis_name="dp"):
    """Mean of x over mesh-axis members where mask==1 (inside shard_map).

    `mask` is [axis_size] data, so the same XLA program serves any group;
    equivalent to the reference's per-group ncclAvg without per-group
    communicator construction.
    """
    idx = lax.axis_index(axis_name)
    mine = mask[idx]
    total = lax.psum(x * mine.astype(x.dtype), axis_name)
    count = jnp.maximum(jnp.sum(mask), 1.0).astype(x.dtype)
    return total / count


class PartialReduce:
    """Client mirroring the reference API: matchmaking + masked-mean reduce.

    Unlike the reference there is no `_comm_map` of lazily created NCCL
    subgroups — `preduce` is one pre-compiled masked psum (see module
    docstring).
    """

    def __init__(self, nworkers, reduce_key=0, scheduler=None):
        self._reduce_key = reduce_key
        self.nworkers = nworkers
        self.scheduler = scheduler or PReduceScheduler(nworkers)

    def get_partner(self, rank, max_worker=-1, wait_time=1.0):
        return self.scheduler.get_partner(self._reduce_key, rank,
                                          max_worker, wait_time)

    def preduce(self, x, partner, axis_name="dp"):
        """Inside shard_map: average x over `partner` members."""
        return masked_mean_allreduce(
            x, jnp.asarray(partner_mask(partner, self.nworkers)), axis_name)
