"""Partial reduce: reduce gradients over whichever workers show up.

Reference: python/hetu/preduce.py `PartialReduce` (get_partner via the PS
scheduler RPC kPReduceGetPartner, then an ncclAvg allreduce over a lazily
created NCCL subgroup) with server-side matchmaking in
ps-lite/src/preduce_handler.cc.  Used by HetPipe-style training to tolerate
stragglers: a slow worker simply misses the round.

TPU redesign: NCCL subcommunicators don't exist under XLA, and compiling one
program per dynamic worker subset would defeat the point (the subset changes
every round).  Instead the member set enters the compiled program as DATA —
a boolean mask — and the reduction is a masked mean over the full `dp` mesh
axis: contribution = where(member, x, 0); psum; divide by member count.  One
compiled program serves every possible group, the collective still rides ICI
at full bandwidth, and non-members simply contribute zeros.
"""

from __future__ import annotations

import ctypes
import threading
import time

import numpy as np
import jax.numpy as jnp
from jax import lax

from .build import load


class PReduceScheduler:
    """In-process matchmaking service (native, thread-safe).

    Each training worker thread calls `get_partner`; the call blocks until
    `target` workers arrived at the same key or the first arrival's
    `wait_time` (ms) elapsed.
    """

    def __init__(self, nworkers):
        self._lib = load()
        self.nworkers = nworkers
        self.handle = self._lib.preduce_create()

    def get_partner(self, key, rank, target=-1, wait_time=1.0):
        if target < 0:
            target = self.nworkers
        buf = (ctypes.c_int * (self.nworkers + 1))()
        n = self._lib.preduce_get_partner(
            self.handle, int(key), int(rank), int(target),
            ctypes.c_float(wait_time), buf)
        assert n > 0, "preduce matchmaking failed"
        return tuple(buf[i] for i in range(n))

    def close(self):
        if getattr(self, "handle", None):
            self._lib.preduce_destroy(self.handle)
            self.handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def partner_mask(partner, nworkers):
    """Member tuple -> float mask [nworkers] feeding the compiled reduce."""
    mask = np.zeros((nworkers,), np.float32)
    mask[list(partner)] = 1.0
    return mask


def masked_mean_allreduce(x, mask, axis_name="dp"):
    """Mean of x over mesh-axis members where mask==1 (inside shard_map).

    `mask` is [axis_size] data, so the same XLA program serves any group;
    equivalent to the reference's per-group ncclAvg without per-group
    communicator construction.

    CONTRACT: exactly ONE group reduces per collective, and every rank on
    the axis must pass the SAME canonical mask (non-members execute the
    psum with the group's mask and discard the result).  If the
    matchmaker split a round into disjoint groups, agree on one first —
    ``PartialReduce.get_round_mask`` does the agreement.  As a safety
    net the denominator is the psum of the per-rank membership bits (not
    the host-side ``sum(mask)``), so numerator and denominator always
    count the same set of contributors: masks that disagree across ranks
    degrade to a well-defined mean over the union of self-declared
    members instead of silently mixing one group's sum with another
    group's count.
    """
    idx = lax.axis_index(axis_name)
    mine = mask[idx].astype(x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                            else jnp.float32)
    total = lax.psum(x * mine.astype(x.dtype), axis_name)
    count = jnp.maximum(lax.psum(mine, axis_name), 1.0)
    return total / count.astype(total.dtype)


class _MaskAgreement:
    """Per-round canonical-group agreement for the SPMD masked psum.

    The matchmaker can split one round into disjoint groups (a straggler
    missing the window forms its own), but the compiled program runs ONE
    psum over the full axis per round — so all ranks must reduce with one
    agreed mask.  Every rank reports its matched group; once all have
    arrived, the canonical group is the one containing the lowest rank
    (deterministic on every caller).  Members of other groups simply miss
    the round, exactly like a straggler in the reference's NCCL-subgroup
    design (preduce_handler.cc).
    """

    def __init__(self, nworkers):
        self.nworkers = nworkers
        self._cv = threading.Condition()
        self._rounds = {}

    def agree(self, round_id, rank, partner, timeout=60.0):
        with self._cv:
            slot = self._rounds.setdefault(round_id,
                                           {"groups": {}, "reads": 0})
            slot["groups"][rank] = tuple(sorted(partner))
            self._cv.notify_all()
            deadline = time.monotonic() + timeout
            while len(slot["groups"]) < self.nworkers:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # withdraw our report so a retry of this round starts
                    # clean instead of desyncing from still-waiting peers
                    slot["groups"].pop(rank, None)
                    if not slot["groups"]:
                        self._rounds.pop(round_id, None)
                    self._cv.notify_all()
                    raise RuntimeError(
                        f"preduce mask agreement round {round_id}: only "
                        f"{sorted(slot['groups'])} of {self.nworkers} ranks "
                        "arrived — every rank on the axis must call "
                        "get_round_mask (non-members too: they execute the "
                        "collective and discard the result)")
                self._cv.wait(remaining)
            canonical = slot["groups"][min(slot["groups"])]
            slot["reads"] += 1
            if slot["reads"] == self.nworkers:
                del self._rounds[round_id]
            return canonical


class PartialReduce:
    """Client mirroring the reference API: matchmaking + masked-mean reduce.

    Unlike the reference there is no `_comm_map` of lazily created NCCL
    subgroups — `preduce` is one pre-compiled masked psum (see module
    docstring).
    """

    def __init__(self, nworkers, reduce_key=0, scheduler=None):
        self._reduce_key = reduce_key
        self.nworkers = nworkers
        self.scheduler = scheduler or PReduceScheduler(nworkers)
        self._agree = _MaskAgreement(nworkers)
        self._round = [0] * nworkers
        self._round_lock = threading.Lock()

    def get_partner(self, rank, max_worker=-1, wait_time=1.0):
        return self.scheduler.get_partner(self._reduce_key, rank,
                                          max_worker, wait_time)

    def get_round_mask(self, rank, max_worker=-1, wait_time=1.0):
        """Matchmake, then agree on the round's single canonical mask.

        Returns ``(mask, group, is_member)``: ``mask`` is identical on
        every rank (the `masked_mean_allreduce` contract); ranks whose
        matched group lost the agreement get ``is_member=False`` — they
        still execute the collective and discard its result.
        """
        partner = self.get_partner(rank, max_worker, wait_time)
        with self._round_lock:
            rid = self._round[rank]
        # advance the round counter only on success: a rank whose
        # agreement timed out retries the SAME round id, staying in sync
        # with peers still waiting on it
        group = self._agree.agree(rid, rank, partner)
        with self._round_lock:
            self._round[rank] = rid + 1
        return partner_mask(group, self.nworkers), group, rank in group

    def preduce(self, x, partner, axis_name="dp"):
        """Inside shard_map: average x over `partner` members.

        ``partner`` must be the round's CANONICAL group — the same tuple
        on every rank of the axis (see get_round_mask / the
        masked_mean_allreduce contract).
        """
        return masked_mean_allreduce(
            x, jnp.asarray(partner_mask(partner, self.nworkers)), axis_name)
