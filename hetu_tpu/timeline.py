"""jax.profiler timeline capture + per-op aggregates.

Reference: python/hetu/timer_subexecutor.py wraps every ``node.compute``
with CUDA event pairs and ``logOut`` dumps per-op totals; Galvatron's
profiler scripts (tools/Hetu-Galvatron/galvatron/core/profiler.py:194)
drive the same per-op JSON into the strategy search.  SURVEY §5 names
"jax.profiler traces + per-step host timing" as the TPU translation.

Under XLA the executable is one fused program, so the honest per-op
breakdown is per-FUSION (and per-runtime-phase) timings from the
profiler's own timeline.  ``jax.profiler.trace`` writes two artifacts
per capture: an ``.xplane.pb`` (for TensorBoard/xprof) and a Chrome
``.trace.json.gz`` — this module aggregates the latter (no tensorflow
dependency) into the ``timer_subexecutor.logOut`` role:

    {op_name: {"total_us": ..., "count": ..., "avg_us": ...}, ...}

Wired into ``Executor.profile(..., trace_dir=...)`` (graph/executor.py),
which times N steps under the trace and writes ``op_aggregates.json``
next to the capture.  View the full timeline with
``tensorboard --logdir <trace_dir>`` (xprof plugin) or chrome://tracing
on the extracted .trace.json.
"""

from __future__ import annotations

import glob
import gzip
import json
import os


def _latest_trace_json(trace_dir):
    pats = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not pats:
        raise FileNotFoundError(
            f"no .trace.json.gz under {trace_dir}/plugins/profile — did "
            "the capture run?")
    return pats[-1]


def trace_aggregates(trace_dir, *, include_host_python=False,
                     device_ops_only=None):
    """Aggregate the newest capture under ``trace_dir`` into per-op
    totals: {name: {total_us, count, avg_us, pct}}, sorted by total.

    When the capture carries a device plane with an "XLA Ops" lane (real
    TPU runs), only those events aggregate by default — the true per-op
    device breakdown, free of host/dispatch lanes.  Host-only captures
    (CPU jax) aggregate every complete event instead, minus Python
    frames (``$file.py:123 fn`` — they time the tracer, not the
    program; ``include_host_python=True`` keeps them).  Force either
    behavior with ``device_ops_only``."""
    path = _latest_trace_json(trace_dir)
    data = json.loads(gzip.open(path).read())
    events = data.get("traceEvents", [])
    # lane metadata: (pid, tid) -> thread name, pid -> process name
    pnames, tnames = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pnames[e["pid"]] = e["args"].get("name", "")
        elif e.get("name") == "thread_name":
            tnames[(e["pid"], e.get("tid"))] = e["args"].get("name", "")
    xla_lanes = {k for k, v in tnames.items()
                 if v == "XLA Ops" and "device" in pnames.get(k[0], "")}
    if device_ops_only is None:
        device_ops_only = bool(xla_lanes)
    agg = {}
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if device_ops_only and (e.get("pid"),
                                e.get("tid")) not in xla_lanes:
            continue
        name = e.get("name", "")
        if not include_host_python and name.startswith("$"):
            continue
        slot = agg.setdefault(name, [0.0, 0])
        slot[0] += float(e["dur"])
        slot[1] += 1
    total = sum(v[0] for v in agg.values()) or 1.0
    out = {
        name: {"total_us": round(v[0], 3), "count": v[1],
               "avg_us": round(v[0] / v[1], 3),
               "pct": round(100.0 * v[0] / total, 2)}
        for name, v in agg.items()}
    return dict(sorted(out.items(), key=lambda kv: -kv[1]["total_us"]))


def write_aggregates(trace_dir, extra=None):
    """Write ``op_aggregates.json`` into ``trace_dir``; returns the
    aggregates dict (already parsed — callers shouldn't re-parse the
    gzipped capture, which can run to tens of MB).

    ``extra``: dict merged in under "meta" (e.g. measured step time) —
    the per-op JSON + host-measured step time pair the reference's
    profile-then-search contract carries."""
    aggs = trace_aggregates(trace_dir)
    doc = {"ops": aggs}
    if extra:
        doc["meta"] = extra
    path = os.path.join(trace_dir, "op_aggregates.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return aggs
