"""Multi-head attention layer.

Reference: /root/reference/python/hetu/layers/attention.py MultiHeadAttention
(the reference flattens to [B*S, H] between every projection).  Here the
layer keeps the [B, S, H] layout end to end — projections are 3D matmuls XLA
maps straight onto the MXU — and the core product is a single fused-attention
op (ops/attention.py) lowered to Pallas flash attention on TPU.
"""

from __future__ import annotations

from .base import BaseLayer, fresh_name
from .common import Linear
from ..ops import array_reshape_op, transpose_op
from ..ops.attention import scaled_dot_product_attention_op


class MultiHeadAttention(BaseLayer):
    def __init__(self, hidden_size, num_heads, sequence_length=None,
                 dropout_rate=0.0, causal_mask=False, name=None):
        assert hidden_size % num_heads == 0
        name = fresh_name(name or "attn")
        self.hidden_size = hidden_size
        self.num_heads = num_heads
        self.head_dim = hidden_size // num_heads
        self.sequence_length = sequence_length
        self.dropout_keep = 1.0 - dropout_rate
        self.causal = causal_mask
        self.q_proj = Linear(hidden_size, hidden_size, name=f"{name}_q")
        self.k_proj = Linear(hidden_size, hidden_size, name=f"{name}_k")
        self.v_proj = Linear(hidden_size, hidden_size, name=f"{name}_v")
        self.out_proj = Linear(hidden_size, hidden_size, name=f"{name}_out")

    def _split_heads(self, x, seq_len):
        # [B, S, H] (or [B*S, H]) -> [B, heads, S, d]
        x = array_reshape_op(
            x, output_shape=(-1, seq_len, self.num_heads, self.head_dim))
        return transpose_op(x, perm=(0, 2, 1, 3))

    def __call__(self, query, key, value, attention_mask=None, seq_len=None):
        """Returns [B, S, H]."""
        seq_len = seq_len or self.sequence_length
        assert seq_len is not None, "sequence length required"
        q = self._split_heads(self.q_proj(query), seq_len)
        k = self._split_heads(self.k_proj(key), seq_len)
        v = self._split_heads(self.v_proj(value), seq_len)
        ctx_ = scaled_dot_product_attention_op(
            q, k, v, mask=attention_mask, causal=self.causal,
            dropout_keep=self.dropout_keep)
        ctx_ = transpose_op(ctx_, perm=(0, 2, 1, 3))
        ctx_ = array_reshape_op(ctx_,
                                output_shape=(-1, seq_len, self.hidden_size))
        return self.out_proj(ctx_)
