"""Multi-head attention layer.

Reference: /root/reference/python/hetu/layers/attention.py MultiHeadAttention
(the reference flattens to [B*S, H] between every projection).  Here the
layer keeps the [B, S, H] layout end to end — projections are 3D matmuls XLA
maps straight onto the MXU — and the core product is a single fused-attention
op (ops/attention.py) lowered to Pallas flash attention on TPU.

Position-encoding variants for the Llama/Baichuan model tier (reference
tools/Hetu-Galvatron/galvatron/models/llama, models/baichuan): ``rope_theta``
applies rotary embeddings to q/k before the attention product; ``alibi``
adds the per-head linear bias instead; ``num_kv_heads`` < num_heads gives
grouped-query attention (K/V projected to the smaller head count and
broadcast back at the attention einsum).
"""

from __future__ import annotations

from .base import BaseLayer, fresh_name
from .common import Linear
from ..ops import array_reshape_op, transpose_op, head_split_linear_op
from ..ops.attention import scaled_dot_product_attention_op
from ..ops.rotary import rotary_embedding_op, repeat_kv_op, alibi_bias_op


class MultiHeadAttention(BaseLayer):
    def __init__(self, hidden_size, num_heads, sequence_length=None,
                 dropout_rate=0.0, causal_mask=False, num_kv_heads=None,
                 rope_theta=None, alibi=False, bias=True,
                 fused_head_projection=False, name=None):
        assert hidden_size % num_heads == 0
        self.fused_head_projection = fused_head_projection
        name = fresh_name(name or "attn")
        self.hidden_size = hidden_size
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        assert num_heads % self.num_kv_heads == 0
        self.head_dim = hidden_size // num_heads
        self.sequence_length = sequence_length
        self.dropout_keep = 1.0 - dropout_rate
        self.causal = causal_mask
        self.rope_theta = rope_theta
        self.alibi = alibi
        assert not (alibi and rope_theta), "pick one position encoding"
        kv_dim = self.num_kv_heads * self.head_dim
        self.q_proj = Linear(hidden_size, hidden_size, bias=bias,
                             name=f"{name}_q")
        self.k_proj = Linear(hidden_size, kv_dim, bias=bias,
                             name=f"{name}_k")
        self.v_proj = Linear(hidden_size, kv_dim, bias=bias,
                             name=f"{name}_v")
        self.out_proj = Linear(hidden_size, hidden_size, bias=bias,
                               name=f"{name}_out")

    def _split_heads(self, x, seq_len, n_heads):
        # [B, S, H] (or [B*S, H]) -> [B, heads, S, d]
        x = array_reshape_op(
            x, output_shape=(-1, seq_len, n_heads, self.head_dim))
        return transpose_op(x, perm=(0, 2, 1, 3))

    def _project_heads(self, x, proj, seq_len, n_heads):
        """Projection + head split.  Inference-only graphs use the fused
        einsum (head_split_linear_op: the head transpose rides the
        matmul epilogue — ~0.25 ms/layer saved at GPT-2.7B fwd shapes);
        training keeps the matmul + reshape + transpose form, whose
        BACKWARD measures ~1% faster end-to-end (the einsum's dW
        contraction lays out worse under XLA)."""
        if self.fused_head_projection:
            return head_split_linear_op(
                x, proj.weight,
                *([] if proj.bias is None else [proj.bias]),
                seq_len=seq_len, n_heads=n_heads, head_dim=self.head_dim)
        return self._split_heads(proj(x), seq_len, n_heads)

    def __call__(self, query, key, value, attention_mask=None, seq_len=None,
                 kv_seq_len=None):
        """Returns [B, S, H].  ``kv_seq_len`` (default: ``seq_len``)
        supports cross-attention over a memory of different length
        (reference examples/nlp/hetu_transformer.py multihead_attention,
        decoder side)."""
        seq_len = seq_len or self.sequence_length
        assert seq_len is not None, "sequence length required"
        if kv_seq_len is not None and kv_seq_len != seq_len:
            # rotary positions implicitly start at 0 on BOTH q and k, the
            # causal mask assumes square [S, S], and the ALiBi bias is
            # built [.., Sq, Sq] from q alone — a differing memory length
            # would silently mis-position/mis-mask (ADVICE r3); only
            # vanilla cross-attention supports it
            assert (self.rope_theta is None and not self.causal
                    and not self.alibi), (
                "kv_seq_len != seq_len is only supported for non-causal, "
                "non-rotary, non-alibi cross-attention")
        kv_seq_len = kv_seq_len or seq_len
        q = self._project_heads(query, self.q_proj, seq_len,
                                self.num_heads)
        k = self._project_heads(key, self.k_proj, kv_seq_len,
                                self.num_kv_heads)
        v = self._project_heads(value, self.v_proj, kv_seq_len,
                                self.num_kv_heads)
        if self.rope_theta is not None:
            q = rotary_embedding_op(q, theta=self.rope_theta)
            k = rotary_embedding_op(k, theta=self.rope_theta)
        if self.num_kv_heads != self.num_heads:
            rep = self.num_heads // self.num_kv_heads
            k = repeat_kv_op(k, n_rep=rep)
            v = repeat_kv_op(v, n_rep=rep)
        if self.alibi:
            bias = alibi_bias_op(q, num_heads=self.num_heads)
            attention_mask = (bias if attention_mask is None
                              else attention_mask + bias)
        ctx_ = scaled_dot_product_attention_op(
            q, k, v, mask=attention_mask, causal=self.causal,
            dropout_keep=self.dropout_keep)
        ctx_ = transpose_op(ctx_, perm=(0, 2, 1, 3))
        ctx_ = array_reshape_op(ctx_,
                                output_shape=(-1, seq_len, self.hidden_size))
        return self.out_proj(ctx_)
