"""Layer library base (reference: /root/reference/python/hetu/layers/base.py).

Layers are callables that build op subgraphs; parameters are VariableOps
created at layer construction.  Unlike flax Modules there is no separate
param pytree — the graph owns the Variables, matching the reference design.
"""

from __future__ import annotations

from ..graph.node import _naming_stack


def fresh_name(prefix):
    # counters live in the innermost `name_scope` (graph/node.py), so a
    # model instance's default layer names don't depend on process history
    counters = _naming_stack()[-1]["layers"]
    c = counters.get(prefix, 0)
    counters[prefix] = c + 1
    return f"{prefix}{c}" if c else prefix


class BaseLayer:
    def __call__(self, *args, **kwargs):
        raise NotImplementedError


class Sequence(BaseLayer):
    """Sequential container (reference layers/sequence.py)."""

    def __init__(self, *layers):
        self.layers = list(layers)

    def __call__(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class Identity(BaseLayer):
    def __call__(self, x):
        return x
