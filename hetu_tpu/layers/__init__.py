from .base import BaseLayer, Sequence, Identity, fresh_name
from .common import (Linear, Conv2d, BatchNorm, LayerNorm, RMSNorm, Embedding,
                     DropOut, Relu, Gelu, Mish, MaxPool2d, AvgPool2d, Reshape,
                     Concatenate, SumLayers)
from .attention import MultiHeadAttention
from .transformer import TransformerLayer, TransformerFFN
from .moe import (MoELayer, TopKGate, HashGate, KTop1Gate, SAMGate,
                  BalanceGate)
