"""Common layers: Linear, Conv2d, norms, Embedding, Dropout, activations.

Reference: /root/reference/python/hetu/layers/{linear,conv,normalization,
embedding,dropout,relu,gelu,mish,pooling,reshape,concatenate,sum,slice}.py
"""

from __future__ import annotations

import numpy as np

from .base import BaseLayer, fresh_name
from .. import initializers as init
from ..graph.node import VariableOp
from ..ops import (matmul_op, linear_op, broadcastto_op, conv2d_op,
                   conv2d_add_bias_op, conv2d_hwio_op,
                   conv2d_hwio_add_bias_op, conv2d_nhwc_op,
                   conv2d_nhwc_add_bias_op, batch_normalization_op,
                   layer_normalization_op, rms_norm_op, dropout_op, relu_op,
                   gelu_op, silu_op, tanh_op, sigmoid_op, leaky_relu_op,
                   max_pool2d_op, avg_pool2d_op, array_reshape_op,
                   embedding_lookup_op, concatenate_op, softplus_op, mul_op)


class Linear(BaseLayer):
    def __init__(self, in_features, out_features, bias=True,
                 initializer=None, activation=None, name=None):
        name = fresh_name(name or "dense")
        self.weight = VariableOp(
            f"{name}_weight", (in_features, out_features),
            initializer or init.xavier_normal())
        self.bias = VariableOp(f"{name}_bias", (out_features,),
                               init.zeros()) if bias else None
        self.activation = activation

    def __call__(self, x):
        if self.bias is not None:
            out = linear_op(x, self.weight, self.bias)
        else:
            out = matmul_op(x, self.weight)
        if self.activation is not None:
            out = self.activation(out)
        return out


class _HWIOAdapter:
    """Run an OIHW-convention initializer, store the result HWIO.

    Keeps fan-in/fan-out semantics (initializers._fans assumes OIHW for
    4-D shapes) bit-identical to the reference convention while the
    layer stores the TPU-native kernel layout."""

    def __init__(self, inner):
        self.inner = inner

    def __call__(self, key, shape, dtype=np.float32):
        kh, kw, ci, co = shape
        w = self.inner(key, (co, ci, kh, kw), dtype)
        import jax.numpy as jnp
        return jnp.transpose(w, (2, 3, 1, 0))


class Conv2d(BaseLayer):
    """2-D convolution (reference layers/conv.py).

    The weight is stored HWIO (TPU-native): the OIHW->HWIO transpose
    that API-layout parity would need costs a physical copy of every
    kernel every step under XLA (~177 MB/step on ResNet-18/2048).
    ``load_oihw``/``dump_oihw`` convert at the checkpoint boundary for
    torch/ONNX-convention arrays."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, bias=True, initializer=None, activation=None,
                 channels_last=False, name=None):
        name = fresh_name(name or "conv2d")
        ks = kernel_size if isinstance(kernel_size, tuple) \
            else (kernel_size, kernel_size)
        self.weight = VariableOp(
            f"{name}_weight", ks + (in_channels, out_channels),
            _HWIOAdapter(initializer or init.he_normal()))
        self.bias = VariableOp(f"{name}_bias", (out_channels,),
                               init.zeros()) if bias else None
        self.stride, self.padding = stride, padding
        self.activation = activation
        # channels_last: activations are NHWC end to end (zero layout
        # transposes — the fully TPU-native form); default keeps the
        # reference's NCHW activation API
        self.channels_last = channels_last

    @staticmethod
    def load_oihw(w):
        """torch/ONNX-convention (O, I, H, W) array -> this layer's
        stored layout."""
        return np.transpose(np.asarray(w), (2, 3, 1, 0))

    @staticmethod
    def dump_oihw(w):
        """Stored layout -> torch/ONNX-convention (O, I, H, W)."""
        return np.transpose(np.asarray(w), (3, 2, 0, 1))

    def __call__(self, x):
        if self.channels_last:
            op, op_b = conv2d_nhwc_op, conv2d_nhwc_add_bias_op
        else:
            op, op_b = conv2d_hwio_op, conv2d_hwio_add_bias_op
        if self.bias is not None:
            out = op_b(x, self.weight, self.bias,
                       padding=self.padding, stride=self.stride)
        else:
            out = op(x, self.weight, padding=self.padding,
                     stride=self.stride)
        if self.activation is not None:
            out = self.activation(out)
        return out


class BatchNorm(BaseLayer):
    """BatchNorm over [N, C, H, W] (reference layers/normalization.py).

    Batch statistics default to a shifted one-pass form whose shift is
    the RUNNING mean — fastest (fuses with the producing conv), but for
    the first steps the zero-initialized shift gives the raw
    E[x^2]-E[x]^2 f32 form, which cancels catastrophically on inputs
    with per-channel |mean| >> std.  For such offset-heavy inputs pass
    ``precise_stats=True`` (exact two-pass stats, one extra read of x;
    see ops/nn.py BatchNormOp)."""

    def __init__(self, num_channels, momentum=0.1, eps=1e-5,
                 precise_stats=False, channels_last=False, name=None):
        name = fresh_name(name or "bn")
        self.scale = VariableOp(f"{name}_scale", (num_channels,), init.ones())
        self.bias = VariableOp(f"{name}_bias", (num_channels,), init.zeros())
        self.momentum, self.eps = momentum, eps
        self.precise_stats = precise_stats
        self.channel_axis = -1 if channels_last else 1

    def __call__(self, x):
        return batch_normalization_op(x, self.scale, self.bias,
                                      momentum=self.momentum, eps=self.eps,
                                      precise_stats=self.precise_stats,
                                      channel_axis=self.channel_axis)


class LayerNorm(BaseLayer):
    def __init__(self, hidden_size, eps=1e-5, name=None):
        name = fresh_name(name or "ln")
        self.scale = VariableOp(f"{name}_scale", (hidden_size,), init.ones())
        self.bias = VariableOp(f"{name}_bias", (hidden_size,), init.zeros())
        self.eps = eps

    def __call__(self, x):
        return layer_normalization_op(x, self.scale, self.bias, eps=self.eps)


class RMSNorm(BaseLayer):
    def __init__(self, hidden_size, eps=1e-6, name=None):
        name = fresh_name(name or "rmsnorm")
        self.scale = VariableOp(f"{name}_scale", (hidden_size,), init.ones())
        self.eps = eps

    def __call__(self, x):
        return rms_norm_op(x, self.scale, eps=self.eps)


class Embedding(BaseLayer):
    def __init__(self, num_embeddings, embedding_dim, initializer=None,
                 name=None):
        name = fresh_name(name or "embedding")
        self.weight = VariableOp(
            f"{name}_table", (num_embeddings, embedding_dim),
            initializer or init.normal(0.0, 0.01))

    def __call__(self, ids):
        return embedding_lookup_op(self.weight, ids)


class DropOut(BaseLayer):
    def __init__(self, keep_prob=0.9):
        self.keep_prob = keep_prob

    def __call__(self, x):
        return dropout_op(x, keep_prob=self.keep_prob)


class Relu(BaseLayer):
    def __call__(self, x):
        return relu_op(x)


class Gelu(BaseLayer):
    def __call__(self, x):
        return gelu_op(x)


class Mish(BaseLayer):
    """x * tanh(softplus(x)) (reference layers/mish.py)."""

    def __call__(self, x):
        return mul_op(x, tanh_op(softplus_op(x)))


class MaxPool2d(BaseLayer):
    def __init__(self, kernel_size, stride=None, padding=0):
        self.k = kernel_size
        self.s = stride or kernel_size
        self.p = padding

    def __call__(self, x):
        return max_pool2d_op(x, kernel_H=self.k, kernel_W=self.k,
                             padding=self.p, stride=self.s)


class AvgPool2d(MaxPool2d):
    def __call__(self, x):
        return avg_pool2d_op(x, kernel_H=self.k, kernel_W=self.k,
                             padding=self.p, stride=self.s)


class Reshape(BaseLayer):
    def __init__(self, shape):
        self.shape = tuple(shape)

    def __call__(self, x):
        return array_reshape_op(x, output_shape=self.shape)


class Concatenate(BaseLayer):
    def __init__(self, axis=0):
        self.axis = axis

    def __call__(self, xs):
        return concatenate_op(list(xs), axis=self.axis)


class SumLayers(BaseLayer):
    def __init__(self, layers):
        self.layers = list(layers)

    def __call__(self, x):
        outs = [l(x) for l in self.layers]
        acc = outs[0]
        for o in outs[1:]:
            acc = acc + o
        return acc
