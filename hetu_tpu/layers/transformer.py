"""Transformer blocks shared by BERT/GPT/MoE models.

Reference builds these ad hoc in examples (examples/nlp/bert/hetu_bert.py,
examples/auto_parallel/transformer); here they are first-class layers.  The
block works on [B, S, H] tensors throughout; TP/SP shardings are attached by
parallel/ strategies via dist_state annotations on the weight Variables.
"""

from __future__ import annotations

from .base import BaseLayer, fresh_name
from .common import Linear, LayerNorm
from .attention import MultiHeadAttention
from ..ops import gelu_op, dropout_op


class TransformerFFN(BaseLayer):
    def __init__(self, hidden_size, intermediate_size, activation=gelu_op,
                 dropout_rate=0.0, name=None):
        name = fresh_name(name or "ffn")
        self.dense1 = Linear(hidden_size, intermediate_size,
                             name=f"{name}_in")
        self.dense2 = Linear(intermediate_size, hidden_size,
                             name=f"{name}_out")
        self.activation = activation
        self.dropout_rate = dropout_rate

    def __call__(self, x):
        h = self.activation(self.dense1(x))
        h = self.dense2(h)
        if self.dropout_rate > 0:
            h = dropout_op(h, keep_prob=1.0 - self.dropout_rate)
        return h


class TransformerLayer(BaseLayer):
    """Post-LN (BERT-style) or pre-LN (GPT-style) transformer block on
    [B, S, H] nodes."""

    def __init__(self, hidden_size, num_heads, intermediate_size,
                 seq_len=None, dropout_rate=0.0, attn_dropout_rate=0.0,
                 causal=False, pre_norm=False, activation=gelu_op,
                 ffn_layer=None, name=None):
        name = fresh_name(name or "layer")
        self.attn = MultiHeadAttention(hidden_size, num_heads,
                                       sequence_length=seq_len,
                                       dropout_rate=attn_dropout_rate,
                                       causal_mask=causal,
                                       name=f"{name}_attn")
        self.ffn = ffn_layer or TransformerFFN(
            hidden_size, intermediate_size, activation=activation,
            dropout_rate=dropout_rate, name=f"{name}_ffn")
        self.ln1 = LayerNorm(hidden_size, name=f"{name}_ln1")
        self.ln2 = LayerNorm(hidden_size, name=f"{name}_ln2")
        self.pre_norm = pre_norm

    def __call__(self, x, attention_mask=None, seq_len=None):
        if self.pre_norm:
            a_in = self.ln1(x)
            a = self.attn(a_in, a_in, a_in, attention_mask=attention_mask,
                          seq_len=seq_len)
            x = x + a
            return x + self.ffn(self.ln2(x))
        else:
            a = self.attn(x, x, x, attention_mask=attention_mask,
                          seq_len=seq_len)
            x = self.ln1(x + a)
            return self.ln2(x + self.ffn(x))
