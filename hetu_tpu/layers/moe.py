"""MoE layer with expert parallelism.

Reference: /root/reference/python/hetu/layers/moe_layer.py — MoELayer:
reshape → gate → layout_transform → alltoall → expert FFNs → alltoall →
reverse_layout_transform (:60-88); BASE-layer variant (:90) with balance
assignment; gates in layers/{TopGate,KTop1Gate,HashGate,SAMGate,BalanceGate}.

TPU redesign: gating + dispatch are dense einsums (ops/moe.py); the expert
dim of dispatched activations and of expert weights carries an 'ep' mesh-axis
annotation, so GSPMD inserts the all-to-all pair the reference ran as
explicit AllToAllOps (for multi-node topologies,
parallel/collectives.hierarchical_all_to_all composes the DCN×ICI staging
explicitly inside shard_map).
"""

from __future__ import annotations

import numpy as np

from .base import BaseLayer, fresh_name
from ..graph.node import Op, VariableOp
from .. import initializers as init
from ..ops.moe import (top_k_gating, hash_gating, ktop1_gating, sam_gating,
                       base_balance_gating, top_k_balance_aux,
                       ktop1_balance_aux, sam_balance_aux,
                       top_k_gating_choices, hash_gating_choices,
                       ktop1_gating_choices, sam_gating_choices)


def _orthogonal_rows(rng, rows, cols, gain=0.1):
    """Orthogonal centroid init (reference BalanceGate.generate_orthogonal)."""
    flat = rng.normal(0, 1, (max(rows, cols), min(rows, cols)))
    q, r = np.linalg.qr(flat)
    q = q * np.sign(np.diag(r))
    if rows < cols:
        q = q.T
    return (q[:rows, :cols] * gain).astype(np.float32)


class TopKGate(BaseLayer):
    """GShard top-1/top-2 gate weights (reference TopGate.py).  Routing
    hyper-parameters (k, capacity) live on the MoELayer, the single source
    of truth."""

    def __init__(self, hidden_size, num_experts, name=None):
        name = fresh_name(name or "gate")
        self.wg = VariableOp(f"{name}_w", (hidden_size, num_experts),
                             init.xavier_uniform())

    def gating(self, tokens, wg, ids, k, capacity):
        return top_k_gating(tokens @ wg, k, capacity)

    def gating_choices(self, tokens, wg, ids, k, capacity):
        return top_k_gating_choices(tokens @ wg, k, capacity)

    def aux(self, tokens, wg, ids, k):
        return top_k_balance_aux(tokens @ wg)


class HashGate(BaseLayer):
    """Deterministic id-hash gate (reference HashGate.py).  Requires token
    ids passed to MoELayer.__call__."""

    has_aux = False   # routing is deterministic: no balance loss

    def __init__(self, num_experts, name=None):
        self.num_experts = num_experts
        self.wg = None

    def gating(self, tokens, wg, ids, k, capacity):
        return hash_gating(ids.reshape(-1), self.num_experts, capacity,
                           dtype=tokens.dtype)

    def gating_choices(self, tokens, wg, ids, k, capacity):
        return hash_gating_choices(ids.reshape(-1), self.num_experts,
                                   capacity, dtype=tokens.dtype)


class KTop1Gate(BaseLayer):
    """k-prototype top-1 gate (reference KTop1Gate.py): experts split into
    k prototypes; each token routes top-1 within every prototype."""

    def __init__(self, hidden_size, num_experts, name=None):
        name = fresh_name(name or "ktop1_gate")
        self.wg = VariableOp(f"{name}_w", (hidden_size, num_experts),
                             init.xavier_uniform())

    def gating(self, tokens, wg, ids, k, capacity):
        return ktop1_gating(tokens @ wg, k, capacity)

    def gating_choices(self, tokens, wg, ids, k, capacity):
        return ktop1_gating_choices(tokens @ wg, k, capacity)

    def aux(self, tokens, wg, ids, k):
        return ktop1_balance_aux(tokens @ wg, k)


class SAMGate(BaseLayer):
    """Switch-and-mix locality gate (reference SAMGate.py): pick the
    expert GROUP (host) with the largest mass, then top-k inside it."""

    def __init__(self, hidden_size, num_experts, num_groups, name=None):
        name = fresh_name(name or "sam_gate")
        assert num_experts % num_groups == 0
        self.num_groups = num_groups
        self.wg = VariableOp(f"{name}_w", (hidden_size, num_experts),
                             init.xavier_uniform())

    def gating(self, tokens, wg, ids, k, capacity):
        return sam_gating(tokens @ wg, k, capacity, self.num_groups)

    def gating_choices(self, tokens, wg, ids, k, capacity):
        return sam_gating_choices(tokens @ wg, k, capacity,
                                  self.num_groups)

    def aux(self, tokens, wg, ids, k):
        return sam_balance_aux(tokens @ wg, self.num_groups)


class BalanceGate(BaseLayer):
    """BASE-layer gate (reference BalanceGate.py): balanced assignment
    against fixed orthogonal expert centroids, sigmoid combine."""

    has_aux = False   # assignment is balanced by construction

    def __init__(self, hidden_size, num_experts, seed=0, name=None):
        name = fresh_name(name or "balance_gate")
        cent = _orthogonal_rows(np.random.default_rng(seed), num_experts,
                                hidden_size)
        # wg = centroids^T so scores = tokens @ wg, like the other gates
        self.wg = VariableOp(f"{name}_centroids", (hidden_size, num_experts),
                             init.NumpyInit(cent.T.copy()), trainable=False)

    def gating(self, tokens, wg, ids, k, capacity):
        return base_balance_gating(tokens @ wg, capacity)


class _MoEOp(Op):
    """Fused gate+dispatch+experts+combine (single graph node so the EP
    sharding annotations stay local to the op)."""

    def __init__(self, x, gate, w1, b1, w2, b2, num_experts, capacity_factor,
                 k, ep_axis=None, ids=None, sparse=True, w3=None,
                 name=None):
        # swiglu experts are biasless: b1/b2 are None and stay out of the
        # graph entirely (no dead optimizer state / checkpoint entries)
        inputs = [x, w1, w2] if b1 is None else [x, w1, b1, w2, b2]
        self.has_biases = b1 is not None
        if w3 is not None:                    # swiglu experts: up proj
            inputs.append(w3)
        if gate.wg is not None:
            inputs.append(gate.wg)
        if ids is not None:
            inputs.append(ids)
        super().__init__(*inputs, name=name or "moe")
        self.gate = gate
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.k = k
        self.ep_axis = ep_axis
        self.sparse = sparse
        self.has_w3 = w3 is not None
        self.has_ids = ids is not None

    def _unpack(self, input_vals):
        """Input layout shared with MoEAuxLossOp (same inputs list)."""
        if self.has_biases:
            x, w1, b1, w2, b2 = input_vals[:5]
            rest = list(input_vals[5:])
        else:
            x, w1, w2 = input_vals[:3]
            b1 = b2 = None
            rest = list(input_vals[3:])
        w3 = rest.pop(0) if self.has_w3 else None
        wg = rest.pop(0) if self.gate.wg is not None else None
        ids = rest.pop(0) if self.has_ids else None
        return x, w1, b1, w2, b2, w3, wg, ids

    def _capacity(self, T):
        return max(int(np.ceil(self.capacity_factor * T * self.k
                               / self.num_experts)), 1)

    def _compute(self, input_vals, ctx):
        import jax
        import jax.numpy as jnp
        from ..ops.moe import sparse_dispatch, sparse_combine
        x, w1, b1, w2, b2, w3, wg, ids = self._unpack(input_vals)

        orig_shape = x.shape
        h = x.shape[-1]
        tokens = x.reshape(-1, h)
        T = tokens.shape[0]
        C = self._capacity(T)

        # scatter-style dispatch (reference LayoutTransform.cu) when the
        # gate exposes routing CHOICES: memory is O(T·H + E·C·H), never
        # the O(T·E·C) one-hot tensors of the dense einsum form — at real
        # T·E·C those are the memory wall (SURVEY §2.1 N3).  Gates
        # without a choices form (BASE auction) keep the dense path.
        sparse = self.sparse and hasattr(self.gate, "gating_choices")
        if sparse:
            choices, aux = self.gate.gating_choices(tokens, wg, ids,
                                                    self.k, C)
            # pallas_call does not partition under GSPMD: inside ANY
            # meshed program (ep-sharded or just dp) the gather lowers
            # via XLA instead
            pallas_ok = ctx.mesh is None
            expert_in = sparse_dispatch(tokens, choices,
                                        self.num_experts, C,
                                        use_pallas=pallas_ok)
        else:
            dispatch, combine, aux = self.gate.gating(tokens, wg, ids,
                                                      self.k, C)
            expert_in = jnp.einsum("tec,th->ech", dispatch, tokens)
        if self.ep_axis is not None and ctx.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            expert_in = jax.lax.with_sharding_constraint(
                expert_in, NamedSharding(ctx.mesh,
                                         P(self.ep_axis, None, None)))
        # per-expert FFN: [E, C, H] @ [E, H, F] -> [E, C, F]
        if self.has_w3:
            # swiglu experts (Mixtral-style): silu(x@w1) * (x@w3) @ w2
            a = (jax.nn.silu(jnp.einsum("ech,ehf->ecf", expert_in, w1))
                 * jnp.einsum("ech,ehf->ecf", expert_in, w3))
            out = jnp.einsum("ecf,efh->ech", a, w2)
        else:
            a = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", expert_in, w1)
                            + b1[:, None, :])
            out = jnp.einsum("ecf,efh->ech", a, w2) + b2[:, None, :]
        if self.ep_axis is not None and ctx.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            out = jax.lax.with_sharding_constraint(
                out, NamedSharding(ctx.mesh, P(self.ep_axis, None, None)))
        if sparse:
            combined = sparse_combine(out, choices,
                                      use_pallas=pallas_ok)
        else:
            combined = jnp.einsum("ech,tec->th", out, combine)
        return combined.reshape(orig_shape)


class MoEAuxLossOp(Op):
    def __init__(self, moe_op):
        super().__init__(*moe_op.inputs, name=f"{moe_op.name}_aux")
        self.moe = moe_op

    def _compute(self, input_vals, ctx):
        # aux-only gate path: O(T·E) logits work, never the [T,E,C]
        # dispatch/combine tensors — an aux evaluated in a separate
        # subexecutor from the MoE op must not pay the full dispatch
        # recompute (in the same jitted program, CSE merges it anyway)
        import jax.numpy as jnp
        x, _, _, _, _, _, wg, ids = self.moe._unpack(input_vals)
        if not getattr(self.moe.gate, "has_aux", True):
            # hash/balance gates have identically-zero aux: skip the
            # dispatch recompute entirely
            return jnp.asarray(0.0, x.dtype)
        tokens = x.reshape(-1, x.shape[-1])
        aux_fn = getattr(self.moe.gate, "aux", None)
        if aux_fn is not None:
            aux = aux_fn(tokens, wg, ids, self.moe.k)
        else:
            # caller-built gate without the aux-only fast path: fall back
            # to full gating (CSE removes the cost when jitted with the
            # MoE op)
            _, _, aux = self.moe.gate.gating(
                tokens, wg, ids, self.moe.k,
                self.moe._capacity(tokens.shape[0]))
        return jnp.asarray(aux, x.dtype)


class MoELayer(BaseLayer):
    """Expert-parallel FFN block (drop-in for TransformerFFN)."""

    def __init__(self, hidden_size, intermediate_size, num_experts, k=2,
                 capacity_factor=1.25, gate="top", ep_axis=None,
                 num_groups=None, sparse=True, expert_act="gelu",
                 name=None):
        name = fresh_name(name or "moe")
        if isinstance(gate, BaseLayer):
            self.gate = gate                      # caller-built gate
        elif gate == "top":
            self.gate = TopKGate(hidden_size, num_experts, name=name)
        elif gate == "hash":
            self.gate = HashGate(num_experts)
        elif gate == "ktop1":
            self.gate = KTop1Gate(hidden_size, num_experts, name=name)
        elif gate == "sam":
            self.gate = SAMGate(hidden_size, num_experts,
                                num_groups or 2, name=name)
        elif gate == "balance":
            self.gate = BalanceGate(hidden_size, num_experts, name=name)
        else:
            raise ValueError(gate)
        assert expert_act in ("gelu", "swiglu")
        self.expert_act = expert_act
        self.w1 = VariableOp(f"{name}_w1",
                             (num_experts, hidden_size, intermediate_size),
                             init.xavier_uniform())
        self.b1 = VariableOp(f"{name}_b1", (num_experts, intermediate_size),
                             init.zeros()) \
            if expert_act == "gelu" else None
        self.w2 = VariableOp(f"{name}_w2",
                             (num_experts, intermediate_size, hidden_size),
                             init.xavier_uniform())
        self.b2 = VariableOp(f"{name}_b2", (num_experts, hidden_size),
                             init.zeros()) \
            if expert_act == "gelu" else None
        # swiglu experts (Mixtral-style, reference-beyond): gated FFN
        # silu(x@w1) * (x@w3) @ w2, no biases
        self.w3 = VariableOp(f"{name}_w3",
                             (num_experts, hidden_size, intermediate_size),
                             init.xavier_uniform()) \
            if expert_act == "swiglu" else None
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.k = k
        self.ep_axis = ep_axis
        # sparse=False forces the dense one-hot einsum dispatch (debug /
        # exactness oracle); sparse routing needs a gate with a choices
        # form and is the default memory-safe path
        self.sparse = sparse
        if ep_axis is not None:
            ep_vars = [v for v in (self.w1, self.b1, self.w2, self.b2,
                                   self.w3) if v is not None]
            for v in ep_vars:
                from ..parallel.mesh import DistState
                v.dist_state = DistState({0: ep_axis})
        self.last_op = None

    def __call__(self, x, ids=None):
        if self.gate.wg is None and ids is None:
            raise ValueError(
                "hash-gated MoELayer requires token ids: moe(x, ids=...)")
        self.last_op = _MoEOp(x, self.gate, self.w1, self.b1, self.w2,
                              self.b2, self.num_experts,
                              self.capacity_factor, self.k,
                              ep_axis=self.ep_axis, ids=ids,
                              sparse=self.sparse, w3=self.w3)
        return self.last_op

    def aux_loss(self):
        assert self.last_op is not None
        return MoEAuxLossOp(self.last_op)
