"""Shared build-and-load helper for in-tree native (C++) components.

The reference ships prebuilt .so files loaded via ctypes (libps.so at
executor.py:100-137, libc_runtime_api.so in _base.py); here each native
component compiles from source on first use so the repo stays
self-contained.  Used by hetu_tpu/ps (embedding store) and
hetu_tpu/galvatron (DP search core).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading


class NativeLib:
    """Lazily compiled + loaded shared library.

    declare(lib) is called once after load to set restype/argtypes.
    """

    def __init__(self, src, lib_path, declare=None, extra_flags=()):
        self.src = src
        self.lib_path = lib_path
        self.declare = declare
        self.extra_flags = list(extra_flags)
        self._lock = threading.Lock()
        self._lib = None

    def _needs_build(self):
        return (not os.path.exists(self.lib_path)
                or os.path.getmtime(self.lib_path) < os.path.getmtime(self.src))

    def build(self):
        cmd = (["g++", "-O3", "-march=native", "-std=c++17", "-shared",
                "-fPIC"] + self.extra_flags
               + ["-o", self.lib_path, self.src])
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"building {os.path.basename(self.lib_path)} failed:\n"
                f"{proc.stderr}")
        return self.lib_path

    def load(self):
        with self._lock:
            if self._lib is not None:
                return self._lib
            if self._needs_build():
                self.build()
            lib = ctypes.CDLL(self.lib_path)
            if self.declare is not None:
                self.declare(lib)
            self._lib = lib
            return lib
