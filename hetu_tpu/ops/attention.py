"""Attention ops.

The reference composes attention from batched matmuls + softmax graph nodes
(layers/attention.py); there is no fused kernel.  Here scaled-dot-product
attention is ONE graph op so the executor can lower it to the Pallas flash
attention kernel on TPU (ops/pallas/flash_attention.py) and fall back to a
fusable jnp composition elsewhere — the TPU answer to cudnn-style fused MHA
and the building block the reference lacks for long-context (ring/blockwise)
variants.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..graph.node import Op

_FLASH_MIN_SEQ = 256  # below this the jnp path is faster (kernel overheads)


def _use_flash(q):
    if q.ndim != 4:
        return False
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return False
    # cheap pre-filter only; pallas.flash_attention._supported is the
    # authoritative gate (it additionally requires seq % 256 == 0 and
    # returns None on rejection, which we handle below)
    return (platform in ("tpu", "axon")
            and q.shape[-2] >= _FLASH_MIN_SEQ
            and 32 <= q.shape[-1] <= 512 and q.shape[-1] % 8 == 0)


class ScaledDotProductAttentionOp(Op):
    def __init__(self, q, k, v, mask=None, causal=False, scale=None,
                 dropout_keep=1.0, name=None):
        inputs = [q, k, v] + ([mask] if mask is not None else [])
        super().__init__(*inputs, name=name)
        self.has_mask = mask is not None
        self.causal = causal
        self.scale = scale
        self.dropout_keep = dropout_keep

    @property
    def needs_rng(self):
        return self.dropout_keep < 1.0

    def _compute(self, input_vals, ctx):
        q, k, v = input_vals[:3]
        mask = input_vals[3] if self.has_mask else None
        d = q.shape[-1]
        scale = self.scale if self.scale is not None else 1.0 / (d ** 0.5)
        # long-context: when the executor's mesh has a 'cp' axis, the
        # sequence dim is context-sharded — lower to flash ring attention
        # (K/V blocks rotate the ICI ring; parallel/context_parallel.py).
        # Dropout/masks stay on the single-device paths.
        if (ctx.mesh is not None and "cp" in ctx.mesh.shape
                and ctx.mesh.shape["cp"] > 1 and mask is None
                and self.dropout_keep >= 1.0 and q.ndim == 4
                and q.shape == k.shape == v.shape
                # shard_map dies opaquely on indivisible shapes — route
                # those to the flash/jnp paths below instead
                and q.shape[2] % ctx.mesh.shape["cp"] == 0
                and ("dp" not in ctx.mesh.shape
                     or q.shape[0] % ctx.mesh.shape["dp"] == 0)):
            impl = getattr(ctx, "cp_impl", "ring")
            if (impl == "ulysses"
                    and q.shape[1] % ctx.mesh.shape["cp"] == 0):
                from ..parallel.context_parallel import ulysses_attention
                return ulysses_attention(ctx.mesh, q, k, v,
                                         causal=self.causal, scale=scale)
            from ..parallel.context_parallel import ring_attention
            return ring_attention(ctx.mesh, q, k, v, causal=self.causal,
                                  scale=scale)
        if _use_flash(q):
            from .pallas.flash_attention import flash_attention
            keep = self.dropout_keep if ctx.training else 1.0
            seed = None
            if keep < 1.0:
                seed = jax.random.bits(ctx.rng_for(self), (1,),
                                       "uint32").astype(jnp.int32)
            out = flash_attention(q, k, v, mask=mask, causal=self.causal,
                                  scale=scale, dropout_keep=keep,
                                  seed=seed)
            if out is not None:
                return out
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * scale
        if self.causal:
            s_q, s_k = scores.shape[-2], scores.shape[-1]
            iq = jnp.arange(s_q)[:, None]
            ik = jnp.arange(s_k)[None, :]
            scores = jnp.where(iq >= ik - (s_k - s_q), scores, -1e9)
        if mask is not None:
            scores = scores + mask
        probs = jax.nn.softmax(scores, axis=-1)
        if self.dropout_keep < 1.0 and ctx.training:
            keep = jax.random.bernoulli(ctx.rng_for(self), self.dropout_keep,
                                        probs.shape)
            probs = jnp.where(keep, probs / self.dropout_keep, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v,
                          preferred_element_type=jnp.float32).astype(v.dtype)


def scaled_dot_product_attention_op(q, k, v, mask=None, causal=False,
                                    scale=None, dropout_keep=1.0, name=None):
    return ScaledDotProductAttentionOp(q, k, v, mask=mask, causal=causal,
                                       scale=scale, dropout_keep=dropout_keep,
                                       name=name)
