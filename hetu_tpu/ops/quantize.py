"""Quantization / compression ops.

Reference kernels: src/ops/Quantize.cu (DLGpuRoundingToInt /
DLGpuDequantize), src/ops/SignedQuantize.cu, src/ops/QuantizeEmbedding.cu
(embedding_prepack / quantized_embedding_lookup), src/ops/PruneMask.cu +
python/hetu/gpu_ops/Prune.py (PruneLowMagnitudeOp threshold search),
src/ops/OptEmbedBinaryStep.cu, and the ALPT LSQ rounding pair
(python/hetu/gpu_ops/QuantizeALPTEmb.py).

TPU redesign: quantized storage is a jnp integer array; rounding and
dequantize are jnp compositions XLA fuses into the surrounding graph.
Training-time quantizers are fake-quant functions with straight-through /
LSQ custom VJPs (the reference splits these into separate fwd/bwd kernels
wired by hand-written gradient() rules).  The reference's host-side binary
search for the prune threshold (Prune.py:28-45, 100 sync'd kernel launches)
becomes a single on-device quantile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .base import simple_op
from ..graph.node import Op


# ---------------------------------------------------------------------------
# plain (inference / storage) quantization — pure functions
# ---------------------------------------------------------------------------

def qinfo(digit, signed=False):
    """(dtype, qmin, qmax) for a bit width. digit ∈ {8, 16}."""
    if digit == 8:
        return (jnp.int8, -128, 127) if signed else (jnp.uint8, 0, 255)
    if digit == 16:
        return (jnp.int16, -(1 << 15), (1 << 15) - 1) if signed \
            else (jnp.uint16, 0, (1 << 16) - 1)
    raise ValueError(f"unsupported quantization width {digit}")


def _round(q, stochastic, key):
    if stochastic:
        if key is None:
            raise ValueError("stochastic rounding needs a PRNG key")
        return jnp.floor(q + jax.random.uniform(key, jnp.shape(q)))
    return jnp.round(q)


def rounding_to_int(x, scale, minele, digit, stochastic=False, key=None):
    """float → uint{8,16} codes: q = round((x - minele)/scale).

    Reference: rounding_kernel src/ops/Quantize.cu:6-20 (fixed_rounding /
    stochastic_rounding in gpu_functions.cuh).
    """
    dtype, qmin, qmax = qinfo(digit)
    q = _round((x - minele) / scale, stochastic, key)
    return jnp.clip(q, qmin, qmax).astype(dtype)


def dequantize(q, scale, minele):
    """uint codes → float: q*scale + minele (src/ops/Quantize.cu:64-72)."""
    return q.astype(jnp.float32) * scale + minele


def signed_quantize(x, scale, digit, stochastic=False, key=None):
    """Symmetric int{8,16} codes q = round(x/scale) (SignedQuantize.cu)."""
    dtype, qmin, qmax = qinfo(digit, signed=True)
    q = _round(x / scale, stochastic, key)
    return jnp.clip(q, qmin, qmax).astype(dtype)


def signed_dequantize(q, scale):
    return q.astype(jnp.float32) * scale


def quantized_embedding_lookup(qtable, ids, scale, minele):
    """Lookup rows of a uint-coded table and dequantize (reference
    unified_quantized_embedding_lookup, QuantizeEmbedding.cu)."""
    return dequantize(jnp.take(qtable, ids, axis=0), scale, minele)


def quantized_embedding_lookup_per_row(qtable, ids, qparams):
    """Per-row (scale, zero_point) variant: qparams is (rows, 2)
    (reference quantized_embedding_lookup + embedding_prepack)."""
    rows = jnp.take(qtable, ids, axis=0).astype(jnp.float32)
    sp = jnp.take(qparams, ids, axis=0)
    return rows * sp[..., :1] + sp[..., 1:2]


# ---------------------------------------------------------------------------
# training-time quantizers (custom VJPs)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fake_quantize(x, scale, digit, signed):
    """Quantize-dequantize with straight-through gradient (in-range pass,
    out-of-range zero).  Forward matches rounding_to_int∘dequantize."""
    _, qmin, qmax = qinfo(digit, signed)
    q = jnp.clip(jnp.round(x / scale), qmin, qmax)
    return q * scale


def _fq_fwd(x, scale, digit, signed):
    _, qmin, qmax = qinfo(digit, signed)
    r = x / scale
    in_range = (r >= qmin) & (r <= qmax)
    q = jnp.clip(jnp.round(r), qmin, qmax)
    return q * scale, in_range


def _fq_bwd(digit, signed, in_range, g):
    return (jnp.where(in_range, g, 0.0), None)


fake_quantize.defvjp(_fq_fwd, _fq_bwd)


_LSQ_EPS = 1e-9


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def lsq_round(x, scale, digit, signed):
    """LSQ (learned-step-size) quantize-dequantize.

    Reference: lsq_rounding / lsq_rounding_gradient kernels
    (src/ops/SignedQuantize.cu:251-312) used by ALPT
    (python/hetu/gpu_ops/QuantizeALPTEmb.py).  Gradient w.r.t. x is
    straight-through inside the clip range; gradient w.r.t. the (learnable)
    scale is (q - x/s) in range and the clip boundary outside — the LSQ rule.

    Unlike the reference (which leaves stabilization to its ALPT scheduler),
    the scale gradient carries the LSQ paper's 1/sqrt(N·Qp) normalization and
    the forward uses |s|+eps, so the op trains stably under a plain SGD/Adam
    step without a bespoke scale-update schedule.
    """
    _, qmin, qmax = qinfo(digit, signed)
    s = jnp.abs(scale) + _LSQ_EPS
    q = jnp.clip(jnp.round(x / s), qmin, qmax)
    return q * s


def _lsq_fwd(x, scale, digit, signed):
    _, qmin, qmax = qinfo(digit, signed)
    s = jnp.abs(scale) + _LSQ_EPS
    r = x / s
    q = jnp.clip(jnp.round(r), qmin, qmax)
    return q * s, (r, q, scale)


def _lsq_bwd(digit, signed, res, g):
    _, qmin, qmax = qinfo(digit, signed)
    r, q, scale = res
    scale_shape = jnp.shape(scale)
    gx = jnp.where((r >= qmin) & (r <= qmax), g, 0.0)
    # d(out)/d(s_eff) = q - r in range; qmin/qmax at the boundaries.
    ds_el = jnp.where(r <= qmin, float(qmin),
                      jnp.where(r >= qmax, float(qmax), q - r)) * g
    # LSQ grad scale: 1/sqrt(#elements-per-scale × Qp)
    n_per_scale = r.size / max(1, int(np.prod(scale_shape)) if scale_shape
                               else 1)
    gscale = 1.0 / np.sqrt(n_per_scale * max(qmax, 1))
    ds_el = ds_el * gscale
    # reduce to the scale's shape: broadcasting right-aligns, so pad the
    # scale shape with leading 1s against ds_el and sum the broadcast axes
    if scale_shape == ():
        gs = jnp.sum(ds_el)
    else:
        padded = (1,) * (ds_el.ndim - len(scale_shape)) + tuple(scale_shape)
        axes = tuple(i for i in range(ds_el.ndim) if padded[i] == 1)
        gs = jnp.sum(ds_el, axis=axes, keepdims=True).reshape(scale_shape)
    gs = gs * jnp.sign(scale)  # chain through s_eff = |s| + eps
    return (gx, gs)


lsq_round.defvjp(_lsq_fwd, _lsq_bwd)


@jax.custom_vjp
def binary_step(x):
    """1[x > 0] with the OptEmbed surrogate derivative
    (src/ops/OptEmbedBinaryStep.cu: 2-4|x| for |x|≤0.4, 0.4 for |x|≤1, 0)."""
    return (x > 0).astype(x.dtype)


def _bs_fwd(x):
    return binary_step(x), x


def _bs_bwd(x, g):
    a = jnp.abs(x)
    d = jnp.where(a > 1.0, 0.0, jnp.where(a > 0.4, 0.4, 2.0 - 4.0 * a))
    return (g * d,)


binary_step.defvjp(_bs_fwd, _bs_bwd)


# ---------------------------------------------------------------------------
# magnitude pruning
# ---------------------------------------------------------------------------

def prune_threshold(x, rate):
    """|x| value below which a `rate` fraction of entries fall.

    Replaces the reference's 100-iteration host/device binary search
    (Prune.py:28-45) with one on-device quantile.
    """
    return jnp.quantile(jnp.abs(x).reshape(-1), rate)


def prune_low_magnitude(x, rate):
    """Zero the lowest-magnitude `rate` fraction of x (DeepLight-style)."""
    thr = prune_threshold(x, rate)
    return jnp.where(jnp.abs(x) < thr, 0.0, x)


def prune_mask(x, rate):
    thr = prune_threshold(x, rate)
    return (jnp.abs(x) >= thr).astype(x.dtype)


# ---------------------------------------------------------------------------
# graph-node constructors
# ---------------------------------------------------------------------------

fake_quantize_op = simple_op(
    lambda x, s, digit=8, signed=True: fake_quantize(x, s, digit, signed),
    "fake_quantize")
lsq_round_op = simple_op(
    lambda x, s, digit=8, signed=True: lsq_round(x, s, digit, signed),
    "lsq_round")
binary_step_op = simple_op(lambda x: binary_step(x), "binary_step")
prune_low_magnitude_op = simple_op(
    lambda x, rate=0.0: prune_low_magnitude(x, rate), "prune_low_magnitude")
dequantize_op = simple_op(
    lambda q, scale=1.0, minele=0.0: dequantize(q, scale, minele),
    "dequantize")


class QuantizedEmbeddingLookupOp(Op):
    """Lookup into a uint-coded embedding table (unified scale/zero or
    per-row qparams).  Reference: QuantizeEmbedding.py
    UnifiedQuantizedEmbeddingLookUpOp / QuantizedEmbeddingLookUpOp."""

    __slots__ = ("op_kind",)

    def __init__(self, qtable, ids, qparams=None, scale=None, minele=None,
                 name=None):
        if qparams is None and (scale is None or minele is None):
            raise ValueError(
                "quantized_embedding_lookup: pass either per-row qparams or "
                "unified scale= and minele=")
        inputs = (qtable, ids) if qparams is None else (qtable, ids, qparams)
        super().__init__(*inputs, name=name, scale=scale, minele=minele)
        self.op_kind = "quantized_embedding_lookup"

    def _compute(self, input_vals, ctx):
        if len(input_vals) == 2:
            qtable, ids = input_vals
            return quantized_embedding_lookup(
                qtable, ids, self.attrs["scale"], self.attrs["minele"])
        qtable, ids, qparams = input_vals
        return quantized_embedding_lookup_per_row(qtable, ids, qparams)


def quantized_embedding_lookup_op(qtable, ids, qparams=None, scale=None,
                                  minele=None, name=None):
    return QuantizedEmbeddingLookupOp(qtable, ids, qparams=qparams,
                                      scale=scale, minele=minele, name=name)
