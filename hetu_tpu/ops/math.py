"""Elementwise math ops.

Parity with reference gpu_ops elementwise set (AddElewise, AddByConst,
MinusElewise, MultiplyElewise, Division, Opposite, Sqrt, ReciprocalSqrt, Exp,
Log, Pow, Abs, Sigmoid, Tanh, Relu, LeakyRelu, Gelu, Clamp, Sign, Floor,
Ceil, Minus/Minimum/Maximum, Where, Triu/Tril, Sin, Cos, Bool ops, ...) —
each a fused-by-XLA jnp expression rather than a CUDA kernel
(/root/reference/src/ops/*.cu).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import simple_op

add_op = simple_op(lambda a, b: a + b, "add")
sub_op = simple_op(lambda a, b: a - b, "minus")
mul_op = simple_op(lambda a, b: a * b, "multiply")
div_op = simple_op(lambda a, b: a / b, "divide")
_addbyconst = simple_op(lambda a, const=0.0: a + const, "add_byconst")
_mulbyconst = simple_op(lambda a, const=1.0: a * const, "mul_byconst")
_divconst = simple_op(lambda a, const=1.0: const / a, "div_const")


def addbyconst_op(node, const=0.0, name=None):
    return _addbyconst(node, const=const, name=name)


def mulbyconst_op(node, const=1.0, name=None):
    return _mulbyconst(node, const=const, name=name)


def div_const_op(const, node, name=None):
    return _divconst(node, const=const, name=name)
opposite_op = simple_op(lambda a: -a, "opposite")
sqrt_op = simple_op(jnp.sqrt, "sqrt")
rsqrt_op = simple_op(lambda a: jax.lax.rsqrt(a), "rsqrt")
exp_op = simple_op(jnp.exp, "exp")
log_op = simple_op(jnp.log, "log")
pow_op = simple_op(lambda a, exponent: jnp.power(a, exponent), "pow")
abs_op = simple_op(jnp.abs, "abs")
sign_op = simple_op(jnp.sign, "sign")
floor_op = simple_op(jnp.floor, "floor")
ceil_op = simple_op(jnp.ceil, "ceil")
sin_op = simple_op(jnp.sin, "sin")
cos_op = simple_op(jnp.cos, "cos")
tanh_op = simple_op(jnp.tanh, "tanh")
sigmoid_op = simple_op(jax.nn.sigmoid, "sigmoid")
relu_op = simple_op(jax.nn.relu, "relu")
leaky_relu_op = simple_op(
    lambda a, alpha=0.01: jax.nn.leaky_relu(a, negative_slope=alpha),
    "leaky_relu")
gelu_op = simple_op(lambda a, approximate=True: jax.nn.gelu(a, approximate=approximate),
                    "gelu")
silu_op = simple_op(jax.nn.silu, "silu")
softplus_op = simple_op(jax.nn.softplus, "softplus")
elu_op = simple_op(lambda a, alpha=1.0: jax.nn.elu(a, alpha=alpha), "elu")
reciprocal_op = simple_op(lambda a: 1.0 / a, "reciprocal")
clamp_op = simple_op(lambda a, min=None, max=None: jnp.clip(a, min, max),
                     "clamp")
minimum_op = simple_op(jnp.minimum, "minimum")
maximum_op = simple_op(jnp.maximum, "maximum")
fmod_op = simple_op(jnp.fmod, "fmod")
where_op = simple_op(lambda c, a, b: jnp.where(c, a, b), "where")
where_const_op = simple_op(lambda c, a, const: jnp.where(c, a, const),
                           "where_const")
triu_op = simple_op(lambda a, diagonal=0: jnp.triu(a, k=diagonal), "triu")
tril_op = simple_op(lambda a, diagonal=0: jnp.tril(a, k=diagonal), "tril")
tril_lookup_op = simple_op(
    lambda a, offset=0: jnp.tril(a, k=offset), "tril_lookup")
cumsum_op = simple_op(lambda a, dim=0: jnp.cumsum(a, axis=dim), "cumsum")

# comparison / bool
equal_op = simple_op(lambda a, b: (a == b).astype(a.dtype), "bool_eq")
not_equal_op = simple_op(lambda a, b: (a != b).astype(a.dtype), "bool_ne")
greater_op = simple_op(lambda a, b: (a > b).astype(a.dtype), "bool_gt")
less_op = simple_op(lambda a, b: (a < b).astype(a.dtype), "bool_lt")
greater_equal_op = simple_op(lambda a, b: (a >= b).astype(a.dtype), "bool_ge")
less_equal_op = simple_op(lambda a, b: (a <= b).astype(a.dtype), "bool_le")
bool_op = simple_op(lambda a: (a != 0).astype(a.dtype), "bool")
logical_not_op = simple_op(lambda a: (a == 0).astype(a.dtype), "logical_not")

ns_like_set_op = simple_op(
    lambda a, scalar=0.0: jnp.full_like(a, scalar), "full_like")
zeroslike_op = simple_op(jnp.zeros_like, "zeros_like")
oneslike_op = simple_op(jnp.ones_like, "ones_like")

cast_op = simple_op(lambda a, dtype=jnp.float32: a.astype(dtype), "cast")
# const^x elementwise (reference ConstPow.py)
const_pow_op = simple_op(
    lambda a, const=2.0: jnp.power(const, a), "const_pow")
