"""Embedding / sparse ops.

Reference kernels: src/ops/EmbeddingLookup.cu, SparseEmbeddingLookup.cu,
IndexedSlices.cu, ReduceIndexedSlice.cu (unique + segment-sum of duplicate
ids), UniqueIndices.cu, CuSparseCsrmm.cu, plus gpu_ops/EmbeddingLookUp.py's
IndexedSlices gradient path.

TPU design: lookup is a gather (XLA lowers to efficient dynamic-gather on
HBM); the gradient is gather's transpose — a scatter-add — which XLA keeps
sparse w.r.t. compute.  For optimizer-visible sparse updates (the reference's
IndexedSlices → sparse optimizer kernels), `reduce_indexedslices` implements
the unique+segment-sum dedup with a fixed-size unique buffer (static shapes
for jit).  PS-backed tables (ps/ subsystem) bypass the graph entirely, like
the reference's CacheSparseTable path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import simple_op


def _embedding_lookup(table, ids):
    return jnp.take(table, ids.astype(jnp.int32), axis=0)


embedding_lookup_op = simple_op(_embedding_lookup, "embedding_lookup")
sparse_embedding_lookup_op = embedding_lookup_op


def reduce_indexedslices(ids, values, num_unique):
    """Dedup ids by segment-summing values of equal ids.

    Returns (unique_ids_padded, summed_values) with static size
    ``num_unique`` (pad id = -1).  Mirrors ReduceIndexedSlice.cu (cub
    sort+unique) under XLA static-shape constraints.
    """
    ids = ids.reshape(-1).astype(jnp.int32)
    flat_vals = values.reshape(ids.shape[0], -1)
    uniq, inv = jnp.unique(ids, return_inverse=True, size=num_unique,
                           fill_value=-1)
    summed = jax.ops.segment_sum(flat_vals, inv.reshape(-1),
                                 num_segments=num_unique)
    return uniq, summed.reshape((num_unique,) + values.shape[len(ids.shape):])


def _scatter_add(table, ids, updates):
    ids = ids.reshape(-1).astype(jnp.int32)
    updates = updates.reshape(ids.shape[0], -1).astype(table.dtype)
    return table.at[ids].add(updates.reshape((ids.shape[0],)
                                             + table.shape[1:]))


scatter_add_op = simple_op(_scatter_add, "scatter_add")


def _csrmm(indptr, indices, data, dense, num_rows=None):
    """CSR × dense (reference CuSparseCsrmm.cu).  Represented via COO
    segment-sum; for TPU-friendly batched spmm use ops in models/gnn."""
    row = jnp.repeat(jnp.arange(num_rows), jnp.diff(indptr),
                     total_repeat_length=indices.shape[0])
    gathered = dense[indices.astype(jnp.int32)] * data[:, None]
    return jax.ops.segment_sum(gathered, row, num_segments=num_rows)


class IndexedSlices:
    """Sparse gradient value (indices + values + dense_shape).

    API parity with reference python/hetu/ndarray.py:680; used by the PS path
    and sparse optimizers.  ``deduplicate`` merges duplicate indices.
    """

    def __init__(self, indices, values, dense_shape):
        self.indices = indices
        self.values = values
        self.dense_shape = tuple(dense_shape)

    def deduplicate(self, num_unique=None):
        n = num_unique or int(self.indices.size)
        ids, vals = reduce_indexedslices(self.indices, self.values, n)
        return IndexedSlices(ids, vals, self.dense_shape)

    def to_dense(self):
        table = jnp.zeros(self.dense_shape, dtype=self.values.dtype)
        mask = (self.indices >= 0).reshape(-1, 1)
        vals = jnp.where(mask, self.values.reshape(mask.shape[0], -1), 0.0)
        safe_ids = jnp.maximum(self.indices.reshape(-1), 0)
        return table.at[safe_ids].add(
            vals.reshape((-1,) + self.dense_shape[1:]))


from ..graph.node import Op as _Op  # noqa: E402


class _PackedLookupOp(_Op):
    """Lookup from a PACKED [p_rows, 128] embedding table (see
    ops/pallas/sparse_densify.py — the TPU-native storage for narrow
    embedding dims whose vjp needs no XLA scatter).  The Pallas write
    kernel engages only off-mesh on TPU; the jnp fallback is
    numerically identical (CPU tests, sharded programs)."""

    def _compute(self, input_vals, ctx):
        from .pallas.sparse_densify import packed_lookup
        table, ids = input_vals
        use_pallas = ctx is None or ctx.mesh is None
        return packed_lookup(table, ids, self.attrs["dim"], use_pallas)


def packed_embedding_lookup_op(table, ids, dim, name=None):
    """Graph op: rows [..., dim] from a packed [p_rows, 128] table."""
    from .base import _peek_id
    return _PackedLookupOp(table, ids,
                           name=name or f"packed_lookup_{_peek_id()}",
                           dim=dim)
