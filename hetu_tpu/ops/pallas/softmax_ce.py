"""Fused sparse softmax-cross-entropy Pallas kernel.

Reference: src/ops/SoftmaxCrossEntropySparse.cu — one of the kernels the
reference fuses by hand and SURVEY §7's build plan names for Pallas
("softmax-CE").  The jnp composition is memory-bound AND hits an XLA
pathology for lane-unaligned vocab sizes (GPT-2's V=50257: 241 ms
fwd+bwd at [8192, V] on v5e vs 72 ms for V=50304); this kernel streams
the vocab once per pass with online logsumexp, handles any V by masking
the ragged tail chunk, and computes the backward from the saved lse
without materializing log-softmax.

  forward : grid (N/bn, V/bv); scratch (m, l, xt) carries the online
            max / sum-exp / target-logit across vocab chunks (TPU grids
            execute sequentially, so VMEM scratch persists along j);
            loss and lse write on the last chunk.
  backward: dlogits = (exp(x - lse) - onehot(label)) * g_row, streamed
            per chunk; rows with label == ignored_index emit zeros.

Per-row vectors (labels, loss, lse, cotangent, scratch) are (rows, 1)
sublane-major — row reductions of a (bn, bv) tile land there without
relayout, and broadcasts against the tile are natural.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BN = 256     # rows per program
_BV = 2048    # vocab lanes per chunk
_NEG = -1e30


def _interpret():
    return jax.default_backend() == "cpu"


def _fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref, m_sc, l_sc, xt_sc, *,
                v, bv, nv, ignored):
    j = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)                       # (bn, bv)
    lab = lab_ref[...]                                       # (bn, 1)
    col = j * bv + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = col < v
    s = jnp.where(valid, x, _NEG)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full(m_sc.shape, _NEG, jnp.float32)
        l_sc[...] = jnp.zeros(l_sc.shape, jnp.float32)
        xt_sc[...] = jnp.zeros(xt_sc.shape, jnp.float32)

    m = m_sc[...]                                            # (bn, 1)
    l = l_sc[...]
    m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
    l_new = l * jnp.exp(m - m_new) + jnp.sum(jnp.exp(s - m_new),
                                             axis=1, keepdims=True)
    m_sc[...] = m_new
    l_sc[...] = l_new
    hit = (col == lab) & valid
    xt_sc[...] = xt_sc[...] + jnp.sum(jnp.where(hit, x, 0.0),
                                      axis=1, keepdims=True)

    @pl.when(j == nv - 1)
    def _fin():
        lse = m_sc[...] + jnp.log(jnp.maximum(l_sc[...], 1e-37))
        loss = lse - xt_sc[...]
        loss_ref[...] = jnp.where(lab == ignored, 0.0, loss)
        lse_ref[...] = lse


def _bwd_kernel(x_ref, lab_ref, lse_ref, g_ref, dx_ref, *, v, bv, ignored):
    j = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)                       # (bn, bv)
    lab = lab_ref[...]                                       # (bn, 1)
    lse = lse_ref[...]
    g = g_ref[...]
    col = j * bv + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = col < v
    p = jnp.where(valid, jnp.exp(x - lse), 0.0)
    onehot = ((col == lab) & valid).astype(jnp.float32)
    d = (p - onehot) * g
    d = jnp.where(lab == ignored, 0.0, d)
    dx_ref[...] = d.astype(dx_ref.dtype)


def _pad_rows(n):
    return n if n % _BN == 0 else -(-n // _BN) * _BN


def _row_spec():
    return pl.BlockSpec((_BN, 1), lambda i, j: (i, 0))


def _fwd(logits, labels, ignored):
    n, v = logits.shape
    npad = _pad_rows(n)
    if npad != n:
        logits = jnp.pad(logits, ((0, npad - n), (0, 0)))
        labels = jnp.pad(labels, (0, npad - n), constant_values=ignored)
    nv = -(-v // _BV)
    kern = functools.partial(_fwd_kernel, v=v, bv=_BV, nv=nv,
                             ignored=ignored)
    loss, lse = pl.pallas_call(
        kern,
        interpret=_interpret(),
        grid=(npad // _BN, nv),
        in_specs=[
            pl.BlockSpec((_BN, _BV), lambda i, j: (i, j)),
            _row_spec(),
        ],
        out_specs=[_row_spec(), _row_spec()],
        out_shape=[
            jax.ShapeDtypeStruct((npad, 1), jnp.float32),
            jax.ShapeDtypeStruct((npad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_BN, 1), jnp.float32),
            pltpu.VMEM((_BN, 1), jnp.float32),
            pltpu.VMEM((_BN, 1), jnp.float32),
        ])(logits, labels.astype(jnp.int32).reshape(npad, 1))
    return loss[:n, 0], lse[:n, 0]


def _bwd(logits, labels, lse, g, ignored):
    n, v = logits.shape
    npad = _pad_rows(n)
    if npad != n:
        logits = jnp.pad(logits, ((0, npad - n), (0, 0)))
        labels = jnp.pad(labels, (0, npad - n), constant_values=ignored)
        lse = jnp.pad(lse, (0, npad - n))
        g = jnp.pad(g, (0, npad - n))
    nv = -(-v // _BV)
    kern = functools.partial(_bwd_kernel, v=v, bv=_BV, ignored=ignored)
    dx = pl.pallas_call(
        kern,
        interpret=_interpret(),
        grid=(npad // _BN, nv),
        in_specs=[
            pl.BlockSpec((_BN, _BV), lambda i, j: (i, j)),
            _row_spec(), _row_spec(), _row_spec(),
        ],
        out_specs=pl.BlockSpec((_BN, _BV), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((npad, v), logits.dtype),
    )(logits, labels.astype(jnp.int32).reshape(npad, 1),
      lse.reshape(npad, 1), g.reshape(npad, 1))
    return dx[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _ce(logits, labels, ignored):
    return _fwd(logits, labels, ignored)[0]


def _ce_fwd(logits, labels, ignored):
    loss, lse = _fwd(logits, labels, ignored)
    return loss, (logits, labels, lse)


def _ce_bwd(ignored, res, g):
    logits, labels, lse = res
    dx = _bwd(logits, labels, lse, g.astype(jnp.float32), ignored)
    return dx, None


_ce.defvjp(_ce_fwd, _ce_bwd)


def fused_softmax_ce_sparse(y, labels, ignored_index=-1):
    """Per-row CE losses (f32), any vocab size; returns None when the
    shape isn't worth the kernel so callers fall back to jnp."""
    if y.ndim < 2:
        return None
    v = y.shape[-1]
    n = int(np.prod(y.shape[:-1]))
    if v < 1024 or n < 8:
        return None
    out = _ce(y.reshape(n, v), labels.reshape(n), int(ignored_index))
    return out.reshape(y.shape[:-1])
