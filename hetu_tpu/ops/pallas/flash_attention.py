"""Pallas TPU flash attention (FlashAttention-2 style, fwd + bwd kernels).

The reference composes attention from batched matmuls + a full [B,H,S,S]
softmax (layers/attention.py) — O(S^2) HBM traffic, which OOMs BERT-base at
per-chip batch 64.  This kernel keeps the score tile in VMEM with online
softmax, so HBM traffic stays O(S·d):

  forward : grid (B*H, S/block_q); the kv loop runs inside the kernel with
            running (m, l, acc) carries; saves the logsumexp for backward.
  backward: two kernels — dQ over q blocks, dK/dV over kv blocks — that
            recompute P tiles from (Q, K, lse) instead of storing them
            (the standard flash backward: dS = P∘(dO·Vᵀ − D),
            D = rowsum(dO∘O)).
  dropout : applied to the probability tiles in-kernel with the TPU PRNG,
            reseeded per (seed, bh, q-block, kv-block) tile so the backward
            kernels replay the identical mask; l accumulates un-dropped
            sums so O = dropout(softmax(S))·V exactly.

Supported: additive key mask [B, 1, 1, S] (BERT padding masks), causal,
any head dim ≤ 512 and any seq ≥ 128: the wrapper zero-pads d to the
8-aligned [32, 512] kernel envelope and pads seq up to a block multiple
with -inf key-column masking, then slices the output (padding/slicing sit
OUTSIDE the custom_vjp, so jnp.pad's own VJP zeroes the padded rows'
cotangents and the gradients stay exact).  Returns None only for truly
unsupported cases (d > 512, short seqs where the O(S^2) composition is
cheaper, non-[B,1,1,S] masks) so callers fall back to the jnp composition
(ops/attention.py).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453

_BLOCK_Q = 512
_BLOCK_K = 512
_NEG_INF = -1e30


def _interpret():
    # CPU has no Mosaic backend; interpret mode keeps the kernels testable
    # on the virtual-device mesh (tests/conftest.py)
    return jax.default_backend() == "cpu"


def _supported(q, k, v, mask):
    if q.ndim != 4 or k.shape != q.shape or v.shape != q.shape:
        return False
    b, h, s, d = q.shape
    # head dim is always the FULL last block dim, so Mosaic only needs it
    # 8-aligned (the wrapper pads to that); > 512 would blow VMEM tiles
    if d > 512:
        return False
    # below one lane-tile of rows the O(S^2) composition is cheaper than
    # padding up to a kernel block
    if s < 128:
        return False
    if mask is not None and tuple(mask.shape) != (b, 1, 1, s):
        return False
    return True


def _pad_plan(s):
    """(padded_seq, block): pad seq to a block multiple and pick the block.

    512 tiles measured fastest on v5e at both BERT (B64·H12·S512·d64:
    9.9 ms vs 13.9 ms fwd+bwd with 256 tiles — beating XLA's S^2
    composition at 13.7 ms) and GPT-2.7B shapes (causal S2048·d80:
    64 ms vs 87 ms); smaller blocks only when the padded seq doesn't
    divide, keeping padding waste < one 128-row tile."""
    s_pad = s if s % 128 == 0 else -(-s // 128) * 128
    for block in (512, 256, 128):
        if s_pad % block == 0:
            return s_pad, block
    raise AssertionError(s_pad)


def _keep_threshold(keep_prob):
    # uint32 threshold: bits < threshold  <=>  keep (prob ~ keep_prob)
    return np.uint32(min(int(keep_prob * 4294967296.0), 4294967295))


def _tile_index(bh, qi, j, nq, nk):
    """Unique int32 per (batch*head, q-block, kv-block) tile — Mosaic's
    prng_seed accepts at most two scalars, so fold the coordinates."""
    return (bh * nq + qi) * nk + j


def _tile_keep(shape, seed_ref, tile, keep_prob):
    """The deterministic keep mask for one prob tile.  ALL kernels (fwd,
    dq, dkv) must obtain masks through this single helper — the backward
    replays the forward's masks purely by reseeding with the same tile
    index."""
    pltpu.prng_seed(seed_ref[0], tile)
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    return bits < _keep_threshold(keep_prob)


def _drop_tile(p, seed_ref, tile, keep_prob):
    keep = _tile_keep(p.shape, seed_ref, tile, keep_prob)
    return jnp.where(keep, p / keep_prob, 0.0)


# -- forward ---------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, seed_ref, offs_ref,
                o_ref, lse_ref, *, scale, causal, block_k, q_len, k_len,
                keep_prob, empty_lse_neg=False):
    """offs_ref (optional SMEM int32[2] = [q_off, k_off]): GLOBAL sequence
    offsets of the local q/k blocks — the ring-attention path attends a
    rotating remote K/V block, so causal masking compares global positions.
    ``empty_lse_neg``: blockwise callers need lse=-inf semantics for rows
    with no live key in THIS block (so the cross-block logaddexp combine
    ignores them); self-attention callers need +inf (see comment below)."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    # inputs stay in their storage dtype (bf16 models hit the MXU's
    # bf16 rate — pre-casting to f32 forced f32-rate matmuls, ~4x
    # slower); products/accumulation are f32 via preferred_element_type,
    # identical numerics on the input side (bf16->f32 casts are exact)
    q = q_ref[0]                                      # (bq, d)
    q_off = offs_ref[0] if offs_ref is not None else 0
    k_off = offs_ref[1] if offs_ref is not None else 0
    row = (q_off + qi * bq
           + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0))

    nk = k_len // block_k
    nk_causal = nk
    if causal:
        # kv blocks strictly above the diagonal contribute nothing; with
        # offsets the bound is dynamic (clamped below), without it's static
        hi = (q_off + (qi + 1) * bq - 1 - k_off) // block_k + 1
        nk_causal = jax.lax.clamp(0, hi, nk) if offs_ref is not None \
            else jax.lax.min(nk, hi)

    def make_body(masked):
        def body(j, carry):
            m, l, acc = carry
            kb = k_ref[0, pl.ds(j * block_k, block_k), :]
            vb = v_ref[0, pl.ds(j * block_k, block_k), :]
            # scores tracked in BASE-2 units (s2 = s * log2(e)): exp2 is
            # the VPU's native exponential; lse converts back to natural
            # units at the end so the backward's exp(s - lse) contract is
            # unchanged
            s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) \
                * (scale * _LOG2E)
            if mask_ref is not None:
                s = s + (mask_ref[0, 0,
                                  pl.ds(j * block_k, block_k)][None, :]
                         * _LOG2E)
            if causal and masked:
                col = (k_off + j * block_k
                       + jax.lax.broadcasted_iota(jnp.int32,
                                                  (bq, block_k), 1))
                s = jnp.where(row >= col, s, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1))
            p = jnp.exp2(s - m_new[:, None])
            alpha = jnp.exp2(m - m_new)
            # l accumulates UN-dropped sums: O = dropout(P_norm) @ V
            l_new = l * alpha + jnp.sum(p, axis=1)
            if keep_prob < 1.0:
                nq, nk_tot = q_len // bq, k_len // block_k
                p = _drop_tile(p, seed_ref,
                               _tile_index(bh, qi, j, nq, nk_tot),
                               keep_prob)
            acc_new = acc * alpha[:, None] + jax.lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new
        return body

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal and k_len // block_k > 8:
        # split loop: kv blocks fully below the diagonal need no mask —
        # the where+iota per tile is pure VPU overhead on ~(nk-1)/nk of
        # the causal work, alternating with the exp2 on the critical
        # path.  Only worth it when there are MANY kv blocks (long
        # context / ring shards); at nk <= ~8 the second loop's
        # bookkeeping outweighs the saved masking (measured +0.1
        # ms/layer on GPT-2.7B S=2048 with 512-blocks, -12% kernel time
        # at S=8192).
        lo = (q_off + qi * bq - k_off) // block_k
        n_full = (jax.lax.clamp(0, lo, nk) if offs_ref is not None
                  else jax.lax.max(0, jax.lax.min(nk, lo)))
        carry = jax.lax.fori_loop(0, n_full, make_body(False),
                                  (m0, l0, acc0))
        m, l, acc = jax.lax.fori_loop(n_full, nk_causal, make_body(True),
                                      carry)
    else:
        m, l, acc = jax.lax.fori_loop(0, nk_causal, make_body(True),
                                      (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    m = m * _LN2    # back to natural-log units for the stored lse
    # fully-masked rows (l == 0, every key at -inf): output is 0; store
    # lse = +large so the backward's p = exp(s - lse) underflows to 0 —
    # storing m (≈ -1e30) instead would give p = exp(0) = 1 everywhere
    # and garbage dq/dk/dv for the row.  Blockwise (ring) callers instead
    # want -large: their backward uses the COMBINED lse (never empty for a
    # causal row), and the fwd combine must treat this block as weightless.
    empty = _NEG_INF if empty_lse_neg else -_NEG_INF
    lse = jnp.where(l == 0.0, empty, m + jnp.log(l_safe))
    lse_ref[0, 0] = lse.astype(jnp.float32)


def _make_kern(base, has_mask, has_seed, n_out, has_offs=False, **consts):
    """Adapts a kernel with optional (mask_ref, seed_ref, offs_ref) slots
    to the positional ref list pallas_call passes."""

    def kern(*refs):
        n_in = len(refs) - n_out
        ins = list(refs[:n_in])
        outs = list(refs[n_in:])
        offs_ref = ins.pop() if has_offs else None
        seed_ref = ins.pop() if has_seed else None
        mask_ref = ins.pop() if has_mask else None
        base(*ins, mask_ref, seed_ref, offs_ref, *outs, **consts)

    return kern


def _fwd(q, k, v, mask, causal, scale, keep_prob=1.0, seed=None,
         block_q=_BLOCK_Q, block_k=_BLOCK_K, offsets=None,
         empty_lse_neg=False):
    """q: [b,h,sq,d]; k,v: [b,h,sk,d] (sq != sk in the blockwise/ring path,
    where ``offsets`` = int32[2] global [q_off, k_off])."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
    ]
    args = [qf, kf, vf]
    if mask is not None:
        in_specs.append(pl.BlockSpec(
            (1, 1, sk), lambda bh, i, h=h: (bh // h, 0, 0)))
        args.append(mask.reshape(b, 1, sk).astype(jnp.float32))
    if keep_prob < 1.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed.reshape(1).astype(jnp.int32))
    if offsets is not None:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(offsets)
    kern = _make_kern(_fwd_kernel, mask is not None, keep_prob < 1.0, 2,
                      has_offs=offsets is not None,
                      scale=scale, causal=causal, block_k=block_k,
                      q_len=sq, k_len=sk, keep_prob=keep_prob,
                      empty_lse_neg=empty_lse_neg)
    o, lse = pl.pallas_call(
        kern,
        interpret=_interpret(),
        grid=(b * h, sq // block_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, i: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32),
        ])(*args)
    return o.reshape(b, h, sq, d), lse


# -- backward --------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref, mask_ref,
                   seed_ref, offs_ref, dq_ref, *, scale, causal, block_k,
                   q_len, k_len, keep_prob):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0]
    dsum = dsum_ref[0, 0]
    q_off = offs_ref[0] if offs_ref is not None else 0
    k_off = offs_ref[1] if offs_ref is not None else 0
    row = (q_off + qi * bq
           + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0))

    def body(j, acc):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, pl.ds(j * block_k, block_k), :]
        # base-2 scores (exp2 = native VPU exponential; p identical)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * (scale * _LOG2E)
        if mask_ref is not None:
            s = s + (mask_ref[0, 0, pl.ds(j * block_k, block_k)][None, :]
                     * _LOG2E)
        if causal:
            col = (k_off + j * block_k
                   + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1))
            s = jnp.where(row >= col, s, _NEG_INF)
        p = jnp.exp2(s - (lse * _LOG2E)[:, None])
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if keep_prob < 1.0:  # replay the fwd tile mask on dP
            nq, nk_tot = q_len // bq, k_len // block_k
            dp = _drop_tile(dp, seed_ref,
                            _tile_index(bh, qi, j, nq, nk_tot), keep_prob)
        ds = p * (dp - dsum[:, None])
        return acc + jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    acc0 = jnp.zeros((bq, d), jnp.float32)
    nk = k_len // block_k
    if causal:
        # above-diagonal kv tiles are fully masked (p == 0): skip them
        hi = (q_off + (qi + 1) * bq - 1 - k_off) // block_k + 1
        nk = jax.lax.clamp(0, hi, nk) if offs_ref is not None \
            else jax.lax.min(nk, hi)
    acc = jax.lax.fori_loop(0, nk, body, acc0)
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref, mask_ref,
                    seed_ref, offs_ref, dk_ref, dv_ref, *, scale, causal,
                    block_q, q_len, k_len, keep_prob):
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    bk = k_ref.shape[1]
    d = k_ref.shape[2]
    k = k_ref[0]
    v = v_ref[0]
    q_off = offs_ref[0] if offs_ref is not None else 0
    k_off = offs_ref[1] if offs_ref is not None else 0
    col = (k_off + ki * bk
           + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1))
    mblk = (mask_ref[0, 0, pl.ds(ki * bk, bk)][None, :]
            if mask_ref is not None else None)

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :]
        dob = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)]
        dsum = dsum_ref[0, 0, pl.ds(i * block_q, block_q)]
        s = jax.lax.dot_general(qb, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * (scale * _LOG2E)
        if mblk is not None:
            s = s + mblk * _LOG2E
        if causal:
            rr = (q_off + i * block_q
                  + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0))
            s = jnp.where(rr >= col, s, _NEG_INF)
        p = jnp.exp2(s - (lse * _LOG2E)[:, None])
        if keep_prob < 1.0:
            # fwd seeded by tile (bh, q-block=i, kv-block=ki)
            nq, nk_tot = q_len // block_q, k_len // bk
            keep = _tile_keep(p.shape, seed_ref,
                              _tile_index(bh, i, ki, nq, nk_tot),
                              keep_prob)
            p_dropped = jnp.where(keep, p / keep_prob, 0.0)
        else:
            keep = None
            p_dropped = p
        dv_new = dv + jax.lax.dot_general(
            p_dropped.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dob, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if keep is not None:
            dp = jnp.where(keep, dp / keep_prob, 0.0)
        ds = p * (dp - dsum[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    i_start = 0
    if causal:
        # q tiles strictly above the diagonal see none of this kv block;
        # with offsets the bound is dynamic (global positions)
        lo = (k_off + ki * bk - q_off) // block_q
        i_start = jax.lax.clamp(0, lo, q_len // block_q) \
            if offs_ref is not None else lo
    dk, dv = jax.lax.fori_loop(i_start, q_len // block_q, body,
                               (dk0, dv0))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_impl(q, k, v, mask, o, lse, dout, causal, scale, keep_prob, seed,
              block_q=_BLOCK_Q, block_k=_BLOCK_K, offsets=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qf = q.reshape(b * h, sq, d)
    kf, vf = (t.reshape(b * h, sk, d) for t in (k, v))
    dof = dout.reshape(b * h, sq, d)
    dsum = jnp.sum(dof.astype(jnp.float32)
                   * o.reshape(b * h, sq, d).astype(jnp.float32),
                   axis=-1)[:, None, :]                      # (BH, 1, Sq)
    args = [qf, kf, vf, dof, lse, dsum]
    base_specs = [
        pl.BlockSpec((1, sq, d), lambda bh, i: (bh, 0, 0)),  # q (full)
        pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),  # k
        pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),  # v
        pl.BlockSpec((1, sq, d), lambda bh, i: (bh, 0, 0)),  # do
        pl.BlockSpec((1, 1, sq), lambda bh, i: (bh, 0, 0)),  # lse
        pl.BlockSpec((1, 1, sq), lambda bh, i: (bh, 0, 0)),  # dsum
    ]
    extra_args, extra_specs = [], []
    if mask is not None:
        extra_args.append(mask.reshape(b, 1, sk).astype(jnp.float32))
        extra_specs.append(pl.BlockSpec(
            (1, 1, sk), lambda bh, i, h=h: (bh // h, 0, 0)))
    if keep_prob < 1.0:
        extra_args.append(seed.reshape(1).astype(jnp.int32))
        extra_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    if offsets is not None:
        extra_args.append(offsets)
        extra_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))

    dq_specs = list(base_specs)
    dq_specs[0] = pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0))
    dq_specs[3] = pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0))
    dq_specs[4] = pl.BlockSpec((1, 1, block_q), lambda bh, i: (bh, 0, i))
    dq_specs[5] = pl.BlockSpec((1, 1, block_q), lambda bh, i: (bh, 0, i))

    dq_kern = _make_kern(_bwd_dq_kernel, mask is not None, keep_prob < 1.0,
                         1, has_offs=offsets is not None,
                         scale=scale, causal=causal, block_k=block_k,
                         q_len=sq, k_len=sk, keep_prob=keep_prob)
    dq = pl.pallas_call(
        dq_kern, interpret=_interpret(), grid=(b * h, sq // block_q),
        in_specs=dq_specs + extra_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
    )(*args, *extra_args)

    dkv_specs = list(base_specs)
    dkv_specs[1] = pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, 0))
    dkv_specs[2] = pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, 0))
    dkv_kern = _make_kern(_bwd_dkv_kernel, mask is not None,
                          keep_prob < 1.0, 2, has_offs=offsets is not None,
                          scale=scale, causal=causal,
                          block_q=block_q, q_len=sq, k_len=sk,
                          keep_prob=keep_prob)
    dk, dv = pl.pallas_call(
        dkv_kern, interpret=_interpret(), grid=(b * h, sk // block_k),
        in_specs=dkv_specs + extra_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ])(*args, *extra_args)

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# -- custom-vjp wrappers ---------------------------------------------------
# two variants (with/without mask) keep the signatures positional; the
# dropout seed is a traced uint32 tensor with zero cotangent.

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_nomask(q, k, v, seed, causal, scale, keep_prob, block):
    return _fwd(q, k, v, None, causal, scale, keep_prob, seed,
                block_q=block, block_k=block)[0]


def _flash_nomask_fwd(q, k, v, seed, causal, scale, keep_prob, block):
    o, lse = _fwd(q, k, v, None, causal, scale, keep_prob, seed,
                  block_q=block, block_k=block)
    return o, (q, k, v, seed, o, lse)


def _flash_nomask_bwd(causal, scale, keep_prob, block, res, g):
    q, k, v, seed, o, lse = res
    dq, dk, dv = _bwd_impl(q, k, v, None, o, lse, g, causal, scale,
                           keep_prob, seed, block_q=block, block_k=block)
    return dq, dk, dv, jnp.zeros_like(seed)


_flash_nomask.defvjp(_flash_nomask_fwd, _flash_nomask_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_mask(q, k, v, mask, seed, causal, scale, keep_prob, block):
    return _fwd(q, k, v, mask, causal, scale, keep_prob, seed,
                block_q=block, block_k=block)[0]


def _flash_mask_fwd(q, k, v, mask, seed, causal, scale, keep_prob, block):
    o, lse = _fwd(q, k, v, mask, causal, scale, keep_prob, seed,
                  block_q=block, block_k=block)
    return o, (q, k, v, mask, seed, o, lse)


def _flash_mask_bwd(causal, scale, keep_prob, block, res, g):
    q, k, v, mask, seed, o, lse = res
    dq, dk, dv = _bwd_impl(q, k, v, mask, o, lse, g, causal, scale,
                           keep_prob, seed, block_q=block, block_k=block)
    # The additive mask is treated as NON-differentiable data (our graphs
    # build it from placeholder attention masks).  A learned attention bias
    # must use the jnp fallback path, which differentiates the bias.
    return dq, dk, dv, jnp.zeros_like(mask), jnp.zeros_like(seed)


_flash_mask.defvjp(_flash_mask_fwd, _flash_mask_bwd)


# -- blockwise API (ring / context parallelism) ----------------------------
# One (Q-local, K/V-block) pair with GLOBAL sequence offsets: the ring
# schedule (parallel/context_parallel.py) rotates K/V blocks around the
# ICI ring and combines per-block results with logaddexp.  No reference
# counterpart (SURVEY §5: the reference has no ring attention); the
# blockwise math follows the flash-attention decomposition.

def _block_sizes(sq, sk):
    bq = next((b for b in (512, 256, 128) if sq % b == 0), None)
    bk = next((b for b in (512, 256, 128) if sk % b == 0), None)
    return bq, bk


def blockwise_supported(q_shape, k_shape):
    b, h, sq, d = q_shape
    sk = k_shape[2]
    bq, bk = _block_sizes(sq, sk)
    return (d <= 512 and d % 8 == 0 and d >= 32
            and bq is not None and bk is not None)


def flash_attention_block(q, k, v, q_off, k_off, *, causal=True,
                          scale=None):
    """Fused attention of local q [B,H,Sq,D] against ONE K/V block
    [B,H,Sk,D] at global offsets (q_off, k_off) — returns
    (o_normalized [B,H,Sq,D], lse [B,H,Sq]) where rows with no live key in
    this block get lse = -1e30 (weightless under the logaddexp combine)."""
    b, h, sq, d = q.shape
    bq, bk = _block_sizes(sq, k.shape[2])
    offsets = jnp.stack([q_off, k_off]).astype(jnp.int32)
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    o, lse = _fwd(q, k, v, None, causal, float(scale), 1.0,
                  jnp.zeros((1,), jnp.int32), block_q=bq, block_k=bk,
                  offsets=offsets, empty_lse_neg=True)
    return o, lse.reshape(b, h, sq)


def flash_attention_block_bwd(q, k, v, o, lse, dout, q_off, k_off, *,
                              causal=True, scale=None):
    """Gradients of one ring step given the COMBINED (o, lse) of the full
    ring forward: p = exp(s - lse_final) is each block's true global
    attention weight, so dq sums over blocks and (dk, dv) are per-block
    exact.  lse: [B,H,Sq]."""
    b, h, sq, d = q.shape
    bq, bk = _block_sizes(sq, k.shape[2])
    offsets = jnp.stack([q_off, k_off]).astype(jnp.int32)
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    return _bwd_impl(q, k, v, None, o, lse.reshape(b * h, 1, sq), dout,
                     causal, float(scale), 1.0,
                     jnp.zeros((1,), jnp.int32), block_q=bq, block_k=bk,
                     offsets=offsets)


def flash_attention(q, k, v, mask=None, causal=False, scale=None,
                    dropout_keep=1.0, seed=None):
    """Fused attention; returns None when shapes are unsupported so the
    caller falls back to the jnp composition (ops/attention.py).

    ``dropout_keep`` < 1 applies attention-prob dropout in-kernel (TPU
    PRNG); ``seed`` must then be an int32/uint32 scalar array.
    """
    if not _supported(q, k, v, mask):
        return None
    if dropout_keep < 1.0 and _interpret():
        return None  # TPU PRNG primitives only under Mosaic
    if dropout_keep < 1.0 and seed is None:
        raise ValueError(
            "flash_attention: dropout_keep < 1 requires seed= (an int32 "
            "scalar array; the per-tile dropout masks derive from it)")
    b, h, s, d = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    if dropout_keep >= 1.0:
        seed = jnp.zeros((1,), jnp.int32)

    # pad into the kernel envelope; padding/slicing live OUTSIDE the
    # custom_vjp so jnp.pad's VJP zero-fills the padded rows' cotangents
    # and the gradients of the real region stay exact
    d_pad = max(32, -(-d // 8) * 8)
    s_pad, block = _pad_plan(s)
    if d_pad != d or s_pad != s:
        pad3 = ((0, 0), (0, 0), (0, s_pad - s), (0, d_pad - d))
        q, k, v = (jnp.pad(t, pad3) for t in (q, k, v))
        if s_pad != s and not (causal and mask is None):
            # padded key columns must not attend; real causal rows never
            # see columns ≥ s, so pure-causal needs no mask
            base = (mask if mask is not None
                    else jnp.zeros((b, 1, 1, s), jnp.float32))
            mask = jnp.pad(base, ((0, 0), (0, 0), (0, 0), (0, s_pad - s)),
                           constant_values=_NEG_INF)

    if mask is None:
        out = _flash_nomask(q, k, v, seed, causal, float(scale),
                            float(dropout_keep), block)
    else:
        out = _flash_mask(q, k, v, mask, seed, causal, float(scale),
                          float(dropout_keep), block)
    if d_pad != d or s_pad != s:
        out = out[:, :, :s, :d]
    return out
