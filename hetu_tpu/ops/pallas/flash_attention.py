"""Pallas flash attention (TPU).  Placeholder fallback until the kernel
lands: returning None makes callers take the jnp path."""


def flash_attention(q, k, v, mask=None, causal=False, scale=None):
    return None
