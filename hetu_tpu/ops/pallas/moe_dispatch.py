"""MoE layout-transform (token dispatch/combine) via a Pallas row gather.

Reference: src/ops/LayoutTransform.cu / ReverseLayoutTransform.cu — CUDA
kernels moving each token's row into its (expert, capacity-slot) and back.
The dense TPU formulation (einsum against one-hot [T, E, C] dispatch
tensors, ops/moe.py) is MXU-friendly but materializes O(T·E·C) memory —
the exact wall LayoutTransform.cu exists to avoid (SURVEY §2.1 N3 lists
this kernel).

TPU redesign: both directions are ROW GATHERS once the routing is known —
  dispatch: expert_in[slot]  = tokens[slot_to_token[slot]]
  combine:  out[t]          += gate_c[t] * expert_out[token_to_slot_c[t]]
so one Pallas kernel serves both.  The gather uses
PrefetchScalarGridSpec: the index vector is prefetched to SMEM and the
BlockSpec index_map selects source row idx[i] for grid step i, so the
pipeline DMAs exactly the rows needed — no one-hot, no [T, E, C]
anywhere.  XLA's own gather lowering on TPU can fall back to one-hot
matmul for small row counts, which would reintroduce the memory wall;
the Pallas kernel makes the row-copy lowering deterministic.

Out-of-range indices (capacity-dropped tokens, empty slots) yield zero
rows, matching the dense path's zero dispatch rows.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp


def _supported(src_shape, dtype):
    if jax.default_backend() != "tpu":
        return False
    n, h = src_shape
    if h % 128 != 0 or h > 16384:
        return False
    return dtype in (jnp.float32, jnp.bfloat16, np.float32)


def _make_kernel():
    import jax.experimental.pallas as pl

    def kernel(n_rows, idx_ref, src_ref, out_ref):
        i = pl.program_id(0)
        j = idx_ref[i]
        # the index_map already clamped the DMA'd block; here we zero
        # rows whose logical index was out of range on EITHER side (the
        # contract — and the jnp fallback — zero-fill both)
        valid = (j >= 0) & (j < n_rows)
        out_ref[...] = jnp.where(valid, src_ref[...],
                                 jnp.zeros_like(src_ref))
    return kernel


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def row_gather(src, idx, use_pallas=True):
    """out[i] = src[idx[i]] for 0 <= idx[i] < src.shape[0], else zeros.

    Falls back to a jnp take when the Pallas envelope doesn't apply
    (CPU tests, ragged hidden sizes) or when ``use_pallas`` is False —
    callers inside GSPMD-sharded programs must pass False, since
    pallas_call does not partition."""
    return _row_gather_fwd_impl(src, idx, use_pallas)


def _row_gather_fwd_impl(src, idx, use_pallas=True):
    n, h = src.shape
    m = idx.shape[0]
    if not use_pallas or not _supported(src.shape, src.dtype):
        # jnp.take wraps NEGATIVE indices numpy-style; remap them to an
        # out-of-bounds sentinel so they fill with zeros like the kernel
        safe = jnp.where(idx >= 0, idx, n)
        return jnp.take(src, safe, axis=0, mode="fill", fill_value=0)
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m,),
        in_specs=[pl.BlockSpec(
            (1, h), lambda i, idx_ref: (jnp.clip(idx_ref[i], 0, n - 1), 0))],
        out_specs=pl.BlockSpec((1, h), lambda i, idx_ref: (i, 0)),
    )
    import functools as _ft
    return pl.pallas_call(
        _ft.partial(_make_kernel(), n),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, h), src.dtype),
    )(idx.astype(jnp.int32), src)


def _row_gather_fwd(src, idx, use_pallas):
    return _row_gather_fwd_impl(src, idx, use_pallas), (idx, src.shape[0])


def _row_gather_bwd(use_pallas, res, ct):
    idx, n = res
    # scatter-add of cotangent rows back to their sources; indices are
    # unique in the MoE use (capacity queue guarantees one token per
    # slot), but add is correct regardless
    valid = (idx >= 0) & (idx < n)
    safe = jnp.clip(idx, 0, n - 1)
    ct = jnp.where(valid[:, None], ct, 0)
    d_src = jnp.zeros((n, ct.shape[1]), ct.dtype).at[safe].add(ct)
    return d_src, None


row_gather.defvjp(_row_gather_fwd, _row_gather_bwd)
