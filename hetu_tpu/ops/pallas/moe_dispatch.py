"""MoE layout-transform (token dispatch/combine) via a Pallas row gather.

Reference: src/ops/LayoutTransform.cu / ReverseLayoutTransform.cu — CUDA
kernels moving each token's row into its (expert, capacity-slot) and back.
The dense TPU formulation (einsum against one-hot [T, E, C] dispatch
tensors, ops/moe.py) is MXU-friendly but materializes O(T·E·C) memory —
the exact wall LayoutTransform.cu exists to avoid (SURVEY §2.1 N3 lists
this kernel).

TPU redesign: both directions are ROW GATHERS once the routing is known —
  dispatch: expert_in[slot]  = tokens[slot_to_token[slot]]
  combine:  out[t]          += gate_c[t] * expert_out[token_to_slot_c[t]]
so one Pallas kernel serves both.  The source table stays in HBM
(`pl.ANY` memory space) and the index vector is scalar-prefetched to
SMEM; each grid step DMAs its 8 arbitrary source rows into a VMEM
scratch (8 parallel `make_async_copy`s) and writes the masked block out
— exactly the rows needed move, no one-hot, no [T, E, C] anywhere.
(A BlockSpec index_map gather with (1, H) blocks is rejected by Mosaic:
the sublane dim of a block must be divisible by 8, and one index_map
can't pick 8 unrelated rows — hence the explicit-DMA form.)  XLA's own
gather lowering on TPU can fall back to one-hot matmul for small row
counts, which would reintroduce the memory wall; the Pallas kernel
makes the row-copy lowering deterministic.

Out-of-range indices (capacity-dropped tokens, empty slots) yield zero
rows, matching the dense path's zero dispatch rows.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp


def _supported(src_shape, dtype):
    if jax.default_backend() != "tpu":
        return False
    n, h = src_shape
    if h % 128 != 0 or h > 16384:
        return False
    return dtype in (jnp.float32, jnp.bfloat16, np.float32)


_BLK = 8  # output rows per grid step = the TPU sublane quantum


def _make_kernel():
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(n_rows, idx_ref, src_hbm, out_ref, scratch, sems):
        # scratch is [_BLK, 1, h]: the DMA'd dim must sit OUTSIDE the
        # (8, 128)-tiled trailing pair — a 1-row slice of a 2-D VMEM
        # buffer is not a legal DMA target ("slice along dimension 0
        # must be aligned to tiling (8)")
        b = pl.program_id(0)
        copies = []
        for k in range(_BLK):
            j = idx_ref[b * _BLK + k]
            jc = jnp.clip(j, 0, n_rows - 1)
            # src arrives as [n, 1, h] so the gathered dim is untiled on
            # the source side too (ANY may resolve to VMEM for small
            # tables, where a 1-row slice of a tiled dim is illegal)
            c = pltpu.make_async_copy(src_hbm.at[jc],
                                      scratch.at[k],
                                      sems.at[k])
            c.start()
            copies.append(c)
        for c in copies:
            c.wait()
        # zero rows whose logical index was out of range (the contract —
        # and the jnp fallback — zero-fill both sides)
        idxs = jnp.stack([idx_ref[b * _BLK + k] for k in range(_BLK)])
        # expand the minor dim while still i32 (Mosaic rejects the
        # equivalent reshape on an i1 vector), then compare
        idxs2 = idxs[:, None]
        valid = (idxs2 >= 0) & (idxs2 < n_rows)
        out_ref[...] = jnp.where(valid, scratch[:, 0, :],
                                 jnp.zeros((_BLK, scratch.shape[2]),
                                           scratch.dtype))
    return kernel


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def row_gather(src, idx, use_pallas=True):
    """out[i] = src[idx[i]] for 0 <= idx[i] < src.shape[0], else zeros.

    Falls back to a jnp take when the Pallas envelope doesn't apply
    (CPU tests, ragged hidden sizes) or when ``use_pallas`` is False —
    callers inside GSPMD-sharded programs must pass False, since
    pallas_call does not partition."""
    return _row_gather_fwd_impl(src, idx, use_pallas)


def _row_gather_fwd_impl(src, idx, use_pallas=True):
    n, h = src.shape
    m = idx.shape[0]
    if not use_pallas or not _supported(src.shape, src.dtype):
        # jnp.take wraps NEGATIVE indices numpy-style; remap them to an
        # out-of-bounds sentinel so they fill with zeros like the kernel
        safe = jnp.where(idx >= 0, idx, n)
        return jnp.take(src, safe, axis=0, mode="fill", fill_value=0)
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # pad the index vector to a whole number of 8-row blocks; the pad
    # rows carry the invalid sentinel and come out zero
    m_pad = (m + _BLK - 1) // _BLK * _BLK
    idx_p = jnp.full((m_pad,), -1, jnp.int32).at[:m].set(
        idx.astype(jnp.int32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m_pad // _BLK,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((_BLK, h), lambda b, idx_ref: (b, 0)),
        scratch_shapes=[pltpu.VMEM((_BLK, 1, h), src.dtype),
                        pltpu.SemaphoreType.DMA((_BLK,))],
    )
    import functools as _ft
    out = pl.pallas_call(
        _ft.partial(_make_kernel(), n),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_pad, h), src.dtype),
    )(idx_p, src[:, None, :])
    return out[:m] if m_pad != m else out


def _row_gather_fwd(src, idx, use_pallas):
    return _row_gather_fwd_impl(src, idx, use_pallas), (idx, src.shape[0])


def _row_gather_bwd(use_pallas, res, ct):
    idx, n = res
    # scatter-add of cotangent rows back to their sources; indices are
    # unique in the MoE use (capacity queue guarantees one token per
    # slot), but add is correct regardless
    valid = (idx >= 0) & (idx < n)
    safe = jnp.clip(idx, 0, n - 1)
    ct = jnp.where(valid[:, None], ct, 0)
    d_src = jnp.zeros((n, ct.shape[1]), ct.dtype).at[safe].add(ct)
    return d_src, None


row_gather.defvjp(_row_gather_fwd, _row_gather_bwd)
