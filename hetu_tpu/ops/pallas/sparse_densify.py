"""Packed embedding tables: scatter-free gradients via a Pallas
row-write DMA kernel.

Reference: src/ops/EmbeddingLookup.cu + IndexedSlices.cu /
OptimizersSparse.cu — the reference's CUDA kernels for embedding
lookup and sparse-gradient application.  On TPU the dense-Adam path
over a CTR embedding table is bottlenecked by XLA's scatter lowering
for the gather-transpose: latency-bound serialized row updates at small
tables (194 us for the W&D bench's 3,328 rows of a 337k x 16 table —
59% of the step) that degrade into FULL-TABLE passes at larger ones
(~390 us/table at 2M rows), and the two-output fusion it anchors splits
the Adam update into two passes over the table.

TPU-native redesign — pack the table to the 128-lane quantum:

- storage is ``[num_rows/q, 128]`` with ``q = 128/dim`` logical rows per
  lane-line (dim 16 -> 8 rows/line).  Elementwise optimizer math is
  shape-agnostic, so Adam/SGD run unchanged — and on the packed shape
  XLA emits the single-pass multi-output fusion (164 us vs 294 us at
  W&D shapes);
- ``packed_lookup`` gathers whole lane-lines and extracts the target
  row by a fused masked select-sum (no strided 16-byte accesses, and a
  non-finite co-resident row cannot leak through a 0·NaN product —
  serving's watchdog containment depends on that);
- its vjp positions each gradient row inside its lane-line, merges
  duplicates with a sort + cumsum difference (NOT segment_sum, whose
  XLA lowering is the very scatter being replaced), and DMAs each
  unique line into a zero-initialized packed gradient with the
  ``pack_write`` kernel (64 write-DMAs in flight: 44 us vs 194 us
  measured, and table-size-independent).

Unique pack ids make the write-only kernel race-free (no two in-flight
DMAs share a target line); invalid lanes (padding / merged duplicates)
are skipped under ``pl.when``.

pallas_call does not partition under GSPMD, so callers inside a
sharded program have two options: pass ``use_pallas=False`` (the jnp
fallback is numerically identical), or call
:func:`sharded_packed_lookup`, which wraps the lookup in the
``platform.shard_map`` shim — the id batch splits over a mesh axis,
the packed table rides replicated into every shard, and each device
runs the SAME kernel on its local slice.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

_BLK = 64      # row-writes in flight per grid step


def pack_factor(dim):
    """Rows per 128-lane line, or 0 when the dim doesn't pack."""
    if dim <= 128 and 128 % dim == 0:
        return 128 // dim
    return 0


def packed_rows(num_rows, dim):
    """Lines needed to hold ``num_rows`` logical rows (last line may be
    partially used; lookups never see the padding)."""
    q = pack_factor(dim)
    return (num_rows + q - 1) // q


def _kernel_supported(dtype):
    return (jax.default_backend() == "tpu"
            and dtype in (jnp.float32, np.float32))


def _make_kernel():
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(idx_ref, upd_vmem, zeros_hbm, out_hbm, sems):
        b = pl.program_id(0)
        started = []
        for k in range(_BLK):
            j = idx_ref[b * _BLK + k]

            def start(k=k, j=j):
                pltpu.make_async_copy(upd_vmem.at[k], out_hbm.at[j],
                                      sems.at[k]).start()

            def wait(k=k, j=j):
                pltpu.make_async_copy(upd_vmem.at[k], out_hbm.at[j],
                                      sems.at[k]).wait()

            pl.when(j >= 0)(start)
            started.append((j, wait))
        for j, wait in started:
            pl.when(j >= 0)(wait)
    return kernel


def _merge_duplicate_lines(pack, rows):
    """Sort by pack id and merge duplicate lines with a cumsum
    difference at each segment's last element.  Returns (pack_ids[M]
    int32 with -1 on merged/invalid slots, lines[M,128] with segment
    totals at the surviving slots)."""
    m = pack.shape[0]
    order = jnp.argsort(pack)
    pack_s = pack[order]
    rows_s = rows[order]
    csum = jnp.cumsum(rows_s, axis=0)
    neq = pack_s[1:] != pack_s[:-1]
    first = jnp.concatenate([jnp.ones((1,), bool), neq])
    last = jnp.concatenate([neq, jnp.ones((1,), bool)])
    start = jax.lax.cummax(jnp.where(first, jnp.arange(m), -1))
    prev = jnp.take(csum, jnp.maximum(start - 1, 0), axis=0)
    totals = jnp.where((start > 0)[:, None], csum - prev, csum)
    packs_u = jnp.where(last & (pack_s >= 0), pack_s, -1)
    return (packs_u.astype(jnp.int32),
            jnp.where(last[:, None], totals, 0.0))


def pack_write(pack_ids, lines, p_rows, use_pallas=True):
    """Write-only densify: out[pack_ids[i]] = lines[i] summed over
    duplicates (negative ids ignored), everything else zero.  Shapes:
    pack_ids [M] int, lines [M, 128] -> [p_rows, 128]."""
    pack_ids = pack_ids.reshape(-1).astype(jnp.int32)
    m = pack_ids.shape[0]
    lines = lines.reshape(m, 128)
    if not use_pallas or not _kernel_supported(lines.dtype):
        safe = jnp.where(pack_ids >= 0, pack_ids, p_rows)
        z = jnp.zeros((p_rows + 1, 128), lines.dtype)
        return z.at[safe].add(lines)[:p_rows]
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m_pad = (m + _BLK - 1) // _BLK * _BLK
    if m_pad != m:
        pack_ids = jnp.concatenate(
            [pack_ids, jnp.full((m_pad - m,), -1, jnp.int32)])
        lines = jnp.concatenate(
            [lines, jnp.zeros((m_pad - m, 128), lines.dtype)])
    packs_u, merged = _merge_duplicate_lines(pack_ids, lines)
    zeros = jnp.zeros((p_rows, 1, 128), lines.dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m_pad // _BLK,),
        in_specs=[pl.BlockSpec((_BLK, 1, 128), lambda b, i: (b, 0, 0)),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((_BLK,))],
    )
    out = pl.pallas_call(
        _make_kernel(),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((p_rows, 1, 128), lines.dtype),
        # alias the zero fill straight into the output: XLA's broadcast
        # provides it and the kernel only touches written lines
        input_output_aliases={2: 0},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(packs_u, merged.reshape(m_pad, 1, 128), zeros)
    return out.reshape(p_rows, 128)


def _position_lines(ids, g, q, dim):
    """Place each [dim] gradient row at its lane offset inside a
    [128] line.  Expressed as tile+mask so XLA keeps it one elementwise
    fusion over [M, 128] — the broadcast-multiply/einsum forms lower
    through a materialized transpose (~56 us at W&D shapes)."""
    off = jnp.where(ids >= 0, ids % q, 0)
    tiled = jnp.concatenate([g] * q, axis=1)                   # [M, 128]
    lane_slot = (jnp.arange(q * dim, dtype=jnp.int32) // dim)  # [128]
    mask = lane_slot[None, :] == off[:, None].astype(jnp.int32)
    return jnp.where(mask, tiled, 0.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def packed_lookup(table, ids, dim, use_pallas=True):
    """Row lookup from a PACKED [p_rows, 128] table: returns
    [..., dim] rows for integer ``ids`` (shape-preserving like
    jnp.take).  The vjp produces the packed dense gradient through
    ``pack_write`` — no XLA scatter anywhere."""
    q = 128 // dim
    flat = ids.reshape(-1).astype(jnp.int32)
    # negative (padding) ids clamp to logical row 0, matching the
    # unpacked embedding_lookup/IndexedSlices path — without the clamp,
    # flat // q clips to line 0 but flat % q picks slot q-1, gathering
    # an arbitrary row (ADVICE r5).  The vjp drops negatives either way.
    safe = jnp.maximum(flat, 0)
    lines = jnp.take(table, safe // q, axis=0)                 # [M, 128]
    # masked select-sum, NOT a one-hot multiply-sum: 0 * NaN = NaN, so
    # the multiply form let one non-finite row poison every row sharing
    # its lane-line (the serving watchdog's per-request containment
    # depends on a poisoned row flagging only itself).  Bitwise
    # identical for finite rows — same summation order, x + 0 terms —
    # and the same single elementwise+reduce fusion.
    mask = (safe % q)[:, None] == jnp.arange(q, dtype=jnp.int32)
    rows = jnp.sum(jnp.where(mask[:, :, None],
                             lines.reshape(-1, q, dim), 0.0), axis=1)
    return rows.reshape(ids.shape + (dim,))


def _packed_lookup_fwd(table, ids, dim, use_pallas):
    return packed_lookup(table, ids, dim, use_pallas), \
        (ids, table.shape[0])


def _packed_lookup_bwd(dim, use_pallas, res, g):
    ids, p_rows = res
    q = 128 // dim
    flat = ids.reshape(-1).astype(jnp.int32)
    lines = _position_lines(flat, g.reshape(-1, dim), q, dim)
    grad = pack_write(flat // q, lines, p_rows, use_pallas=use_pallas)
    return grad, np.zeros(ids.shape, jax.dtypes.float0)


packed_lookup.defvjp(_packed_lookup_fwd, _packed_lookup_bwd)


def sharded_packed_lookup(mesh, table, ids, dim, axis="model",
                          use_pallas=True):
    """:func:`packed_lookup` inside a GSPMD mesh program.

    ``pallas_call`` does not partition, so the lookup runs under the
    platform ``shard_map`` shim: the packed ``[p_rows, 128]`` table is
    replicated into every shard, the id batch's LEADING dim splits over
    mesh axis ``axis`` (it must divide the axis size), and each device
    runs the identical kernel — or the bitwise-equal jnp fallback off
    TPU — on its local slice.  Returns ``[..., dim]`` rows sharded the
    same way as ``ids``.  This is the inference/scoring path (the
    embedding server's lookups); training gradients keep flowing
    through the unsharded ``packed_lookup`` vjp."""
    from jax.sharding import PartitionSpec as P
    from ...platform import shard_map

    n_shards = int(mesh.shape[axis])
    if ids.shape[0] % n_shards:
        raise ValueError(
            f"ids leading dim {ids.shape[0]} must divide mesh axis "
            f"{axis!r} (size {n_shards})")

    def local(tbl, local_ids):
        return packed_lookup(tbl, local_ids, dim, use_pallas)

    spec = P(axis) if ids.ndim == 1 else P(*((axis,) + (None,) *
                                             (ids.ndim - 1)))
    out_spec = P(*(tuple(spec) + (None,)))
    f = shard_map(local, mesh=mesh, in_specs=(P(), spec),
                  out_specs=out_spec)
    return f(table, ids)


def pack_table(table, dim=None):
    """[num_rows, dim] -> packed [p_rows, 128] (host or device),
    zero-padding the tail line."""
    n, d = table.shape
    q = pack_factor(d)
    assert q, f"dim {d} does not pack into 128 lanes"
    p = packed_rows(n, d)
    pad = p * q - n
    if pad:
        table = jnp.concatenate(
            [jnp.asarray(table),
             jnp.zeros((pad, d), jnp.asarray(table).dtype)])
    return jnp.asarray(table).reshape(p, 128)


def unpack_table(packed, num_rows, dim):
    """Packed [p_rows, 128] -> [num_rows, dim]."""
    q = pack_factor(dim)
    return packed.reshape(-1, dim)[:num_rows]
