"""Rotary position embeddings (RoPE), ALiBi biases, and GQA head repeat.

The reference's Llama family applies rotary embeddings inside its
flash-attn mixer (tools/Hetu-Galvatron/galvatron/models/llama/
LlamaModel_sequential.py:14 imports rotary_pos_embedding) and its
Baichuan-13B family uses ALiBi biases (models/baichuan/).  Here RoPE is a
pure pre-transform on q/k — the cos/sin tables are built from static
shapes, so XLA constant-folds them once per compile and fuses the rotation
into the surrounding projection matmuls; flash attention then runs
unchanged on the rotated tensors.

Conventions match huggingface's ``rotate_half`` (non-interleaved halves),
so HF Llama checkpoints import bit-tight (tests/test_torch_parity.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import simple_op


def _rope_tables(seq_len, dim, theta, pos_offset=0):
    # always f32 tables: bf16 positions past ~256 lose the low rotation
    # frequencies entirely
    pos = jnp.arange(pos_offset, pos_offset + seq_len, dtype=jnp.float32)
    inv = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    freqs = jnp.outer(pos, inv)                       # [S, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)    # [S, D]
    return jnp.cos(emb), jnp.sin(emb)


def _rotary(x, *, theta=10000.0, pos_offset=0):
    """Apply RoPE to [B, H, S, D] (HF rotate_half convention)."""
    d, s = x.shape[-1], x.shape[-2]
    cos, sin = _rope_tables(s, d, theta, pos_offset)
    cos = cos[None, None, :, :]
    sin = sin[None, None, :, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (xf * cos + rotated * sin).astype(x.dtype)


rotary_embedding_op = simple_op(_rotary, "rotary_embedding")


def _repeat_kv(x, *, n_rep):
    """[B, KV, S, D] -> [B, KV*n_rep, S, D] for grouped-query attention.

    Broadcast + reshape (not jnp.repeat): XLA lowers it to a view-like
    broadcast that fuses into the attention einsum instead of
    materializing the repeated K/V in HBM.
    """
    if n_rep == 1:
        return x
    b, kv, s, d = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, :], (b, kv, n_rep, s, d))
    return x.reshape(b, kv * n_rep, s, d)


repeat_kv_op = simple_op(_repeat_kv, "repeat_kv")


def alibi_slopes(num_heads):
    """Per-head ALiBi slopes (Press et al., the published closed form)."""
    import math

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(num_heads).is_integer():
        return pow2_slopes(num_heads)
    closest = 2 ** math.floor(math.log2(num_heads))
    extra = pow2_slopes(2 * closest)[0::2][: num_heads - closest]
    return pow2_slopes(closest) + extra


def _alibi_bias(q, *, num_heads):
    """Additive [1, H, S, S] ALiBi bias from a [B, H, S, D] query.

    Only the linear -slope*(i-j) term; the causal cut is the attention
    op's ``causal`` flag (reference Baichuan builds both into one mask).
    """
    s = q.shape[-2]
    slopes = jnp.asarray(alibi_slopes(num_heads), dtype=jnp.float32)
    rel = jnp.arange(s, dtype=jnp.float32)[None, :] \
        - jnp.arange(s, dtype=jnp.float32)[:, None]   # j - i  (<= 0 past)
    bias = slopes[:, None, None] * rel[None, :, :]    # [H, S, S]
    return bias[None].astype(q.dtype)


alibi_bias_op = simple_op(_alibi_bias, "alibi_bias")
