"""Shared per-block quantize/dequantize codec for the serving plane.

ONE arithmetic core for all three quantized-transport legs (ISSUE 16):
int8/fp8 paged KV pages (``serving/kv_cache.py``), the block-quantized
PS wire codec (``ps/rpc.py``), and quantized TP all-gathers
(``models/_decode_common.make_gather``).  Keeping every
narrow-dtype cast in this module is load-bearing: the round-trip error
bounds in ``tests/test_quant.py`` are proved against THIS code, and the
AST gate there fails any ad-hoc ``astype(int8)``/bitcast elsewhere in
the package — inline quantization drifting out of the error-bound tests
is exactly the bug class the gate exists to catch.

Scheme: symmetric per-block absmax scaling along the LAST axis.  A
block of ``block`` consecutive elements shares one float32 scale
``absmax / QMAX[dtype]``; codes are ``x / scale`` rounded into the
target dtype's representable range.  Zero blocks emit scale 0 and codes
0, so dequantization reproduces exact zeros (freshly allocated KV pages
stay bitwise-zero through a round trip).  EQuARX (PAPERS.md) uses the
same block-scaled layout for quantized collectives; per-block rather
than per-tensor scales are what keep one outlier row from wiping out
the mantissa budget of every other row in a KV page.

Every function is generic over the array namespace: pass numpy arrays
for host/wire paths (the PS server quantizes replies without touching
jax) and jax arrays for in-graph paths (KV gather/scatter, TP gathers).
``int8`` works everywhere; ``fp8`` (e4m3) needs dtype support from the
platform — gate with :func:`fp8_supported` / ``platform.fp8_dtype()``.
"""

from __future__ import annotations

import numpy as np

#: largest representable magnitude per codec dtype: int8 is symmetric
#: [-127, 127] (-128 unused so negation round-trips), fp8 e4m3 saturates
#: at +-448
QMAX = {"int8": 127.0, "fp8": 448.0}

#: codec dtypes whose codes are themselves floats (scaled, not rounded
#: to integers)
_FLOAT_CODES = ("fp8",)


def fp8_supported():
    """True when this environment can represent fp8 e4m3 codes."""
    return _fp8_np_dtype() is not None


def _fp8_np_dtype():
    """The numpy-compatible float8_e4m3fn dtype, or None.  jax >= 0.4
    re-exports the ml_dtypes definition, so one lookup covers both the
    numpy and the jax.numpy paths."""
    try:
        import ml_dtypes
        return np.dtype(ml_dtypes.float8_e4m3fn)
    except (ImportError, AttributeError):
        from .. import platform
        dt = platform.fp8_dtype()
        return None if dt is None else np.dtype(dt)


def code_dtype(dtype):
    """The storage dtype of ``dtype``'s codes (np.dtype)."""
    if dtype == "int8":
        return np.dtype(np.int8)
    if dtype == "fp8":
        dt = _fp8_np_dtype()
        if dt is None:
            raise ValueError(
                "fp8 codes are unavailable: neither ml_dtypes nor this "
                "jax build defines float8_e4m3fn (use kv_dtype='int8')")
        return dt
    raise ValueError(f"unknown quantization dtype {dtype!r}; "
                     f"expected one of {sorted(QMAX)}")


def code_bytes_per_element(dtype):
    """Storage bytes per quantized element (both codecs are 1 today,
    but the ledger/bench math must not hard-code that)."""
    return int(code_dtype(dtype).itemsize)


def _namespace(x):
    """numpy for host arrays, jax.numpy for everything else (tracers
    included) — imported lazily so the wire path never pulls in jax."""
    if isinstance(x, (np.ndarray, np.generic)):
        return np
    import jax.numpy as jnp
    return jnp


def quantize_blocks(x, block=None, dtype="int8"):
    """Quantize ``x`` along its last axis in blocks of ``block``.

    Returns ``(codes, scales)``: ``codes`` has ``x``'s shape in the
    codec storage dtype; ``scales`` is float32 with shape
    ``x.shape[:-1] + (x.shape[-1] // block,)`` — one scale per block.
    ``block=None`` means one block spanning the whole last axis
    (``scales`` ends in a broadcast-ready trailing 1, the paged-KV
    layout).  ``block`` must divide the last axis exactly: transport
    blocking is a layout decision made where shapes are known, not
    something this core pads silently."""
    xp = _namespace(x)
    d = int(x.shape[-1])
    block = d if block is None else int(block)
    if block < 1 or d % block:
        raise ValueError(
            f"block={block} must divide the last axis ({d}) exactly")
    qmax = QMAX[dtype]          # raises KeyError-shaped below if bad
    cdt = code_dtype(dtype)
    nblocks = d // block
    blocked = xp.reshape(xp.asarray(x, np.float32),
                         x.shape[:-1] + (nblocks, block))
    absmax = xp.max(xp.abs(blocked), axis=-1, keepdims=True)
    # zero blocks: emit scale 0 (dequant reproduces exact zeros) but
    # divide by 1 so the codes stay finite
    safe = xp.where(absmax > 0, absmax / qmax, xp.float32(1.0))
    scaled = blocked / safe
    if dtype in _FLOAT_CODES:
        codes = scaled.astype(cdt)
    else:
        codes = xp.clip(xp.rint(scaled), -qmax, qmax).astype(cdt)
    scales = xp.where(absmax > 0, absmax / qmax, xp.float32(0.0))
    return (xp.reshape(codes, x.shape),
            xp.reshape(scales, x.shape[:-1] + (nblocks,))
              .astype(np.float32))


def dequantize_blocks(codes, scales):
    """Invert :func:`quantize_blocks`: ``codes`` in any codec storage
    dtype times the per-block ``scales`` back to float32, in ``codes``'s
    shape.  Block size is recovered from the shapes, so call sites never
    thread it separately (and can't get it wrong)."""
    xp = _namespace(codes)
    d, nblocks = int(codes.shape[-1]), int(scales.shape[-1])
    if nblocks < 1 or d % nblocks:
        raise ValueError(
            f"scales last axis ({nblocks}) must divide codes last "
            f"axis ({d})")
    block = d // nblocks
    blocked = xp.reshape(codes.astype(np.float32),
                         codes.shape[:-1] + (nblocks, block))
    out = blocked * xp.reshape(scales, scales.shape + (1,)).astype(
        np.float32)
    return xp.reshape(out, codes.shape)


def roundtrip_bound(dtype, absmax=1.0, block=None):
    """Worst-case absolute round-trip error for one block whose largest
    magnitude is ``absmax``: half a quantization step for int8's
    round-to-nearest, one e4m3 ulp-at-absmax (2^-3 relative) for fp8.
    ``block`` is accepted for signature symmetry — the bound depends on
    the block's absmax, not its width."""
    del block
    if dtype == "int8":
        return float(absmax) / QMAX["int8"] * 0.5
    if dtype == "fp8":
        return float(absmax) * 2.0 ** -3
    raise ValueError(f"unknown quantization dtype {dtype!r}")
