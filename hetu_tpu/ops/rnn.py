"""Recurrent ops: Elman RNN and LSTM as single scan ops.

Reference: examples/cnn/models/RNN.py / LSTM.py build the recurrence by
UNROLLING graph ops per timestep (28 slice/concat/matmul nodes for MNIST).
On TPU that defeats the compiler — the idiomatic form is ONE op whose
``_compute`` runs `lax.scan` over time: XLA sees a fori-style loop with a
fused cell body, autodiff scans backward for free, and sequence length is
static only in the scan bound (no per-step graph blowup).

Gate packing follows torch.nn.LSTM ([i, f, g, o] rows of w_ih/w_hh) so
weights transfer 1:1 (pinned by tests/test_models.py torch parity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..graph.node import Op


class RNNOp(Op):
    """Elman RNN over x [N, T, D]: h_t = tanh(x_t @ w_x + h_{t-1} @ w_h + b).

    Returns the full hidden sequence [N, T, H] (slice the last step for a
    classifier head).
    """

    def _compute(self, input_vals, ctx):
        x, w_x, w_h, b = input_vals

        def cell(h, x_t):
            h = jnp.tanh(x_t @ w_x + h @ w_h + b)
            return h, h

        n = x.shape[0]
        h0 = jnp.zeros((n, w_h.shape[0]), x.dtype)
        _, hs = lax.scan(cell, h0, jnp.swapaxes(x, 0, 1))
        return jnp.swapaxes(hs, 0, 1)


def rnn_op(x, w_x, w_h, b, name=None):
    return RNNOp(x, w_x, w_h, b, name=name)


class LSTMOp(Op):
    """LSTM over x [N, T, D] with torch-packed gates.

    w_ih: [4H, D], w_hh: [4H, H], b_ih/b_hh: [4H] in [i, f, g, o] order
    (exactly torch.nn.LSTM's layout).  Returns hidden sequence [N, T, H].
    """

    def _compute(self, input_vals, ctx):
        x, w_ih, w_hh, b_ih, b_hh = input_vals
        hdim = w_hh.shape[1]

        def cell(carry, x_t):
            h, c = carry
            z = x_t @ w_ih.T + h @ w_hh.T + b_ih + b_hh       # [N, 4H]
            i, f, g, o = (z[:, :hdim], z[:, hdim:2 * hdim],
                          z[:, 2 * hdim:3 * hdim], z[:, 3 * hdim:])
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h, c), h

        n = x.shape[0]
        h0 = jnp.zeros((n, hdim), x.dtype)
        (_, _), hs = lax.scan(cell, (h0, h0), jnp.swapaxes(x, 0, 1))
        return jnp.swapaxes(hs, 0, 1)


def lstm_op(x, w_ih, w_hh, b_ih, b_hh, name=None):
    return LSTMOp(x, w_ih, w_hh, b_ih, b_hh, name=name)
