"""Dense linear algebra ops — the MXU workhorses.

Reference kernels: src/ops/MatrixMult.cu, BatchMatrixMult.cu, Linear.cu,
Addmm.cu, Baddbmm.cu, MatrixDot.cu, Transpose.cu, Outer.cu (cublas calls).
On TPU these all lower to MXU matmuls via lax.dot_general; bf16 inputs with
f32 accumulation is the default precision policy (preferred_element_type).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .base import simple_op


def _mm(a, b, trans_A=False, trans_B=False):
    if trans_A:
        a = a.T
    if trans_B:
        b = b.T
    return jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)


def _bmm(a, b, trans_A=False, trans_B=False):
    if trans_A:
        a = jnp.swapaxes(a, -1, -2)
    if trans_B:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)


def _head_split_linear(x, w, bias=None, seq_len=None, n_heads=None,
                       head_dim=None):
    """x [B, S, E] (or [B*S, E]) @ w [E, n_heads*head_dim] emitted
    directly as [B, heads, S, d]: the head transpose rides the matmul
    epilogue instead of materializing a copy of the projected tensor
    (attention layers' q/k/v path)."""
    e = x.shape[-1]
    x3 = x.reshape(-1, seq_len, e)
    w4 = w.reshape(e, n_heads, head_dim)
    out = jnp.einsum("bse,ehd->bhsd", x3, w4,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    if bias is not None:
        out = out + bias.reshape(1, n_heads, 1, head_dim).astype(out.dtype)
    return out


matmul_op = simple_op(_mm, "matmul")
head_split_linear_op = simple_op(_head_split_linear, "head_split_linear")
batch_matmul_op = simple_op(_bmm, "batch_matmul")
linear_op = simple_op(
    lambda x, w, bias, trans_A=False, trans_B=False:
        _mm(x, w, trans_A, trans_B) + bias,
    "linear")
addmm_op = simple_op(
    lambda inp, a, b, alpha=1.0, beta=1.0: beta * inp + alpha * _mm(a, b),
    "addmm")
baddbmm_op = simple_op(
    lambda inp, a, b, alpha=1.0, beta=1.0: beta * inp + alpha * _bmm(a, b),
    "baddbmm")
matrix_dot_op = simple_op(lambda a, b: jnp.sum(a * b), "matrix_dot")
outer_op = simple_op(lambda a, b: jnp.outer(a, b), "outer")
dot_op = simple_op(lambda a, b: jnp.dot(a, b), "dot")
transpose_op = simple_op(
    lambda a, perm=None: jnp.transpose(a, axes=perm), "transpose")
norm_op = simple_op(
    lambda a, axis=None, p=2: jnp.linalg.norm(a, ord=p, axis=axis), "norm")
