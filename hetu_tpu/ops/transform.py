"""Shape/layout transforms (reference: Reshape.cu, BroadcastTo/BroadcastShape,
Concat/Concatenate.cu, Split/Slice.cu, Pad.cu, OneHot.cu, Gather.cu, Tile,
Repeat.cu, Roll.cu, Flip? (no), Interpolate.cu, MaskedFill.cu, Arange,
SliceAssign, DynamicStitch-style ops)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import simple_op

array_reshape_op = simple_op(
    lambda a, output_shape=None: jnp.reshape(a, output_shape), "array_reshape")
reshape_op = array_reshape_op
flatten_op = simple_op(lambda a: jnp.reshape(a, (a.shape[0], -1)), "flatten")
broadcastto_op = simple_op(
    lambda a, b: jnp.broadcast_to(a, b.shape), "broadcastto")
broadcast_shape_op = simple_op(
    lambda a, shape=None, add_axes=None:
        jnp.broadcast_to(
            jnp.expand_dims(a, tuple(add_axes)) if add_axes else a, shape),
    "broadcast_shape")
concat_op = simple_op(
    lambda a, b, axis=0: jnp.concatenate([a, b], axis=axis), "concat")


def concatenate_op(nodes, axis=0, name=None):
    from .base import SimpleOp
    return SimpleOp(
        lambda *vals, axis=0: jnp.concatenate(vals, axis=axis),
        "concatenate", *nodes, name=name, axis=axis)


def _slice(a, begin_pos=None, output_shape=None):
    # size -1 = "to the end of the dim" (reference Slice.cu semantics,
    # e.g. examples/rec/models/neumf.py slices with [-1, -1, -1])
    idx = tuple(slice(b, d if s == -1 else b + s)
                for b, s, d in zip(begin_pos, output_shape, a.shape))
    return a[idx]


slice_op = simple_op(_slice, "slice")


def _split(a, axes=None, indices=None, splits=None):
    """Take the ``indices``-th of ``splits`` chunks along ``axes``
    (reference Split.cu semantics used for model parallelism)."""
    if isinstance(axes, int):
        axes, indices, splits = [axes], [indices], [splits]
    for ax, ind, spl in zip(axes, indices, splits):
        size = a.shape[ax] // spl
        a = jax.lax.slice_in_dim(a, ind * size, (ind + 1) * size, axis=ax)
    return a


split_op = simple_op(_split, "split")
pad_op = simple_op(
    lambda a, paddings=None, mode="constant", constant_values=0:
        jnp.pad(a, paddings, mode=mode, constant_values=constant_values)
        if mode == "constant" else jnp.pad(a, paddings, mode=mode),
    "pad")
one_hot_op = simple_op(
    lambda a, num_classes=None: jax.nn.one_hot(a.astype(jnp.int32),
                                               num_classes, dtype=jnp.float32),
    "one_hot")
gather_op = simple_op(
    lambda a, idx, dim=0: jnp.take_along_axis(
        a, idx.astype(jnp.int32), axis=dim),
    "gather")
tile_op = simple_op(lambda a, reps=None: jnp.tile(a, reps), "tile")
repeat_op = simple_op(
    lambda a, repeats=None, dim=None: jnp.repeat(a, repeats, axis=dim),
    "repeat")
roll_op = simple_op(
    lambda a, shift=None, axis=None: jnp.roll(a, shift, axis=axis), "roll")
expand_dims_op = simple_op(
    lambda a, axis=0: jnp.expand_dims(a, axis), "expand_dims")
unsqueeze_op = expand_dims_op
squeeze_op = simple_op(lambda a, axis=None: jnp.squeeze(a, axis), "squeeze")
masked_fill_op = simple_op(
    lambda a, mask, val=0.0: jnp.where(mask != 0, val, a), "masked_fill")
interpolate_op = simple_op(
    lambda a, scale_factor=2, mode="bilinear": jax.image.resize(
        a, (a.shape[0], a.shape[1],
            int(a.shape[2] * scale_factor), int(a.shape[3] * scale_factor)),
        method="bilinear" if mode == "bilinear" else "nearest"),
    "interpolate")
slice_assign_op = simple_op(
    lambda a, b, begin_pos=None: jax.lax.dynamic_update_slice(
        a, b, tuple(begin_pos)),
    "slice_assign")


def _slice_by_matrix(a, idx0, idx1):
    return a[idx0.astype(jnp.int32), idx1.astype(jnp.int32)]


slice_by_matrix_op = simple_op(_slice_by_matrix, "slice_by_matrix")
argsort_op = simple_op(
    lambda a, dim=-1, descending=False:
        jnp.argsort(a, axis=dim, descending=descending),
    "argsort")


def _sparse_set(table, ids, values):
    """table[ids] = values (reference SparseSet.py / gpu sparse_set)."""
    ids = ids.reshape(-1).astype(jnp.int32)
    vals = values.reshape((ids.shape[0],) + table.shape[1:])
    return table.at[ids].set(vals.astype(table.dtype))


sparse_set_op = simple_op(_sparse_set, "sparse_set")


def _unique(a, size=None, fill_value=-1):
    """Static-size unique (reference UniqueIndices.cu); pads with
    fill_value.  `size` is required under jit (static shapes)."""
    if size is None:
        raise ValueError("unique_op requires size= (static output length)")
    return jnp.unique(a.reshape(-1), size=size, fill_value=fill_value)


unique_op = simple_op(_unique, "unique")
# source ops (no tensor inputs; reference Arange.py, Full.py)
arange_op = simple_op(
    lambda start=0, stop=None, step=1, dtype=jnp.float32:
        jnp.arange(start, stop, step, dtype=dtype),
    "arange")
full_op = simple_op(
    lambda shape=None, fill_value=0.0, dtype=jnp.float32:
        jnp.full(shape, fill_value, dtype=dtype),
    "full")
# reshape a to b's shape (reference gpu_ops/Reshape.py reshape_to_op)
reshape_to_op = simple_op(lambda a, b: jnp.reshape(a, b.shape), "reshape_to")
stop_gradient_op = simple_op(jax.lax.stop_gradient, "stop_gradient")


def _argmax_partial(a, mask, topk=None, dim=-1):
    """Argmax over ``dim``, restricted to the first ``topk`` entries where
    ``mask`` (broadcast over leading dims) is 0 (reference ArgmaxPartial.cu:
    low-frequency rows only see the first ``topk`` codewords)."""
    if topk is None:
        raise ValueError("argmax_partial requires topk= (the restricted "
                         "range for mask==0 rows)")
    dim = dim % a.ndim
    idx = jnp.arange(a.shape[dim])
    idx = idx.reshape((1,) * dim + (-1,) + (1,) * (a.ndim - dim - 1))
    mask = mask.reshape(mask.shape + (1,) * (a.ndim - mask.ndim))
    allowed = (mask != 0) | (idx < topk)
    neg = jnp.finfo(a.dtype).min
    return jnp.argmax(jnp.where(allowed, a, neg), axis=dim)


argmax_partial_op = simple_op(_argmax_partial, "argmax_partial")
