from .base import SimpleOp, simple_op
from .math import *          # noqa: F401,F403
from .linalg import *        # noqa: F401,F403
from .reduce import *        # noqa: F401,F403
from .transform import *     # noqa: F401,F403
from .nn import *            # noqa: F401,F403
from .losses import *        # noqa: F401,F403
from .embedding import (embedding_lookup_op, sparse_embedding_lookup_op,
                        scatter_add_op, reduce_indexedslices, IndexedSlices)
from .moe import (top_k_gating, hash_gating, layout_transform_op,
                  reverse_layout_transform_op, topk_idx_op, topk_val_op,
                  scatter1d_op, balance_assignment, sam_group_sum)
from .attention import scaled_dot_product_attention_op
from .rotary import (rotary_embedding_op, repeat_kv_op, alibi_bias_op,
                     alibi_slopes)
from .quantize import (rounding_to_int, dequantize, signed_quantize,
                       signed_dequantize, quantized_embedding_lookup,
                       quantized_embedding_lookup_per_row, fake_quantize,
                       lsq_round, binary_step, prune_low_magnitude,
                       prune_mask, prune_threshold, fake_quantize_op,
                       lsq_round_op, binary_step_op, prune_low_magnitude_op,
                       dequantize_op, quantized_embedding_lookup_op)
