from .base import SimpleOp, simple_op
from .math import *          # noqa: F401,F403
from .linalg import *        # noqa: F401,F403
from .reduce import *        # noqa: F401,F403
from .transform import *     # noqa: F401,F403
from .nn import *            # noqa: F401,F403
from .losses import *        # noqa: F401,F403
from .embedding import (embedding_lookup_op, sparse_embedding_lookup_op,
                        scatter_add_op, reduce_indexedslices, IndexedSlices)
from .moe import (top_k_gating, hash_gating, layout_transform_op,
                  reverse_layout_transform_op, topk_idx_op, topk_val_op,
                  scatter1d_op, balance_assignment, sam_group_sum)
from .attention import scaled_dot_product_attention_op
