"""NN ops: conv/pool/norm/dropout/softmax.

Reference kernels: src/ops/CudnnConv2d*.cu, MaxPool.cu, AvgPool.cu,
CudnnBn.cu, LayerNorm.cu, InstanceNorm2d.cu, Dropout.cu, Softmax.cu,
CudnnSoftmax.cu.  Layouts follow the reference (NCHW, OIHW) for API parity;
XLA re-layouts internally for the MXU so no transposes are exposed.
Dropout uses counter-based per-op RNG (TraceContext.rng_for) so the autodiff
re-trace replays identical masks — the TPU analogue of the reference's
seed+seqnum scheme (python/hetu/random.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..graph.node import Op, VariableOp
from .base import simple_op, SimpleOp
from .. import initializers as init


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _conv2d(x, w, padding=0, stride=1, dilation=1, groups=1):
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    dh, dw = _pair(dilation)
    # API layout is NCHW (reference parity) but the compute runs NHWC —
    # the TPU-native conv layout (channels on the lane dim).  XLA's
    # algebraic simplifier pushes the boundary transposes through the
    # elementwise/BN chain so conv→bn→relu→conv stays NHWC end to end
    # (measured: ResNet-18/CIFAR trains ~25% faster than NCHW compute).
    out = lax.conv_general_dilated(
        x.transpose(0, 2, 3, 1), w.transpose(2, 3, 1, 0),
        window_strides=(sh, sw), padding=((ph, ph), (pw, pw)),
        rhs_dilation=(dh, dw), feature_group_count=groups,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32).astype(x.dtype)
    return out.transpose(0, 3, 1, 2)


conv2d_op = simple_op(_conv2d, "conv2d")
conv2d_add_bias_op = simple_op(
    lambda x, w, b, padding=0, stride=1, dilation=1, groups=1:
        _conv2d(x, w, padding, stride, dilation, groups)
        + b.reshape(1, -1, 1, 1),
    "conv2d_add_bias")


def _conv2d_nhwc(x, w, padding=0, stride=1, dilation=1, groups=1):
    """Fully channels-last conv: x NHWC, w HWIO, out NHWC — zero layout
    transposes anywhere (the TPU-native end-to-end form; the NCHW API
    ops keep reference parity and cost boundary transposes that XLA
    mostly, but not always, cancels)."""
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    dh, dw = _pair(dilation)
    return lax.conv_general_dilated(
        x, w, window_strides=(sh, sw), padding=((ph, ph), (pw, pw)),
        rhs_dilation=(dh, dw), feature_group_count=groups,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32).astype(x.dtype)



def _conv2d_hwio(x, w, padding=0, stride=1, dilation=1, groups=1):
    """Conv with the weight ALREADY in HWIO (the TPU-native kernel
    layout).  The OIHW->HWIO transpose in ``_conv2d`` is a logical
    no-op but XLA materializes it as a physical copy of every kernel
    every step (~177 MB/step on ResNet-18); layers that own their
    weights store HWIO natively (layers/common.py Conv2d) and only the
    op API keeps NCHW activations for reference parity."""
    return _conv2d_nhwc(x.transpose(0, 2, 3, 1), w, padding, stride,
                        dilation, groups).transpose(0, 3, 1, 2)


conv2d_hwio_op = simple_op(_conv2d_hwio, "conv2d_hwio")
conv2d_hwio_add_bias_op = simple_op(
    lambda x, w, b, padding=0, stride=1, dilation=1, groups=1:
        _conv2d_hwio(x, w, padding, stride, dilation, groups)
        + b.reshape(1, -1, 1, 1),
    "conv2d_hwio_add_bias")


conv2d_nhwc_op = simple_op(_conv2d_nhwc, "conv2d_nhwc")
conv2d_nhwc_add_bias_op = simple_op(
    lambda x, w, b, padding=0, stride=1, dilation=1, groups=1:
        _conv2d_nhwc(x, w, padding, stride, dilation, groups) + b,
    "conv2d_nhwc_add_bias")


def _conv2d_transpose(x, w, padding=0, stride=1):
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    return lax.conv_transpose(
        x, w, strides=(sh, sw), padding=((ph, ph), (pw, pw)),
        dimension_numbers=("NCHW", "IOHW", "NCHW"))


conv2d_transpose_op = simple_op(_conv2d_transpose, "conv2d_transpose")


def _pool(x, kernel_H, kernel_W, padding=0, stride=1, mode="max"):
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    window = (1, 1, kernel_H, kernel_W)
    strides = (1, 1, sh, sw)
    pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
    if mode == "max":
        # -inf init (not finfo.min): jax only attaches the max-pool VJP
        # rule when the reduction is recognizably reduce-window-max
        neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, neg, lax.max, window, strides, pads)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    # count_include_pad=True matches the reference AvgPool.cu
    return s / (kernel_H * kernel_W)


max_pool2d_op = simple_op(
    lambda x, kernel_H=2, kernel_W=2, padding=0, stride=2:
        _pool(x, kernel_H, kernel_W, padding, stride, "max"),
    "max_pool2d")
avg_pool2d_op = simple_op(
    lambda x, kernel_H=2, kernel_W=2, padding=0, stride=2:
        _pool(x, kernel_H, kernel_W, padding, stride, "avg"),
    "avg_pool2d")
global_avg_pool2d_op = simple_op(
    lambda x, channels_last=False:
        jnp.mean(x, axis=(1, 2) if channels_last else (2, 3)),
    "global_avg_pool2d")

softmax_op = simple_op(
    lambda x, dim=-1: jax.nn.softmax(x, axis=dim), "softmax")
log_softmax_op = simple_op(
    lambda x, dim=-1: jax.nn.log_softmax(x, axis=dim), "log_softmax")


def _layer_norm(x, scale, bias, eps=1e-5):
    # moments in f32 (bf16 mean/variance loses too much precision)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    return (((xf - mean) * lax.rsqrt(var + eps)).astype(x.dtype)
            * scale + bias)


layer_normalization_op = simple_op(_layer_norm, "layer_normalization")


def _rms_norm(x, scale, eps=1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(x.dtype) * scale


rms_norm_op = simple_op(_rms_norm, "rms_norm")


def _instance_norm2d(x, eps=1e-7):
    mean = jnp.mean(x, axis=(2, 3), keepdims=True)
    var = jnp.var(x, axis=(2, 3), keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps)


instance_normalization2d_op = simple_op(_instance_norm2d, "instance_norm2d")


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _shifted_stats(xf, shift, red, vec):
    """Shifted one-pass batch stats: (mean, var) over ``red`` axes with
    deviations taken against the per-channel ``shift`` (see
    BatchNormOp).  The custom vjp emits the backward in the DISTRIBUTED
    form ``x * k + broadcast(c)`` instead of autodiff's
    ``(x - shift) * k``: numerically identical per element, but the
    subtract in the backward's big elementwise producer blocks XLA from
    matching the canonical conv+BN backward fusion (measured 963
    us/step on ResNet-18/2048 — the whole r2-r4 gap vs the flax twin)."""
    s = shift.reshape(vec)
    d = xf - s
    dmean = jnp.mean(d, axis=red)
    d2mean = jnp.mean(jnp.square(d), axis=red)
    var = jnp.maximum(d2mean - jnp.square(dmean), 0.0)
    return shift + dmean, var


def _shifted_stats_fwd(xf, shift, red, vec):
    mean, var = _shifted_stats(xf, shift, red, vec)
    return (mean, var), (xf, mean, shift)


def _shifted_stats_bwd(red, vec, res, cts):
    xf, mean, shift = res
    ct_mean, ct_var = cts
    n = 1
    for ax in red:
        n *= xf.shape[ax]
    inv_n = 1.0 / n
    # d mean / d x = 1/N;  d var / d x = (2/N) (x - mean) — distributed
    # as x * (2/N ct_var) - broadcast((2/N) ct_var * mean) so the big
    # term stays LINEAR in x (fusable into the backward conv).  The
    # var<0 clamp's boundary gradient is intentionally ignored: it only
    # engages on numerically-negative variances (degenerate inputs).
    k = (2.0 * inv_n) * ct_var
    g = (xf * k.reshape(vec)
         + (inv_n * ct_mean - k * mean).reshape(vec))
    return g.astype(xf.dtype), jnp.zeros_like(shift)


_shifted_stats.defvjp(_shifted_stats_fwd, _shifted_stats_bwd)


class BatchNormOp(Op):
    """BatchNorm with running-stat state (reference CudnnBn.cu keeps
    running mean/var on the op; here they are non-trainable Variables updated
    through the trace context).

    Batch statistics use a shifted one-pass form by default (shift = the
    running mean — a parameter, so the reductions fuse with the producing
    conv; flax's ``use_fast_variance`` default accepts the same
    single-read tradeoff with NO shift at all).  The shift lags the data
    by the EMA horizon, so pathological inputs (per-channel |mean| >> std
    before the EMA catches up) can still lose variance precision in f32;
    ``precise_stats=True`` selects the exact two-pass mean-then-deviations
    form (one extra read of x) for such inputs."""

    def __init__(self, x, scale, bias, momentum=0.1, eps=1e-5,
                 precise_stats=False, channel_axis=1, name=None):
        base = name or f"bn_{scale.name}"
        c = scale.shape[0] if isinstance(scale, VariableOp) else None
        assert c is not None, "BatchNorm scale must be a Variable"
        self.running_mean = VariableOp(base + "_running_mean", (c,),
                                       init.zeros(), trainable=False)
        self.running_var = VariableOp(base + "_running_var", (c,),
                                      init.ones(), trainable=False)
        super().__init__(x, scale, bias, self.running_mean, self.running_var,
                         name=base)
        self.momentum = momentum
        self.eps = eps
        self.precise_stats = precise_stats
        # 1 = NCHW (reference layout); -1 = channels-last (NHWC)
        self.channel_axis = channel_axis

    @property
    def is_stateful(self):
        return True

    def _compute(self, input_vals, ctx):
        x, scale, bias, rmean, rvar = input_vals
        ax = self.channel_axis % x.ndim
        vec = [1] * x.ndim
        vec[ax] = -1
        red = tuple(i for i in range(x.ndim) if i != ax)
        bias = bias.reshape(vec)
        if ctx.training:
            # batch stats in f32; running stats update against the f32
            # masters (bf16 bindings would re-quantize them every step and
            # round small momentum updates away)
            xf = x.astype(jnp.float32)
            m = self.momentum
            master = ctx.master_params
            rm = (master[self.running_mean.name]
                  if master is not None else rmean).astype(jnp.float32)
            rv = (master[self.running_var.name]
                  if master is not None else rvar).astype(jnp.float32)
            if self.precise_stats:
                # exact two-pass mean-then-deviations (one extra read)
                mean = jnp.mean(xf, axis=red)
                var = jnp.mean(jnp.square(
                    xf - mean.reshape(vec)), axis=red)
            else:
                # shifted one-pass stats: x is read once for both
                # reductions (half the stats traffic of the two-pass
                # form), deviations taken against a per-channel shift
                # before squaring — the raw E[x^2]-E[x]^2 form cancels
                # catastrophically in f32 when |mean| >> std.  The shift
                # is the RUNNING mean: a parameter, so it fuses freely (a
                # shift sliced from x itself costs ~7% of a ResNet-18
                # step by blocking the reduction's fusion with the
                # producing conv) and converges to the true mean, the
                # optimal shift.  mean/var are mathematically
                # shift-independent, so stop_gradient keeps the backward
                # pass exact.  See the class docstring for the
                # early-steps caveat and the precise_stats escape hatch.
                # _shifted_stats carries a hand-written vjp in the
                # distributed x*k + broadcast form (autodiff's (x-s)*k
                # blocks the backward conv fusion — 963 us/step on
                # ResNet-18/2048).
                mean, var = _shifted_stats(
                    xf, lax.stop_gradient(rm), red, tuple(vec))
            ctx.record_update(self.running_mean, (1 - m) * rm + m * mean)
            ctx.record_update(self.running_var, (1 - m) * rv + m * var)
            mean = mean.astype(x.dtype)
            var = var.astype(x.dtype)
        else:
            mean, var = rmean, rvar
        # stop_gradient on batch stats is NOT applied: gradients flow through
        # mean/var exactly as in cudnnBatchNormalizationBackward.
        # scale folds into the rsqrt as ONE per-channel multiplier BEFORE
        # touching x: one whole-tensor multiply instead of two, and — the
        # real win — the backward's big reductions become channel-
        # -scalar-free bilinear terms of (x-mean) and g that XLA can CSE
        # into 3 reduces instead of 4 (the 963 us/step ResNet-18 gap vs
        # the flax twin was exactly this extra fused reduction).
        inv = (lax.rsqrt(var.astype(jnp.float32) + self.eps)
               * scale.astype(jnp.float32)).astype(x.dtype)
        return (x - mean.reshape(vec)) * inv.reshape(vec) + bias


def batch_normalization_op(x, scale, bias, momentum=0.1, eps=1e-5,
                           precise_stats=False, channel_axis=1, name=None):
    return BatchNormOp(x, scale, bias, momentum=momentum, eps=eps,
                       precise_stats=precise_stats,
                       channel_axis=channel_axis, name=name)


class DropoutOp(Op):
    """Inverted dropout (reference Dropout.cu / CudnnDropout)."""

    def __init__(self, x, keep_prob=0.9, name=None):
        super().__init__(x, name=name)
        self.keep_prob = keep_prob

    @property
    def needs_rng(self):
        return True

    def _compute(self, input_vals, ctx):
        (x,) = input_vals
        if not ctx.training or self.keep_prob >= 1.0:
            return x
        mask = jax.random.bernoulli(ctx.rng_for(self), self.keep_prob,
                                    x.shape)
        return jnp.where(mask, x / self.keep_prob, 0.0).astype(x.dtype)


def dropout_op(x, keep_prob=0.9, name=None):
    return DropoutOp(x, keep_prob=keep_prob, name=name)


def dropout2d_op(x, keep_prob=0.9, name=None):
    """Channel-wise dropout (reference Dropout2d.cu)."""

    class Dropout2dOp(DropoutOp):
        def _compute(self, input_vals, ctx):
            (x,) = input_vals
            if not ctx.training or self.keep_prob >= 1.0:
                return x
            mask = jax.random.bernoulli(
                ctx.rng_for(self), self.keep_prob, x.shape[:2])
            mask = mask.reshape(x.shape[0], x.shape[1], 1, 1)
            return jnp.where(mask, x / self.keep_prob, 0.0).astype(x.dtype)

    return Dropout2dOp(x, keep_prob=keep_prob, name=name)


class RandomSampleOp(Op):
    """Source RNG ops (reference gpu_ops/Rand.py, Sample.py,
    src/ops/Initializers.cu): uniform / normal / gumbel draws as graph
    nodes, keyed by the trace's per-op counter-based RNG so autodiff
    re-traces see identical draws."""

    def __init__(self, shape, dist="normal", low=0.0, high=1.0, mean=0.0,
                 stddev=1.0, dtype=jnp.float32, name=None):
        assert dist in ("normal", "uniform", "gumbel", "randint")
        super().__init__(name=name)
        self.shape = tuple(shape)
        self.dist = dist
        self.low, self.high = low, high
        self.mean, self.stddev = mean, stddev
        self.dtype = dtype

    @property
    def needs_rng(self):
        return True

    def _compute(self, input_vals, ctx):
        key = ctx.rng_for(self)
        if self.dist == "normal":
            return (self.mean + self.stddev
                    * jax.random.normal(key, self.shape, self.dtype))
        if self.dist == "uniform":
            return jax.random.uniform(key, self.shape, self.dtype,
                                      self.low, self.high)
        if self.dist == "randint":
            dt = (jnp.int32 if self.dtype in (jnp.float32, None)
                  else self.dtype)
            return jax.random.randint(key, self.shape, int(self.low),
                                      int(self.high), dt)
        u = jax.random.uniform(key, self.shape, self.dtype, 1e-20, 1.0)
        return -jnp.log(-jnp.log(u))


def random_normal_op(shape, mean=0.0, stddev=1.0, name=None):
    return RandomSampleOp(shape, "normal", mean=mean, stddev=stddev,
                          name=name)


def random_uniform_op(shape, low=0.0, high=1.0, name=None):
    return RandomSampleOp(shape, "uniform", low=low, high=high, name=name)


def gumbel_sample_op(shape, name=None):
    return RandomSampleOp(shape, "gumbel", name=name)


def randint_sample_op(shape, low, high, name=None):
    return RandomSampleOp(shape, "randint", low=low, high=high, name=name)
