"""MoE plumbing ops.

Reference kernels: src/ops/{LayoutTransform,H_A2A_LayoutTransform,TopKIdx,
GroupTopKIdx,Scatter1D,SamMax,SamGroupSum,MinDist}.cu and graph ops
gpu_ops/{LayoutTransform,ReverseLayoutTransform,TopKIdx,BalanceAssignment,
Sample,Scatter1D}.py — scatter tokens into (expert, capacity) buffers before
the all-to-all and back after.

TPU redesign: dispatch is expressed densely (GShard-style one-hot
dispatch/combine einsums) so it is MXU work with static shapes instead of
data-dependent scatters; capacity overflow drops match the reference's
LayoutTransform semantics.  The EP all-to-all is inserted by GSPMD from the
expert-dim shardings (layers/moe.py), or composed explicitly with
parallel/collectives.hierarchical_all_to_all for DCN×ICI topologies.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import simple_op


def top_k_gating(logits, k, capacity, *, second_renorm=True,
                 noise_rng=None, noise_eps=0.0):
    """GShard top-k gating (k∈{1,2}).

    logits: [T, E] raw gate outputs.  Returns (dispatch [T, E, C] float,
    combine [T, E, C] float, aux_loss scalar).  Tokens beyond per-expert
    capacity C are dropped (zero rows), as in the reference TopGate
    (python/hetu/layers/TopGate.py GShard top-2 with capacity).
    """
    if k not in (1, 2):
        raise ValueError(f"top_k_gating supports k in (1, 2), got k={k}")
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    if noise_rng is not None and noise_eps > 0:
        logits = logits + noise_eps * jax.random.normal(noise_rng,
                                                        logits.shape)
    idx1 = jnp.argmax(logits, axis=-1)                       # [T]
    mask1 = jax.nn.one_hot(idx1, E, dtype=probs.dtype)       # [T, E]
    gate1 = jnp.sum(probs * mask1, axis=-1)

    # load-balancing aux loss (GShard eq.4): E * mean(me * ce)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux = E * jnp.sum(me * ce)

    # position of each token within its expert's queue
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1         # [T, E]
    pos1_tok = jnp.sum(pos1, axis=-1)                        # [T]
    keep1 = pos1_tok < capacity
    gates = [(idx1, gate1 * keep1, pos1_tok)]

    if k == 2:
        logits2 = jnp.where(mask1 > 0, -jnp.inf, logits)
        idx2 = jnp.argmax(logits2, axis=-1)
        mask2 = jax.nn.one_hot(idx2, E, dtype=probs.dtype)
        gate2 = jnp.sum(probs * mask2, axis=-1)
        # expert queues continue after top-1 assignments
        used = jnp.sum(mask1, axis=0, keepdims=True)         # [1, E] counts
        pos2 = (jnp.cumsum(mask2, axis=0) - mask2 + used) * mask2
        pos2_tok = jnp.sum(pos2, axis=-1)
        keep2 = pos2_tok < capacity
        gates.append((idx2, gate2 * keep2, pos2_tok))
        if second_renorm:
            denom = gates[0][1] + gates[1][1] + 1e-9
            gates = [(i, g / denom * (gates[0][1] + gates[1][1] > 0), p)
                     for (i, g, p) in gates]

    dispatch = jnp.zeros((T, E, capacity), dtype=probs.dtype)
    combine = jnp.zeros((T, E, capacity), dtype=probs.dtype)
    t_idx = jnp.arange(T)
    for idx, gate, pos in gates:
        oh = (jax.nn.one_hot(idx, E, dtype=probs.dtype)[:, :, None]
              * jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                               dtype=probs.dtype)[:, None, :])
        keep = (gate > 0).astype(probs.dtype)[:, None, None]
        dispatch = dispatch + oh * keep
        combine = combine + oh * gate[:, None, None]
    return dispatch, combine, aux


def hash_gating(ids, num_experts, capacity, dtype=jnp.float32):
    """HashGate (reference layers/HashGate.py): expert = id % E, gate = 1."""
    T = ids.shape[0]
    idx = jnp.mod(ids.astype(jnp.int32), num_experts)
    mask = jax.nn.one_hot(idx, num_experts, dtype=dtype)
    pos = jnp.sum(jnp.cumsum(mask, axis=0) * mask - mask, axis=-1)
    keep = (pos < capacity).astype(dtype)
    oh = (mask[:, :, None]
          * jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=dtype)
          [:, None, :])
    dispatch = oh * keep[:, None, None]
    return dispatch, dispatch, jnp.asarray(0.0, dtype)


layout_transform_op = simple_op(
    lambda x, dispatch: jnp.einsum("tec,th->ech", dispatch, x),
    "layout_transform")
reverse_layout_transform_op = simple_op(
    lambda expert_out, combine: jnp.einsum("ech,tec->th", expert_out,
                                           combine),
    "reverse_layout_transform")
topk_idx_op = simple_op(
    lambda x, k=1: jax.lax.top_k(x, k)[1], "topk_idx")
topk_val_op = simple_op(
    lambda x, k=1: jax.lax.top_k(x, k)[0], "topk_val")
def _scatter1d(x, idx, size=None):
    if size is None:
        raise ValueError("scatter1d_op requires size= (static output length;"
                         " XLA needs static shapes)")
    return jnp.zeros((size,) + x.shape[1:],
                     x.dtype).at[idx.astype(jnp.int32)].set(x)


scatter1d_op = simple_op(_scatter1d, "scatter1d")


def balance_assignment(scores, capacity=None):
    """BASE-layer balanced assignment (reference BalanceAssignment op /
    MinDist.cu auction).  Greedy capacity-constrained approximation with
    static shapes: iterate experts in score order per token.
    scores: [T, E]; returns expert index per token balancing load to T/E."""
    T, E = scores.shape
    cap = capacity or (T + E - 1) // E

    def assign_token(carry, t):
        load, out = carry
        s = scores[t] - jnp.where(load >= cap, jnp.inf, 0.0)
        e = jnp.argmax(s)
        load = load.at[e].add(1)
        out = out.at[t].set(e)
        return (load, out), None

    load0 = jnp.zeros((E,), jnp.int32)
    out0 = jnp.zeros((T,), jnp.int32)
    (_, out), _ = jax.lax.scan(assign_token, (load0, out0), jnp.arange(T))
    return out


def sam_group_sum(x, group_idx, num_groups):
    """SamGroupSum.cu: segment-sum of gate scores per group."""
    return jax.ops.segment_sum(x, group_idx.astype(jnp.int32),
                               num_segments=num_groups)
