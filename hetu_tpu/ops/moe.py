"""MoE plumbing ops.

Reference kernels: src/ops/{LayoutTransform,H_A2A_LayoutTransform,TopKIdx,
GroupTopKIdx,Scatter1D,SamMax,SamGroupSum,MinDist}.cu and graph ops
gpu_ops/{LayoutTransform,ReverseLayoutTransform,TopKIdx,BalanceAssignment,
Sample,Scatter1D}.py — scatter tokens into (expert, capacity) buffers before
the all-to-all and back after.

TPU redesign: dispatch is expressed densely (GShard-style one-hot
dispatch/combine einsums) so it is MXU work with static shapes instead of
data-dependent scatters; capacity overflow drops match the reference's
LayoutTransform semantics.  The EP all-to-all is inserted by GSPMD from the
expert-dim shardings (layers/moe.py), or composed explicitly with
parallel/collectives.hierarchical_all_to_all for DCN×ICI topologies.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import simple_op


def top_k_gating(logits, k, capacity, *, second_renorm=True,
                 noise_rng=None, noise_eps=0.0):
    """GShard top-k gating (k∈{1,2}).

    logits: [T, E] raw gate outputs.  Returns (dispatch [T, E, C] float,
    combine [T, E, C] float, aux_loss scalar).  Tokens beyond per-expert
    capacity C are dropped (zero rows), as in the reference TopGate
    (python/hetu/layers/TopGate.py GShard top-2 with capacity).
    """
    choices, aux = top_k_gating_choices(
        logits, k, capacity, second_renorm=second_renorm,
        noise_rng=noise_rng, noise_eps=noise_eps)
    T, E = logits.shape
    dispatch, combine = _accumulate_dispatch(T, E, capacity, choices,
                                             logits.dtype)
    return dispatch, combine, aux


def top_k_gating_choices(logits, k, capacity, *, second_renorm=True,
                         noise_rng=None, noise_eps=0.0):
    """``top_k_gating`` in CHOICES form — [(expert_idx, gate, pos)] per
    routing choice plus the aux loss, never materializing the [T, E, C]
    dispatch/combine tensors (the sparse dispatch path feeds these to
    ops/pallas/moe_dispatch.row_gather)."""
    if k not in (1, 2):
        raise ValueError(f"top_k_gating supports k in (1, 2), got k={k}")
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    if noise_rng is not None and noise_eps > 0:
        logits = logits + noise_eps * jax.random.normal(noise_rng,
                                                        logits.shape)
    idx1 = jnp.argmax(logits, axis=-1)                       # [T]
    mask1 = jax.nn.one_hot(idx1, E, dtype=probs.dtype)       # [T, E]
    gate1 = jnp.sum(probs * mask1, axis=-1)

    # load-balancing aux loss (GShard eq.4): E * mean(me * ce)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux = E * jnp.sum(me * ce)

    masks_gates = [(mask1, gate1)]
    if k == 2:
        logits2 = jnp.where(mask1 > 0, -jnp.inf, logits)
        mask2 = jax.nn.one_hot(jnp.argmax(logits2, axis=-1), E,
                               dtype=probs.dtype)
        masks_gates.append((mask2, jnp.sum(probs * mask2, axis=-1)))
    choices = _choices_with_positions(masks_gates)
    # zero dropped gates BEFORE renorm so kept mass renormalizes to 1
    choices = [(i, g * (p < capacity), p) for (i, g, p) in choices]
    if k == 2 and second_renorm:
        total = choices[0][1] + choices[1][1]
        denom = total + 1e-9
        choices = [(i, g / denom * (total > 0), p)
                   for (i, g, p) in choices]
    return choices, aux


def sparse_dispatch(tokens, choices, num_experts, capacity,
                    use_pallas=True):
    """[E, C, H] expert inputs straight from routing choices (reference
    LayoutTransform.cu) — a row gather by the slot→token inverse map; the
    O(T·E·C) one-hot tensors never exist."""
    from .pallas.moe_dispatch import row_gather
    T, H = tokens.shape
    S = num_experts * capacity
    slot_tok = jnp.full((S,), -1, jnp.int32)
    for idx, gate, pos in choices:
        keep = (pos < capacity) & (gate > 0)
        slot = jnp.where(keep,
                         idx.astype(jnp.int32) * capacity
                         + pos.astype(jnp.int32), S)
        slot_tok = slot_tok.at[slot].set(
            jnp.arange(T, dtype=jnp.int32), mode="drop",
            unique_indices=True)
    return row_gather(tokens, slot_tok, use_pallas).reshape(
        num_experts, capacity, H)


def sparse_combine(expert_out, choices, use_pallas=True):
    """[T, H] outputs from [E, C, H] expert results + routing choices
    (reference ReverseLayoutTransform.cu): per choice, gather the token's
    slot row and scale by its gate."""
    from .pallas.moe_dispatch import row_gather
    E, C, H = expert_out.shape
    flat = expert_out.reshape(E * C, H)
    out = None
    for idx, gate, pos in choices:
        keep = (pos < C) & (gate > 0)
        slot = jnp.where(keep,
                         idx.astype(jnp.int32) * C
                         + pos.astype(jnp.int32), -1)
        term = (row_gather(flat, slot, use_pallas)
                * gate[:, None].astype(flat.dtype))
        out = term if out is None else out + term
    return out


def top_k_balance_aux(logits):
    """Just the GShard balance loss of ``top_k_gating`` — O(T·E), no
    [T,E,C] dispatch/combine tensors (for aux evaluated in a separate
    program from the MoE op, where CSE can't merge the gating)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    mask1 = jax.nn.one_hot(jnp.argmax(logits, axis=-1), E,
                           dtype=probs.dtype)
    return E * jnp.sum(jnp.mean(probs, axis=0) * jnp.mean(mask1, axis=0))


def ktop1_balance_aux(logits, k):
    """Just the per-prototype balance loss of ``ktop1_gating``."""
    T, E = logits.shape
    Ep = E // k
    sub = logits.reshape(T, k, Ep)
    probs = jax.nn.softmax(sub, axis=-1)
    aux = 0.0
    for i in range(k):
        mask_local = jax.nn.one_hot(jnp.argmax(sub[:, i], axis=-1), Ep,
                                    dtype=probs.dtype)
        aux = aux + Ep * jnp.sum(jnp.mean(probs[:, i], axis=0)
                                 * jnp.mean(mask_local, axis=0))
    return aux


def sam_balance_aux(logits, num_groups):
    """Just the balance + group-alignment terms of ``sam_gating``."""
    T, E = logits.shape
    Eg = E // num_groups
    probs = jax.nn.softmax(logits, axis=-1)
    gidx = jnp.repeat(jnp.arange(num_groups), Eg)
    gmass = sam_group_sum(probs.T, gidx, num_groups).T
    top_group = jnp.argmax(gmass, axis=-1)
    in_group = gidx[None, :] == top_group[:, None]
    first_mask = jax.nn.one_hot(
        jnp.argmax(jnp.where(in_group, logits, -jnp.inf), axis=-1), E,
        dtype=probs.dtype)
    balance = E * jnp.sum(jnp.mean(probs, axis=0)
                          * jnp.mean(first_mask, axis=0))
    alignment = jnp.mean(1.0 - jnp.max(gmass, axis=-1))
    return balance + alignment


def hash_gating_choices(ids, num_experts, capacity, dtype=jnp.float32):
    """``hash_gating`` in CHOICES form (see top_k_gating_choices)."""
    T = ids.shape[0]
    idx = jnp.mod(ids.astype(jnp.int32), num_experts)
    mask = jax.nn.one_hot(idx, num_experts, dtype=dtype)
    choices = _choices_with_positions([(mask, jnp.ones((T,), dtype))])
    return choices, jnp.asarray(0.0, dtype)


def hash_gating(ids, num_experts, capacity, dtype=jnp.float32):
    """HashGate (reference layers/HashGate.py): expert = id % E, gate = 1."""
    T = ids.shape[0]
    choices, _ = hash_gating_choices(ids, num_experts, capacity, dtype)
    dispatch, _ = _accumulate_dispatch(T, num_experts, capacity, choices,
                                       dtype)
    return dispatch, dispatch, jnp.asarray(0.0, dtype)


layout_transform_op = simple_op(
    lambda x, dispatch: jnp.einsum("tec,th->ech", dispatch, x),
    "layout_transform")
reverse_layout_transform_op = simple_op(
    lambda expert_out, combine: jnp.einsum("ech,tec->th", expert_out,
                                           combine),
    "reverse_layout_transform")
topk_idx_op = simple_op(
    lambda x, k=1: jax.lax.top_k(x, k)[1], "topk_idx")
topk_val_op = simple_op(
    lambda x, k=1: jax.lax.top_k(x, k)[0], "topk_val")
def _scatter1d(x, idx, size=None):
    if size is None:
        raise ValueError("scatter1d_op requires size= (static output length;"
                         " XLA needs static shapes)")
    return jnp.zeros((size,) + x.shape[1:],
                     x.dtype).at[idx.astype(jnp.int32)].set(x)


scatter1d_op = simple_op(_scatter1d, "scatter1d")


def _positions_in_queue(mask):
    """Per-token position within its expert's arrival queue; mask [T, E]."""
    return jnp.sum(jnp.cumsum(mask, axis=0) * mask - mask, axis=-1)


def _choices_with_positions(masks_gates):
    """[(mask [T,E], gate [T])] -> [(expert_idx, gate, pos)] with positions
    drawn from per-expert queues SHARED across choices: a later choice
    queues behind every earlier choice's tokens, so two choices can never
    collide in the same (expert, capacity-slot)."""
    used = None
    out = []
    for mask, gate in masks_gates:
        pos = _positions_in_queue(mask)
        if used is not None:
            pos = pos + jnp.sum(mask * used, axis=-1)
        out.append((jnp.argmax(mask, axis=-1), gate, pos))
        counts = jnp.sum(mask, axis=0, keepdims=True)
        used = counts if used is None else used + counts
    return out


def _accumulate_dispatch(T, E, C, choices, dtype):
    """choices: [(expert_idx [T], gate [T], pos [T])] -> dispatch/combine
    [T, E, C] (zero rows for capacity-dropped tokens)."""
    dispatch = jnp.zeros((T, E, C), dtype=dtype)
    combine = jnp.zeros((T, E, C), dtype=dtype)
    for idx, gate, pos in choices:
        keep = (pos < C).astype(dtype)
        oh = (jax.nn.one_hot(idx, E, dtype=dtype)[:, :, None]
              * jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=dtype)
              [:, None, :])
        oh = oh * keep[:, None, None]
        dispatch = dispatch + oh * (gate > 0).astype(dtype)[:, None, None]
        combine = combine + oh * gate[:, None, None]
    return dispatch, combine


def ktop1_gating_choices(logits, k, capacity):
    """``ktop1_gating`` in CHOICES form (see top_k_gating_choices)."""
    T, E = logits.shape
    assert E % k == 0, "KTop1 needs num_experts divisible by k"
    Ep = E // k
    sub = logits.reshape(T, k, Ep)
    probs = jax.nn.softmax(sub, axis=-1)         # softmax per prototype
    aux = 0.0
    masks_gates = []
    for i in range(k):
        idx_local = jnp.argmax(sub[:, i], axis=-1)
        mask_local = jax.nn.one_hot(idx_local, Ep, dtype=probs.dtype)
        gate = jnp.sum(probs[:, i] * mask_local, axis=-1)
        aux = aux + Ep * jnp.sum(jnp.mean(probs[:, i], axis=0)
                                 * jnp.mean(mask_local, axis=0))
        mask = jax.nn.one_hot(i * Ep + idx_local, E, dtype=probs.dtype)
        masks_gates.append((mask, gate))
    return _choices_with_positions(masks_gates), aux


def ktop1_gating(logits, k, capacity):
    """KTop1 gate (reference layers/KTop1Gate.py): experts split into k
    prototypes of E/k; each token routes top-1 WITHIN every prototype
    (k assignments total), with an independent balance loss per prototype.
    """
    T, E = logits.shape
    choices, aux = ktop1_gating_choices(logits, k, capacity)
    dispatch, combine = _accumulate_dispatch(T, E, capacity, choices,
                                             logits.dtype)
    return dispatch, combine, aux


def sam_gating_choices(logits, k, capacity, num_groups):
    """``sam_gating`` in CHOICES form (see top_k_gating_choices)."""
    T, E = logits.shape
    assert E % num_groups == 0
    Eg = E // num_groups
    assert k <= Eg, (f"SAM routes within one group of {Eg} experts; "
                     f"k={k} would exhaust it")
    probs = jax.nn.softmax(logits, axis=-1)
    gmass = sam_group_sum(probs.T, jnp.repeat(jnp.arange(num_groups), Eg),
                          num_groups).T                    # [T, G]
    top_group = jnp.argmax(gmass, axis=-1)                 # [T]
    in_group = (jnp.repeat(jnp.arange(num_groups), Eg)[None, :]
                == top_group[:, None])
    masked = jnp.where(in_group, logits, -jnp.inf)
    masks_gates = []
    remaining = masked
    first_mask = None
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        mask = jax.nn.one_hot(idx, E, dtype=probs.dtype)
        if first_mask is None:
            first_mask = mask
        masks_gates.append((mask, jnp.sum(probs * mask, axis=-1)))
        remaining = jnp.where(mask > 0, -jnp.inf, remaining)
    choices = _choices_with_positions(masks_gates)
    balance = E * jnp.sum(jnp.mean(probs, axis=0)
                          * jnp.mean(first_mask, axis=0))
    alignment = jnp.mean(1.0 - jnp.max(gmass, axis=-1))
    return choices, balance + alignment


def sam_gating(logits, k, capacity, num_groups):
    """SAM gate (reference layers/SAMGate.py): experts form ``num_groups``
    locality groups (one per host in the reference); each token picks the
    group with the largest probability mass, then its top-k experts INSIDE
    that group — keeping all its expert traffic on one host.  Aux = GShard
    balance loss + an alignment term rewarding the chosen group's mass
    (adaptation of SamMax.cu's alignment objective).
    """
    T, E = logits.shape
    choices, aux = sam_gating_choices(logits, k, capacity, num_groups)
    dispatch, combine = _accumulate_dispatch(T, E, capacity, choices,
                                             logits.dtype)
    return dispatch, combine, aux


def base_balance_gating(scores, capacity):
    """BASE-layer gate (reference BalanceGate.py + BalanceAssignment op):
    capacity-constrained assignment balances load exactly; combine weight
    is sigmoid(token · centroid) as in the BASE layer."""
    T, E = scores.shape
    idx = balance_assignment(scores, capacity)
    gate = jax.nn.sigmoid(scores[jnp.arange(T), idx])
    mask = jax.nn.one_hot(idx, E, dtype=scores.dtype)
    pos = _positions_in_queue(mask)
    dispatch, combine = _accumulate_dispatch(
        T, E, capacity, [(idx, gate, pos)], scores.dtype)
    return dispatch, combine, jnp.asarray(0.0, scores.dtype)


def balance_assignment(scores, capacity=None):
    """BASE-layer balanced assignment (reference BalanceAssignment op /
    MinDist.cu auction).  Greedy capacity-constrained approximation with
    static shapes: iterate experts in score order per token.
    scores: [T, E]; returns expert index per token balancing load to T/E."""
    T, E = scores.shape
    cap = capacity or (T + E - 1) // E

    def assign_token(carry, t):
        load, out = carry
        s = scores[t] - jnp.where(load >= cap, jnp.inf, 0.0)
        e = jnp.argmax(s)
        load = load.at[e].add(1)
        out = out.at[t].set(e)
        return (load, out), None

    load0 = jnp.zeros((E,), jnp.int32)
    out0 = jnp.zeros((T,), jnp.int32)
    (_, out), _ = jax.lax.scan(assign_token, (load0, out0), jnp.arange(T))
    return out


def sam_group_sum(x, group_idx, num_groups):
    """SamGroupSum.cu: segment-sum of gate scores per group."""
    return jax.ops.segment_sum(x, group_idx.astype(jnp.int32),
                               num_segments=num_groups)
