"""Loss ops.

Reference kernels: src/ops/SoftmaxCrossEntropy.cu (fused),
SoftmaxCrossEntropySparse.cu, CrossEntropy.cu, CrossEntropySparse.cu,
NllLoss.cu, BinaryCrossEntropyWithLogits.cu, MSELoss via compositions.
The fused softmax-CE forms are written as max-subtracted logsumexp
expressions that XLA fuses into a single pass (no separate softmax
materialization), matching the fusion the reference hand-codes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import simple_op


def _softmax_cross_entropy(y, y_, dim=-1):
    """y = logits, y_ = one-hot (or soft) targets; returns per-row loss."""
    y = y.astype(jnp.float32)  # stable under bf16 compute policies
    y_ = y_.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(y, axis=dim, keepdims=True)
    log_probs = y - lse
    return -jnp.sum(y_ * log_probs, axis=dim)


softmax_cross_entropy_op = simple_op(_softmax_cross_entropy,
                                     "softmax_cross_entropy")


def _softmax_cross_entropy_sparse(y, labels, dim=-1, ignored_index=-1):
    if dim in (-1, y.ndim - 1):
        # fused Pallas path: streams the vocab once with online logsumexp;
        # also sidesteps an XLA pathology for lane-unaligned vocab sizes
        # (GPT-2's 50257: 3.3x slower than 50304 through the jnp form)
        from .pallas.softmax_ce import fused_softmax_ce_sparse
        out = fused_softmax_ce_sparse(y, labels,
                                      ignored_index=ignored_index)
        if out is not None:
            return out
    y = y.astype(jnp.float32)  # stable under bf16 compute policies
    lse = jax.scipy.special.logsumexp(y, axis=dim)
    labels = labels.astype(jnp.int32)
    picked = jnp.take_along_axis(
        y, jnp.expand_dims(jnp.maximum(labels, 0), dim), axis=dim
    ).squeeze(dim)
    loss = lse - picked
    return jnp.where(labels == ignored_index, 0.0, loss)


softmax_cross_entropy_sparse_op = simple_op(
    _softmax_cross_entropy_sparse, "softmax_cross_entropy_sparse")


def _cross_entropy(y, y_, dim=-1, eps=1e-12):
    """y = probabilities (post-softmax), y_ = one-hot targets."""
    y = y.astype(jnp.float32)
    return -jnp.sum(y_ * jnp.log(jnp.maximum(y, eps)), axis=dim)


crossentropy_op = simple_op(_cross_entropy, "crossentropy")


def _cross_entropy_sparse(y, labels, dim=-1, ignored_index=-1, eps=1e-12):
    y = y.astype(jnp.float32)
    labels = labels.astype(jnp.int32)
    picked = jnp.take_along_axis(
        y, jnp.expand_dims(jnp.maximum(labels, 0), dim), axis=dim
    ).squeeze(dim)
    loss = -jnp.log(jnp.maximum(picked, eps))
    return jnp.where(labels == ignored_index, 0.0, loss)


crossentropy_sparse_op = simple_op(_cross_entropy_sparse,
                                   "crossentropy_sparse")


def _nll_loss(log_probs, labels):
    log_probs = log_probs.astype(jnp.float32)
    labels = labels.astype(jnp.int32)
    return -jnp.take_along_axis(log_probs, labels[:, None], axis=-1)[:, 0]


nll_loss_op = simple_op(_nll_loss, "nll_loss")


def _bce_with_logits(logits, targets):
    # numerically stable: max(x,0) - x*z + log(1+exp(-|x|))
    logits = logits.astype(jnp.float32)
    targets = targets.astype(jnp.float32)
    return (jnp.maximum(logits, 0) - logits * targets
            + jnp.log1p(jnp.exp(-jnp.abs(logits))))


binarycrossentropywithlogits_op = simple_op(_bce_with_logits,
                                            "bce_with_logits")
binary_cross_entropy_op = simple_op(
    lambda y, y_, eps=1e-12:
        -(y_.astype(jnp.float32)
          * jnp.log(jnp.maximum(y.astype(jnp.float32), eps))
          + (1 - y_.astype(jnp.float32))
          * jnp.log(jnp.maximum(1 - y.astype(jnp.float32), eps))),
    "binary_cross_entropy")
mse_loss_op = simple_op(
    lambda y, y_, reduction="mean":
        jnp.mean(jnp.square(y - y_)) if reduction == "mean"
        else jnp.square(y - y_),
    "mse_loss")
mae_loss_op = simple_op(
    lambda y, y_, reduction="mean":
        jnp.mean(jnp.abs(y - y_)) if reduction == "mean"
        else jnp.abs(y - y_),
    "mae_loss")
huber_loss_op = simple_op(
    lambda y, y_, delta=1.0: jnp.where(
        jnp.abs(y - y_) <= delta,
        0.5 * jnp.square(y - y_),
        delta * (jnp.abs(y - y_) - 0.5 * delta)),
    "huber_loss")
kl_div_op = simple_op(
    lambda log_p, q, eps=1e-12: q * (jnp.log(jnp.maximum(q, eps)) - log_p),
    "kl_div")
