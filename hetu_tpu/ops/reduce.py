"""Reduction ops (reference: src/ops/ReduceSum.cu, ReduceMean.cu,
ReduceGeneral.cu, ReduceMin.cu, ReduceMul.cu, ReduceNorm1/2.cu, MaxOp/MinOp,
Argmax.cu, Argmin.cu, ArgmaxPartial.cu)."""

from __future__ import annotations

import jax.numpy as jnp

from .base import simple_op


def _axes(axes):
    if axes is None:
        return None
    if isinstance(axes, int):
        return (axes,)
    return tuple(axes)


reduce_sum_op = simple_op(
    lambda a, axes=None, keepdims=False: jnp.sum(a, axis=_axes(axes),
                                                 keepdims=keepdims),
    "reduce_sum")
reduce_mean_op = simple_op(
    lambda a, axes=None, keepdims=False: jnp.mean(a, axis=_axes(axes),
                                                  keepdims=keepdims),
    "reduce_mean")
reduce_max_op = simple_op(
    lambda a, axes=None, keepdims=False: jnp.max(a, axis=_axes(axes),
                                                 keepdims=keepdims),
    "reduce_max")
reduce_min_op = simple_op(
    lambda a, axes=None, keepdims=False: jnp.min(a, axis=_axes(axes),
                                                 keepdims=keepdims),
    "reduce_min")
reduce_mul_op = simple_op(
    lambda a, axes=None, keepdims=False: jnp.prod(a, axis=_axes(axes),
                                                  keepdims=keepdims),
    "reduce_mul")
reduce_norm1_op = simple_op(
    lambda a, axes=None, keepdims=False: jnp.sum(jnp.abs(a), axis=_axes(axes),
                                                 keepdims=keepdims),
    "reduce_norm1")
reduce_norm2_op = simple_op(
    lambda a, axes=None, keepdims=False: jnp.sqrt(
        jnp.sum(jnp.square(a), axis=_axes(axes), keepdims=keepdims)),
    "reduce_norm2")
argmax_op = simple_op(
    lambda a, dim=-1, keepdims=False: jnp.argmax(a, axis=dim,
                                                 keepdims=keepdims),
    "argmax")
argmin_op = simple_op(
    lambda a, dim=-1, keepdims=False: jnp.argmin(a, axis=dim,
                                                 keepdims=keepdims),
    "argmin")
max_op = simple_op(lambda a, dim=-1: jnp.max(a, axis=dim), "max")
min_op = simple_op(lambda a, dim=-1: jnp.min(a, axis=dim), "min")
