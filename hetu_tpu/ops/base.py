"""Op definition helpers.

The reference implements each op as a Python class + a hand-written CUDA
kernel (one file per op under /root/reference/python/hetu/gpu_ops/ and
/root/reference/src/ops/).  On TPU the kernel body is a jnp/lax composition
that XLA fuses, so an op definition reduces to a pure function; this module
turns such functions into graph-node constructors.  Ops that need RNG,
train/eval mode, or state updates subclass Op directly in their modules.
"""

from __future__ import annotations

from ..graph.node import Op


class SimpleOp(Op):
    """Graph node wrapping a pure jnp function of its inputs + attrs."""

    __slots__ = ("impl", "op_kind")

    def __init__(self, impl, op_kind, *inputs, name=None, **attrs):
        super().__init__(*inputs, name=name or f"{op_kind}_{_peek_id()}",
                         **attrs)
        self.impl = impl
        self.op_kind = op_kind

    def _compute(self, input_vals, ctx):
        return self.impl(*input_vals, **self.attrs)


def _peek_id():
    from ..graph import node as _n
    return _n._node_counter[0] + 1


def simple_op(impl, op_kind):
    """Returns a graph-node constructor for a pure function.

    ``impl(*input_arrays, **attrs)`` must be jax-traceable; non-Op positional
    arguments are forbidden (constants go through attrs).
    """

    def ctor(*inputs, name=None, **attrs):
        for i in inputs:
            if not isinstance(i, Op):
                raise TypeError(
                    f"{op_kind}: expected graph nodes as inputs, got "
                    f"{type(i).__name__}; pass constants as keyword attrs")
        return SimpleOp(impl, op_kind, *inputs, name=name, **attrs)

    ctor.__name__ = op_kind
    return ctor
