"""Portable graph IR for the ONNX bridge.

The reference bridge (python/hetu/onnx/hetu2onnx.py, onnx2hetu.py,
onnx_opset/) converts between its op DAG and onnx protobufs directly.  Here
conversion goes through a small neutral IR — `OnnxModel`, a list of ONNX-
shaped nodes plus initializers — so the bridge works (export, import,
save/load, round-trip tests) even when the `onnx` package is absent; when it
is importable, proto.py converts OnnxModel <-> onnx.ModelProto losslessly.
"""

from __future__ import annotations

import io
import json
import zipfile
from dataclasses import dataclass, field

import numpy as np


@dataclass
class NodeIR:
    """One ONNX graph node: op_type + named edges + attributes."""
    op_type: str
    inputs: list
    outputs: list
    attrs: dict = field(default_factory=dict)
    name: str = ""


@dataclass
class TensorInfo:
    name: str
    shape: tuple
    dtype: str = "float32"


@dataclass
class OnnxModel:
    name: str = "hetu_tpu_graph"
    nodes: list = field(default_factory=list)            # [NodeIR]
    initializers: dict = field(default_factory=dict)     # name -> np.ndarray
    inputs: list = field(default_factory=list)           # [TensorInfo]
    outputs: list = field(default_factory=list)          # [TensorInfo]
    opset: int = 20   # Gelu needs >= 20; Reduce* axes-as-input needs >= 18

    def add_initializer(self, name, value):
        self.initializers[name] = np.asarray(value)
        return name

    def summary(self):
        ops = {}
        for n in self.nodes:
            ops[n.op_type] = ops.get(n.op_type, 0) + 1
        return {"name": self.name, "num_nodes": len(self.nodes),
                "num_initializers": len(self.initializers),
                "inputs": [t.name for t in self.inputs],
                "outputs": [t.name for t in self.outputs], "op_counts": ops}


def _attrs_to_json(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__nd__": True, "data": v.tolist(),
                      "dtype": str(v.dtype)}
        elif isinstance(v, tuple):
            out[k] = {"__tuple__": True, "data": list(v)}
        else:
            out[k] = v
    return out


def _attrs_from_json(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and v.get("__nd__"):
            out[k] = np.asarray(v["data"], dtype=v["dtype"])
        elif isinstance(v, dict) and v.get("__tuple__"):
            out[k] = tuple(v["data"])
        else:
            out[k] = v
    return out


def save_model(model: OnnxModel, path: str):
    """Serialize to a zip: graph.json + one .npy per initializer."""
    header = {
        "name": model.name, "opset": model.opset,
        "nodes": [{"op_type": n.op_type, "inputs": n.inputs,
                   "outputs": n.outputs, "attrs": _attrs_to_json(n.attrs),
                   "name": n.name} for n in model.nodes],
        "inputs": [{"name": t.name, "shape": list(t.shape),
                    "dtype": t.dtype} for t in model.inputs],
        "outputs": [{"name": t.name, "shape": list(t.shape),
                     "dtype": t.dtype} for t in model.outputs],
        "initializer_names": list(model.initializers),
    }
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("graph.json", json.dumps(header))
        for name, arr in model.initializers.items():
            buf = io.BytesIO()
            np.save(buf, np.asarray(arr))
            z.writestr(f"init/{name}.npy", buf.getvalue())


def load_model(path: str) -> OnnxModel:
    with zipfile.ZipFile(path, "r") as z:
        header = json.loads(z.read("graph.json"))
        inits = {}
        for name in header["initializer_names"]:
            inits[name] = np.load(io.BytesIO(z.read(f"init/{name}.npy")))
    return OnnxModel(
        name=header["name"], opset=header["opset"],
        nodes=[NodeIR(d["op_type"], d["inputs"], d["outputs"],
                      _attrs_from_json(d["attrs"]), d["name"])
               for d in header["nodes"]],
        initializers=inits,
        inputs=[TensorInfo(d["name"], tuple(d["shape"]), d["dtype"])
                for d in header["inputs"]],
        outputs=[TensorInfo(d["name"], tuple(d["shape"]), d["dtype"])
                 for d in header["outputs"]])
