"""OnnxModel <-> onnx.ModelProto, gated on the `onnx` package.

The build environment does not ship `onnx`; everything else in the bridge
(export, import, save/load, round-trips) works without it through the
neutral IR (ir.py).  When `onnx` is importable these two functions produce /
consume real protobufs for interop with other frameworks (the reference's
tests round-trip through tensorflow, tests/onnx/).
"""

from __future__ import annotations

import numpy as np

from .ir import OnnxModel, NodeIR, TensorInfo

try:
    import onnx  # noqa: F401
    from onnx import helper, numpy_helper, TensorProto
    HAS_ONNX = True
except ImportError:  # pragma: no cover - onnx not in the build image
    HAS_ONNX = False

_DTYPE2PROTO = {"float32": 1, "float64": 11, "int32": 6, "int64": 7}
_PROTO2DTYPE = {v: k for k, v in _DTYPE2PROTO.items()}


def _require():
    if not HAS_ONNX:
        raise ImportError(
            "the `onnx` package is not installed; use ir.save_model / "
            "ir.load_model for the portable zip format instead")


def to_onnx_proto(model: OnnxModel):
    """OnnxModel -> onnx.ModelProto (requires the onnx package)."""
    _require()
    nodes = []
    for n in model.nodes:
        attrs = {}
        for k, v in n.attrs.items():
            if k == "to":  # Cast dtype: translate to TensorProto enum
                v = _DTYPE2PROTO[str(np.dtype(v))]
            if isinstance(v, tuple):
                v = list(v)
            attrs[k] = v
        nodes.append(helper.make_node(n.op_type, n.inputs, n.outputs,
                                      name=n.name, **attrs))
    inputs = [helper.make_tensor_value_info(
        t.name, _DTYPE2PROTO.get(t.dtype, 1), list(t.shape) or None)
        for t in model.inputs]
    outputs = [helper.make_tensor_value_info(
        t.name, _DTYPE2PROTO.get(t.dtype, 1), None) for t in model.outputs]
    inits = [numpy_helper.from_array(np.asarray(v), name=k)
             for k, v in model.initializers.items()]
    graph = helper.make_graph(nodes, model.name, inputs, outputs, inits)
    proto = helper.make_model(
        graph, opset_imports=[helper.make_opsetid("", model.opset)])
    return proto


def from_onnx_proto(proto) -> OnnxModel:
    """onnx.ModelProto -> OnnxModel (requires the onnx package)."""
    _require()
    g = proto.graph
    model = OnnxModel(name=g.name)
    if proto.opset_import:
        model.opset = proto.opset_import[0].version
    for init in g.initializer:
        model.initializers[init.name] = numpy_helper.to_array(init)
    init_names = set(model.initializers)
    for vi in g.input:
        if vi.name in init_names:
            continue
        shape = tuple(d.dim_value for d in vi.type.tensor_type.shape.dim)
        model.inputs.append(TensorInfo(
            vi.name, shape,
            _PROTO2DTYPE.get(vi.type.tensor_type.elem_type, "float32")))
    for vi in g.output:
        model.outputs.append(TensorInfo(vi.name, ()))
    for n in g.node:
        attrs = {}
        for a in n.attribute:
            v = helper.get_attribute_value(a)
            if n.op_type == "Cast" and a.name == "to":
                v = _PROTO2DTYPE[v]
            if isinstance(v, bytes):
                v = v.decode()
            attrs[a.name] = v
        model.nodes.append(NodeIR(n.op_type, list(n.input), list(n.output),
                                  attrs, n.name))
    return model
