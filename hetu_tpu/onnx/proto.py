"""Real ONNX protobuf serialization for OnnxModel.

`serialize_model` / `deserialize_model` produce and consume genuine
`ModelProto` bytes via the pure-Python wire codec (wire.py) — no `onnx`
package needed, so `.onnx` files interoperate with other frameworks the
way the reference's bridge did through tensorflow (tests/onnx/,
python/hetu/onnx/hetu2onnx.py).

When the `onnx` package IS importable, `to_onnx_proto`/`from_onnx_proto`
additionally convert to its in-memory objects (handy for checker/runtime
use); they are optional — the byte path stands alone.
"""

from __future__ import annotations

import numpy as np

from .ir import OnnxModel, NodeIR, TensorInfo
from . import wire

try:
    import onnx
    HAS_ONNX = True
except ImportError:  # pragma: no cover - onnx not in the build image
    HAS_ONNX = False

_DTYPE2PROTO = wire.DTYPE_TO_ONNX
_PROTO2DTYPE = wire.ONNX_TO_DTYPE


def _encode_attrs(node: NodeIR):
    out = {}
    for k, v in node.attrs.items():
        if k == "to":  # Cast dtype: translate to TensorProto enum
            v = _DTYPE2PROTO[str(np.dtype(v))]
        out[k] = v
    return out


def _decode_attrs(op_type, attrs):
    out = {}
    for k, v in attrs.items():
        if op_type == "Cast" and k == "to":
            v = _PROTO2DTYPE[int(v)]
        out[k] = v
    return out


def serialize_model(model: OnnxModel, producer="hetu_tpu") -> bytes:
    """OnnxModel -> ONNX ModelProto bytes (pure Python, no onnx pkg)."""
    encoded = OnnxModel(name=model.name, opset=model.opset,
                        initializers=model.initializers,
                        inputs=model.inputs, outputs=model.outputs,
                        nodes=[NodeIR(n.op_type, n.inputs, n.outputs,
                                      _encode_attrs(n), n.name)
                               for n in model.nodes])
    return wire.enc_model(encoded, producer=producer)


def deserialize_model(data: bytes) -> OnnxModel:
    """ONNX ModelProto bytes -> OnnxModel (pure Python, no onnx pkg)."""
    (name, nodes, inits, inputs, outputs), opset = wire.dec_model(data)
    model = OnnxModel(name=name or "onnx_graph", opset=opset)
    model.initializers = inits
    init_names = set(inits)
    for vname, elem, shape in inputs:
        if vname in init_names:
            continue
        model.inputs.append(TensorInfo(
            vname, shape, _PROTO2DTYPE.get(elem, "float32")))
    for vname, elem, shape in outputs:
        model.outputs.append(TensorInfo(
            vname, (), _PROTO2DTYPE.get(elem, "float32")))
    for op_type, n_in, n_out, attrs, nname in nodes:
        model.nodes.append(NodeIR(op_type, n_in, n_out,
                                  _decode_attrs(op_type, attrs), nname))
    return model


def save_onnx(model: OnnxModel, path, producer="hetu_tpu"):
    """Write a real `.onnx` protobuf file."""
    with open(path, "wb") as f:
        f.write(serialize_model(model, producer=producer))


def load_onnx(path) -> OnnxModel:
    """Read a real `.onnx` protobuf file (any producer)."""
    with open(path, "rb") as f:
        return deserialize_model(f.read())


# -- optional onnx-package object converters -------------------------------

def _require():
    if not HAS_ONNX:
        raise ImportError(
            "the `onnx` package is not installed; serialize_model/"
            "deserialize_model (pure-Python protobuf) cover files, and "
            "ir.save_model/load_model cover the portable zip format")


def to_onnx_proto(model: OnnxModel):
    """OnnxModel -> onnx.ModelProto object (requires the onnx package).
    Parses the pure-Python bytes, so both paths stay consistent."""
    _require()
    proto = onnx.ModelProto()
    proto.ParseFromString(serialize_model(model))
    return proto


def from_onnx_proto(proto) -> OnnxModel:
    """onnx.ModelProto object -> OnnxModel (requires the onnx package)."""
    _require()
    return deserialize_model(proto.SerializeToString())
