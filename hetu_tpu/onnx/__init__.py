"""ONNX bridge (reference: python/hetu/onnx/ — hetu2onnx.py, onnx2hetu.py,
onnx_opset/; see SURVEY.md P20).

* `hetu2onnx(eval_nodes, params)` — graph + trained weights -> OnnxModel
* `onnx2hetu(model)`              — OnnxModel -> (placeholders, outputs)
* `save_model` / `load_model`     — portable zip (works without `onnx`)
* `save_onnx` / `load_onnx`       — REAL `.onnx` protobuf files via the
  pure-Python wire codec (wire.py); no `onnx` package needed
* `serialize_model`/`deserialize_model` — the same, to/from bytes
* `to_onnx_proto`/`from_onnx_proto` — onnx-package objects when available
"""

from .ir import OnnxModel, NodeIR, TensorInfo, save_model, load_model
from .export import hetu2onnx
from .import_ import onnx2hetu
from .proto import (HAS_ONNX, to_onnx_proto, from_onnx_proto,
                    serialize_model, deserialize_model, save_onnx,
                    load_onnx)

__all__ = ["OnnxModel", "NodeIR", "TensorInfo", "save_model", "load_model",
           "hetu2onnx", "onnx2hetu", "HAS_ONNX", "to_onnx_proto",
           "from_onnx_proto", "serialize_model", "deserialize_model",
           "save_onnx", "load_onnx"]
