"""ONNX bridge (reference: python/hetu/onnx/ — hetu2onnx.py, onnx2hetu.py,
onnx_opset/; see SURVEY.md P20).

* `hetu2onnx(eval_nodes, params)` — graph + trained weights -> OnnxModel
* `onnx2hetu(model)`              — OnnxModel -> (placeholders, outputs)
* `save_model` / `load_model`     — portable zip (works without `onnx`)
* `to_onnx_proto`/`from_onnx_proto` — real protobufs when `onnx` is present
  (`HAS_ONNX` flags availability; the build image does not ship it)
"""

from .ir import OnnxModel, NodeIR, TensorInfo, save_model, load_model
from .export import hetu2onnx
from .import_ import onnx2hetu
from .proto import HAS_ONNX, to_onnx_proto, from_onnx_proto

__all__ = ["OnnxModel", "NodeIR", "TensorInfo", "save_model", "load_model",
           "hetu2onnx", "onnx2hetu", "HAS_ONNX", "to_onnx_proto",
           "from_onnx_proto"]
