"""OnnxModel -> hetu_tpu graph (reference: python/hetu/onnx/onnx2hetu.py).

Rebuilds placeholders for graph inputs, Variables for initializers, and our
graph ops for each node.  Tensor inputs that exist only to carry static
config (Reshape shape, Clip bounds, ...) are folded back into op attrs.
"""

from __future__ import annotations

import numpy as np

from ..graph.node import PlaceholderOp, VariableOp
from .. import initializers as init
from .. import ops as O
from .ir import OnnxModel

_IMPORTERS = {}


def importer(*types):
    def deco(fn):
        for t in types:
            _IMPORTERS[t] = fn
        return fn
    return deco


class _Env:
    """Resolution scope: name -> graph Op; folds initializer constants."""

    def __init__(self, model):
        self.model = model
        self.nodes = {}

    def is_const(self, name):
        return name in self.model.initializers

    def const(self, name):
        return np.asarray(self.model.initializers[name])

    def op(self, name):
        if name not in self.nodes:
            if self.is_const(name):
                arr = self.const(name)
                self.nodes[name] = VariableOp(
                    name, arr.shape, init.NumpyInit(arr),
                    trainable=np.issubdtype(arr.dtype, np.floating),
                    dtype=arr.dtype)
            else:
                raise KeyError(f"tensor {name!r} undefined at use site")
        return self.nodes[name]


_BINOPS = {"Add": O.add_op, "Sub": O.sub_op, "Mul": O.mul_op,
           "Div": O.div_op, "MatMul": O.matmul_op, "Max": O.maximum_op,
           "Min": O.minimum_op, "Equal": O.equal_op,
           "Greater": O.greater_op, "Less": O.less_op}
_UNARY = {"Relu": O.relu_op, "Sigmoid": O.sigmoid_op, "Tanh": O.tanh_op,
          "Exp": O.exp_op, "Log": O.log_op, "Sqrt": O.sqrt_op,
          "Abs": O.abs_op, "Sign": O.sign_op, "Floor": O.floor_op,
          "Ceil": O.ceil_op, "Softplus": O.softplus_op,
          "Neg": O.opposite_op, "Reciprocal": O.reciprocal_op,
          "Flatten": O.flatten_op,
          "Identity": lambda x: x, "GlobalAveragePool": O.global_avg_pool2d_op}


@importer(*_BINOPS)
def _binop(node, env):
    a, b = node.inputs[:2]
    # constant operand from a byconst export: fold scalars back
    if env.is_const(b) and env.const(b).ndim == 0 \
            and node.op_type in ("Add", "Mul"):
        c = float(env.const(b))
        return (O.addbyconst_op(env.op(a), const=c)
                if node.op_type == "Add"
                else O.mulbyconst_op(env.op(a), const=c))
    return _BINOPS[node.op_type](env.op(a), env.op(b))


@importer(*_UNARY)
def _unary(node, env):
    return _UNARY[node.op_type](env.op(node.inputs[0]))


@importer("Gelu")
def _gelu(node, env):
    return O.gelu_op(env.op(node.inputs[0]),
                     approximate=node.attrs.get("approximate",
                                                "tanh") == "tanh")


@importer("Pow")
def _pow(node, env):
    return O.pow_op(env.op(node.inputs[0]),
                    exponent=float(env.const(node.inputs[1])))


@importer("Gemm")
def _gemm(node, env):
    x, w = env.op(node.inputs[0]), env.op(node.inputs[1])
    bias = env.op(node.inputs[2]) if len(node.inputs) > 2 else None
    ta = bool(node.attrs.get("transA", 0))
    tb = bool(node.attrs.get("transB", 0))
    if bias is None:
        return O.matmul_op(x, w, trans_A=ta, trans_B=tb)
    return O.linear_op(x, w, bias, trans_A=ta, trans_B=tb)


@importer("Gather")
def _gather(node, env):
    return O.embedding_lookup_op(env.op(node.inputs[0]),
                                 env.op(node.inputs[1]))


@importer("Softmax")
def _softmax(node, env):
    return O.softmax_op(env.op(node.inputs[0]),
                        dim=node.attrs.get("axis", -1))


@importer("LogSoftmax")
def _log_softmax(node, env):
    return O.log_softmax_op(env.op(node.inputs[0]),
                            dim=node.attrs.get("axis", -1))


@importer("Reshape")
def _reshape(node, env):
    shape = tuple(int(v) for v in env.const(node.inputs[1]))
    return O.array_reshape_op(env.op(node.inputs[0]), output_shape=shape)


@importer("Transpose")
def _transpose(node, env):
    return O.transpose_op(env.op(node.inputs[0]),
                          perm=tuple(node.attrs["perm"]))


@importer("Concat")
def _concat(node, env):
    return O.concatenate_op([env.op(i) for i in node.inputs],
                            axis=node.attrs.get("axis", 0))


@importer("Unsqueeze")
def _unsqueeze(node, env):
    axes = [int(v) for v in env.const(node.inputs[1])]
    out = env.op(node.inputs[0])
    for ax in axes:
        out = O.expand_dims_op(out, axis=ax)
    return out


@importer("Slice")
def _slice_imp(node, env):
    """Static Slice (starts/ends/axes from initializers), the form our
    exporter and most inference exporters emit."""
    import jax

    starts = [int(v) for v in env.const(node.inputs[1])]
    ends = [int(v) for v in env.const(node.inputs[2])]
    axes = ([int(v) for v in env.const(node.inputs[3])]
            if len(node.inputs) > 3 else list(range(len(starts))))
    if len(node.inputs) > 4:
        steps = [int(v) for v in env.const(node.inputs[4])]
        if any(s != 1 for s in steps):
            raise NotImplementedError("Slice with step != 1")
    x = env.op(node.inputs[0])

    def body(a, starts=tuple(starts), ends=tuple(ends), axes=tuple(axes)):
        idx = [slice(None)] * a.ndim
        for st, en, ax in zip(starts, ends, axes):
            dim = a.shape[ax]
            en_c = min(en, dim) if en >= 0 else en + dim
            st_c = st if st >= 0 else st + dim
            idx[ax] = slice(st_c, en_c)
        return a[tuple(idx)]

    from ..ops.base import simple_op
    return simple_op(body, "slice_static")(x)


@importer("Squeeze")
def _squeeze(node, env):
    if len(node.inputs) > 1 and node.inputs[1]:
        axes = tuple(int(v) for v in env.const(node.inputs[1]))
        ax = axes[0] if len(axes) == 1 else axes
        return O.squeeze_op(env.op(node.inputs[0]), axis=ax)
    return O.squeeze_op(env.op(node.inputs[0]))


@importer("Conv")
def _conv(node, env):
    pads = list(node.attrs.get("pads", [0, 0, 0, 0]))
    strides = list(node.attrs.get("strides", [1, 1]))
    if pads[:2] != pads[2:]:
        raise NotImplementedError(
            f"asymmetric Conv pads {pads} unsupported ({node.name})")
    x, w = env.op(node.inputs[0]), env.op(node.inputs[1])
    kw = dict(padding=tuple(pads[:2]), stride=tuple(strides),
              groups=node.attrs.get("group", 1))
    if len(node.inputs) > 2:
        return O.conv2d_add_bias_op(x, w, env.op(node.inputs[2]), **kw)
    return O.conv2d_op(x, w, **kw)


@importer("MaxPool", "AveragePool")
def _pool(node, env):
    k = node.attrs["kernel_shape"]
    pads = list(node.attrs.get("pads", [0, 0, 0, 0]))
    strides = list(node.attrs.get("strides", [1, 1]))
    if pads[:2] != pads[2:]:
        raise NotImplementedError(
            f"asymmetric pool pads {pads} unsupported ({node.name})")
    ctor = O.max_pool2d_op if node.op_type == "MaxPool" else O.avg_pool2d_op
    return ctor(env.op(node.inputs[0]), kernel_H=k[0], kernel_W=k[1],
                padding=tuple(pads[:2]), stride=tuple(strides))


@importer("BatchNormalization")
def _bn(node, env):
    x, scale, bias, rmean, rvar = (env.op(i) for i in node.inputs[:5])
    # our BatchNormOp creates running-stat vars itself; rebind them to the
    # imported values by constructing then overwriting the initializers
    op = O.batch_normalization_op(
        x, scale, bias, momentum=1.0 - node.attrs.get("momentum", 0.9),
        eps=node.attrs.get("epsilon", 1e-5))
    if isinstance(rmean, VariableOp):
        op.running_mean.initializer = rmean.initializer
    if isinstance(rvar, VariableOp):
        op.running_var.initializer = rvar.initializer
    return op


@importer("LayerNormalization")
def _ln(node, env):
    return O.layer_normalization_op(
        env.op(node.inputs[0]), env.op(node.inputs[1]),
        env.op(node.inputs[2]), eps=node.attrs.get("epsilon", 1e-5))


@importer("ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin")
def _reduce(node, env):
    ctor = {"ReduceMean": O.reduce_mean_op, "ReduceSum": O.reduce_sum_op,
            "ReduceMax": O.reduce_max_op,
            "ReduceMin": O.reduce_min_op}[node.op_type]
    if len(node.inputs) > 1 and node.inputs[1]:
        axes = tuple(int(v) for v in env.const(node.inputs[1]))
    else:
        axes = node.attrs.get("axes")   # pre-opset-18 models
        axes = tuple(axes) if axes is not None else None
    return ctor(env.op(node.inputs[0]), axes=axes,
                keepdims=bool(node.attrs.get("keepdims", 0)))


@importer("Cast")
def _cast(node, env):
    return O.cast_op(env.op(node.inputs[0]),
                     dtype=np.dtype(node.attrs["to"]))


@importer("Clip")
def _clip(node, env):
    lo = (float(env.const(node.inputs[1]))
          if len(node.inputs) > 1 and node.inputs[1] else None)
    hi = (float(env.const(node.inputs[2]))
          if len(node.inputs) > 2 and node.inputs[2] else None)
    return O.clamp_op(env.op(node.inputs[0]), min=lo, max=hi)


@importer("OneHot")
def _one_hot(node, env):
    depth = int(env.const(node.inputs[1]))
    return O.one_hot_op(env.op(node.inputs[0]), num_classes=depth)


@importer("Tile")
def _tile(node, env):
    return O.tile_op(env.op(node.inputs[0]),
                     reps=tuple(int(v) for v in env.const(node.inputs[1])))


@importer("Dropout")
def _dropout(node, env):
    ratio = (float(env.const(node.inputs[1]))
             if len(node.inputs) > 1 else 0.5)
    return O.dropout_op(env.op(node.inputs[0]), keep_prob=1.0 - ratio)


@importer("Where")
def _where(node, env):
    return O.where_op(*(env.op(i) for i in node.inputs))


def onnx2hetu(model: OnnxModel):
    """Returns (placeholders {name: PlaceholderOp}, outputs [Op])."""
    env = _Env(model)
    placeholders = {}
    for t in model.inputs:
        ph = PlaceholderOp(t.name, t.shape or None, dtype=np.dtype(t.dtype))
        env.nodes[t.name] = ph
        placeholders[t.name] = ph
    for node in model.nodes:
        fn = _IMPORTERS.get(node.op_type)
        if fn is None:
            raise NotImplementedError(
                f"no importer for ONNX op {node.op_type!r} ({node.name})")
        out = fn(node, env)
        env.nodes[node.outputs[0]] = out
    outputs = [env.op(t.name) for t in model.outputs]
    return placeholders, outputs
