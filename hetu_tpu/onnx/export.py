"""hetu_tpu graph -> OnnxModel (reference: python/hetu/onnx/hetu2onnx.py).

Each graph Op kind has a converter emitting ONNX-shaped NodeIR(s).  Variable
values come from an Executor's params (or any {name: array} dict), so the
exported file carries trained weights like the reference's bridge.
"""

from __future__ import annotations

import numpy as np

from ..graph.node import Op, PlaceholderOp, VariableOp, find_topo_sort
from ..ops.base import SimpleOp
from ..ops.nn import BatchNormOp, DropoutOp
from ..ops.attention import ScaledDotProductAttentionOp
from .ir import OnnxModel, NodeIR, TensorInfo

_EXPORTERS = {}


def exporter(*kinds):
    def deco(fn):
        for k in kinds:
            _EXPORTERS[k] = fn
        return fn
    return deco


class _Ctx:
    def __init__(self, model, shapes=None):
        self.model = model
        self.shapes = shapes or {}     # Op -> inferred shape tuple
        self._n = 0

    def aux(self, hint):
        self._n += 1
        return f"{hint}_{self._n}"

    def const(self, hint, value):
        name = self.aux(hint)
        self.model.add_initializer(name, value)
        return name


def _in(node, i):
    return node.inputs[i].name


def _simple(onnx_type, **fixed):
    def fn(node, ctx):
        return [NodeIR(onnx_type, [i.name for i in node.inputs],
                       [node.name], dict(fixed), name=node.name)]
    return fn


for kind, typ in [
        ("add", "Add"), ("minus", "Sub"), ("multiply", "Mul"),
        ("divide", "Div"),
        ("relu", "Relu"), ("sigmoid", "Sigmoid"), ("tanh", "Tanh"),
        ("exp", "Exp"), ("log", "Log"), ("sqrt", "Sqrt"),
        ("abs", "Abs"), ("sign", "Sign"), ("floor", "Floor"),
        ("ceil", "Ceil"), ("softplus", "Softplus"),
        ("opposite", "Neg"), ("reciprocal", "Reciprocal"),
        ("maximum", "Max"), ("minimum", "Min"), ("where", "Where"),
        ("embedding_lookup", "Gather"), ("flatten", "Flatten"),
        ("bool_eq", "Equal"), ("bool_gt", "Greater"), ("bool_lt", "Less"),
        ("stop_gradient", "Identity"), ("zeros_like", "Identity")]:
    _EXPORTERS[kind] = _simple(typ)


@exporter("matmul", "batch_matmul")
def _matmul(node, ctx):
    """MatMul honoring trans_A/trans_B attrs (the tied LM head uses
    h @ table^T): emit explicit Transpose nodes on the transposed side."""
    names = [node.inputs[0].name, node.inputs[1].name]
    out = []
    for slot, key in ((0, "trans_A"), (1, "trans_B")):
        if node.attrs.get(key):
            shp = ctx.shapes.get(node.inputs[slot])
            if shp is None:
                raise NotImplementedError(
                    f"matmul export for {node.name} with {key} needs "
                    "inferable shapes (declare placeholder shapes)")
            ndim = len(shp)
            perm = tuple(range(ndim - 2)) + (ndim - 1, ndim - 2)
            t = ctx.aux(f"{node.name}_t{slot}")
            out.append(NodeIR("Transpose", [names[slot]], [t],
                              {"perm": perm}))
            names[slot] = t
    out.append(NodeIR("MatMul", names, [node.name], name=node.name))
    return out


@exporter("gelu")
def _gelu(node, ctx):
    # Gelu is a standard op from opset 20 (model.opset is 20)
    approx = "tanh" if node.attrs.get("approximate", True) else "none"
    return [NodeIR("Gelu", [_in(node, 0)], [node.name],
                   {"approximate": approx}, name=node.name)]


@exporter("silu")
def _silu(node, ctx):
    # silu(x) = x * sigmoid(x); no standard SiLU op -> decompose
    sig = ctx.aux(f"{node.name}_sig")
    return [NodeIR("Sigmoid", [_in(node, 0)], [sig],
                   name=f"{node.name}_sigmoid"),
            NodeIR("Mul", [_in(node, 0), sig], [node.name],
                   name=node.name)]


@exporter("add_byconst", "mul_byconst")
def _byconst(node, ctx):
    typ = "Add" if node.op_kind == "add_byconst" else "Mul"
    c = ctx.const(f"{node.name}_const",
                  np.asarray(node.attrs["const"], np.float32))
    return [NodeIR(typ, [_in(node, 0), c], [node.name], name=node.name)]


@exporter("pow")
def _pow(node, ctx):
    c = ctx.const(f"{node.name}_exp",
                  np.asarray(node.attrs["exponent"], np.float32))
    return [NodeIR("Pow", [_in(node, 0), c], [node.name], name=node.name)]


@exporter("linear")
def _linear(node, ctx):
    # Gemm(A, B, C): alpha*A@B + beta*C with transA/transB
    return [NodeIR("Gemm", [i.name for i in node.inputs], [node.name],
                   {"alpha": 1.0, "beta": 1.0,
                    "transA": int(bool(node.attrs.get("trans_A", False))),
                    "transB": int(bool(node.attrs.get("trans_B", False)))},
                   name=node.name)]


@exporter("softmax")
def _softmax(node, ctx):
    return [NodeIR("Softmax", [_in(node, 0)], [node.name],
                   {"axis": node.attrs.get("dim", -1)}, name=node.name)]


@exporter("log_softmax")
def _log_softmax(node, ctx):
    return [NodeIR("LogSoftmax", [_in(node, 0)], [node.name],
                   {"axis": node.attrs.get("dim", -1)}, name=node.name)]


@exporter("array_reshape")
def _reshape(node, ctx):
    shape = ctx.const(f"{node.name}_shape",
                      np.asarray(node.attrs["output_shape"], np.int64))
    return [NodeIR("Reshape", [_in(node, 0), shape], [node.name],
                   name=node.name)]


@exporter("transpose")
def _transpose(node, ctx):
    return [NodeIR("Transpose", [_in(node, 0)], [node.name],
                   {"perm": list(node.attrs.get("perm"))}, name=node.name)]


@exporter("concat", "concatenate")
def _concat(node, ctx):
    return [NodeIR("Concat", [i.name for i in node.inputs], [node.name],
                   {"axis": node.attrs.get("axis", 0)}, name=node.name)]


@exporter("expand_dims")
def _unsqueeze(node, ctx):
    ax = node.attrs.get("axis", 0)
    axes = ctx.const(f"{node.name}_axes",
                     np.asarray([ax] if np.isscalar(ax) else list(ax),
                                np.int64))
    return [NodeIR("Unsqueeze", [_in(node, 0), axes], [node.name],
                   name=node.name)]


@exporter("squeeze")
def _squeeze(node, ctx):
    ax = node.attrs.get("axis")
    ins = [_in(node, 0)]
    if ax is not None:
        ins.append(ctx.const(
            f"{node.name}_axes",
            np.asarray([ax] if np.isscalar(ax) else list(ax), np.int64)))
    return [NodeIR("Squeeze", ins, [node.name], name=node.name)]


def _pair(v):
    return (v, v) if np.isscalar(v) else tuple(v)


@exporter("conv2d", "conv2d_add_bias")
def _conv(node, ctx):
    p = _pair(node.attrs.get("padding", 0))
    s = _pair(node.attrs.get("stride", 1))
    return [NodeIR("Conv", [i.name for i in node.inputs], [node.name],
                   {"pads": [p[0], p[1], p[0], p[1]],
                    "strides": list(s),
                    "group": node.attrs.get("groups", 1)},
                   name=node.name)]


@exporter("head_split_linear")
def _head_split_linear(node, ctx):
    # decomposes to MatMul (+Add) + Reshape + Transpose — all standard
    # ONNX ops the importer round-trips
    nh = node.attrs["n_heads"]
    hd = node.attrs["head_dim"]
    seq = node.attrs["seq_len"]
    mm = f"{node.name}_mm"
    nodes = [NodeIR("MatMul", [node.inputs[0].name, node.inputs[1].name],
                    [mm], name=mm)]
    cur = mm
    if len(node.inputs) > 2:
        ad = f"{node.name}_bias"
        nodes.append(NodeIR("Add", [cur, node.inputs[2].name], [ad],
                            name=ad))
        cur = ad
    shp = ctx.const(f"{node.name}_shape",
                    np.asarray([-1, seq, nh, hd], np.int64))
    rs = f"{node.name}_rs"
    nodes.append(NodeIR("Reshape", [cur, shp], [rs], name=rs))
    nodes.append(NodeIR("Transpose", [rs], [node.name],
                        {"perm": [0, 2, 1, 3]}, name=node.name))
    return nodes


@exporter("conv2d_hwio", "conv2d_hwio_add_bias")
def _conv_hwio(node, ctx):
    # layer weights are stored HWIO (TPU-native); ONNX Conv wants OIHW —
    # emit an explicit Transpose on the weight input
    p = _pair(node.attrs.get("padding", 0))
    s = _pair(node.attrs.get("stride", 1))
    wname = node.inputs[1].name
    tname = f"{node.name}_w_oihw"
    tr = NodeIR("Transpose", [wname], [tname], {"perm": [3, 2, 0, 1]},
                name=tname)
    ins = [node.inputs[0].name, tname] + [i.name for i in node.inputs[2:]]
    return [tr, NodeIR("Conv", ins, [node.name],
                       {"pads": [p[0], p[1], p[0], p[1]],
                        "strides": list(s),
                        "group": node.attrs.get("groups", 1)},
                       name=node.name)]


@exporter("max_pool2d", "avg_pool2d")
def _pool(node, ctx):
    typ = "MaxPool" if node.op_kind == "max_pool2d" else "AveragePool"
    p = _pair(node.attrs.get("padding", 0))
    s = _pair(node.attrs.get("stride", 1))
    k = (node.attrs["kernel_H"], node.attrs["kernel_W"])
    return [NodeIR(typ, [_in(node, 0)], [node.name],
                   {"kernel_shape": list(k), "pads": [p[0], p[1], p[0], p[1]],
                    "strides": list(s)}, name=node.name)]


@exporter("global_avg_pool2d")
def _gap(node, ctx):
    if node.attrs.get("channels_last"):
        raise NotImplementedError(
            "ONNX export supports NCHW global_avg_pool2d only; rebuild "
            "the model with channels_last=False for export")
    return [NodeIR("GlobalAveragePool", [_in(node, 0)], [node.name],
                   name=node.name)]


@exporter("layer_normalization")
def _ln(node, ctx):
    return [NodeIR("LayerNormalization", [i.name for i in node.inputs],
                   [node.name], {"epsilon": node.attrs.get("eps", 1e-5),
                                 "axis": -1}, name=node.name)]


@exporter("reduce_mean", "reduce_sum", "reduce_max", "reduce_min")
def _reduce(node, ctx):
    typ = {"reduce_mean": "ReduceMean", "reduce_sum": "ReduceSum",
           "reduce_max": "ReduceMax", "reduce_min": "ReduceMin"}[node.op_kind]
    axes = node.attrs.get("axes")
    attrs = {"keepdims": int(bool(node.attrs.get("keepdims", False)))}
    ins = [_in(node, 0)]
    if axes is not None:
        # opset >= 18: axes are a tensor input for all Reduce* ops
        ins.append(ctx.const(
            f"{node.name}_axes",
            np.asarray([axes] if np.isscalar(axes) else list(axes),
                       np.int64)))
    return [NodeIR(typ, ins, [node.name], attrs, name=node.name)]


@exporter("cast")
def _cast(node, ctx):
    return [NodeIR("Cast", [_in(node, 0)], [node.name],
                   {"to": str(np.dtype(node.attrs.get("dtype", "float32")))},
                   name=node.name)]


@exporter("clamp")
def _clip(node, ctx):
    ins = [_in(node, 0)]
    for key in ("min", "max"):
        v = node.attrs.get(key)
        ins.append(ctx.const(f"{node.name}_{key}",
                             np.asarray(v, np.float32))
                   if v is not None else "")
    return [NodeIR("Clip", ins, [node.name], name=node.name)]


@exporter("one_hot")
def _one_hot(node, ctx):
    depth = ctx.const(f"{node.name}_depth",
                      np.asarray(node.attrs["num_classes"], np.int64))
    values = ctx.const(f"{node.name}_values",
                       np.asarray([0.0, 1.0], np.float32))
    return [NodeIR("OneHot", [_in(node, 0), depth, values], [node.name],
                   {"axis": -1}, name=node.name)]


@exporter("tile")
def _tile(node, ctx):
    reps = ctx.const(f"{node.name}_reps",
                     np.asarray(node.attrs["reps"], np.int64))
    return [NodeIR("Tile", [_in(node, 0), reps], [node.name],
                   name=node.name)]


@exporter("rms_norm")
def _rms_norm_exp(node, ctx):
    """x / sqrt(mean(x^2) + eps) * scale as standard ONNX ops (no RMSNorm
    in mainline opsets), so any consumer — and our importer — runs the
    Llama tier's normalization without custom ops."""
    x, g = _in(node, 0), _in(node, 1)
    eps = float(node.attrs.get("eps", 1e-6))
    sq, mn, ve, sd, nm = (ctx.aux(f"{node.name}_{h}")
                          for h in ("sq", "mean", "vareps", "std", "norm"))
    axes = ctx.const(f"{node.name}_axes", np.asarray([-1], np.int64))
    epsc = ctx.const(f"{node.name}_eps", np.asarray(eps, np.float32))
    return [
        NodeIR("Mul", [x, x], [sq]),
        NodeIR("ReduceMean", [sq, axes], [mn], {"keepdims": 1}),
        NodeIR("Add", [mn, epsc], [ve]),
        NodeIR("Sqrt", [ve], [sd]),
        NodeIR("Div", [x, sd], [nm]),
        NodeIR("Mul", [nm, g], [node.name], name=node.name),
    ]


@exporter("rotary_embedding")
def _rotary_exp(node, ctx):
    """RoPE (HF rotate_half convention) on [B, H, S, D]: the cos/sin
    tables are precomputed constants (shapes are static), the rotation is
    Slice/Neg/Concat/Mul/Add — plain opset ops (ops/rotary.py:33)."""
    shape = ctx.shapes.get(node.inputs[0])
    if shape is None:
        raise NotImplementedError(
            "rotary_embedding export needs inferred shapes "
            "(placeholders must declare shapes)")
    s, d = int(shape[-2]), int(shape[-1])
    theta = float(node.attrs.get("theta", 10000.0))
    off = int(node.attrs.get("pos_offset", 0))
    pos = np.arange(off, off + s, dtype=np.float32)
    inv = 1.0 / (theta ** (np.arange(0, d, 2, dtype=np.float32) / d))
    freqs = np.outer(pos, inv)
    emb = np.concatenate([freqs, freqs], axis=-1)[None, None]   # [1,1,S,D]
    cosc = ctx.const(f"{node.name}_cos", np.cos(emb).astype(np.float32))
    sinc = ctx.const(f"{node.name}_sin", np.sin(emb).astype(np.float32))
    ax = ctx.const(f"{node.name}_ax", np.asarray([-1], np.int64))
    s0 = ctx.const(f"{node.name}_0", np.asarray([0], np.int64))
    sh = ctx.const(f"{node.name}_h", np.asarray([d // 2], np.int64))
    sd_ = ctx.const(f"{node.name}_d", np.asarray([d], np.int64))
    x = _in(node, 0)
    x1, x2, neg, rot, xc, rs = (ctx.aux(f"{node.name}_{h}") for h in
                                ("x1", "x2", "neg", "rot", "xcos", "rsin"))
    return [
        NodeIR("Slice", [x, s0, sh, ax], [x1]),
        NodeIR("Slice", [x, sh, sd_, ax], [x2]),
        NodeIR("Neg", [x2], [neg]),
        NodeIR("Concat", [neg, x1], [rot], {"axis": -1}),
        NodeIR("Mul", [x, cosc], [xc]),
        NodeIR("Mul", [rot, sinc], [rs]),
        NodeIR("Add", [xc, rs], [node.name], name=node.name),
    ]


@exporter("repeat_kv")
def _repeat_kv_exp(node, ctx):
    """GQA K/V head repetition: Reshape → Tile → Reshape (the broadcast
    trick of ops/rotary.py:48 has no ONNX spelling; Tile is the portable
    equivalent)."""
    n = int(node.attrs["n_rep"])
    if n == 1:
        return [NodeIR("Identity", [_in(node, 0)], [node.name],
                       name=node.name)]
    shape = ctx.shapes.get(node.inputs[0])
    if shape is None:
        raise NotImplementedError("repeat_kv export needs inferred shapes")
    b, kv, s, d = (int(v) for v in shape)
    sh5 = ctx.const(f"{node.name}_s5",
                    np.asarray([b, kv, 1, s, d], np.int64))
    reps = ctx.const(f"{node.name}_reps",
                     np.asarray([1, 1, n, 1, 1], np.int64))
    sh4 = ctx.const(f"{node.name}_s4",
                    np.asarray([b, kv * n, s, d], np.int64))
    r5, tl = ctx.aux(f"{node.name}_r5"), ctx.aux(f"{node.name}_tile")
    return [
        NodeIR("Reshape", [_in(node, 0), sh5], [r5]),
        NodeIR("Tile", [r5, reps], [tl]),
        NodeIR("Reshape", [tl, sh4], [node.name], name=node.name),
    ]


@exporter("alibi_bias")
def _alibi_exp(node, ctx):
    """ALiBi additive bias depends only on (num_heads, seq_len), both
    static — exported as a constant initializer (ops/rotary.py:78)."""
    shape = ctx.shapes.get(node.inputs[0])
    if shape is None:
        raise NotImplementedError("alibi_bias export needs inferred shapes")
    s = int(shape[-2])
    nh = int(node.attrs["num_heads"])
    from ..ops.rotary import alibi_slopes
    slopes = np.asarray(alibi_slopes(nh), np.float32)
    rel = (np.arange(s, dtype=np.float32)[None, :]
           - np.arange(s, dtype=np.float32)[:, None])
    bias = (slopes[:, None, None] * rel[None, :, :])[None]   # [1,H,S,S]
    c = ctx.const(f"{node.name}_bias", bias.astype(np.float32))
    return [NodeIR("Identity", [c], [node.name], name=node.name)]


def _export_batchnorm(node, ctx):
    if getattr(node, "channel_axis", 1) not in (1,):
        # ONNX BatchNormalization is channel-axis-1 only; silently
        # exporting a channels-last graph would normalize over H
        raise NotImplementedError(
            "ONNX export supports NCHW BatchNorm only; rebuild the model "
            "with channels_last=False for export")
    return [NodeIR("BatchNormalization", [i.name for i in node.inputs],
                   [node.name],
                   {"epsilon": node.eps, "momentum": 1.0 - node.momentum},
                   name=node.name)]


def _export_dropout(node, ctx):
    ratio = ctx.const(f"{node.name}_ratio",
                      np.asarray(1.0 - node.keep_prob, np.float32))
    return [NodeIR("Dropout", [_in(node, 0), ratio], [node.name],
                   name=node.name)]


def _export_sdpa(node, ctx):
    """ScaledDotProductAttentionOp -> Transpose/MatMul/Mul/Add/Softmax/
    MatMul decomposition (inference export: attention dropout off), the
    same lowering the reference's bridge applies to its attention layers."""
    q, k, v = node.inputs[:3]
    qshape = ctx.shapes.get(q)
    if qshape is None:
        raise NotImplementedError(
            f"attention export for {node.name} needs inferable shapes "
            "(declare placeholder shapes)")
    d = qshape[-1]
    scale = node.scale if node.scale is not None else 1.0 / float(np.sqrt(d))
    out = []
    kt = ctx.aux(f"{node.name}_kT")
    out.append(NodeIR("Transpose", [k.name], [kt], {"perm": (0, 1, 3, 2)}))
    scores = ctx.aux(f"{node.name}_scores")
    out.append(NodeIR("MatMul", [q.name, kt], [scores]))
    cur = ctx.aux(f"{node.name}_scaled")
    out.append(NodeIR("Mul", [scores,
                              ctx.const(f"{node.name}_scale",
                                        np.asarray(scale, np.float32))],
                      [cur]))
    if node.causal:
        s_q = qshape[-2]
        s_k = ctx.shapes.get(k, qshape)[-2]
        causal = np.where(
            np.arange(s_q)[:, None] >= np.arange(s_k)[None, :] - (s_k - s_q),
            0.0, -1e9).astype(np.float32)[None, None]
        nxt = ctx.aux(f"{node.name}_causal")
        out.append(NodeIR("Add", [cur, ctx.const(f"{node.name}_cmask",
                                                 causal)], [nxt]))
        cur = nxt
    if node.has_mask:
        nxt = ctx.aux(f"{node.name}_masked")
        out.append(NodeIR("Add", [cur, node.inputs[3].name], [nxt]))
        cur = nxt
    probs = ctx.aux(f"{node.name}_probs")
    out.append(NodeIR("Softmax", [cur], [probs], {"axis": -1}))
    out.append(NodeIR("MatMul", [probs, v.name], [node.name],
                      name=node.name))
    return out


def _export_position_ids(node, ctx):
    """models.bert.PositionIdsOp: table[None, :S, :] as Slice+Unsqueeze."""
    starts = ctx.const(f"{node.name}_s0", np.asarray([0], np.int64))
    ends = ctx.const(f"{node.name}_s1",
                     np.asarray([node.seq_len], np.int64))
    axes0 = ctx.const(f"{node.name}_ax", np.asarray([0], np.int64))
    sliced = ctx.aux(f"{node.name}_rows")
    return [
        NodeIR("Slice", [_in(node, 0), starts, ends, axes0], [sliced]),
        NodeIR("Unsqueeze", [sliced, axes0], [node.name], name=node.name),
    ]


def _infer_shapes(eval_nodes, params):
    """Abstractly evaluate the graph to get every node's shape (the role
    of the reference's per-op infer_shape pass, Node.py:130).  Returns {}
    when placeholders lack declared shapes."""
    import jax
    import jax.numpy as jnp
    from ..graph.trace import TraceContext, evaluate

    topo = find_topo_sort(list(eval_nodes))
    phs = [n for n in topo if isinstance(n, PlaceholderOp)]
    vars_ = [n for n in topo if isinstance(n, VariableOp)]
    if any(p.shape is None for p in phs):
        return {}
    interior = [n for n in topo
                if not isinstance(n, (PlaceholderOp, VariableOp))]

    def f(feed_vals):
        ctx = TraceContext(key=jax.random.key(0), training=False)
        bindings = dict(zip(phs, feed_vals))
        for vr in vars_:
            bindings[vr] = jnp.zeros(np.shape(params[vr.name]),
                                     np.asarray(params[vr.name]).dtype)
        # _remat=False: shape inference has no backward pass, and remat
        # grouping binds only group OUTPUTS in env — interior nodes would
        # KeyError here
        _, env = evaluate(eval_nodes, bindings, ctx, _remat=False)
        return [env[n] for n in interior]

    feed_structs = [jax.ShapeDtypeStruct(tuple(p.shape), p.dtype)
                    for p in phs]
    try:
        outs = jax.eval_shape(f, feed_structs)
    except Exception:
        return {}
    shapes = {n: tuple(o.shape) for n, o in zip(interior, outs)}
    shapes.update({p: tuple(p.shape) for p in phs})
    shapes.update({vr: tuple(np.shape(params[vr.name])) for vr in vars_})
    return shapes


_NP2ONNX_DTYPE = {"float32": "float32", "float64": "float64",
                  "int32": "int32", "int64": "int64"}


def hetu2onnx(eval_nodes, params, name="hetu_tpu_graph"):
    """Export the graph reaching ``eval_nodes`` to an OnnxModel.

    ``params``: {variable_name: array} (e.g. `Executor.params`) supplying
    initializer values.  Placeholders become graph inputs; ``eval_nodes``
    become graph outputs.
    """
    from ..graph.executor import Executor  # noqa: F401 (doc only)
    model = OnnxModel(name=name)
    ctx = _Ctx(model, shapes=_infer_shapes(eval_nodes, params))
    topo = find_topo_sort(list(eval_nodes))
    for node in topo:
        if isinstance(node, PlaceholderOp):
            model.inputs.append(TensorInfo(
                node.name, tuple(node.shape or ()),
                _NP2ONNX_DTYPE.get(str(node.dtype), "float32")))
        elif isinstance(node, VariableOp):
            if node.name not in params:
                raise KeyError(f"no value for variable {node.name}; pass "
                               f"Executor.params")
            model.add_initializer(node.name, np.asarray(params[node.name]))
        elif isinstance(node, BatchNormOp):
            model.nodes.extend(_export_batchnorm(node, ctx))
        elif isinstance(node, DropoutOp):
            model.nodes.extend(_export_dropout(node, ctx))
        elif isinstance(node, ScaledDotProductAttentionOp):
            model.nodes.extend(_export_sdpa(node, ctx))
        elif type(node).__name__ == "PositionIdsOp":
            model.nodes.extend(_export_position_ids(node, ctx))
        elif isinstance(node, SimpleOp):
            fn = _EXPORTERS.get(node.op_kind)
            if fn is None:
                raise NotImplementedError(
                    f"no ONNX exporter for op kind {node.op_kind!r} "
                    f"(node {node.name})")
            model.nodes.extend(fn(node, ctx))
        else:
            raise NotImplementedError(
                f"no ONNX exporter for {type(node).__name__} ({node.name})")
    for node in eval_nodes:
        model.outputs.append(TensorInfo(node.name, ()))
    return model
