"""Pure-Python protobuf wire codec for the ONNX schema subset.

The build image does not ship the `onnx` package, but real interop needs
real protobuf bytes (the reference round-trips hetu↔onnx↔tensorflow,
tests/onnx/).  Protobuf's wire format is tiny — varint keys, three wire
types — so this module encodes/decodes ONNX `ModelProto` directly from
the public onnx.proto3 field numbers, producing files any ONNX runtime
can read and reading files any exporter produced (for the ops the bridge
supports).

Schema subset (field numbers from onnx/onnx.proto, public):
  ModelProto      : ir_version=1, producer_name=2, producer_version=3,
                    domain=4, model_version=5, doc_string=6, graph=7,
                    opset_import=8
  GraphProto      : node=1, name=2, initializer=5, doc_string=10,
                    input=11, output=12, value_info=13
  NodeProto       : input=1, output=2, name=3, op_type=4, attribute=5,
                    doc_string=6, domain=7
  AttributeProto  : name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
                    strings=9, type=20
  TensorProto     : dims=1, data_type=2, name=8, raw_data=9
  ValueInfoProto  : name=1, type=2
  TypeProto       : tensor_type=1 {elem_type=1, shape=2}
  TensorShapeProto: dim=1 {dim_value=1, dim_param=2}
  OperatorSetId   : domain=1, version=2
"""

from __future__ import annotations

import numpy as np

# -- wire primitives -------------------------------------------------------

_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _enc_varint(v):
    v &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _enc_key(field, wtype):
    return _enc_varint((field << 3) | wtype)


def _enc_int(field, v):
    if v is None:
        return b""
    return _enc_key(field, _VARINT) + _enc_varint(int(v))


def _enc_bytes(field, data):
    return _enc_key(field, _LEN) + _enc_varint(len(data)) + data


def _enc_str(field, s):
    return _enc_bytes(field, s.encode("utf-8")) if s else b""


def _enc_float(field, v):
    return _enc_key(field, _I32) + np.float32(v).tobytes()


def _dec_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed(v):
    """int64 two's-complement reinterpretation of a decoded varint."""
    return v - (1 << 64) if v >= (1 << 63) else v


def iter_fields(buf):
    """Yield (field_number, wire_type, value) over one message's bytes.
    LEN fields yield memoryview payloads; varints yield ints."""
    buf = memoryview(buf)
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = _dec_varint(buf, pos)
        field, wtype = key >> 3, key & 7
        if wtype == _VARINT:
            v, pos = _dec_varint(buf, pos)
        elif wtype == _I64:
            v, pos = bytes(buf[pos:pos + 8]), pos + 8
        elif wtype == _LEN:
            n, pos = _dec_varint(buf, pos)
            v, pos = buf[pos:pos + n], pos + n
        elif wtype == _I32:
            v, pos = bytes(buf[pos:pos + 4]), pos + 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield field, wtype, v


# -- ONNX dtype enum -------------------------------------------------------

DTYPE_TO_ONNX = {
    "float32": 1, "uint8": 2, "int8": 3, "uint16": 4, "int16": 5,
    "int32": 6, "int64": 7, "bool": 9, "float16": 10, "float64": 11,
    "uint32": 12, "uint64": 13, "bfloat16": 16,
}
ONNX_TO_DTYPE = {v: k for k, v in DTYPE_TO_ONNX.items()}


# -- encoders --------------------------------------------------------------

def enc_tensor(name, arr):
    arr = np.asarray(arr)
    dt = DTYPE_TO_ONNX[str(arr.dtype)]
    out = b"".join(_enc_int(1, d) for d in arr.shape)
    out += _enc_int(2, dt)
    out += _enc_str(8, name)
    raw = arr.astype(arr.dtype.newbyteorder("<")).tobytes()
    out += _enc_bytes(9, raw)
    return out


def enc_attribute(name, value):
    out = _enc_str(1, name)
    if isinstance(value, np.ndarray):
        out += _enc_bytes(5, enc_tensor("", value)) + _enc_int(20, 4)
    elif isinstance(value, bool) or isinstance(value, (int, np.integer)):
        out += _enc_int(3, int(value)) + _enc_int(20, 2)
    elif isinstance(value, (float, np.floating)):
        out += _enc_float(2, value) + _enc_int(20, 1)
    elif isinstance(value, str):
        out += _enc_bytes(4, value.encode()) + _enc_int(20, 3)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, str) for v in value):
            out += b"".join(_enc_bytes(9, v.encode()) for v in value)
            out += _enc_int(20, 8)
        elif any(isinstance(v, (float, np.floating)) for v in value):
            out += b"".join(_enc_key(7, _I32) + np.float32(v).tobytes()
                            for v in value)
            out += _enc_int(20, 6)
        else:
            out += b"".join(_enc_int(8, int(v)) for v in value)
            out += _enc_int(20, 7)
    else:
        raise TypeError(f"attribute {name}: unsupported {type(value)}")
    return out


def enc_node(op_type, inputs, outputs, attrs, name=""):
    out = b"".join(_enc_str(1, i) for i in inputs)
    out += b"".join(_enc_str(2, o) for o in outputs)
    out += _enc_str(3, name) + _enc_str(4, op_type)
    out += b"".join(_enc_bytes(5, enc_attribute(k, v))
                    for k, v in attrs.items())
    return out


def enc_value_info(name, elem_type, shape):
    shape_msg = b""
    if shape:
        for d in shape:
            if d is None or (isinstance(d, int) and d < 0):
                dim = _enc_str(2, "N")
            else:
                dim = _enc_int(1, int(d))
            shape_msg += _enc_bytes(1, dim)
    tensor_type = _enc_int(1, elem_type)
    if shape_msg or shape == ():
        tensor_type += _enc_bytes(2, shape_msg)
    type_proto = _enc_bytes(1, tensor_type)
    return _enc_str(1, name) + _enc_bytes(2, type_proto)


def enc_graph(model):
    out = b""
    for n in model.nodes:
        attrs = {k: (tuple(v) if isinstance(v, list) else v)
                 for k, v in n.attrs.items()}
        out += _enc_bytes(1, enc_node(n.op_type, n.inputs, n.outputs,
                                      attrs, n.name))
    out += _enc_str(2, model.name)
    for name, arr in model.initializers.items():
        out += _enc_bytes(5, enc_tensor(name, arr))
    for t in model.inputs:
        out += _enc_bytes(11, enc_value_info(
            t.name, DTYPE_TO_ONNX.get(t.dtype, 1), tuple(t.shape)))
    for t in model.outputs:
        out += _enc_bytes(12, enc_value_info(
            t.name, DTYPE_TO_ONNX.get(t.dtype, 1), None))
    return out


def enc_model(model, producer="hetu_tpu"):
    out = _enc_int(1, 10)                      # ir_version 10 (onnx 1.16)
    out += _enc_str(2, producer)
    out += _enc_bytes(7, enc_graph(model))
    opset = _enc_str(1, "") + _enc_int(2, model.opset)
    out += _enc_bytes(8, opset)
    return out


# -- decoders --------------------------------------------------------------

def _varints(mv):
    """All varints in a packed LEN payload."""
    out, pos = [], 0
    mv = memoryview(mv)
    while pos < len(mv):
        x, pos = _dec_varint(mv, pos)
        out.append(_signed(x))
    return out


def dec_tensor(buf):
    dims, dt, name, raw = [], 1, "", b""
    data_fields = {}
    for field, wtype, v in iter_fields(buf):
        if field == 1:
            # proto3 packs repeated scalars by default (external files);
            # our encoder emits them unpacked — accept both
            if wtype == _LEN:
                dims.extend(_varints(v))
            else:
                dims.append(_signed(v))
        elif field == 2:
            dt = v
        elif field == 8:
            name = bytes(v).decode()
        elif field == 9:
            raw = bytes(v)
        elif field in (4, 5, 7, 10):
            data_fields.setdefault(field, []).append((wtype, v))
    dtype = np.dtype(ONNX_TO_DTYPE.get(dt, "float32"))
    if raw:
        arr = np.frombuffer(raw, dtype=dtype.newbyteorder("<"))
        arr = arr.astype(dtype).reshape(dims)
    elif data_fields:
        # packed or repeated typed data (other exporters may use these)
        field, entries = next(iter(data_fields.items()))
        vals = []
        kind = {4: np.float32, 5: np.int32, 7: np.int64,
                10: np.float64}[field]
        for wtype, v in entries:
            if wtype == _LEN:                      # packed
                if kind in (np.float32,):
                    vals.extend(np.frombuffer(bytes(v), "<f4"))
                elif kind is np.float64:
                    vals.extend(np.frombuffer(bytes(v), "<f8"))
                else:
                    vals.extend(_varints(v))
            elif wtype == _I32:
                vals.append(np.frombuffer(v, "<f4")[0])
            elif wtype == _I64:
                vals.append(np.frombuffer(v, "<f8")[0])
            else:
                vals.append(_signed(v))
        arr = np.asarray(vals, kind).astype(dtype).reshape(dims)
    else:
        arr = np.zeros(dims, dtype)
    return name, arr


def dec_attribute(buf):
    name, atype = "", None
    f = i = s = t = None
    floats, ints, strings = [], [], []
    for field, wtype, v in iter_fields(buf):
        if field == 1:
            name = bytes(v).decode()
        elif field == 2:
            f = float(np.frombuffer(v, "<f4")[0])
        elif field == 3:
            i = _signed(v)
        elif field == 4:
            s = bytes(v).decode()
        elif field == 5:
            t = dec_tensor(v)[1]
        elif field == 7:
            if wtype == _LEN:
                floats.extend(float(x)
                              for x in np.frombuffer(bytes(v), "<f4"))
            else:
                floats.append(float(np.frombuffer(v, "<f4")[0]))
        elif field == 8:
            if wtype == _LEN:
                ints.extend(_varints(v))
            else:
                ints.append(_signed(v))
        elif field == 9:
            strings.append(bytes(v).decode())
        elif field == 20:
            atype = v
    # proto3 omits zero scalars on the wire: when the declared type says
    # scalar but no value field arrived, the value IS the proto default
    # (0 / 0.0 / "") — not an empty tuple
    by_type = {1: f if f is not None else 0.0,
               2: i if i is not None else 0,
               3: s if s is not None else "",
               4: t,
               6: tuple(floats), 7: tuple(ints), 8: tuple(strings)}
    if atype in by_type and by_type[atype] is not None:
        return name, by_type[atype]
    for v in (t, s, f, i):
        if v is not None:
            return name, v
    if floats:
        return name, tuple(floats)
    if strings:
        return name, tuple(strings)
    return name, tuple(ints)


def dec_node(buf):
    inputs, outputs, attrs = [], [], {}
    name = op_type = ""
    for field, wtype, v in iter_fields(buf):
        if field == 1:
            inputs.append(bytes(v).decode())
        elif field == 2:
            outputs.append(bytes(v).decode())
        elif field == 3:
            name = bytes(v).decode()
        elif field == 4:
            op_type = bytes(v).decode()
        elif field == 5:
            k, val = dec_attribute(v)
            attrs[k] = val
    return op_type, inputs, outputs, attrs, name


def dec_value_info(buf):
    name, elem_type, shape = "", 1, []
    for field, wtype, v in iter_fields(buf):
        if field == 1:
            name = bytes(v).decode()
        elif field == 2:
            for f2, _, v2 in iter_fields(v):
                if f2 == 1:                         # tensor_type
                    for f3, _, v3 in iter_fields(v2):
                        if f3 == 1:
                            elem_type = v3
                        elif f3 == 2:               # shape
                            for f4, _, v4 in iter_fields(v3):
                                if f4 == 1:         # dim
                                    dv = None       # dim_param -> dynamic
                                    for f5, _, v5 in iter_fields(v4):
                                        if f5 == 1:
                                            dv = _signed(v5)
                                    shape.append(dv)
    return name, elem_type, tuple(shape)


def dec_graph(buf):
    nodes, inits, inputs, outputs = [], {}, [], []
    name = ""
    for field, wtype, v in iter_fields(buf):
        if field == 1:
            nodes.append(dec_node(v))
        elif field == 2:
            name = bytes(v).decode()
        elif field == 5:
            n, arr = dec_tensor(v)
            inits[n] = arr
        elif field == 11:
            inputs.append(dec_value_info(v))
        elif field == 12:
            outputs.append(dec_value_info(v))
    return name, nodes, inits, inputs, outputs


def dec_model(buf):
    graph = None
    opset = 20
    for field, wtype, v in iter_fields(buf):
        if field == 7:
            graph = v
        elif field == 8:
            domain, version = "", None
            for f2, _, v2 in iter_fields(v):
                if f2 == 1:
                    domain = bytes(v2).decode()
                elif f2 == 2:
                    version = _signed(v2)
            # only the default ai.onnx domain sets the model opset —
            # com.microsoft etc. entries must not clobber it
            if version is not None and domain in ("", "ai.onnx"):
                opset = version
    if graph is None:
        raise ValueError("ModelProto has no graph")
    return dec_graph(graph), opset
