"""BERT WordPiece tokenizer (reference: python/hetu/tokenizers/
bert_tokenizer.py — vocab-file driven basic+wordpiece tokenization feeding
the BERT example pipeline).

Fresh implementation of the standard WordPiece scheme: whitespace/punct
basic tokenization (with optional lowercasing + accent stripping), then
greedy longest-match-first subword splitting with '##' continuations.
"""

from __future__ import annotations

import collections
import unicodedata


def load_vocab(vocab_file):
    vocab = collections.OrderedDict()
    with open(vocab_file, encoding="utf-8") as f:
        for i, line in enumerate(f):
            tok = line.strip()
            if tok:
                vocab[tok] = i
    return vocab


# ---------------------------------------------------------------------------
# Named-vocabulary registry (reference: python/hetu/tokenizers/
# bert_tokenizer.py:11-29 PRETRAINED_VOCAB_ARCHIVE_MAP + cached_path).
# The reference resolves well-known names to S3 URLs with a download
# cache; this environment has no egress, so the registry resolves names
# to LOCAL files instead: explicit `register_vocab` calls, a
# `HETU_VOCAB_DIR` directory of `<name>-vocab.txt` / `<name>/vocab.txt`
# files, and the ~/.cache/hetu_tpu/vocabs default cache dir.  The
# per-name tokenizer defaults (casing, positional size) ARE carried over
# — they are part of the public BERT contract, not code.

PRETRAINED_VOCAB_NAMES = (
    "bert-base-uncased", "bert-large-uncased", "bert-base-cased",
    "bert-large-cased", "bert-base-multilingual-uncased",
    "bert-base-multilingual-cased", "bert-base-chinese")

# every public BERT vocab pairs with 512 positions; only the "uncased"
# variants lowercase (bert-base-chinese's published config keeps case)
PRETRAINED_DEFAULTS = {
    name: {"max_len": 512, "do_lower_case": "uncased" in name}
    for name in PRETRAINED_VOCAB_NAMES}

_REGISTRY = {}


def register_vocab(name, path):
    """Map a vocabulary name to a local vocab.txt path (no network)."""
    _REGISTRY[name] = path


def _vocab_search_dirs():
    import os
    dirs = []
    env = os.environ.get("HETU_VOCAB_DIR")
    if env:
        dirs.extend(env.split(os.pathsep))
    dirs.append(os.path.join(os.path.expanduser("~"), ".cache",
                             "hetu_tpu", "vocabs"))
    return dirs


def resolve_vocab(name_or_path):
    """Resolve a vocab NAME (e.g. 'bert-base-uncased') or file path to a
    local vocab file.  Resolution order: existing path > register_vocab
    entries > HETU_VOCAB_DIR / default cache dir (``<name>-vocab.txt``,
    ``<name>.txt`` or ``<name>/vocab.txt``)."""
    import os
    if os.path.isfile(name_or_path):
        return name_or_path
    if name_or_path in _REGISTRY:
        return _REGISTRY[name_or_path]
    for d in _vocab_search_dirs():
        for cand in (os.path.join(d, name_or_path + "-vocab.txt"),
                     os.path.join(d, name_or_path + ".txt"),
                     os.path.join(d, name_or_path, "vocab.txt")):
            if os.path.isfile(cand):
                return cand
    known = ", ".join(sorted(set(list(_REGISTRY)
                                 + list(PRETRAINED_VOCAB_NAMES))))
    raise FileNotFoundError(
        f"vocabulary {name_or_path!r} is neither a file nor a registered "
        f"name; register_vocab() it, or drop <name>-vocab.txt under "
        f"$HETU_VOCAB_DIR or ~/.cache/hetu_tpu/vocabs (known names: "
        f"{known})")


def _is_whitespace(ch):
    return ch in (" ", "\t", "\n", "\r") or unicodedata.category(ch) == "Zs"


def _is_control(ch):
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch):
    cp = ord(ch)
    if ((33 <= cp <= 47) or (58 <= cp <= 64)
            or (91 <= cp <= 96) or (123 <= cp <= 126)):
        return True
    return unicodedata.category(ch).startswith("P")


class BasicTokenizer:
    """Whitespace + punctuation splitting, lowercasing, accent stripping,
    CJK char isolation."""

    def __init__(self, do_lower_case=True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text):
        text = self._clean(text)
        text = self._tokenize_cjk(text)
        tokens = []
        for tok in text.strip().split():
            if self.do_lower_case:
                tok = tok.lower()
                tok = self._strip_accents(tok)
            tokens.extend(self._split_punct(tok))
        return [t for t in tokens if t]

    @staticmethod
    def _clean(text):
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            out.append(" " if _is_whitespace(ch) else ch)
        return "".join(out)

    @staticmethod
    def _strip_accents(text):
        return "".join(ch for ch in unicodedata.normalize("NFD", text)
                       if unicodedata.category(ch) != "Mn")

    @staticmethod
    def _split_punct(tok):
        out = [[]]
        for ch in tok:
            if _is_punctuation(ch):
                out.append([ch])
                out.append([])
            else:
                out[-1].append(ch)
        return ["".join(p) for p in out if p]

    @staticmethod
    def _is_cjk(cp):
        return ((0x4E00 <= cp <= 0x9FFF) or (0x3400 <= cp <= 0x4DBF)
                or (0x20000 <= cp <= 0x2A6DF) or (0x2A700 <= cp <= 0x2B73F)
                or (0x2B740 <= cp <= 0x2B81F) or (0x2B820 <= cp <= 0x2CEAF)
                or (0xF900 <= cp <= 0xFAFF) or (0x2F800 <= cp <= 0x2FA1F))

    def _tokenize_cjk(self, text):
        out = []
        for ch in text:
            if self._is_cjk(ord(ch)):
                out.extend([" ", ch, " "])
            else:
                out.append(ch)
        return "".join(out)


class WordpieceTokenizer:
    """Greedy longest-match-first subword splitting."""

    def __init__(self, vocab, unk_token="[UNK]", max_chars_per_word=100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars_per_word = max_chars_per_word

    def tokenize(self, text):
        out = []
        for token in text.strip().split():
            if len(token) > self.max_chars_per_word:
                out.append(self.unk_token)
                continue
            start = 0
            pieces = []
            bad = False
            while start < len(token):
                end = len(token)
                cur = None
                while start < end:
                    piece = token[start:end]
                    if start > 0:
                        piece = "##" + piece
                    if piece in self.vocab:
                        cur = piece
                        break
                    end -= 1
                if cur is None:
                    bad = True
                    break
                pieces.append(cur)
                start = end
            out.extend([self.unk_token] if bad else pieces)
        return out


class BertTokenizer:
    """Full pipeline: basic → wordpiece, id conversion, pair encoding with
    special tokens and padding (the surface the BERT examples use)."""

    def __init__(self, vocab_file=None, vocab=None, do_lower_case=True,
                 max_len=512, unk_token="[UNK]", cls_token="[CLS]",
                 sep_token="[SEP]", pad_token="[PAD]", mask_token="[MASK]"):
        if vocab is None:
            assert vocab_file is not None, "need vocab_file or vocab"
            vocab = load_vocab(vocab_file)
        self.vocab = dict(vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(self.vocab, unk_token)
        self.max_len = max_len
        self.unk_token, self.cls_token = unk_token, cls_token
        self.sep_token, self.pad_token = sep_token, pad_token
        self.mask_token = mask_token

    @classmethod
    def from_pretrained(cls, name_or_path, **kw):
        """Build a tokenizer from a vocab NAME or file path (reference:
        bert_tokenizer.py from_pretrained — minus the download; names
        resolve locally via `resolve_vocab`).  Known names contribute
        their casing/max_len defaults unless overridden."""
        defaults = dict(PRETRAINED_DEFAULTS.get(name_or_path, {}))
        defaults.update(kw)
        return cls(vocab_file=resolve_vocab(name_or_path), **defaults)

    @classmethod
    def from_vocab_list(cls, words, **kw):
        specials = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
        vocab = {t: i for i, t in enumerate(
            specials + [w for w in words if w not in specials])}
        return cls(vocab=vocab, **kw)

    def tokenize(self, text):
        out = []
        for tok in self.basic.tokenize(text):
            out.extend(self.wordpiece.tokenize(tok))
        return out

    def convert_tokens_to_ids(self, tokens):
        unk = self.vocab[self.unk_token]
        return [self.vocab.get(t, unk) for t in tokens]

    def convert_ids_to_tokens(self, ids):
        return [self.inv_vocab.get(int(i), self.unk_token) for i in ids]

    def encode(self, text_a, text_b=None, max_len=None, pad=True):
        """Returns (input_ids, token_type_ids, attention_mask) lists."""
        max_len = max_len or self.max_len
        ta = self.tokenize(text_a)
        tb = self.tokenize(text_b) if text_b is not None else None
        # truncate longest-first to fit specials
        budget = max_len - 2 - (1 if tb is not None else 0)
        if tb is None:
            ta = ta[:budget]
        else:
            while len(ta) + len(tb) > budget:
                (ta if len(ta) >= len(tb) else tb).pop()
        tokens = [self.cls_token] + ta + [self.sep_token]
        types = [0] * len(tokens)
        if tb is not None:
            tokens += tb + [self.sep_token]
            types += [1] * (len(tb) + 1)
        ids = self.convert_tokens_to_ids(tokens)
        mask = [1] * len(ids)
        if pad:
            pad_id = self.vocab[self.pad_token]
            while len(ids) < max_len:
                ids.append(pad_id)
                types.append(0)
                mask.append(0)
        return ids, types, mask

    def decode(self, ids, skip_special=True):
        toks = self.convert_ids_to_tokens(ids)
        specials = {self.cls_token, self.sep_token, self.pad_token}
        out = []
        for t in toks:
            if skip_special and t in specials:
                continue
            if t.startswith("##") and out:
                out[-1] += t[2:]
            else:
                out.append(t)
        return " ".join(out)
