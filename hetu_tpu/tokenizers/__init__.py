from .bert_tokenizer import (BasicTokenizer, WordpieceTokenizer,
                             BertTokenizer, register_vocab, resolve_vocab,
                             PRETRAINED_VOCAB_NAMES)

__all__ = ["BasicTokenizer", "WordpieceTokenizer", "BertTokenizer",
           "register_vocab", "resolve_vocab", "PRETRAINED_VOCAB_NAMES"]
