from .bert_tokenizer import (BasicTokenizer, WordpieceTokenizer,
                             BertTokenizer)

__all__ = ["BasicTokenizer", "WordpieceTokenizer", "BertTokenizer"]
