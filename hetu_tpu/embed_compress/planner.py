"""Compression planning: memory-budget sizing + stage-transition exports.

The reference wraps each method in an `EmbeddingTrainer` scheduler
(tools/EmbeddingMemoryCompression/methods/scheduler/*) that (a) solves the
method's hyper-parameters from a target ``compress_rate`` and (b) converts
search-phase state into retrain-phase layers.  Here those two jobs are plain
numpy functions, decoupled from the training loop.
"""

from __future__ import annotations

import math

import numpy as np


def binary_search(lo, hi, evaluate, tol=1e-3, iters=200):
    """Find x in [lo, hi] with evaluate(x) ~ 0 (evaluate monotone increasing);
    returns (lo, hi) bracket (reference scheduler/base.py binary_search)."""
    elo, ehi = evaluate(lo), evaluate(hi)
    if elo >= 0:
        return lo, lo
    if ehi <= 0:
        return hi, hi
    for _ in range(iters):
        if hi - lo < tol:
            break
        mid = (lo + hi) / 2
        if evaluate(mid) < 0:
            lo = mid
        else:
            hi = mid
    return lo, hi


# -- sizing ---------------------------------------------------------------

def hash_rows(num_embed, compress_rate):
    """HashEmb: rows of the shared table (scheduler/hash.py)."""
    return math.ceil(num_embed * compress_rate)


def qr_sizes(num_embed, compress_rate):
    """Compositional QR: (num_quotient, num_remainder) such that
    Q + R ~ num_embed * rate with R the collision divisor
    (scheduler/compo.py: memory(x) = ceil(n/x) + x)."""
    target = num_embed * compress_rate

    # memory(x) = ceil(n/x) + x decreases on [1, sqrt(n)], so
    # target - memory(x) is increasing there
    def evaluate(x):
        return target - (math.ceil(num_embed / x) + x)

    lo, _ = binary_search(1, math.sqrt(num_embed) + 1, evaluate)
    collision = max(1, math.ceil(lo))
    return math.ceil(num_embed / collision), collision


def tt_decomp_dims(embedding_dim):
    """Factor the embedding dim into 3 near-equal factors; powers of two get
    the reference's halving scheme (scheduler/tensortrain.py:_get_decomp_dim)."""
    d = embedding_dim
    if d & (d - 1) == 0:
        assert d >= 8
        decomp = [2, 2, 2]
        idx = 2
        d //= 8
        while d != 1:
            decomp[idx] *= 2
            d //= 2
            idx = (idx - 1) % 3
        return decomp
    n1 = math.ceil(d ** (1 / 3))
    while d % n1 != 0:
        n1 -= 1
    rest = d // n1
    n2 = math.ceil(rest ** 0.5)
    while rest % n2 != 0:
        n2 -= 1
    return sorted([n1, n2, rest // n2])


def tt_decomp_rows(num_embed):
    """3-way row decomposition (largest last, reference _get_decomp_emb)."""
    n1 = math.ceil(num_embed ** (1 / 3))
    n2 = math.ceil((num_embed / n1) ** 0.5)
    n3 = math.ceil(num_embed / n1 / n2)
    return [n3, n2, n1]


def tt_rank(num_embed, embedding_dim, compress_rate,
            decomp_rows=None, decomp_dims=None):
    """Largest rank whose TT memory fits num_embed*dim*rate."""
    rows = decomp_rows or tt_decomp_rows(num_embed)
    dims = decomp_dims or tt_decomp_dims(embedding_dim)
    target = num_embed * embedding_dim * compress_rate

    def memory(r):
        return (rows[0] * dims[0] + rows[1] * dims[1] * r
                + rows[2] * dims[2]) * r

    lo, _ = binary_search(0, 1000, lambda r: memory(r) - target)
    rank = max(1, math.floor(lo))
    if memory(rank) > target and rank > 1:
        rank -= 1
    return rank


def robe_size(num_embed, embedding_dim, compress_rate):
    return math.ceil(num_embed * embedding_dim * compress_rate)


def dhe_mlp_dim(num_embed, embedding_dim, compress_rate, num_hash):
    """Solve the MLP width m from the memory budget: params(m) =
    num_hash*m + 4*m^2 + m*dim + biases/BN ~ 4m^2 + (num_hash+dim+11)m
    (5 hidden layers as in layers/dhe.py)."""
    budget = num_embed * embedding_dim * compress_rate
    a, b, c = 4.0, num_hash + embedding_dim + 11.0, -float(budget)
    m = (-b + math.sqrt(b * b - 4 * a * c)) / (2 * a)
    return max(8, int(m))


def md_solver(num_embed_fields, embedding_dim, alpha, round_dim=True):
    """Mixed-dim rule d_f = lamb * n_f^-alpha with the largest field pinned
    to embedding_dim (reference scheduler/md.py:_md_solver)."""
    n = np.asarray(sorted(num_embed_fields), dtype=np.float64)
    lamb = embedding_dim * (n[0] ** alpha)
    d = lamb * (n ** -alpha)
    if round_dim:
        d = 2 ** np.round(np.log2(d))
    d = np.clip(d, 1, embedding_dim).astype(np.int64)
    order = np.argsort(np.argsort(num_embed_fields))
    return d[order]  # back to input field order


def md_dims(num_embed_fields, embedding_dim, compress_rate, round_dim=True):
    """Binary-search alpha to hit the compress_rate (scheduler/md.py)."""
    num_embed = sum(num_embed_fields)
    target = num_embed * embedding_dim * compress_rate

    def memory(alpha):
        dims = md_solver(num_embed_fields, embedding_dim, alpha, round_dim)
        return sum(ne * nd + nd * embedding_dim * (nd != embedding_dim)
                   for ne, nd in zip(num_embed_fields, dims))

    lo, hi = binary_search(0.0, 1.0, lambda a: target - memory(a))
    dims = md_solver(num_embed_fields, embedding_dim, lo, round_dim)
    if memory(lo) > target * (1 + 1e-3):
        dims = md_solver(num_embed_fields, embedding_dim, hi, round_dim)
    return list(dims)


def adapt_remap(frequencies, top_percent):
    """AdaEmbed remap from id frequency counts: top ids (by count) get dense
    indices 0..nfreq-1; the rest get -(rank+1) (consumed by
    mod_hash_negative).  Returns (remap[int32], nfreq)."""
    freq = np.asarray(frequencies)
    nemb = freq.shape[0]
    nfreq = math.ceil(nemb * top_percent)
    order = np.argsort(-freq, kind="stable")
    remap = np.empty((nemb,), np.int32)
    remap[order[:nfreq]] = np.arange(nfreq, dtype=np.int32)
    nrare_ids = nemb - nfreq
    remap[order[nfreq:]] = -(np.arange(nrare_ids, dtype=np.int32) + 1)
    return remap, nfreq


def adapt_sizes(num_embed, compress_rate, nfreq):
    """nrare rows from the leftover budget (scheduler/adapt.py)."""
    nrare = math.ceil(num_embed * compress_rate) - nfreq
    assert nrare > 0, "top_percent must be < compress_rate"
    return nrare


def autosrh_group_indices(frequencies, nsplit):
    """Group ids into nsplit frequency tiers (equal-size by rank)."""
    freq = np.asarray(frequencies)
    order = np.argsort(-freq, kind="stable")
    group = np.empty(freq.shape[0], np.int32)
    per = math.ceil(freq.shape[0] / nsplit)
    for g in range(nsplit):
        group[order[g * per:(g + 1) * per]] = g
    return group


# -- stage-transition exports --------------------------------------------

def autodim_choose(alpha, dim_candidates):
    """Per-slot dim choice = argmax alpha (scheduler/autodim.py retrain)."""
    cands = sorted(dim_candidates)
    return [cands[i] for i in np.argmax(np.asarray(alpha), axis=1)]


def pep_export_mask(table, threshold, threshold_type):
    """Binary mask |w| > sigmoid(th) for PEPRetrainEmbedding."""
    table = np.asarray(table)
    th = 1.0 / (1.0 + np.exp(-np.asarray(threshold, np.float64)))
    if threshold_type == "dimension":
        th = th.reshape(1, -1)
    elif threshold_type == "global":
        th = th.reshape(1, 1)
    return (np.abs(table) > th).astype(np.float32)


def optembed_row_prune(table, threshold, field_of_row):
    """Rows surviving |row|_1 > sigmoid-free threshold of their field;
    returns (remap[-1 for pruned], kept_rows index array)."""
    table = np.asarray(table)
    th = np.asarray(threshold).reshape(-1)[np.asarray(field_of_row)]
    keep = np.abs(table).sum(1) > th
    remap = np.full((table.shape[0],), -1, np.int32)
    remap[keep] = np.arange(int(keep.sum()), dtype=np.int32)
    return remap, np.nonzero(keep)[0]


def evolutionary_dim_search(fitness, num_slot, embedding_dim, rng,
                            population=20, generations=10, keep=5,
                            mutate_prob=0.1):
    """OptEmbed-style evolutionary search over per-field dim *candidates*:
    maximize ``fitness(candidates)``.  A candidate c in [0, embedding_dim)
    keeps dims 0..c — the exact index space the OptEmbedding supernet
    samples (RandintSampleOp low=0, high=D) and that
    OptEmbeddingAfterRowPruning consumes as mask-table rows."""
    pop = [rng.integers(0, embedding_dim, size=(num_slot,))
           for _ in range(population)]
    scored = [(fitness(p), p) for p in pop]
    for _ in range(generations):
        scored.sort(key=lambda t: -t[0])
        parents = [p for _, p in scored[:keep]]
        children = []
        while len(children) < population - keep:
            a, b = (parents[rng.integers(len(parents))] for _ in range(2))
            cross = np.where(rng.random(num_slot) < 0.5, a, b)
            mut = rng.random(num_slot) < mutate_prob
            cross = np.where(mut, rng.integers(0, embedding_dim,
                                               size=(num_slot,)), cross)
            children.append(cross)
        scored = scored[:keep] + [(fitness(c), c) for c in children]
    scored.sort(key=lambda t: -t[0])
    return scored[0][1]


def dedup_build(table, nemb_per_block, grid):
    """Block-level dedup of a trained table: consecutive groups of
    ``nemb_per_block`` rows form a block; blocks equal after rounding to
    ``grid`` share storage.  Returns (unique_block_rows, remap) for
    DedupEmbedding (reference scheduler/deduplication.py uses an LSH match;
    the rounding grid plays the similarity-threshold role)."""
    table = np.asarray(table, np.float32)
    nemb, dim = table.shape
    nblocks = math.ceil(nemb / nemb_per_block)
    pad = nblocks * nemb_per_block - nemb
    if pad:
        table = np.concatenate([table, np.zeros((pad, dim), np.float32)])
    blocks = table.reshape(nblocks, nemb_per_block * dim)
    keys = np.round(blocks / grid).astype(np.int64)
    _, first, inverse = np.unique(keys, axis=0, return_index=True,
                                  return_inverse=True)
    uniq_rows = np.concatenate(
        [blocks[i].reshape(nemb_per_block, dim) for i in first])
    # remap old block id -> position of its representative block
    remap = np.empty(nblocks, np.int32)
    remap[:] = inverse
    return uniq_rows, remap
