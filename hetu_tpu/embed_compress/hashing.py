"""Hash ops for compressed embeddings.

TPU-native equivalents of the reference hash kernels in
src/ops/CompressedEmbedding.cu (robe_hash_kernel :3, robe_sign_kernel :27,
mod_hash_kernel :50, mod_hash_negative_kernel :58, div_hash_kernel :72,
compo_hash_kernel :80, learn_hash_kernel :93) and their graph ops in
python/hetu/gpu_ops/CompressedEmbedding.py.  Each is a pure jnp int
composition that XLA fuses straight into the surrounding gather.

Arithmetic note: the reference computes the universal hashes in int64.  JAX
on TPU defaults to 32-bit ints, so our hashes are DEFINED over int32
wraparound arithmetic ((a*x + b) mod 2^32 mod P mod M) — deterministic,
well-mixed, and fast on the VPU, but numerically different from the CUDA
kernels.  `%` follows Python sign semantics, so results are non-negative.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..ops.base import simple_op


def _mod_hash(x, nembed=None):
    return (x.astype(jnp.int32) % jnp.int32(nembed)).astype(jnp.int32)


def _div_hash(x, nembed=None):
    return (x.astype(jnp.int32) // jnp.int32(nembed)).astype(jnp.int32)


def _mod_hash_negative(x, nembed=None):
    """Adaptive-embedding rare path: remapped ids are stored as -(i+1) for
    rare id i; map those into [0, nembed) and keep frequent ids negative so
    the (zero-padding) lookup ignores them."""
    prev = -(x.astype(jnp.int32) + 1)
    return jnp.where(prev >= 0, prev % jnp.int32(nembed), prev)


def _compo_hash(x, ntable=None, nembed=None):
    """Decompose each id into ``ntable`` base-``nembed`` digits -> [..., ntable]."""
    x = x.astype(jnp.int32)
    digits = []
    for _ in range(ntable):
        digits.append(x % jnp.int32(nembed))
        x = x // jnp.int32(nembed)
    return jnp.stack(digits, axis=-1)


def _learn_hash(x, slope, bias, prime, nbucket=None, dist="uniform",
                eps=1e-12):
    """DHE (KDD'21) k universal hashes + distribution transform.

    h_i(x) = ((x * slope_i + bias_i) mod prime_i) mod nbucket, scaled to
    [0, 1]; 'uniform' maps to [-1, 1], 'normal' applies Box-Muller to
    consecutive pairs (reference learn_hash_kernel semantics).
    Returns [..., num_hash] float32.
    """
    x = x.astype(jnp.int32)[..., None]
    res = x * slope.astype(jnp.int32) + bias.astype(jnp.int32)
    res = res % prime.astype(jnp.int32) % jnp.int32(nbucket)
    pos = res.astype(jnp.float32) / float(nbucket - 1)
    if dist == "uniform":
        return pos * 2.0 - 1.0
    # Box-Muller over (even, odd) pairs
    p0, p1 = pos[..., 0::2], pos[..., 1::2]
    lcontent = jnp.sqrt(-2.0 * jnp.log(jnp.maximum(p0, eps)))
    out0 = lcontent * jnp.cos(jnp.pi * 2.0 * p1)
    out1 = lcontent * jnp.sin(jnp.pi * 2.0 * p1)
    return jnp.stack([out0, out1], axis=-1).reshape(pos.shape)


def _slot_ids(x, nslot):
    flat = (jnp.arange(int(np.prod(x.shape)), dtype=jnp.int32)
            % jnp.int32(nslot))
    return flat.reshape(x.shape)[..., None]


def _robe_hash(x, random_numbers, robe_size=None, dim=None, Z=None,
               use_slot_coef=True, nslot=1):
    """ROBE-Z (MLSys'22) position hash: (Ah*e + Bh*x + Ch*c + Dh) mod P mod M.

    ``random_numbers`` = [P, Dh, Ch, Bh, Ah, Dg, Cg, Bg, Ag] (index 0 is the
    large prime, as in the reference's 10-number array).  x: [...] int ids ->
    [..., dim] int32 indices into the 1-D ROBE array.

    Convention note: following the reference kernel exactly
    (robe_hash_kernel: c = ind % npart, e = (ind % dim) / npart with
    npart = dim/Z), ``Z`` is the number of hashed chunks per row and the
    contiguous run length in the array is dim/Z — i.e. the reference treats
    Z as chunk COUNT, not the ROBE-Z paper's chunk size.  We match the
    reference.
    """
    rn = random_numbers.astype(jnp.int32)
    ids = x.astype(jnp.int32)[..., None]
    j = jnp.arange(dim, dtype=jnp.int32)
    npart = dim // Z
    c = j % npart                 # offset within a chunk
    e = j // npart                # chunk id within the row
    result = rn[3] * ids + rn[1] + c + rn[2] * e
    if use_slot_coef:
        result = result + rn[4] * _slot_ids(x, nslot)
    return (result % rn[0] % jnp.int32(robe_size)).astype(jnp.int32)


def _robe_sign(x, random_numbers, dim=None, use_slot_coef=True, nslot=1):
    """ROBE sign hash: ((Ag*e + Bg*x + Cg*i + Dg) mod P mod 2)*2 - 1."""
    rn = random_numbers.astype(jnp.int32)
    ids = x.astype(jnp.int32)[..., None]
    j = jnp.arange(dim, dtype=jnp.int32)
    result = rn[7] * ids + rn[5] + rn[6] * j
    if use_slot_coef:
        result = result + rn[8] * _slot_ids(x, nslot)
    return (2 * (result % rn[0] % 2) - 1).astype(jnp.float32)


mod_hash_op = simple_op(_mod_hash, "mod_hash")
div_hash_op = simple_op(_div_hash, "div_hash")
mod_hash_negative_op = simple_op(_mod_hash_negative, "mod_hash_negative")
compo_hash_op = simple_op(_compo_hash, "compo_hash")
learn_hash_op = simple_op(_learn_hash, "learn_hash")
robe_hash_op = simple_op(_robe_hash, "robe_hash")
robe_sign_op = simple_op(_robe_sign, "robe_sign")


def make_robe_random_numbers(rng, prime=2038074743):
    """[P] + 9 uniform draws in [1, P) (reference robe.py layer init)."""
    return np.concatenate([
        np.array([prime], dtype=np.int64),
        rng.integers(1, prime, size=(9,)),
    ]).astype(np.int32)


def primes_at_least(n, count):
    """First ``count`` primes >= n (replacement for the reference's vendored
    primes.npy table, layers/dhe.py)."""
    out = []
    cand = max(int(n), 2)
    while len(out) < count:
        is_p = True
        i = 2
        while i * i <= cand:
            if cand % i == 0:
                is_p = False
                break
            i += 1
        if is_p:
            out.append(cand)
        cand += 1
    return np.asarray(out, dtype=np.int32)
