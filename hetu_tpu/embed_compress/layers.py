"""Compressed-embedding layer zoo.

TPU-native re-implementations of the VLDB'24 EmbeddingMemoryCompression
method layers (reference tools/EmbeddingMemoryCompression/methods/layers/*,
one class per method).  Each layer maps an int id tensor ``x`` (any shape,
typically [B, F]) to embeddings [*, x.shape, D] as graph ops, so every method
slots into the CTR models (models/ctr.py) interchangeably.  The heavy lifting
is gathers + small matmuls — both MXU/HBM-friendly; all hashing fuses into
the gather (embed_compress/hashing.py).

Methods (reference layer file in parens):
  * HashEmbedding           (hash.py)      — mod-hash shared table
  * CompositionalEmbedding  (compo.py)     — quotient-remainder two tables
  * TensorTrainEmbedding    (tensortrain.py) — TT-Rec 3-core chain
  * RobeEmbedding           (robe.py)      — ROBE-Z 1-D array + sign hash
  * DeepHashEmbedding       (dhe.py)       — DHE hash-encoding + MLP decoder
  * AdaptiveEmbedding       (adapt.py)     — AdaEmbed frequent/rare split
  * MDEmbedding             (mde.py)       — mixed-dimension + projection
  * AutoDimEmbedding        (autodim.py)   — dim-candidate gumbel search
  * OptEmbedding            (optembed.py)  — learnable row/dim masks
  * PEPEmbedding            (pep.py)       — soft-threshold pruning
  * DeepLightEmbedding      (deeplight.py) — magnitude pruning schedule
  * AutoSrhEmbedding        (autosrh.py)   — group-alpha dimension scaling
  * QuantizedEmbedding      (quantize.py)  — int8/16 fake-quantized lookup
  * ALPTEmbedding           (alpt.py)      — learned per-row scale (LSQ)
  * DPQEmbedding            (dpq.py)       — product quantization (vq/sx)
  * MGQEmbedding            (mgqe.py)      — frequency-tiered DPQ
  * DedupEmbedding          (deduplication.py) — block dedup remap
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..graph.node import Op, VariableOp
from .. import initializers as init
from ..layers import Linear, Sequence, Mish, fresh_name
from ..ops import (embedding_lookup_op, array_reshape_op, add_op, mul_op,
                   sub_op, batch_matmul_op, matmul_op, transpose_op,
                   concat_op, sigmoid_op, relu_op, sign_op, abs_op,
                   reduce_sum_op, reduce_mean_op, reduce_norm1_op,
                   log_softmax_op, softmax_op, one_hot_op, concatenate_op,
                   broadcastto_op, broadcast_shape_op, argmax_op,
                   linear_op, mulbyconst_op, binary_step_op,
                   stop_gradient_op, reshape_to_op, argmax_partial_op,
                   expand_dims_op)
from ..ops.base import simple_op, SimpleOp
from .hashing import (mod_hash_op, div_hash_op, mod_hash_negative_op,
                      learn_hash_op, robe_hash_op, robe_sign_op,
                      make_robe_random_numbers, primes_at_least)


def constant_var(name, value, dtype=np.float32, trainable=False):
    """Non-trainable valued Variable (reference placeholder_op(value=...))."""
    value = np.asarray(value, dtype=dtype)
    return VariableOp(fresh_name(name), value.shape, init.NumpyInit(value),
                      trainable=trainable, dtype=dtype)


def _lookup_or_zero(table, ids):
    """Gather returning zeros for out-of-range ids (the reference
    EmbeddingLookup.cu zero-fills out-of-bound indices; jnp.take clamps,
    so mask explicitly)."""
    ids = ids.astype(jnp.int32)
    ok = (ids >= 0) & (ids < table.shape[0])
    rows = jnp.take(table, jnp.clip(ids, 0, table.shape[0] - 1), axis=0)
    return jnp.where(ok[..., None], rows, 0).astype(table.dtype)


lookup_or_zero_op = simple_op(_lookup_or_zero, "lookup_or_zero")


class CompressedEmbedding:
    """Base: plain full table (compress_rate=1 fallback)."""

    num_embeddings: int
    embedding_dim: int

    def __init__(self, num_embeddings, embedding_dim, initializer=None,
                 name="embedding"):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.name = fresh_name(name)
        if initializer is None:
            initializer = init.xavier_normal()
        self.initializer = initializer
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, embedding_dim),
            initializer)

    def __call__(self, x):
        return embedding_lookup_op(self.embedding_table, x)

    def extra_loss(self):
        """Auxiliary loss term (e.g. DPQ regularizer); None if none."""
        return None


class HashEmbedding(CompressedEmbedding):
    """The hashing trick: ids share rows of a smaller table."""

    def __call__(self, x):
        return embedding_lookup_op(
            self.embedding_table, mod_hash_op(x, nembed=self.num_embeddings))


class CompositionalEmbedding:
    """Quotient-remainder compositional hashing (KDD'20 / QREmbeddingBag)."""

    def __init__(self, num_quotient, num_remainder, embedding_dim,
                 aggregator="mul", initializer=None, name="compo_emb"):
        assert aggregator[:3] in ("sum", "mul")
        self.aggregator = aggregator[:3]
        self.num_quotient = num_quotient
        self.num_remainder = num_remainder
        self.embedding_dim = embedding_dim
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.qemb = VariableOp(f"{self.name}_q",
                               (num_quotient, embedding_dim), initializer)
        self.remb = VariableOp(f"{self.name}_r",
                               (num_remainder, embedding_dim), initializer)

    def __call__(self, x):
        q = embedding_lookup_op(self.qemb,
                                div_hash_op(x, nembed=self.num_remainder))
        r = embedding_lookup_op(self.remb,
                                mod_hash_op(x, nembed=self.num_remainder))
        return add_op(q, r) if self.aggregator == "sum" else mul_op(q, r)

    def extra_loss(self):
        return None


class TensorTrainEmbedding:
    """TT-Rec: the table as a 3-core tensor-train; a row materializes as a
    chain of two small matmuls (batched on MXU)."""

    def __init__(self, decomp_nemb, decomp_ndim, rank, name="tt_emb"):
        self.num_tables = len(decomp_nemb)
        assert len(decomp_ndim) == self.num_tables
        self.decomp_nemb = list(decomp_nemb)
        self.decomp_ndim = list(decomp_ndim)
        self.ranks = [1] + [rank] * (self.num_tables - 1) + [1]
        self.embedding_dim = int(np.prod(decomp_ndim))
        self.name = fresh_name(name)
        std = 1.0 / ((np.sqrt(1 / 3 * np.prod(decomp_nemb))) ** (1 / 3))
        ttcore_init = init.truncated_normal(0.0, std)
        self.tt_cores = []
        for i in range(self.num_tables):
            ncol = self.ranks[i] * self.decomp_ndim[i] * self.ranks[i + 1]
            self.tt_cores.append(VariableOp(
                f"{self.name}_core{i}", (self.decomp_nemb[i], ncol),
                ttcore_init))

    def __call__(self, x):
        indices = x
        accum = None
        accum_dim = 1
        for i in range(self.num_tables):
            if i == self.num_tables - 1:
                cur_ind = indices
            else:
                cur_ind = mod_hash_op(indices, nembed=self.decomp_nemb[i])
                indices = div_hash_op(indices, nembed=self.decomp_nemb[i])
            part = embedding_lookup_op(self.tt_cores[i], cur_ind)
            if i == 0:
                accum = part
            else:
                accum = array_reshape_op(
                    accum, output_shape=(-1, accum_dim, self.ranks[i]))
                part = array_reshape_op(
                    part, output_shape=(-1, self.ranks[i],
                           self.decomp_ndim[i] * self.ranks[i + 1]))
                accum = batch_matmul_op(accum, part)
            accum_dim *= self.decomp_ndim[i]
        return array_reshape_op(accum, output_shape=(-1, accum_dim))

    def extra_loss(self):
        return None


class RobeEmbedding:
    """ROBE-Z: all embeddings live in one 1-D parameter array; each output
    element is array[hash(id, pos)] * sign(id, pos)."""

    def __init__(self, robe_array_size, embedding_dim, Z, rng,
                 use_slot_coef=True, nslot=1, initializer=None,
                 name="robe_emb"):
        assert Z <= embedding_dim and embedding_dim % Z == 0
        self.robe_array_size = robe_array_size
        self.embedding_dim = embedding_dim
        self.Z = Z
        self.use_slot_coef = use_slot_coef
        self.nslot = nslot
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_array", (robe_array_size, 1), initializer)
        self.random_numbers = constant_var(
            f"{self.name}_rand", make_robe_random_numbers(rng),
            dtype=np.int32)

    def __call__(self, x):
        idx = robe_hash_op(x, self.random_numbers,
                           robe_size=self.robe_array_size,
                           dim=self.embedding_dim, Z=self.Z,
                           use_slot_coef=self.use_slot_coef,
                           nslot=self.nslot)
        signs = robe_sign_op(x, self.random_numbers,
                             dim=self.embedding_dim,
                             use_slot_coef=self.use_slot_coef,
                             nslot=self.nslot)
        rows = embedding_lookup_op(self.embedding_table, idx)
        return mul_op(reshape_to_op(rows, signs), signs)

    def extra_loss(self):
        return None


class BatchNorm1d:
    """BatchNorm over the leading axes of a [..., C] tensor with running
    stats; the compression layers (DHE/AutoDim/DPQ) normalize 2-D/3-D
    activations, which the 4-D conv BatchNorm (ops/nn.py) doesn't cover."""

    def __init__(self, num_features, scale=True, bias=True, momentum=0.1,
                 eps=1e-5, name=None):
        name = fresh_name(name or "bn1d")
        self.scale = (VariableOp(f"{name}_scale", (num_features,),
                                 init.ones()) if scale else None)
        self.bias = (VariableOp(f"{name}_bias", (num_features,),
                                init.zeros()) if bias else None)
        self.running_mean = VariableOp(f"{name}_running_mean",
                                       (num_features,), init.zeros(),
                                       trainable=False)
        self.running_var = VariableOp(f"{name}_running_var",
                                      (num_features,), init.ones(),
                                      trainable=False)
        self.momentum, self.eps = momentum, eps

    def __call__(self, x):
        return _BatchNorm1dOp(self, x)


class _BatchNorm1dOp(Op):
    def __init__(self, layer, x):
        self.layer = layer
        inputs = [x, layer.running_mean, layer.running_var]
        if layer.scale is not None:
            inputs.append(layer.scale)
        if layer.bias is not None:
            inputs.append(layer.bias)
        super().__init__(*inputs, name=f"{layer.running_mean.name}_apply")

    @property
    def is_stateful(self):
        return True

    def _compute(self, input_vals, ctx):
        lay = self.layer
        x, rmean, rvar = input_vals[:3]
        rest = list(input_vals[3:])
        scale = rest.pop(0) if lay.scale is not None else None
        bias = rest.pop(0) if lay.bias is not None else None
        axes = tuple(range(x.ndim - 1))
        if ctx.training:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            m = lay.momentum
            master = ctx.master_params
            rm = (master[lay.running_mean.name] if master is not None
                  else rmean).astype(jnp.float32)
            rv = (master[lay.running_var.name] if master is not None
                  else rvar).astype(jnp.float32)
            ctx.record_update(lay.running_mean, (1 - m) * rm + m * mean)
            ctx.record_update(lay.running_var, (1 - m) * rv + m * var)
            mean, var = mean.astype(x.dtype), var.astype(x.dtype)
        else:
            mean, var = rmean, rvar
        out = (x - mean) * jax.lax.rsqrt(var + lay.eps)
        if scale is not None:
            out = out * scale
        if bias is not None:
            out = out + bias
        return out


class DeepHashEmbedding:
    """DHE (KDD'21): k universal hashes of the id are the 'encoding'; a deep
    MLP (Mish + BatchNorm) decodes it to the embedding.  Parameter count is
    independent of vocabulary size."""

    def __init__(self, embedding_dim, mlp_dim, num_buckets, num_hash, rng,
                 dist="uniform", initializer=None, name="dhe_emb"):
        assert dist in ("uniform", "normal")
        assert num_hash % 2 == 0
        self.distribution = dist
        self.embedding_dim = embedding_dim
        self.num_buckets = num_buckets
        self.num_hash = num_hash
        self.mlp_dim = mlp_dim
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        primes = primes_at_least(num_buckets, max(num_hash * 4, 64))
        self.slopes = constant_var(
            f"{self.name}_slopes",
            rng.integers(1, num_buckets, size=(num_hash,)), np.int32)
        self.biases = constant_var(
            f"{self.name}_biases",
            rng.integers(1, num_buckets, size=(num_hash,)), np.int32)
        self.primes = constant_var(
            f"{self.name}_primes", rng.choice(primes, size=(num_hash,)),
            np.int32)
        layers = [Linear(num_hash, mlp_dim, initializer=initializer,
                         name=f"{self.name}_l1"),
                  BatchNorm1d(mlp_dim, name=f"{self.name}_bn1"), Mish()]
        for i in range(4):
            layers += [Linear(mlp_dim, mlp_dim, initializer=initializer,
                              name=f"{self.name}_l{i + 2}"),
                       BatchNorm1d(mlp_dim, name=f"{self.name}_bn{i + 2}"),
                       Mish()]
        layers.append(Linear(mlp_dim, embedding_dim,
                             initializer=initializer,
                             name=f"{self.name}_l6"))
        self.layers = Sequence(*layers)

    def __call__(self, x):
        enc = learn_hash_op(x, self.slopes, self.biases, self.primes,
                            nbucket=self.num_buckets,
                            dist=self.distribution)
        enc = array_reshape_op(enc, output_shape=(-1, self.num_hash))
        return self.layers(enc)

    def extra_loss(self):
        return None


class AdaptiveEmbedding:
    """AdaEmbed-style frequent/rare split: frequent ids get private rows,
    rare ids share a small mod-hashed table; remap is precomputed from id
    frequencies (planner.adapt_remap)."""

    def __init__(self, num_freq_emb, num_rare_emb, remap_indices,
                 embedding_dim, initializer=None, name="adapt_emb"):
        self.num_freq_emb = num_freq_emb
        self.num_rare_emb = num_rare_emb
        self.embedding_dim = embedding_dim
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.freq_emb = VariableOp(f"{self.name}_freq",
                                   (num_freq_emb, embedding_dim),
                                   initializer)
        self.rare_emb = VariableOp(f"{self.name}_rare",
                                   (num_rare_emb, embedding_dim),
                                   initializer)
        self.remap_indices = constant_var(
            f"{self.name}_remap", np.asarray(remap_indices).reshape(-1),
            np.int32)

    def __call__(self, x):
        remap = embedding_lookup_op(self.remap_indices, x)
        high = lookup_or_zero_op(self.freq_emb, remap)
        low_inds = mod_hash_negative_op(remap, nembed=self.num_rare_emb)
        low = lookup_or_zero_op(self.rare_emb, low_inds)
        return add_op(high, low)

    def extra_loss(self):
        return None


class MDEmbedding:
    """Mixed-dimension: store at a (popularity-chosen) smaller dim, project
    up to the model dim (reference mde.py)."""

    def __init__(self, num_embeddings, compressed_dim, embedding_dim,
                 initializer=None, name="md_emb"):
        self.num_embeddings = num_embeddings
        self.compressed_dim = compressed_dim
        self.embedding_dim = embedding_dim
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, compressed_dim),
            initializer)
        self.projection = None
        if compressed_dim < embedding_dim:
            self.projection = VariableOp(
                f"{self.name}_proj", (compressed_dim, embedding_dim),
                initializer)

    def __call__(self, x):
        res = embedding_lookup_op(self.embedding_table, x)
        if self.projection is not None:
            flat = array_reshape_op(res, output_shape=(-1, self.compressed_dim))
            res = matmul_op(flat, self.projection)
        return res

    def extra_loss(self):
        return None


class GumbelSampleOp(Op):
    """Standard Gumbel(0,1) noise of a given shape (reference
    gpu_ops/Sample.py gumbel_sample_op)."""

    def __init__(self, shape, name=None):
        super().__init__(name=name)
        self.shape = tuple(shape)

    @property
    def needs_rng(self):
        return True

    def _compute(self, input_vals, ctx):
        u = jax.random.uniform(ctx.rng_for(self), self.shape,
                               minval=1e-20, maxval=1.0)
        return -jnp.log(-jnp.log(u))


class StepCounterOp(Op):
    """Reads and post-increments a step Variable — the graph analogue of the
    reference's `const_updater(n_iter)` closures (AutoDim temperature,
    DeepLight schedule)."""

    def __init__(self, var):
        super().__init__(var, name=f"{var.name}_tick")
        self.var = var

    @property
    def is_stateful(self):
        return True

    def _compute(self, input_vals, ctx):
        (step,) = input_vals
        if ctx.training:
            master = ctx.master_params
            cur = (master[self.var.name] if master is not None
                   else step).astype(jnp.float32)
            ctx.record_update(self.var, cur + 1.0)
        return step


class AutoDimEmbedding:
    """AutoDim (NAS over embedding dims): one candidate table per dim, each
    projected to max_dim + BN; a gumbel-softmax over per-slot alphas mixes
    candidates.  After search, `planner.autodim_choose` reads the alphas and
    the table is rebuilt as AutoDimRetrainEmbedding."""

    def __init__(self, num_embeddings, dim_candidates, num_slot, batch_size,
                 initializer=None, name="autodim_emb"):
        self.num_embeddings = num_embeddings
        self.num_slot = num_slot
        self.batch_size = batch_size
        self.dim_candidates = sorted(dim_candidates)
        self.num_cands = len(self.dim_candidates)
        self.max_dim = self.dim_candidates[-1]
        self.embedding_dim = self.max_dim
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        # reference: temperature = 1/max(0.01, 1 - decay*step)
        self.temperature_decay = 0.00005 / 2000 * batch_size
        self.step = VariableOp(f"{self.name}_step", (), init.zeros(),
                               trainable=False)
        self.bn_layers = {d: BatchNorm1d(self.max_dim, scale=False,
                                         bias=False,
                                         name=f"{self.name}_bn{d}")
                          for d in self.dim_candidates}
        self.embedding_tables = {d: VariableOp(f"{self.name}_t{d}",
                                               (num_embeddings, d),
                                               initializer)
                                 for d in self.dim_candidates}
        self.weights = {d: VariableOp(f"{self.name}_w{d}",
                                      (num_slot, d, self.max_dim),
                                      initializer)
                        for d in self.dim_candidates}
        self.biases = {d: VariableOp(f"{self.name}_b{d}",
                                     (num_slot, 1, self.max_dim),
                                     init.zeros())
                       for d in self.dim_candidates}
        self.alpha = VariableOp(f"{self.name}_alpha",
                                (num_slot, self.num_cands), initializer)

    def __call__(self, x):
        middles = []
        for d in self.dim_candidates:
            cur = embedding_lookup_op(self.embedding_tables[d], x)
            # (bs, nslot, d) -> (nslot, bs, d)
            cur = transpose_op(cur, perm=(1, 0, 2))
            cur = batch_matmul_op(cur, self.weights[d])
            cur = add_op(cur, broadcastto_op(self.biases[d], cur))
            cur = transpose_op(cur, perm=(1, 0, 2))
            cur = array_reshape_op(cur, output_shape=(-1, self.max_dim))
            cur = self.bn_layers[d](cur)
            cur = array_reshape_op(
                cur, output_shape=(-1, self.num_slot, self.max_dim, 1))
            middles.append(cur)
        log_alpha = log_softmax_op(self.alpha)
        noise = add_op(log_alpha,
                       GumbelSampleOp((self.num_slot, self.num_cands)))
        w = _TemperatureScaleOp(noise, StepCounterOp(self.step),
                                self.temperature_decay)
        p = softmax_op(w)
        p = array_reshape_op(p, output_shape=(1, self.num_slot, self.num_cands, 1))
        p = broadcast_shape_op(
            p, shape=(self.batch_size, self.num_slot, self.num_cands, 1))
        stacked = concatenate_op(middles, axis=3)
        out = batch_matmul_op(
            array_reshape_op(stacked,
                             output_shape=(-1, self.max_dim, self.num_cands)),
            array_reshape_op(p, output_shape=(-1, self.num_cands, 1)))
        return array_reshape_op(
            out, output_shape=(self.batch_size, self.num_slot, self.max_dim))

    def extra_loss(self):
        return None


class _TemperatureScaleOp(SimpleOp):
    """noise / temperature(step) with temperature = max(0.01, 1-decay*t)."""

    def __init__(self, noise, step, decay):
        super().__init__(
            lambda n, s, decay=decay: n / jnp.maximum(0.01, 1.0 - decay * s),
            "temperature_scale", noise, step)


class AutoDimRetrainEmbedding:
    """Post-search AutoDim: per-slot compressed table + linear projection."""

    def __init__(self, num_embeddings, compressed_dim, embedding_dim,
                 initializer=None, name="autodim_retrain"):
        self.num_embeddings = num_embeddings
        self.compressed_dim = compressed_dim
        self.embedding_dim = embedding_dim
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, compressed_dim),
            initializer)
        self.weight = VariableOp(f"{self.name}_w",
                                 (compressed_dim, embedding_dim),
                                 initializer)
        self.bias = VariableOp(f"{self.name}_b", (embedding_dim,),
                               init.zeros())

    def __call__(self, x):
        res = embedding_lookup_op(self.embedding_table, x)
        flat = array_reshape_op(res, output_shape=(-1, self.compressed_dim))
        return linear_op(flat, self.weight, self.bias)

    def extra_loss(self):
        return None


class RandintSampleOp(Op):
    """Uniform int sample in [low, high) (reference gpu_ops/Sample.py)."""

    def __init__(self, shape, low, high, name=None):
        super().__init__(name=name)
        self.shape = tuple(shape)
        self.low, self.high = low, high

    @property
    def needs_rng(self):
        return True

    def _compute(self, input_vals, ctx):
        return jax.random.randint(ctx.rng_for(self), self.shape, self.low,
                                  self.high, dtype=jnp.int32)


class OptEmbedding:
    """OptEmbed supernet: feature mask = binary_step(|row|_1 - threshold)
    (learnable row pruning, STE) × random dim-truncation field masks."""

    def __init__(self, num_embeddings, embedding_dim, num_slot, batch_size,
                 initializer=None, name="optembed"):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.num_slot = num_slot
        self.batch_size = batch_size
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, embedding_dim),
            initializer)
        self.threshold = VariableOp(f"{self.name}_threshold",
                                    (num_slot, 1), init.zeros())
        self.potential_field_masks = constant_var(
            f"{self.name}_pmask", self._potential_field_masks(),
            np.float32)

    def _potential_field_masks(self):
        # row i = [1]*（i+1) + [0]*(D-i-1): truncate-to-dim masks
        d = self.embedding_dim
        return np.tril(np.ones((d, d), np.float32))

    def _feature_mask(self, xv):
        norm = reduce_norm1_op(xv, axes=2, keepdims=True)
        th = broadcastto_op(self.threshold, norm)
        return binary_step_op(sub_op(norm, th))

    def __call__(self, x):
        xv = embedding_lookup_op(self.embedding_table, x)  # (bs, slot, D)
        mask_f = broadcastto_op(self._feature_mask(xv), xv)
        dims = RandintSampleOp((self.batch_size, self.num_slot), 0,
                               self.embedding_dim)
        mask_e = embedding_lookup_op(self.potential_field_masks, dims)
        return mul_op(mask_f, mul_op(mask_e, xv))

    def make_inference(self, x):
        xv = embedding_lookup_op(self.embedding_table, x)
        mask_f = broadcastto_op(self._feature_mask(xv), xv)
        return mul_op(mask_f, xv)

    def extra_loss(self):
        return None


class OptEmbeddingAfterRowPruning:
    """OptEmbed retrain: surviving rows remapped into a dense table, fixed
    per-field dim choice from the evolutionary search."""

    def __init__(self, num_embeddings, remap_indices, candidate_dims,
                 embedding_dim, num_slot, batch_size, initializer=None,
                 name="optembed_retrain"):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.num_slot = num_slot
        self.batch_size = batch_size
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, embedding_dim),
            initializer)
        self.remap_indices = constant_var(
            f"{self.name}_remap", np.asarray(remap_indices).reshape(-1),
            np.int32)
        d = embedding_dim
        self.potential_field_masks = constant_var(
            f"{self.name}_pmask", np.tril(np.ones((d, d), np.float32)),
            np.float32)
        self.candidate = constant_var(
            f"{self.name}_candidate",
            np.asarray(candidate_dims).reshape(-1), np.int32)

    def __call__(self, x):
        new_ids = embedding_lookup_op(self.remap_indices, x)
        xe = lookup_or_zero_op(self.embedding_table, new_ids)
        mask_e = embedding_lookup_op(self.potential_field_masks,
                                     self.candidate)  # (nslot, D)
        mask_e = broadcast_shape_op(
            expand_dims_op(mask_e, axis=0),
            shape=(self.batch_size, self.num_slot, self.embedding_dim))
        return mul_op(mask_e, xe)

    def extra_loss(self):
        return None


class PEPEmbedding:
    """PEP: soft-threshold reparameterization — emb = sign(w) *
    relu(|w| - sigmoid(threshold)), threshold learnable per
    global/dimension/feature/feature_dimension granularity."""

    def __init__(self, num_embeddings, embedding_dim, threshold_type,
                 threshold_init, initializer=None, name="pep_emb"):
        assert threshold_type in ("dimension", "feature", "global",
                                  "feature_dimension")
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.threshold_type = threshold_type
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, embedding_dim),
            initializer)
        th_shape = {"feature_dimension": (num_embeddings, embedding_dim),
                    "dimension": (embedding_dim,),
                    "feature": (num_embeddings, 1),
                    "global": (1,)}[threshold_type]
        self.threshold = VariableOp(f"{self.name}_threshold", th_shape,
                                    init.constant(threshold_init))

    def __call__(self, x):
        raw = embedding_lookup_op(self.embedding_table, x)
        if self.threshold_type.startswith("feature"):
            th = embedding_lookup_op(self.threshold, x)
        else:
            th = self.threshold
        th = sigmoid_op(th)
        if self.threshold_type != "feature_dimension":
            th = broadcastto_op(th, raw)
        return mul_op(sign_op(raw), relu_op(sub_op(abs_op(raw), th)))

    def extra_loss(self):
        return None


class PEPRetrainEmbedding:
    """PEP retrain: fixed binary mask from the search phase."""

    def __init__(self, num_embeddings, embedding_dim, mask,
                 initializer=None, name="pep_retrain"):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, embedding_dim),
            initializer)
        self.mask = constant_var(f"{self.name}_mask",
                                 np.asarray(mask, np.float32), np.float32)

    def __call__(self, x):
        lookups = embedding_lookup_op(self.embedding_table, x)
        masks = embedding_lookup_op(self.mask, x)
        return mul_op(lookups, masks)

    def make_inference(self, table_value, mask_value=None):
        """Trained table -> SparseEmbedding (reference layers/sparse.py
        via scheduler switchinference)."""
        table = np.asarray(table_value, np.float32)
        mask = (np.asarray(mask_value, np.float32) if mask_value is not None
                else None)
        if mask is not None:
            table = table * mask
        return SparseEmbedding.from_dense(table,
                                          name=f"{self.name}_sparse")

    def extra_loss(self):
        return None


class DeepLightEmbedding:
    """DeepLight: plain lookup; a pruning schedule zeroes the smallest
    |w| fraction of the table in-place as training proceeds (stateful op,
    reference make_prune_op / PruneMask.cu)."""

    def __init__(self, num_embeddings, embedding_dim, prune_rate,
                 batch_num=1000, initializer=None, name="deeplight"):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.prune_rate = prune_rate
        self.batch_num = batch_num
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, embedding_dim),
            initializer)
        self.step = VariableOp(f"{self.name}_step", (), init.zeros(),
                               trainable=False)

    def __call__(self, x):
        return embedding_lookup_op(self.embedding_table, x)

    def make_prune_op(self, after=None):
        """Stateful node: every 10 steps (and every epoch boundary, i.e.
        ``batch_num`` steps), prune the table to the scheduled adaptive
        sparsity rate = prune_rate * (1 - 0.99^(step/100)).

        Pass the optimizer node as ``after`` so the prune composes with —
        instead of clobbering — the same step's gradient update."""
        return _DeepLightPruneOp(self, after)

    def make_inference(self, table_value):
        """Pruned trained table -> SparseEmbedding (padded-ELL), the
        deployment form of the reference's sparse.py/switchinference."""
        return SparseEmbedding.from_dense(np.asarray(table_value),
                                          name=f"{self.name}_sparse")

    def extra_loss(self):
        return None


class _DeepLightPruneOp(Op):
    def __init__(self, layer, after=None):
        inputs = [layer.embedding_table, layer.step]
        if after is not None:
            inputs.append(after)   # topo-order after the optimizer node
        super().__init__(*inputs, name=f"{layer.name}_prune")
        self.layer = layer

    @property
    def is_stateful(self):
        return True

    def _compute(self, input_vals, ctx):
        table, step = input_vals[:2]
        lay = self.layer
        if not ctx.training:
            return step
        master = ctx.master_params
        cur_step = (master[lay.step.name] if master is not None
                    else step).astype(jnp.float32)
        # compose with this step's pending optimizer update (last-write-wins
        # dict: reading the pending value instead of the stale binding keeps
        # the gradient step alive)
        cur_table = ctx.updates.get(lay.embedding_table)
        if cur_table is None:
            cur_table = (master[lay.embedding_table.name]
                         if master is not None else table)
        rate = lay.prune_rate * (1.0 - 0.99 ** (cur_step / 100.0))
        apply_now = ((jnp.mod(cur_step, 10.0) == 0)
                     | (jnp.mod(cur_step, float(lay.batch_num)) == 0))

        def prune(tbl):
            absval = jnp.abs(tbl)
            th = jnp.quantile(absval.reshape(-1), jnp.clip(rate, 0.0, 1.0))
            return jnp.where(absval > th, tbl, 0.0)

        # lax.cond so the O(N*D log) quantile sort only runs on prune steps
        pruned = jax.lax.cond(apply_now, prune, lambda t: t, cur_table)
        ctx.record_update(lay.embedding_table,
                          pruned.astype(cur_table.dtype))
        ctx.record_update(lay.step, cur_step + 1.0)
        return step


class AutoSrhEmbedding:
    """AutoSrh: per-(frequency-group, dimension) trainable salience alphas
    scale the embedding; after search, alphas are thresholded to a mask."""

    def __init__(self, num_embeddings, embedding_dim, nsplit, group_indices,
                 initializer=None, name="autosrh"):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.nsplit = nsplit
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, embedding_dim),
            initializer)
        self.group_indices = constant_var(
            f"{self.name}_groupind", np.asarray(group_indices).reshape(-1),
            np.int32)
        self.alpha = VariableOp(f"{self.name}_alpha",
                                (nsplit, embedding_dim), init.ones())

    def __call__(self, x):
        emb = embedding_lookup_op(self.embedding_table, x)
        gidx = embedding_lookup_op(self.group_indices, x)
        alphas = embedding_lookup_op(self.alpha, gidx)
        return mul_op(emb, reshape_to_op(alphas, emb))

    def extra_loss(self):
        return None


class QuantizedEmbedding:
    """Fixed-point table: rows are fake-quantized to `digit` bits on lookup
    (uniform scale/middle, or per-row min/max qparams).  Gradients flow
    straight-through (reference QuantizeEmbedding.cu)."""

    def __init__(self, num_embeddings, embedding_dim, digit, scale=0.01,
                 middle=0.0, use_qparam=False, initializer=None,
                 name="quant_emb"):
        assert digit in (8, 16)
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.digit = digit
        self.scale, self.middle = scale, middle
        self.use_qparam = use_qparam
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, embedding_dim),
            initializer)

    def __call__(self, x):
        rows = embedding_lookup_op(self.embedding_table, x)
        return _FakeQuantRowsOp(rows, self.digit, self.scale, self.middle,
                                self.use_qparam)

    def extra_loss(self):
        return None


class _FakeQuantRowsOp(SimpleOp):
    """round((rows - middle)/scale) clamped to digit range, dequantized; STE
    through the rounding.  With use_qparam, scale/middle are per-row
    min/max-derived (reference qparams path)."""

    def __init__(self, rows, digit, scale, middle, use_qparam):
        def impl(r, digit=digit, scale=scale, middle=middle,
                 use_qparam=use_qparam):
            qmin = -(1 << (digit - 1))
            qmax = (1 << (digit - 1)) - 1
            if use_qparam:
                rmin = jnp.min(r, axis=-1, keepdims=True)
                rmax = jnp.max(r, axis=-1, keepdims=True)
                scale_ = jnp.maximum((rmax - rmin) / (qmax - qmin), 1e-8)
                middle_ = (rmax + rmin) / 2
            else:
                scale_, middle_ = scale, middle
            q = jnp.clip(jnp.round((r - middle_) / scale_), qmin, qmax)
            deq = q * scale_ + middle_
            return r + jax.lax.stop_gradient(deq - r)   # STE
        super().__init__(impl, "fake_quant_rows", rows)


class ALPTEmbedding:
    """ALPT: per-row learnable quantization scale trained jointly with the
    table via the LSQ straight-through estimator (ops/quantize.py lsq_round)."""

    def __init__(self, num_embeddings, embedding_dim, digit, init_scale,
                 initializer=None, name="alpt_emb"):
        assert digit in (8, 16)
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.digit = digit
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_table", (num_embeddings, embedding_dim),
            initializer)
        self.scale = VariableOp(f"{self.name}_scale", (num_embeddings, 1),
                                init.constant(init_scale))

    def __call__(self, x):
        rows = embedding_lookup_op(self.embedding_table, x)
        scales = embedding_lookup_op(self.scale, x)
        return _LSQRowsOp(rows, scales, self.digit)

    def extra_loss(self):
        return None


class _LSQRowsOp(SimpleOp):
    def __init__(self, rows, scales, digit):
        from ..ops.quantize import lsq_round

        def impl(r, s, digit=digit):
            return lsq_round(r, s, digit, True)
        super().__init__(impl, "lsq_rows", rows, scales)


class DPQEmbedding:
    """Differentiable product quantization: rows split into `num_parts`
    sub-vectors, each snapped to the nearest of `num_choices` codewords
    ('vq': euclidean + STE; 'sx': softmax relaxation).  The int codebook (for
    post-training inference) is maintained by a stateful scatter."""

    def __init__(self, num_embeddings, embedding_dim, num_choices, num_parts,
                 batch_size, share_weights=False, mode="vq",
                 initializer=None, name="dpq_emb"):
        assert mode in ("vq", "sx")
        assert embedding_dim % num_parts == 0
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.num_choices = num_choices
        self.num_parts = num_parts
        self.batch_size = batch_size
        self.share_weights = share_weights
        self.mode = mode
        self.part_embedding_dim = embedding_dim // num_parts
        self.name = fresh_name(name)
        initializer = initializer or init.xavier_normal()
        self.embedding_table = VariableOp(
            f"{self.name}_query", (num_embeddings, embedding_dim),
            initializer)
        kshape = ((num_choices, self.part_embedding_dim) if share_weights
                  else (num_parts * num_choices, self.part_embedding_dim))
        self.key_matrix = VariableOp(f"{self.name}_key", kshape, initializer)
        self.value_matrix = (self.key_matrix if mode == "vq"
                             else VariableOp(f"{self.name}_value", kshape,
                                             initializer))
        self.bn_layer = BatchNorm1d(num_choices, scale=False, bias=False,
                                    name=f"{self.name}_bn")
        self.codebooks = VariableOp(f"{self.name}_codebook",
                                    (num_embeddings, num_parts),
                                    init.zeros(), trainable=False,
                                    dtype=np.int32)
        self.reg = None

    def _codes(self, x, resp):
        return argmax_op(resp, dim=2)

    def __call__(self, x):
        lookups = embedding_lookup_op(self.embedding_table, x)
        inputs = array_reshape_op(
            lookups, output_shape=(-1, self.num_parts, self.part_embedding_dim))
        q = array_reshape_op(
            lookups, output_shape=(-1, self.num_parts, 1, self.part_embedding_dim))
        keys = array_reshape_op(
            self.key_matrix,
            output_shape=(-1, self.num_choices, self.part_embedding_dim))
        if self.mode == "vq":
            resp = _NegSqDistOp(q, keys)
        else:
            resp = _DotRespOp(q, keys)
        resp = self.bn_layer(resp)          # (N, nparts, nchoices)
        codes = self._codes(x, resp)        # (N, nparts) int
        # stateful scatter; trainers add this node to the eval list so the
        # trained codes persist (reference adds layer.codebook_update)
        self.codebook_update = _CodebookUpdateOp(self.codebooks, x, codes)
        if self.mode == "vq":
            lookup_codes = codes
            if not self.share_weights:
                lookup_codes = _AddPartOffsetsOp(codes, self.num_choices)
            outputs = embedding_lookup_op(self.value_matrix, lookup_codes)
            final = add_op(stop_gradient_op(sub_op(outputs, inputs)),
                           inputs)
            reg = sub_op(outputs, stop_gradient_op(inputs))
            self.reg = reduce_mean_op(mul_op(reg, reg), axes=(0, 1, 2))
        else:
            prob = softmax_op(resp)
            hard = one_hot_op(codes, num_classes=self.num_choices)
            # straight-through softmax: hard in fwd, soft in bwd
            st = add_op(stop_gradient_op(sub_op(hard, prob)), prob)
            vals = array_reshape_op(
                self.value_matrix,
                output_shape=(-1, self.num_choices, self.part_embedding_dim))
            outputs = _MixCodewordsOp(st, vals)
            final = outputs
            self.reg = None
        return array_reshape_op(final, output_shape=(-1, self.embedding_dim))

    def extra_loss(self):
        return self.reg


class _NegSqDistOp(SimpleOp):
    """-(||q - k||^2) responses: q (N,P,1,D), keys (P|1,C,D) -> (N,P,C)."""

    def __init__(self, q, keys):
        def impl(qv, kv):
            diff = qv - kv[None]
            return -jnp.sum(jnp.square(diff), axis=3)
        super().__init__(impl, "neg_sqdist", q, keys)


class _DotRespOp(SimpleOp):
    def __init__(self, q, keys):
        def impl(qv, kv):
            return jnp.sum(qv * kv[None], axis=3)
        super().__init__(impl, "dot_resp", q, keys)


class _AddPartOffsetsOp(SimpleOp):
    """codes[..., p] += p * num_choices (the reference's dbase tile)."""

    def __init__(self, codes, num_choices):
        def impl(c, num_choices=num_choices):
            off = jnp.arange(c.shape[-1], dtype=c.dtype) * num_choices
            return c + off
        super().__init__(impl, "add_part_offsets", codes)


class _MixCodewordsOp(SimpleOp):
    """(N,P,C) soft-assign × (P|1,C,D) codewords -> (N,P,D)."""

    def __init__(self, st, vals):
        def impl(s, v):
            if v.shape[0] == 1:
                v = jnp.broadcast_to(v, (s.shape[1],) + v.shape[1:])
            return jnp.einsum("npc,pcd->npd", s, v)
        super().__init__(impl, "mix_codewords", st, vals)


class _CodebookUpdateOp(Op):
    """codebooks[x] = codes (reference sparse_set_op): stateful scatter so
    the trained codes survive for switch-to-inference."""

    def __init__(self, codebooks_var, x, codes):
        super().__init__(codebooks_var, x, codes,
                         name=f"{codebooks_var.name}_set")
        self.var = codebooks_var

    @property
    def is_stateful(self):
        return True

    def _compute(self, input_vals, ctx):
        book, ids, codes = input_vals
        if ctx.training:
            master = ctx.master_params
            cur = (master[self.var.name] if master is not None else book)
            flat_ids = ids.reshape(-1).astype(jnp.int32)
            flat_codes = codes.reshape(flat_ids.shape[0], -1)
            ctx.record_update(
                self.var,
                cur.at[flat_ids].set(flat_codes.astype(cur.dtype)))
        return codes


class MGQEmbedding(DPQEmbedding):
    """MGQE: DPQ where low-frequency ids may only use the first
    `low_num_choices` codewords (frequency-tiered codebook capacity)."""

    def __init__(self, num_embeddings, embedding_dim, high_num_choices,
                 low_num_choices, num_parts, frequency, batch_size,
                 initializer=None, name="mgqe_emb"):
        super().__init__(num_embeddings, embedding_dim, high_num_choices,
                         num_parts, batch_size, share_weights=False,
                         mode="vq", initializer=initializer, name=name)
        self.low_num_choices = low_num_choices
        self.frequency = constant_var(
            f"{self.name}_frequency", np.asarray(frequency).reshape(-1),
            np.int32)

    def _codes(self, x, resp):
        mask = embedding_lookup_op(self.frequency, x)
        flat_mask = array_reshape_op(mask, output_shape=(-1,))
        return argmax_partial_op(resp, flat_mask,
                                 topk=self.low_num_choices, dim=2)


_ell_to_dense_op = simple_op(
    lambda v, c, dim=None: jnp.einsum(
        "...k,...kd->...d", v,
        jax.nn.one_hot(c, dim, dtype=v.dtype)),
    "ell_to_dense")


class SparseEmbedding:
    """Inference-only pruned embedding in padded-ELL form.

    Reference layers/sparse.py serves pruned tables (DeepLight/PEP) from
    a CSR `ND_Sparse_Array` through SparseEmbeddingLookup.cu.  CSR's
    per-row ragged extents are hostile to XLA's static shapes, so the
    TPU form is ELL: ``values``/``cols`` [N, K] with K = max nonzeros
    per row (zero-padded).  Lookup is two gathers + a one-hot einsum —
    static shapes, MXU work, fuses — and storage is 2·N·K vs N·D
    elements (wins when the table is < 50% dense).
    """

    def __init__(self, values, cols, embedding_dim, name="sparse_emb"):
        values = np.asarray(values, np.float32)
        cols = np.asarray(cols, np.int32)
        assert values.shape == cols.shape and values.ndim == 2
        self.num_embeddings = values.shape[0]
        self.max_nnz = values.shape[1]
        self.embedding_dim = embedding_dim
        self.name = fresh_name(name)
        self.values = constant_var(f"{self.name}_vals", values)
        self.cols = constant_var(f"{self.name}_cols", cols, np.int32)

    @classmethod
    def from_dense(cls, table, name="sparse_emb", tol=0.0):
        """Convert a (pruned) dense [N, D] table; |w| <= tol drops."""
        table = np.asarray(table, np.float32)
        n, d = table.shape
        keep = np.abs(table) > tol
        k = max(1, int(keep.sum(axis=1).max()))
        # vectorized ELL packing (tables are multi-million-row): stable
        # argsort floats kept entries to the front of each row
        order = np.argsort(~keep, axis=1, kind="stable")[:, :k]
        packed_keep = np.take_along_axis(keep, order, axis=1)
        values = np.where(packed_keep,
                          np.take_along_axis(table, order, axis=1),
                          0.0).astype(np.float32)
        cols = np.where(packed_keep, order, 0).astype(np.int32)
        return cls(values, cols, d, name=name)

    def __call__(self, x):
        v = embedding_lookup_op(self.values, x)     # [..., K]
        c = embedding_lookup_op(self.cols, x)       # [..., K]
        return _ell_to_dense_op(v, c, dim=self.embedding_dim)

    def memory_elements(self):
        return 2 * self.num_embeddings * self.max_nnz

    def extra_loss(self):
        return None


class DedupEmbedding:
    """Deduplicated table: rows grouped into blocks of `nemb_per_block`;
    near-duplicate blocks share storage via a remap (built offline by
    planner.dedup_build from a trained table)."""

    def __init__(self, emb, remap_indices, nemb_per_block, trainable=True,
                 name="dedup_emb"):
        emb = np.asarray(emb, np.float32)
        self.num_blocks = emb.shape[0]
        self.embedding_dim = emb.shape[1]
        self.nemb_per_block = nemb_per_block
        self.name = fresh_name(name)
        self.embedding_table = VariableOp(
            f"{self.name}_table", emb.shape, init.NumpyInit(emb),
            trainable=trainable)
        self.remap_indices = constant_var(
            f"{self.name}_remap", np.asarray(remap_indices).reshape(-1),
            np.int32)

    def __call__(self, x):
        block = embedding_lookup_op(
            self.remap_indices,
            div_hash_op(x, nembed=self.nemb_per_block))
        real = add_op(mulbyconst_op(block, self.nemb_per_block),
                      mod_hash_op(x, nembed=self.nemb_per_block))
        return embedding_lookup_op(self.embedding_table, real)

    def extra_loss(self):
        return None
