"""Per-field ("use_multi") compression.

The reference schedulers' use_multi mode (scheduler/base.py:51,
scheduler/hash.py etc.) builds ONE embedding per sparse field: fields with
more rows than a threshold get the compressed variant, small fields keep a
plain table — compression where it pays, exactness where it's cheap.  The
memory budget solvers (planner.py) already understand per-field sizes
(qr_sizes/tt_rank multi_evaluate); this module assembles the layer.
"""

from __future__ import annotations

import numpy as np

from . import make_compressed_embedding
from .layers import CompressedEmbedding
from ..graph.node import VariableOp
from ..ops import concatenate_op, array_reshape_op, split_op


def param_elements(obj, _seen=None):
    """Total stored elements across every Variable reachable from a layer
    (recursive attribute walk) — the unit the compress-rate budget is
    denominated in.  Counts non-trainable state too (remaps, codebooks)."""
    _seen = _seen if _seen is not None else set()
    if id(obj) in _seen:
        return 0
    _seen.add(id(obj))
    if isinstance(obj, VariableOp):
        total = 1
        for s in obj.shape:
            total *= int(s)
        return total
    if isinstance(obj, (list, tuple)):
        return sum(param_elements(v, _seen) for v in obj)
    if isinstance(obj, dict):
        return sum(param_elements(v, _seen) for v in obj.values())
    if hasattr(obj, "__dict__"):
        return sum(param_elements(v, _seen)
                   for v in vars(obj).values())
    return 0


class MultiFieldCompressedEmbedding:
    """One (possibly compressed) embedding per field; ids [B, F] ->
    [B, F, D].

    ``num_embed_separate``: rows per field (reference
    dataset.num_embed_separate — Criteo's 26 sparse fields range from 10s
    to millions of ids).  Fields with rows > ``threshold`` use ``method``
    at ``compress_rate``; the rest keep full tables.  Per-field id spaces
    are LOCAL (0..rows_f), as in the reference's separate_fields mode.
    """

    def __init__(self, method, num_embed_separate, embedding_dim,
                 compress_rate=0.25, threshold=10000, batch_size=None,
                 frequencies_separate=None, rng=None, name="multi_emb",
                 **kwargs):
        self.num_embed_separate = list(num_embed_separate)
        self.num_fields = len(self.num_embed_separate)
        self.embedding_dim = embedding_dim
        self.fields = []
        rng = rng or np.random.default_rng(0)
        for f, rows in enumerate(self.num_embed_separate):
            freq = (frequencies_separate[f]
                    if frequencies_separate is not None else None)
            if rows > threshold:
                layer = make_compressed_embedding(
                    method, rows, embedding_dim,
                    compress_rate=compress_rate, batch_size=batch_size,
                    num_slot=1, frequencies=freq, rng=rng,
                    name=f"{name}_f{f}_{method}", **kwargs)
            else:
                layer = CompressedEmbedding(rows, embedding_dim,
                                            name=f"{name}_f{f}_full")
            self.fields.append(layer)

    def memory_elements(self):
        """Actual stored elements per field (method-agnostic: counts every
        Variable the field's layer holds, incl. MLP decoders and
        codebooks) — compare against rows * embedding_dim."""
        return [param_elements(layer) for layer in self.fields]

    def __call__(self, ids):
        """ids [B, F] (field-local) -> [B, F, D]."""
        outs = []
        for f, layer in enumerate(self.fields):
            col = split_op(ids, axes=1, indices=f, splits=self.num_fields)
            e = layer(col)                       # [B, 1, D] or [B*1, D]
            outs.append(array_reshape_op(
                e, output_shape=(-1, 1, self.embedding_dim)))
        return concatenate_op(outs, axis=1)

    def extra_loss(self):
        terms = [f.extra_loss() for f in self.fields]
        terms = [t for t in terms if t is not None]
        if not terms:
            return None
        total = terms[0]
        for t in terms[1:]:
            total = total + t
        return total


class MixedDimEmbedding:
    """Mixed-dimension embedding across fields (reference
    scheduler/md.py MDETrainer in separate-fields mode): the MD solver
    assigns each field a dimension d_f ∝ n_f^-alpha (popular/small
    fields keep large dims, huge sparse fields shrink), binary-searching
    alpha to hit ``compress_rate``; each field is an MDEmbedding storing
    at d_f and projecting up to the model dim."""

    def __init__(self, num_embed_separate, embedding_dim,
                 compress_rate=0.125, round_dim=True, name="mixdim"):
        from .layers import MDEmbedding
        from .planner import md_dims
        self.num_embed_separate = list(num_embed_separate)
        self.num_fields = len(self.num_embed_separate)
        self.embedding_dim = embedding_dim
        self.dims = md_dims(self.num_embed_separate, embedding_dim,
                            compress_rate, round_dim=round_dim)
        self.fields = [
            MDEmbedding(rows, int(d), embedding_dim,
                        name=f"{name}_f{f}")
            for f, (rows, d) in enumerate(zip(self.num_embed_separate,
                                              self.dims))]

    def memory_elements(self):
        return [param_elements(layer) for layer in self.fields]

    def __call__(self, ids):
        """ids [B, F] (field-local) -> [B, F, D]."""
        outs = []
        for f, layer in enumerate(self.fields):
            col = split_op(ids, axes=1, indices=f, splits=self.num_fields)
            e = layer(col)
            outs.append(array_reshape_op(
                e, output_shape=(-1, 1, self.embedding_dim)))
        return concatenate_op(outs, axis=1)

    def extra_loss(self):
        return None
