"""Embedding memory compression (VLDB'24 suite, TPU-native).

Equivalent of the reference's tools/EmbeddingMemoryCompression: ~17
compression methods as interchangeable embedding layers (layers.py), sizing
/ stage-transition planning (planner.py), and hash ops (hashing.py).
``make_compressed_embedding`` is the method registry the reference exposes
through run_compressed.py's --method flag.
"""

from __future__ import annotations

import numpy as np

from .hashing import (mod_hash_op, div_hash_op, mod_hash_negative_op,
                      compo_hash_op, learn_hash_op, robe_hash_op,
                      robe_sign_op, make_robe_random_numbers,
                      primes_at_least)
from .layers import (CompressedEmbedding, HashEmbedding,
                     CompositionalEmbedding, TensorTrainEmbedding,
                     RobeEmbedding, DeepHashEmbedding, AdaptiveEmbedding,
                     MDEmbedding, AutoDimEmbedding, AutoDimRetrainEmbedding,
                     OptEmbedding, OptEmbeddingAfterRowPruning,
                     PEPEmbedding, PEPRetrainEmbedding, DeepLightEmbedding,
                     AutoSrhEmbedding, QuantizedEmbedding, ALPTEmbedding,
                     DPQEmbedding, MGQEmbedding, DedupEmbedding,
                     SparseEmbedding, BatchNorm1d, lookup_or_zero_op)
from . import planner
from .planner import (hash_rows, qr_sizes, tt_decomp_dims, tt_decomp_rows,
                      tt_rank, robe_size, dhe_mlp_dim, md_dims, adapt_remap,
                      adapt_sizes, autosrh_group_indices, autodim_choose,
                      pep_export_mask, optembed_row_prune,
                      evolutionary_dim_search, dedup_build)

METHODS = ("full", "hash", "compo", "tt", "robe", "dhe", "adapt", "md",
           "autodim", "optembed", "pep", "deeplight", "autosrh", "quantize",
           "alpt", "dpq", "mgqe")
# beyond the single-table constructors: "mixdim" (multi-field MD-solver
# dims — MixedDimEmbedding) and "sparse" (padded-ELL inference form of a
# pruned table — SparseEmbedding.from_dense / make_inference on
# DeepLight/PEPRetrain), completing the reference's 19-method zoo.


def make_compressed_embedding(method, num_embeddings, embedding_dim,
                              compress_rate=0.5, batch_size=None,
                              num_slot=None, frequencies=None, rng=None,
                              name=None, **kwargs):
    """Build a compression layer from a target compress_rate.

    Mirrors the scheduler sizing of the reference's --method registry
    (methods/scheduler/__init__.py).  ``frequencies`` (id counts) is required
    for adapt/mgqe/autosrh; ``batch_size``+``num_slot`` for
    autodim/optembed/dpq/mgqe.
    """
    rng = rng or np.random.default_rng(0)
    name = name or f"{method}_emb"
    if method == "full":
        return CompressedEmbedding(num_embeddings, embedding_dim, name=name)
    if method == "hash":
        return HashEmbedding(hash_rows(num_embeddings, compress_rate),
                             embedding_dim, name=name)
    if method == "compo":
        nq, nr = qr_sizes(num_embeddings, compress_rate)
        return CompositionalEmbedding(nq, nr, embedding_dim,
                                      kwargs.get("aggregator", "mul"),
                                      name=name)
    if method == "tt":
        rows = tt_decomp_rows(num_embeddings)
        dims = tt_decomp_dims(embedding_dim)
        rank = tt_rank(num_embeddings, embedding_dim, compress_rate, rows,
                       dims)
        return TensorTrainEmbedding(rows, dims, rank, name=name)
    if method == "robe":
        Z = kwargs.get("Z", min(8, embedding_dim))
        return RobeEmbedding(robe_size(num_embeddings, embedding_dim,
                                       compress_rate),
                             embedding_dim, Z, rng,
                             nslot=num_slot or 1, name=name)
    if method == "dhe":
        num_hash = kwargs.get("num_hash", 64)
        nbuckets = kwargs.get("num_buckets", 1000000)
        mlp = dhe_mlp_dim(num_embeddings, embedding_dim, compress_rate,
                          num_hash)
        return DeepHashEmbedding(embedding_dim, mlp, nbuckets, num_hash,
                                 rng, dist=kwargs.get("dist", "uniform"),
                                 name=name)
    if method == "adapt":
        assert frequencies is not None, "adapt needs id frequencies"
        top = kwargs.get("top_percent", compress_rate / 2)
        remap, nfreq = adapt_remap(frequencies, top)
        nrare = adapt_sizes(num_embeddings, compress_rate, nfreq)
        return AdaptiveEmbedding(nfreq, nrare, remap, embedding_dim,
                                 name=name)
    if method == "md":
        cdim = max(1, int(embedding_dim * compress_rate))
        return MDEmbedding(num_embeddings, cdim, embedding_dim, name=name)
    if method == "autodim":
        assert batch_size and num_slot
        cands = kwargs.get("dim_candidates",
                           [d for d in (2, 4, 8, 16, 32, 64)
                            if d <= embedding_dim])
        return AutoDimEmbedding(num_embeddings, cands, num_slot, batch_size,
                                name=name)
    if method == "optembed":
        assert batch_size and num_slot
        return OptEmbedding(num_embeddings, embedding_dim, num_slot,
                            batch_size, name=name)
    if method == "pep":
        return PEPEmbedding(num_embeddings, embedding_dim,
                            kwargs.get("threshold_type", "feature"),
                            kwargs.get("threshold_init", -15.0), name=name)
    if method == "deeplight":
        return DeepLightEmbedding(num_embeddings, embedding_dim,
                                  prune_rate=1.0 - compress_rate,
                                  name=name)
    if method == "autosrh":
        assert frequencies is not None, "autosrh needs id frequencies"
        nsplit = kwargs.get("nsplit", 10)
        groups = autosrh_group_indices(frequencies, nsplit)
        return AutoSrhEmbedding(num_embeddings, embedding_dim, nsplit,
                                groups, name=name)
    if method == "quantize":
        return QuantizedEmbedding(num_embeddings, embedding_dim,
                                  kwargs.get("digit", 8),
                                  scale=kwargs.get("scale", 0.01),
                                  use_qparam=kwargs.get("use_qparam", False),
                                  name=name)
    if method == "alpt":
        return ALPTEmbedding(num_embeddings, embedding_dim,
                             kwargs.get("digit", 8),
                             kwargs.get("init_scale", 0.01), name=name)
    if method == "dpq":
        assert batch_size
        return DPQEmbedding(num_embeddings, embedding_dim,
                            kwargs.get("num_choices", 32),
                            kwargs.get("num_parts", 4), batch_size,
                            share_weights=kwargs.get("share_weights", False),
                            mode=kwargs.get("mode", "vq"), name=name)
    if method == "mgqe":
        assert batch_size and frequencies is not None
        # MGQEmbedding's mask is an indicator (nonzero = high-frequency id
        # gets the full codebook); threshold raw counts at the top-percent
        # quantile, as the reference scheduler does before constructing the
        # layer (scheduler/mgqe.py)
        counts = np.asarray(frequencies)
        top = kwargs.get("top_percent", 0.1)
        cut = np.quantile(counts, 1.0 - top)
        indicator = (counts >= cut).astype(np.int32)
        return MGQEmbedding(num_embeddings, embedding_dim,
                            kwargs.get("high_num_choices", 32),
                            kwargs.get("low_num_choices", 8),
                            kwargs.get("num_parts", 4), indicator,
                            batch_size, name=name)
    raise ValueError(f"unknown compression method {method!r}; "
                     f"choose from {METHODS}")


from .multi_field import (MultiFieldCompressedEmbedding,  # noqa: E402
                          MixedDimEmbedding)  # (need the registry above)
