"""hetu_tpu — a TPU-native distributed deep-learning framework.

Capability parity with AFDWang/Hetu (define-then-run dataflow graphs,
DP/TP/PP/EP(+SP/CP) parallelism, PS-backed sparse embeddings with bounded
staleness caches, auto-parallel search), rebuilt idiomatically on
JAX/XLA/Pallas: the op DAG traces into a single jitted XLA program,
collectives come from GSPMD/shard_map over a device mesh, and the hot kernels
are Pallas.  See SURVEY.md for the reference structural map this follows.
"""

from __future__ import annotations

import numpy as np

from .graph import (Op, PlaceholderOp, VariableOp, find_topo_sort,
                    graph_variables, gradients, Executor, stage,
                    name_scope, remat)
from . import initializers as init
from .ops import *  # noqa: F401,F403
from .optim import (SGDOptimizer, MomentumOptimizer, AdaGradOptimizer,
                    AdamOptimizer, AdamWOptimizer, AMSGradOptimizer,
                    LambOptimizer)
from .optim import lr_scheduler
from . import ps
from . import resilience
from .resilience import (CheckpointError, GuardTripped,
                         RollingCheckpointManager, StepGuard, retry)
from . import metrics
from . import telemetry
from .dataloader import Dataloader, DataloaderOp, dataloader_op
from .datasets.prefetch import DevicePrefetcher, prefetch_feeds
from .logger import HetuLogger, WandbLogger
from .profiler import HetuProfiler, HetuSimulator
from . import timeline
from . import embed_compress
from . import onnx
from . import graphboard
from .launcher import DistConfig, launch, launch_local, initialize_from_env

__version__ = "0.1.0"


def placeholder_op(name, shape=None, dtype=np.float32, trainable=False):
    """Create a fed input node (reference: gpu_ops/Variable.py)."""
    return PlaceholderOp(name, shape=shape, dtype=dtype)


def Variable(name, value=None, initializer=None, shape=None, trainable=True,
             dtype=np.float32):
    """Create a persistent (optionally trainable) tensor.

    Either ``value`` (a concrete numpy array) or ``initializer`` + ``shape``
    must be given, matching the reference's Variable signature.
    """
    if value is not None:
        value = np.asarray(value)
        initializer = init.NumpyInit(value)
        shape = value.shape
    assert initializer is not None and shape is not None, \
        "Variable needs value= or (initializer=, shape=)"
    return VariableOp(name, shape, initializer, trainable=trainable,
                      dtype=dtype)


# torch/tf-style aliases used across reference examples
scalar = lambda name, value: Variable(name, value=np.asarray(value))  # noqa: E731
