from .optimizer import (Optimizer, OptimizerOp, SGDOptimizer,
                        MomentumOptimizer, AdaGradOptimizer, AdamOptimizer,
                        AdamWOptimizer, AMSGradOptimizer, LambOptimizer)
from .lr_scheduler import (LRScheduler, FixedScheduler, StepScheduler,
                           MultiStepScheduler, ExponentialScheduler,
                           CosineScheduler, LinearWarmupScheduler,
                           as_schedule)
