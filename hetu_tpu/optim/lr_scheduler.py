"""Learning-rate schedules (reference: /root/reference/python/hetu/lr_scheduler.py).

Schedules are pure functions of the (traced) step counter so they live inside
the jitted training step — no host round-trip per step.
"""

from __future__ import annotations

import jax.numpy as jnp


class LRScheduler:
    def get(self, step):
        raise NotImplementedError

    def __call__(self, step):
        return self.get(step)


class FixedScheduler(LRScheduler):
    def __init__(self, learning_rate):
        self.learning_rate = learning_rate

    def get(self, step):
        return jnp.asarray(self.learning_rate, dtype=jnp.float32)


class StepScheduler(LRScheduler):
    """lr * gamma^(step // step_size)."""

    def __init__(self, learning_rate, step_size, gamma=0.1):
        assert step_size > 0
        self.learning_rate = learning_rate
        self.step_size = step_size
        self.gamma = gamma

    def get(self, step):
        e = (step // self.step_size).astype(jnp.float32)
        return self.learning_rate * jnp.power(self.gamma, e)


class MultiStepScheduler(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1):
        self.learning_rate = learning_rate
        self.milestones = tuple(sorted(milestones))
        self.gamma = gamma

    def get(self, step):
        ms = jnp.asarray(self.milestones)
        n = jnp.sum(step >= ms).astype(jnp.float32)
        return self.learning_rate * jnp.power(self.gamma, n)


class ExponentialScheduler(LRScheduler):
    def __init__(self, learning_rate, gamma=0.99):
        self.learning_rate = learning_rate
        self.gamma = gamma

    def get(self, step):
        return self.learning_rate * jnp.power(self.gamma, step.astype(jnp.float32))


class CosineScheduler(LRScheduler):
    def __init__(self, learning_rate, total_steps, min_lr=0.0, warmup_steps=0):
        self.learning_rate = learning_rate
        self.total_steps = total_steps
        self.min_lr = min_lr
        self.warmup_steps = warmup_steps

    def get(self, step):
        s = step.astype(jnp.float32)
        warm = self.learning_rate * s / max(self.warmup_steps, 1)
        t = jnp.clip((s - self.warmup_steps)
                     / max(self.total_steps - self.warmup_steps, 1), 0.0, 1.0)
        cos = self.min_lr + 0.5 * (self.learning_rate - self.min_lr) \
            * (1.0 + jnp.cos(jnp.pi * t))
        return jnp.where(s < self.warmup_steps, warm, cos)


class LinearWarmupScheduler(LRScheduler):
    """Linear warmup then linear decay to zero (BERT-style)."""

    def __init__(self, learning_rate, warmup_steps, total_steps):
        self.learning_rate = learning_rate
        self.warmup_steps = warmup_steps
        self.total_steps = total_steps

    def get(self, step):
        s = step.astype(jnp.float32)
        warm = s / max(self.warmup_steps, 1)
        decay = jnp.clip((self.total_steps - s)
                         / max(self.total_steps - self.warmup_steps, 1), 0.0, 1.0)
        return self.learning_rate * jnp.where(s < self.warmup_steps, warm, decay)


def as_schedule(lr):
    if isinstance(lr, LRScheduler):
        return lr
    return FixedScheduler(lr)
