"""Optimizers as graph ops.

Reference: /root/reference/python/hetu/optimizer.py — `Optimizer.minimize`
builds gradient nodes and an `OptimizerOp` whose compute applies fused CUDA
updates (src/ops/Optimizers.cu).  Here the update math is plain jnp inside the
traced step, fused by XLA into the backward program; parameters are threaded
functionally (old value in, new value out) with buffer donation, which is the
TPU analogue of the reference's in-place kernels.

Sparse (IndexedSlices) updates: the reference keeps sparse-aware op pairs for
embedding grads.  Under XLA, gradient-of-gather is already a scatter-add that
never densifies the embedding table update path when wrapped in
``apply_sparse`` (segment-sum on unique ids); the ps/ subsystem additionally
hosts server-side optimizer states for PS-mode tables.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graph.node import Op, VariableOp
from ..graph.autodiff import gradients
from .lr_scheduler import as_schedule


class Optimizer:
    """Base optimizer: subclasses define slot init + dense update rule."""

    slot_names = ()

    def __init__(self, learning_rate=0.01, l2reg=0.0):
        self.lr = as_schedule(learning_rate)
        self.l2reg = l2reg

    # -- functional update rule -------------------------------------------
    def init_slots(self, param):
        return {name: jnp.zeros_like(param) for name in self.slot_names}

    def apply_dense(self, param, grad, slots, lr, step):
        raise NotImplementedError

    def _regularized(self, param, grad):
        if self.l2reg > 0.0:
            return grad + self.l2reg * param
        return grad

    # -- graph construction ------------------------------------------------
    def minimize(self, loss, var_list=None):
        from ..graph.node import graph_variables
        if var_list is None:
            var_list = graph_variables([loss], trainable_only=True)
        # var_list may be empty (all params PS-resident); the OptimizerOp
        # then only anchors the loss for PS-embedding grad derivation
        grads = gradients(loss, var_list) if var_list else []
        op = OptimizerOp(grads, var_list, self)
        op.loss = loss  # lets the executor derive PS-embedding grads
        return op

    def apply_gradients(self, grads_and_vars):
        grads, var_list = zip(*grads_and_vars)
        return OptimizerOp(list(grads), list(var_list), self)


class SGDOptimizer(Optimizer):
    def apply_dense(self, param, grad, slots, lr, step):
        grad = self._regularized(param, grad)
        return param - lr * grad, slots


class MomentumOptimizer(Optimizer):
    slot_names = ("velocity",)

    def __init__(self, learning_rate=0.01, momentum=0.9, nesterov=False,
                 l2reg=0.0):
        super().__init__(learning_rate, l2reg)
        self.momentum = momentum
        self.nesterov = nesterov

    def apply_dense(self, param, grad, slots, lr, step):
        grad = self._regularized(param, grad)
        v = self.momentum * slots["velocity"] - lr * grad
        if self.nesterov:
            new_param = param + self.momentum * v - lr * grad
        else:
            new_param = param + v
        return new_param, {"velocity": v}


class AdaGradOptimizer(Optimizer):
    slot_names = ("accum",)

    def __init__(self, learning_rate=0.01, initial_accumulator_value=0.0,
                 eps=1e-7, l2reg=0.0):
        super().__init__(learning_rate, l2reg)
        self.initial_accumulator_value = initial_accumulator_value
        self.eps = eps

    def init_slots(self, param):
        return {"accum": jnp.full_like(param, self.initial_accumulator_value)}

    def apply_dense(self, param, grad, slots, lr, step):
        grad = self._regularized(param, grad)
        acc = slots["accum"] + grad * grad
        new_param = param - lr * grad / (jnp.sqrt(acc) + self.eps)
        return new_param, {"accum": acc}


class AdamOptimizer(Optimizer):
    slot_names = ("m", "v")

    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999, eps=1e-7,
                 amsgrad=False, l2reg=0.0):
        super().__init__(learning_rate, l2reg)
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.amsgrad = amsgrad

    def init_slots(self, param):
        slots = {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}
        if self.amsgrad:
            slots["vhat"] = jnp.zeros_like(param)
        return slots

    def _moments(self, grad, slots, step):
        t = step.astype(jnp.float32) + 1.0
        m = self.beta1 * slots["m"] + (1.0 - self.beta1) * grad
        v = self.beta2 * slots["v"] + (1.0 - self.beta2) * grad * grad
        # bias correction from the step counter replaces the reference's
        # BetatsUpdateOp running-product state (optimizer.py:434).
        mhat = m / (1.0 - jnp.power(self.beta1, t))
        vhat = v / (1.0 - jnp.power(self.beta2, t))
        return m, v, mhat, vhat

    def apply_dense(self, param, grad, slots, lr, step):
        grad = self._regularized(param, grad)
        m, v, mhat, vhat = self._moments(grad, slots, step)
        new_slots = {"m": m, "v": v}
        if self.amsgrad:
            vmax = jnp.maximum(slots["vhat"], vhat)
            new_slots["vhat"] = vmax
            denom = jnp.sqrt(vmax) + self.eps
        else:
            denom = jnp.sqrt(vhat) + self.eps
        return param - lr * mhat / denom, new_slots


class AMSGradOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999, eps=1e-7,
                 l2reg=0.0):
        super().__init__(learning_rate, beta1, beta2, eps, amsgrad=True,
                         l2reg=l2reg)


class AdamWOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999, eps=1e-7,
                 weight_decay=0.01):
        super().__init__(learning_rate, beta1, beta2, eps)
        self.weight_decay = weight_decay

    def apply_dense(self, param, grad, slots, lr, step):
        m, v, mhat, vhat = self._moments(grad, slots, step)
        update = mhat / (jnp.sqrt(vhat) + self.eps) + self.weight_decay * param
        return param - lr * update, {"m": m, "v": v}


class LambOptimizer(AdamOptimizer):
    """Layer-wise adaptive moments (reference optimizer.py:686)."""

    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999, eps=1e-6,
                 weight_decay=0.0):
        super().__init__(learning_rate, beta1, beta2, eps)
        self.weight_decay = weight_decay

    def apply_dense(self, param, grad, slots, lr, step):
        m, v, mhat, vhat = self._moments(grad, slots, step)
        update = mhat / (jnp.sqrt(vhat) + self.eps) + self.weight_decay * param
        w_norm = jnp.linalg.norm(param.reshape(-1))
        u_norm = jnp.linalg.norm(update.reshape(-1))
        trust = jnp.where(w_norm > 0,
                          jnp.where(u_norm > 0, w_norm / u_norm, 1.0), 1.0)
        return param - lr * trust * update, {"m": m, "v": v}


class OptimizerOp(Op):
    """Graph node applying the optimizer to (grad, var) pairs.

    Evaluated with env access: reads current parameter values bound in the
    trace env, reads/writes optimizer slot state via the TraceContext, records
    new parameter values for the executor to thread out.  Evaluates to None
    (matching reference train_op semantics).
    """

    def __init__(self, grads, var_list, optimizer, clip_global_norm=None):
        assert len(grads) == len(var_list)
        super().__init__(*grads, name=f"optimizer_{_opt_count()}")
        self.var_list = list(var_list)
        self.optimizer = optimizer
        self.clip_global_norm = clip_global_norm
        self.loss = None
        for v in var_list:
            assert isinstance(v, VariableOp), f"cannot optimize {v}"

    @property
    def is_stateful(self):
        return True

    def init_state(self, params):
        """Initial optimizer state given {var_name: value}."""
        return {
            "step": jnp.zeros((), dtype=jnp.int32),
            "slots": {v.name: self.optimizer.init_slots(params[v.name])
                      for v in self.var_list},
        }

    def _compute_with_env(self, env, ctx):
        state = ctx.opt_state[self.name]
        step = state["step"]
        lr = self.optimizer.lr.get(step)
        grads = [env[g] for g in self.inputs]
        if self.clip_global_norm is not None:
            # accumulate the norm in f32 (bf16 grads under mixed precision
            # would underestimate it once the sum saturates the mantissa)
            gnorm = jnp.sqrt(sum(
                jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads))
            scale = jnp.minimum(1.0, self.clip_global_norm / (gnorm + 1e-6))
            grads = [g * scale for g in grads]
        new_slots = {}
        master = ctx.master_params
        for var, grad in zip(self.var_list, grads):
            # mixed precision: update the full-precision master copy, not
            # the low-precision working value bound in the trace env.
            param = master[var.name] if (master is not None
                                         and var.name in master) else env[var]
            grad = grad.astype(param.dtype)
            new_p, ns = self.optimizer.apply_dense(
                param, grad, state["slots"][var.name], lr, step)
            new_slots[var.name] = ns
            ctx.record_update(var, new_p)
        ctx.new_opt_state[self.name] = {"step": step + 1, "slots": new_slots}
        return None


_opt_counter = [0]


def _opt_count():
    _opt_counter[0] += 1
    return _opt_counter[0]
