"""Optimizers as graph ops.

Reference: /root/reference/python/hetu/optimizer.py — `Optimizer.minimize`
builds gradient nodes and an `OptimizerOp` whose compute applies fused CUDA
updates (src/ops/Optimizers.cu).  Here the update math is plain jnp inside the
traced step, fused by XLA into the backward program; parameters are threaded
functionally (old value in, new value out) with buffer donation, which is the
TPU analogue of the reference's in-place kernels.

Sparse (IndexedSlices) updates: the reference keeps sparse-aware op pairs for
embedding grads.  Under XLA, gradient-of-gather is already a scatter-add that
never densifies the embedding table update path when wrapped in
``apply_sparse`` (segment-sum on unique ids); the ps/ subsystem additionally
hosts server-side optimizer states for PS-mode tables.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graph.node import Op, VariableOp
from ..graph.autodiff import gradients
from .lr_scheduler import as_schedule


class Optimizer:
    """Base optimizer: subclasses define slot init + dense update rule."""

    slot_names = ()
    # rowwise (lazy) sparse application is exact for elementwise update
    # rules; optimizers with whole-tensor terms (Lamb trust ratio) opt out
    supports_sparse = True

    def __init__(self, learning_rate=0.01, l2reg=0.0):
        self.lr = as_schedule(learning_rate)
        self.l2reg = l2reg

    # -- functional update rule -------------------------------------------
    def init_slots(self, param):
        return {name: jnp.zeros_like(param) for name in self.slot_names}

    def apply_dense(self, param, grad, slots, lr, step):
        raise NotImplementedError

    def apply_sparse(self, param, ids, grad_rows, slots, lr, step):
        """LAZY sparse update (reference src/ops/OptimizersSparse.cu):
        gather the touched rows of param and slots, run the dense rule
        rowwise, scatter back — untouched rows (and their moments) are
        never read or written.  ``ids`` are deduped with pad -1."""
        mask = (ids >= 0).reshape(-1, *([1] * (param.ndim - 1)))
        gather = jnp.maximum(ids, 0).astype(jnp.int32)
        # pad entries write OUT OF BOUNDS so the scatter DROPS them — a
        # clamped pad index would race the real row-0 update (duplicate
        # scatter indices have no ordering guarantee)
        scatter = jnp.where(ids >= 0, ids,
                            param.shape[0]).astype(jnp.int32)
        p_rows = param[gather]
        s_rows = {k: v[gather] for k, v in slots.items()}
        g_rows = jnp.where(mask, grad_rows.astype(param.dtype), 0)
        new_rows, new_s = self.apply_dense(p_rows, g_rows, s_rows, lr, step)
        new_param = param.at[scatter].set(new_rows, mode="drop")
        new_slots = {k: slots[k].at[scatter].set(new_s[k], mode="drop")
                     for k in slots}
        return new_param, new_slots

    def _regularized(self, param, grad):
        if self.l2reg > 0.0:
            return grad + self.l2reg * param
        return grad

    # -- graph construction ------------------------------------------------
    def minimize(self, loss, var_list=None, sparse_vars=()):
        """Build grads + the OptimizerOp.

        ``sparse_vars``: variables (embedding tables) to update LAZILY —
        gradients are taken w.r.t. their lookup OUTPUTS and applied as
        deduped (ids, rows) without ever densifying a [V, H] gradient
        (reference optimizer.py sparse op pairs + OptimizersSparse.cu).
        A listed var consumed by anything other than embedding_lookup
        falls back to the dense path.
        """
        from ..graph.node import graph_variables, find_topo_sort
        if var_list is None:
            var_list = graph_variables([loss], trainable_only=True)
        sparse_set = set(sparse_vars)
        if sparse_set and not self.supports_sparse:
            raise ValueError(
                f"{type(self).__name__} has whole-tensor update terms; "
                "rowwise sparse application would change its semantics")
        unknown = sparse_set - set(var_list)
        if unknown:
            # loud, not silent: a sparse var outside var_list would get
            # no gradient and no fallback — the table would never train
            raise ValueError(
                "sparse_vars must be optimized variables (in var_list / "
                "trainable): " + ", ".join(v.name for v in unknown))
        dense_vars, sparse_entries = [], []
        topo = find_topo_sort([loss]) if sparse_set else []
        for v in var_list:
            if v not in sparse_set:
                dense_vars.append(v)
                continue
            uses = [n for n in topo if v in n.inputs]
            lookups = [n for n in uses
                       if getattr(n, "op_kind", None) == "embedding_lookup"
                       and n.inputs[0] is v]
            if not lookups or len(uses) != len(lookups):
                dense_vars.append(v)     # non-lookup uses: stay dense
                continue
            sparse_entries.append((v, lookups))
        targets = dense_vars + [lk for _, lks in sparse_entries
                                for lk in lks]
        # var_list may be empty (all params PS-resident); the OptimizerOp
        # then only anchors the loss for PS-embedding grad derivation
        grads = gradients(loss, targets) if targets else []
        nd = len(dense_vars)
        sparse, k = [], nd
        for v, lks in sparse_entries:
            sites = []
            for lk in lks:
                sites.append((grads[k], lk.inputs[1]))
                k += 1
            sparse.append((v, sites))
        op = OptimizerOp(grads[:nd], dense_vars, self, sparse=sparse)
        op.loss = loss  # lets the executor derive PS-embedding grads
        return op

    def apply_gradients(self, grads_and_vars):
        grads, var_list = zip(*grads_and_vars)
        return OptimizerOp(list(grads), list(var_list), self)


class SGDOptimizer(Optimizer):
    def apply_dense(self, param, grad, slots, lr, step):
        grad = self._regularized(param, grad)
        return param - lr * grad, slots


class MomentumOptimizer(Optimizer):
    slot_names = ("velocity",)

    def __init__(self, learning_rate=0.01, momentum=0.9, nesterov=False,
                 l2reg=0.0):
        super().__init__(learning_rate, l2reg)
        self.momentum = momentum
        self.nesterov = nesterov

    def apply_dense(self, param, grad, slots, lr, step):
        grad = self._regularized(param, grad)
        v = self.momentum * slots["velocity"] - lr * grad
        if self.nesterov:
            new_param = param + self.momentum * v - lr * grad
        else:
            new_param = param + v
        return new_param, {"velocity": v}


class AdaGradOptimizer(Optimizer):
    slot_names = ("accum",)

    def __init__(self, learning_rate=0.01, initial_accumulator_value=0.0,
                 eps=1e-7, l2reg=0.0):
        super().__init__(learning_rate, l2reg)
        self.initial_accumulator_value = initial_accumulator_value
        self.eps = eps

    def init_slots(self, param):
        return {"accum": jnp.full_like(param, self.initial_accumulator_value)}

    def apply_dense(self, param, grad, slots, lr, step):
        grad = self._regularized(param, grad)
        acc = slots["accum"] + grad * grad
        new_param = param - lr * grad / (jnp.sqrt(acc) + self.eps)
        return new_param, {"accum": acc}


class AdamOptimizer(Optimizer):
    slot_names = ("m", "v")

    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999, eps=1e-7,
                 amsgrad=False, l2reg=0.0):
        super().__init__(learning_rate, l2reg)
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.amsgrad = amsgrad

    def init_slots(self, param):
        slots = {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}
        if self.amsgrad:
            slots["vhat"] = jnp.zeros_like(param)
        return slots

    def _moments(self, grad, slots, step):
        t = step.astype(jnp.float32) + 1.0
        m = self.beta1 * slots["m"] + (1.0 - self.beta1) * grad
        v = self.beta2 * slots["v"] + (1.0 - self.beta2) * grad * grad
        # bias correction from the step counter replaces the reference's
        # BetatsUpdateOp running-product state (optimizer.py:434).
        mhat = m / (1.0 - jnp.power(self.beta1, t))
        vhat = v / (1.0 - jnp.power(self.beta2, t))
        return m, v, mhat, vhat

    def apply_dense(self, param, grad, slots, lr, step):
        grad = self._regularized(param, grad)
        m, v, mhat, vhat = self._moments(grad, slots, step)
        new_slots = {"m": m, "v": v}
        if self.amsgrad:
            vmax = jnp.maximum(slots["vhat"], vhat)
            new_slots["vhat"] = vmax
            denom = jnp.sqrt(vmax) + self.eps
        else:
            denom = jnp.sqrt(vhat) + self.eps
        return param - lr * mhat / denom, new_slots


class AMSGradOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999, eps=1e-7,
                 l2reg=0.0):
        super().__init__(learning_rate, beta1, beta2, eps, amsgrad=True,
                         l2reg=l2reg)


class AdamWOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999, eps=1e-7,
                 weight_decay=0.01):
        super().__init__(learning_rate, beta1, beta2, eps)
        self.weight_decay = weight_decay

    def apply_dense(self, param, grad, slots, lr, step):
        m, v, mhat, vhat = self._moments(grad, slots, step)
        update = mhat / (jnp.sqrt(vhat) + self.eps) + self.weight_decay * param
        return param - lr * update, {"m": m, "v": v}


class LambOptimizer(AdamOptimizer):
    """Layer-wise adaptive moments (reference optimizer.py:686)."""

    supports_sparse = False   # whole-tensor trust ratio

    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999, eps=1e-6,
                 weight_decay=0.0):
        super().__init__(learning_rate, beta1, beta2, eps)
        self.weight_decay = weight_decay

    def apply_dense(self, param, grad, slots, lr, step):
        m, v, mhat, vhat = self._moments(grad, slots, step)
        update = mhat / (jnp.sqrt(vhat) + self.eps) + self.weight_decay * param
        w_norm = jnp.linalg.norm(param.reshape(-1))
        u_norm = jnp.linalg.norm(update.reshape(-1))
        trust = jnp.where(w_norm > 0,
                          jnp.where(u_norm > 0, w_norm / u_norm, 1.0), 1.0)
        return param - lr * trust * update, {"m": m, "v": v}


class OptimizerOp(Op):
    """Graph node applying the optimizer to (grad, var) pairs.

    Evaluated with env access: reads current parameter values bound in the
    trace env, reads/writes optimizer slot state via the TraceContext, records
    new parameter values for the executor to thread out.  Evaluates to None
    (matching reference train_op semantics).
    """

    def __init__(self, grads, var_list, optimizer, clip_global_norm=None,
                 sparse=None):
        assert len(grads) == len(var_list)
        # sparse: [(var, [(rows_grad_node, ids_node), ...]), ...] — lazy
        # embedding updates (Optimizer.minimize sparse_vars)
        self.sparse = list(sparse or [])
        extra = [n for _, sites in self.sparse
                 for g, ids in sites for n in (g, ids)]
        super().__init__(*grads, *extra, name=f"optimizer_{_opt_count()}")
        self.var_list = list(var_list)
        self.optimizer = optimizer
        self.clip_global_norm = clip_global_norm
        self.loss = None
        for v in list(var_list) + [v for v, _ in self.sparse]:
            assert isinstance(v, VariableOp), f"cannot optimize {v}"

    @property
    def is_stateful(self):
        return True

    def init_state(self, params):
        """Initial optimizer state given {var_name: value}."""
        return {
            "step": jnp.zeros((), dtype=jnp.int32),
            "slots": {v.name: self.optimizer.init_slots(params[v.name])
                      for v in (self.var_list
                                + [sv for sv, _ in self.sparse])},
        }

    @staticmethod
    def _bucket(n, floor=64):
        b = floor
        while b < n:
            b *= 2
        return b

    def _compute_with_env(self, env, ctx):
        from ..ops.embedding import reduce_indexedslices
        state = ctx.opt_state[self.name]
        step = state["step"]
        lr = self.optimizer.lr.get(step)
        grads = [env[g] for g in self.inputs[:len(self.var_list)]]
        # lazy-sparse vars: dedup each var's (ids, rows) across its
        # lookup sites FIRST, so the clip norm matches the dense norm
        # exactly (duplicate ids would double-count otherwise)
        sparse_ready = []
        for var, sites in self.sparse:
            ids = jnp.concatenate(
                [env[i].reshape(-1) for _, i in sites]).astype(jnp.int32)
            rows = jnp.concatenate(
                [env[g].reshape(-1, env[g].shape[-1]) for g, _ in sites])
            uniq, summed = reduce_indexedslices(
                ids, rows, self._bucket(int(ids.shape[0])))
            sparse_ready.append((var, uniq, summed))
        if self.clip_global_norm is not None:
            # accumulate the norm in f32 (bf16 grads under mixed precision
            # would underestimate it once the sum saturates the mantissa)
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for g in grads)
            sq += sum(jnp.sum(jnp.square(r.astype(jnp.float32)))
                      for _, _, r in sparse_ready)
            gnorm = jnp.sqrt(sq)
            scale = jnp.minimum(1.0, self.clip_global_norm / (gnorm + 1e-6))
            grads = [g * scale for g in grads]
            sparse_ready = [(v, i, r * scale) for v, i, r in sparse_ready]
        new_slots = {}
        master = ctx.master_params

        def _param_of(var):
            # mixed precision: update the full-precision master copy, not
            # the low-precision working value bound in the trace env.
            return master[var.name] if (master is not None
                                        and var.name in master) else env[var]

        for var, grad in zip(self.var_list, grads):
            param = _param_of(var)
            grad = grad.astype(param.dtype)
            new_p, ns = self.optimizer.apply_dense(
                param, grad, state["slots"][var.name], lr, step)
            new_slots[var.name] = ns
            ctx.record_update(var, new_p)
        for var, uniq, summed in sparse_ready:
            param = _param_of(var)
            new_p, ns = self.optimizer.apply_sparse(
                param, uniq, summed, state["slots"][var.name], lr, step)
            new_slots[var.name] = ns
            ctx.record_update(var, new_p)
        ctx.new_opt_state[self.name] = {"step": step + 1, "slots": new_slots}
        return None


_opt_counter = [0]


def _opt_count():
    _opt_counter[0] += 1
    return _opt_counter[0]
