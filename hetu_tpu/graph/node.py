"""Graph node model: the define-then-run op DAG.

TPU-native re-design of the reference's op/node layer
(/root/reference/python/hetu/gpu_ops/Node.py:20 `class Op`).  The reference
dispatches each node through ctypes into hand-written CUDA kernels; here every
op's ``compute`` is a pure jax-traceable function, and the whole DAG is traced
once into a single XLA program by the executor (see graph/executor.py).  That
means:

  * no per-op streams/events — XLA owns scheduling,
  * no hand-written shape rules — shapes come from ``jax.eval_shape``,
  * no hand-written per-op gradients — autodiff is trace-time ``jax.vjp``
    (graph/autodiff.py), with op-level custom VJPs only for Pallas kernels.

The graph API itself (placeholders, Variables, functional ``*_op``
constructors, ``Executor``) is kept compatible in spirit with the reference so
users of Hetu find the same surface.
"""

from __future__ import annotations

import numpy as np

_node_counter = [0]


def _next_id() -> int:
    _node_counter[0] += 1
    return _node_counter[0]


import threading as _threading

_stage_tls = _threading.local()


def _stage_stack():
    # thread-local: launcher.launch_local builds graphs on worker threads
    # concurrently; a shared stack would cross-assign their stages
    stack = getattr(_stage_tls, "stack", None)
    if stack is None:
        stack = _stage_tls.stack = [None]
    return stack


class stage:
    """Pipeline-stage scope: ops created inside get ``raw_ctx = idx``.

    Mirrors the reference's ``with ht.context(ctx)`` device-group scoping
    (context.py:830) that drives pipeline stage inference
    (executor.py:1430); here the annotation is consumed by
    parallel/graph_pipeline.py.  Nests: the innermost scope wins.
    """

    def __init__(self, idx):
        self.idx = int(idx)

    def __enter__(self):
        _stage_stack().append(self.idx)
        return self

    def __exit__(self, *exc):
        _stage_stack().pop()
        return False


_remat_tls = _threading.local()
_remat_counter = [0]


def _remat_stack():
    stack = getattr(_remat_tls, "stack", None)
    if stack is None:
        stack = _remat_tls.stack = [None]
    return stack


class remat:
    """Rematerialization scope: ops created inside form one
    `jax.checkpoint` group — their activations are NOT saved for the
    backward pass; the group recomputes during the vjp instead.

    The graph-API face of the reference's memory planner (SURVEY §2.2
    P10: memory_pool.py / swap — on TPU the trade is FLOPs-for-HBM via
    remat, not host swap).  Typical use wraps each transformer layer::

        with ht.remat():
            x = layer(x, ...)

    Stateful ops (batchnorm update, assign) must stay outside — the
    recompute would replay their side effects; `evaluate` raises.
    Nested scopes merge into the outermost group (one coarse checkpoint).
    """

    def __enter__(self):
        _remat_counter[0] += 1
        self.idx = _remat_counter[0]
        _remat_stack().append(self.idx)
        return self

    def __exit__(self, *exc):
        _remat_stack().pop()
        return False


def current_stage():
    return _stage_stack()[-1]


_naming_tls = _threading.local()


def _naming_stack():
    # index 0 is the process-global namespace (scope-less construction
    # keeps its historical behavior); each `with name_scope():` pushes a
    # fresh namespace so names are deterministic per instance.
    stack = getattr(_naming_tls, "stack", None)
    if stack is None:
        stack = _naming_tls.stack = [{"vars": {}, "layers": {}}]
    return stack


class name_scope:
    """Fresh, deterministic naming namespace for variables and layers.

    Construction inside ``with name_scope():`` always produces the same
    variable names, independent of what else was built in the process
    before — so checkpoints keyed by name are stable across construction
    order.  Model constructors open one per instance.  Genuine collisions
    (two same-named variables reaching one Executor) raise there instead
    of being silently renamed.
    """

    def __enter__(self):
        _naming_stack().append({"vars": {}, "layers": {}})
        return self

    def __exit__(self, *exc):
        _naming_stack().pop()
        return False


def scoped_init(init):
    """Decorator: run a model's ``__init__`` inside its own `name_scope`,
    making its parameter names independent of construction order."""
    import functools

    @functools.wraps(init)
    def wrapper(self, *args, **kwargs):
        with name_scope():
            return init(self, *args, **kwargs)

    return wrapper


def _unique_var_name(name: str) -> str:
    table = _naming_stack()[-1]["vars"]
    count = table.get(name)
    if count is None:
        table[name] = 1
        return name
    table[name] = count + 1
    name = f"{name}_{count}"
    table[name] = 1
    return name


class Op:
    """A node in the dataflow graph.

    Subclasses implement ``_compute(input_vals, ctx)`` as a pure jax function
    of the input arrays.  ``ctx`` is a TraceContext (graph/trace.py) giving
    access to per-step RNG, the training flag, and state-update recording.
    """

    __slots__ = (
        "id", "name", "inputs", "attrs", "dist_state", "raw_ctx",
        "remat_scope", "_shape_cache",
    )

    def __init__(self, *inputs, name=None, **attrs):
        self.id = _next_id()
        self.inputs = list(inputs)
        self.name = name or f"{type(self).__name__}_{self.id}"
        self.attrs = attrs
        # Sharding annotation (parallel/mesh.py DistState), set by dispatch()
        # or by a Strategy; mirrors reference NodeStatus (context.py:248).
        self.dist_state = None
        # Device-group annotation for pipeline-stage placement; mirrors
        # reference raw_ctx (Node.py / context.py DeviceGroup).  Picked up
        # from an enclosing `with stage(i):` scope.
        self.raw_ctx = _stage_stack()[-1]
        # `with remat():` group id (jax.checkpoint at trace time), or
        # None.  The OUTERMOST active scope wins: nested scopes merge
        # into one coarser checkpoint group (wrapping a block whose
        # sublayers also remat composes instead of erroring).
        _rs = _remat_stack()
        self.remat_scope = next((s for s in _rs[1:] if s is not None),
                                None) if len(_rs) > 1 else None
        self._shape_cache = None

    # -- graph protocol ----------------------------------------------------
    def _compute(self, input_vals, ctx):
        raise NotImplementedError(type(self).__name__)

    @property
    def needs_rng(self) -> bool:
        return False

    @property
    def is_stateful(self) -> bool:
        """True for ops that update variables (optimizer, batchnorm, assign)."""
        return False

    # -- sugar -------------------------------------------------------------
    def __add__(self, other):
        from ..ops.math import add_op, addbyconst_op
        if isinstance(other, Op):
            return add_op(self, other)
        return addbyconst_op(self, other)

    __radd__ = __add__

    def __mul__(self, other):
        from ..ops.math import mul_op, mulbyconst_op
        if isinstance(other, Op):
            return mul_op(self, other)
        return mulbyconst_op(self, other)

    __rmul__ = __mul__

    def __sub__(self, other):
        from ..ops.math import sub_op, addbyconst_op, mulbyconst_op
        if isinstance(other, Op):
            return sub_op(self, other)
        return addbyconst_op(self, -other)

    def __rsub__(self, other):
        from ..ops.math import mulbyconst_op, addbyconst_op
        return addbyconst_op(mulbyconst_op(self, -1.0), other)

    def __neg__(self):
        from ..ops.math import mulbyconst_op
        return mulbyconst_op(self, -1.0)

    def __truediv__(self, other):
        from ..ops.math import div_op, mulbyconst_op
        if isinstance(other, Op):
            return div_op(self, other)
        return mulbyconst_op(self, 1.0 / other)

    def __matmul__(self, other):
        from ..ops.linalg import matmul_op
        return matmul_op(self, other)

    def __repr__(self):
        return f"<{type(self).__name__} {self.name} #{self.id}>"

    def __hash__(self):
        return self.id

    def __eq__(self, other):
        return self is other


class PlaceholderOp(Op):
    """Fed input (reference: gpu_ops/Variable.py placeholder path)."""

    __slots__ = ("shape", "dtype")

    def __init__(self, name, shape=None, dtype=np.float32):
        super().__init__(name=name)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = np.dtype(dtype)

    def _compute(self, input_vals, ctx):  # value comes from feed_dict
        raise RuntimeError(f"placeholder {self.name} was not fed")


class VariableOp(Op):
    """Trainable / persistent state.

    Reference: gpu_ops/Variable.py Variable (initializer held on node,
    materialized by executor at construction).  Values live in the executor's
    functional state dict, not on the node.
    """

    # monitor: optional callable(float) -> warning-message-or-None; the
    # executor polls monitored variables host-side every monitor_interval
    # steps (in-graph counters, e.g. the BERT MLM overflow total)
    __slots__ = ("shape", "dtype", "initializer", "trainable", "monitor")

    # Executor state is keyed by variable name, so names must be unique
    # within a namespace (`name_scope`); the Executor raises on genuine
    # cross-scope collisions rather than silently renaming.

    def __init__(self, name, shape, initializer, trainable=True,
                 dtype=np.float32):
        name = _unique_var_name(name)
        super().__init__(name=name)
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.initializer = initializer
        self.trainable = bool(trainable)

    def _compute(self, input_vals, ctx):
        raise RuntimeError(
            f"variable {self.name} must be bound by the executor")


def find_topo_sort(node_list):
    """Post-order DFS topo sort (reference: executor.py:1515)."""
    visited = set()
    order = []

    def dfs(node):
        stack = [(node, False)]
        while stack:
            n, expanded = stack.pop()
            if expanded:
                order.append(n)
                continue
            if n.id in visited:
                continue
            visited.add(n.id)
            stack.append((n, True))
            for inp in reversed(n.inputs):
                if inp.id not in visited:
                    stack.append((inp, False))

    for node in node_list:
        dfs(node)
    return order


def graph_variables(node_list, trainable_only=False):
    """All VariableOps reachable from node_list, in topo order."""
    out = []
    for n in find_topo_sort(node_list):
        if isinstance(n, VariableOp) and (n.trainable or not trainable_only):
            out.append(n)
    return out


def graph_placeholders(node_list):
    return [n for n in find_topo_sort(node_list) if isinstance(n, PlaceholderOp)]
