"""Graph tracing: evaluate an op DAG as a pure jax function.

This replaces the reference's interpreted per-node dispatch loop
(/root/reference/python/hetu/gpu_ops/executor.py:1191 `SubExecutor.compute`):
instead of dispatching one ctypes kernel per node per step, we walk the topo
order ONCE inside `jax.jit` tracing, so the whole step compiles to a single
XLA program.  Python dispatch overhead disappears after the first call and XLA
fuses across op boundaries (the reference relied on stream overlap to hide its
per-node Python hot loop).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from .node import Op, PlaceholderOp, VariableOp, find_topo_sort


class TraceContext:
    """Per-trace services available to op ``_compute`` implementations.

    * ``rng_for(op)`` — deterministic per-op, per-step PRNG key (reference
      keeps a seed + seqnum in python/hetu/random.py:1-43 for reproducible
      dropout; here we fold the op id into the step key, which also makes the
      autodiff re-trace of the forward see identical randomness).
    * ``training`` — train/eval flag (dropout, batchnorm).
    * ``record_update(var, value)`` — stateful ops (batchnorm running stats,
      assign) register new values for VariableOps; the executor threads them
      into the functional state.
    """

    def __init__(self, key=None, training=True, mesh=None,
                 master_params=None, cp_impl="ring"):
        self.key = key
        self.training = training
        self.mesh = mesh
        # long-context lowering flavor over a 'cp' mesh axis: 'ring'
        # (K/V rotate the ICI ring) or 'ulysses' (all-to-all head
        # parallelism); Executor(cp_impl=...) selects it
        self.cp_impl = cp_impl
        self.updates = {}        # VariableOp -> new value (tracer)
        self.opt_state = {}      # {optimizer_op_name: state pytree} (input)
        self.new_opt_state = {}  # {optimizer_op_name: state pytree} (output)
        # mixed precision: full-precision {var_name: value} master copies;
        # set when the executor casts bindings to a lower compute dtype so
        # optimizers update the f32 masters, not the bf16 working copies.
        self.master_params = master_params

    def rng_for(self, op: Op):
        if self.key is None:
            raise RuntimeError(
                f"op {op.name} needs RNG but no key was provided to the trace")
        return jax.random.fold_in(self.key, op.id)

    def record_update(self, var: VariableOp, value):
        self.updates[var] = value


def evaluate(eval_nodes, bindings, ctx: TraceContext, topo=None,
             _remat=True):
    """Evaluate ``eval_nodes`` given ``bindings`` {node: value}.

    ``bindings`` must cover every PlaceholderOp/VariableOp reachable; other
    nodes may also be pre-bound (used by autodiff to rebase gradients).
    Returns (values list, env dict).  ``_remat=False`` disables remat-group
    handling (used INSIDE a group's checkpointed body, where the group's
    own nodes must evaluate plainly).
    """
    env = dict(bindings)
    if topo is None:
        topo = find_topo_sort(eval_nodes)
    # -- primal-fusion pass: gradient bundles compute the loss as their
    # vjp primal.  When the loss subgraph is stateless and the bundle's
    # other operands are already bound, run the bundle FIRST and inject
    # its primal as the loss value — the forward then traces exactly once
    # (XLA CSE does not reliably dedupe the re-trace, and cannot across
    # Pallas custom_vjp boundaries; 25% extra FLOPs on BERT-base).
    for node in topo:
        if (getattr(node, "fuses_primal", False) and node not in env
                and node.loss not in env
                and all(x in env for x in node.xs)
                and (node.grad_out is None or node.grad_out in env)):
            primal, grads, updates = node._compute_with_env(
                env, ctx, want_primal=True)
            env[node] = grads
            env[node.loss] = primal
            # stateful ops in the (now skipped) primal forward recorded
            # their updates on the vjp's inner trace; thread them out
            for var, val in updates.items():
                ctx.record_update(var, val)
    # -- demand pruning: with losses pre-bound, their interior forward
    # nodes may be orphaned; compute only what the eval nodes still need
    needed = set()
    stack = [n for n in eval_nodes if n not in env]
    while stack:
        n = stack.pop()
        if n.id in needed:
            continue
        needed.add(n.id)
        stack.extend(i for i in n.inputs if i not in env)
    # -- remat groups: ops created under `with ht.remat():` evaluate as
    # one jax.checkpoint'ed function (their activations recompute in the
    # vjp instead of being saved — the FLOPs-for-HBM memory planner)
    remat_groups = {}
    if _remat:
        for node in topo:
            if (node.id in needed and node not in env
                    and not isinstance(node, (PlaceholderOp, VariableOp))
                    and node.remat_scope is not None):
                remat_groups.setdefault(node.remat_scope, []).append(node)
    group_outputs = {}
    if remat_groups:
        eval_ids = {n.id for n in eval_nodes}
        consumed_outside = {}
        for n in topo:
            scope = getattr(n, "remat_scope", None)
            for i in n.inputs:
                iscope = getattr(i, "remat_scope", None)
                if iscope is not None and iscope != scope:
                    consumed_outside.setdefault(iscope, set()).add(i.id)
        for scope, group in remat_groups.items():
            outs = [n for n in group
                    if n.id in consumed_outside.get(scope, ())
                    or n.id in eval_ids]
            group_outputs[scope] = outs or group[-1:]

    done_ids = set()

    def eval_remat_group(scope):
        group = remat_groups[scope]
        gids = {n.id for n in group}
        for n in group:
            if n.is_stateful:
                raise ValueError(
                    f"stateful op {n.name} inside a remat scope — its "
                    "update would replay on recompute; move it outside")
        ins, seen = [], set()
        for n in group:
            for i in n.inputs:
                if i.id not in gids and i.id not in seen:
                    seen.add(i.id)
                    ins.append(i)
        missing = [i for i in ins if i not in env]
        if missing:
            # external inputs later in topo than the group's first node:
            # demand-evaluate them now (a cycle through the group itself
            # is impossible to checkpoint as one function)
            closure = find_topo_sort(missing)
            if any(getattr(c, "remat_scope", None) == scope
                   for c in closure if c not in env):
                raise ValueError(
                    "remat scope interleaves with outside computation; "
                    "split the scope")
            _, env2 = evaluate(missing, env, ctx)
            env.update(env2)
        outs = group_outputs[scope]

        def f(*in_vals):
            # bind ONLY the group's external inputs: everything the group
            # needs flows through the checkpoint boundary as an argument
            # (no closure captures), so the vjp recomputes exactly the
            # group's interior and saves only `ins`
            vals, _ = evaluate(outs, dict(zip(ins, in_vals)), ctx,
                               _remat=False)
            return tuple(vals)

        out_vals = jax.checkpoint(f)(*[env[i] for i in ins])
        for n, v in zip(outs, out_vals):
            env[n] = v
        done_ids.update(gids)

    for node in topo:
        if node in env or node.id not in needed or node.id in done_ids:
            continue
        if isinstance(node, (PlaceholderOp, VariableOp)):
            raise RuntimeError(f"{node} reached trace without a binding")
        if _remat and node.remat_scope is not None:
            eval_remat_group(node.remat_scope)
            continue
        if hasattr(node, "_compute_with_env"):
            env[node] = node._compute_with_env(env, ctx)
        else:
            input_vals = [env[i] for i in node.inputs]
            env[node] = node._compute(input_vals, ctx)
        # interior sharding annotations (set by a Strategy or ht.dispatch)
        # lower to with_sharding_constraint — the per-node reshard points
        # the reference's rewrite pass materialized as comm ops
        # (context.py:1469); GSPMD emits the collectives.
        if (node.dist_state is not None and ctx.mesh is not None
                and hasattr(env[node], "ndim")):
            sh = NamedSharding(ctx.mesh,
                               node.dist_state.to_pspec(env[node].ndim))
            env[node] = jax.lax.with_sharding_constraint(env[node], sh)
    return [env[n] for n in eval_nodes], env


def constant_like(shape, dtype, value=0):
    return jnp.full(shape, value, dtype=dtype)
