from .node import (Op, PlaceholderOp, VariableOp, find_topo_sort,
                   graph_variables, graph_placeholders, stage,
                   current_stage, name_scope, scoped_init, remat)
from .trace import TraceContext, evaluate
from .autodiff import gradients
from .executor import Executor, SubExecutor
from .checkpoint import save_sharded, load_sharded
