"""Sharded checkpointing (orbax-backed).

The reference pickles name→numpy on rank 0 and PS-resident params via
SaveParam RPCs (executor.py:558-670).  `Executor.save/load` keeps that
single-file contract (plus RNG state for bitwise resume); this module adds
the multi-host path: each host writes only its addressable shards and
restores straight into the live sharding layout, which is how TPU-pod
checkpoints must work (a 100B-param state never materializes on one host).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _state_tree(executor):
    return {
        "params": dict(executor.params),
        "opt_state": executor.opt_state,
        "meta": {
            "global_step": jnp.asarray(executor._global_step),
            "base_key": jax.random.key_data(executor._base_key),
        },
    }


def _abstract(leaf):
    """Restore template leaf: shape/dtype + the LIVE sharding so orbax
    reassembles each host's shards in place (no full-host materialization)."""
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                    sharding=leaf.sharding)
    arr = jnp.asarray(leaf)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def save_sharded(executor, path):
    """Write a sharded (orbax) checkpoint of params + optimizer state +
    RNG.  Safe to call from every process of a multi-host run."""
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(str(path), _state_tree(executor), force=True)
    ckptr.wait_until_finished()


def load_sharded(executor, path):
    """Restore a sharded checkpoint into the executor, preserving each
    value's current device placement/sharding."""
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    template = jax.tree_util.tree_map(_abstract, _state_tree(executor))
    state = ckptr.restore(str(path), template)
    # reuse the single restore contract (Executor.load_state_dict)
    executor.load_state_dict({
        "params": state["params"],
        "opt_state": state["opt_state"],
        "global_step": int(state["meta"]["global_step"]),
        "base_key": state["meta"]["base_key"],
    })
    return executor
