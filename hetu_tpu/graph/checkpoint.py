"""Sharded checkpointing (orbax-backed).

The reference pickles name→numpy on rank 0 and PS-resident params via
SaveParam RPCs (executor.py:558-670).  `Executor.save/load` keeps that
single-file contract (plus RNG state for bitwise resume); this module adds
the multi-host path: each host writes only its addressable shards and
restores straight into the live sharding layout, which is how TPU-pod
checkpoints must work (a 100B-param state never materializes on one host).
"""

from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp


class CheckpointError(RuntimeError):
    """A checkpoint file or payload is torn, corrupt, or structurally
    invalid.  Raised instead of the opaque ``KeyError``/unpickle crash a
    garbage or stale file used to produce, so callers (and the rolling
    checkpoint manager's fallback scan) can tell "bad file" from "bug"."""


# the single-file checkpoint contract (Executor.state_dict); "format" /
# "opt_meta" are optional so pre-tag checkpoints keep loading
REQUIRED_STATE_KEYS = frozenset(
    {"params", "opt_state", "global_step", "base_key"})
SUPPORTED_FORMAT_VERSIONS = (1,)


def validate_state(state, source="checkpoint"):
    """Check a checkpoint payload against the state_dict contract.

    Raises :class:`CheckpointError` naming exactly what is wrong
    (non-dict payload, missing required keys, format version from a
    newer writer) instead of letting ``load_state_dict`` die on an
    arbitrary ``KeyError`` deep inside the restore."""
    if not isinstance(state, dict):
        raise CheckpointError(
            f"{source}: payload is {type(state).__name__}, expected the "
            "dict produced by Executor.state_dict()")
    missing = sorted(REQUIRED_STATE_KEYS - set(state))
    if missing:
        raise CheckpointError(
            f"{source}: missing required keys {missing} — not an "
            "Executor checkpoint (or a torn/stale file)")
    if not isinstance(state["params"], dict):
        raise CheckpointError(
            f"{source}: 'params' is {type(state['params']).__name__}, "
            "expected a name->array dict")
    fmt = state.get("format")
    if fmt is not None:
        if not isinstance(fmt, dict):
            raise CheckpointError(
                f"{source}: 'format' is {type(fmt).__name__}, expected a "
                "dict tag")
        version = fmt.get("version")
        if version is not None and version not in SUPPORTED_FORMAT_VERSIONS:
            raise CheckpointError(
                f"{source}: format version {version} is newer than this "
                f"build supports ({SUPPORTED_FORMAT_VERSIONS}); upgrade "
                "hetu_tpu or re-save the checkpoint from the old version")
    return state


def atomic_write_bytes(blob, path):
    """Write ``blob`` to ``path`` via a same-directory temp file +
    ``os.replace``: a kill mid-write leaves the previous file intact and
    never a half-written one under the final name."""
    path = str(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return path


def atomic_pickle(state, path):
    """Pickle ``state`` to ``path`` torn-proof (tmp + ``os.replace``)."""
    return atomic_write_bytes(
        pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL), path)


def read_checkpoint(path):
    """Read + unpickle + validate a single-file checkpoint.

    Garbage, truncated, or non-checkpoint pickles surface as
    :class:`CheckpointError` with the path named; a missing file stays a
    ``FileNotFoundError`` (a different operator mistake)."""
    with open(path, "rb") as f:
        blob = f.read()
    try:
        state = pickle.loads(blob)
    except Exception as e:  # pickle raises a zoo of types on garbage
        raise CheckpointError(
            f"{path}: not a readable checkpoint "
            f"({type(e).__name__}: {e}) — torn write or corrupt file?"
        ) from e
    return validate_state(state, source=str(path))


def _state_tree(executor):
    return {
        "params": dict(executor.params),
        "opt_state": executor.opt_state,
        "meta": {
            "global_step": jnp.asarray(executor._global_step),
            "base_key": jax.random.key_data(executor._base_key),
        },
    }


def _abstract(leaf):
    """Restore template leaf: shape/dtype + the LIVE sharding so orbax
    reassembles each host's shards in place (no full-host materialization)."""
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                    sharding=leaf.sharding)
    arr = jnp.asarray(leaf)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def save_sharded(executor, path):
    """Write a sharded (orbax) checkpoint of params + optimizer state +
    RNG.  Safe to call from every process of a multi-host run."""
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(str(path), _state_tree(executor), force=True)
    ckptr.wait_until_finished()


def restore_sharded_state(executor, path):
    """Read a sharded (orbax) checkpoint back into a
    ``Executor.state_dict``-shaped payload WITHOUT mutating the
    executor — so callers (the rolling checkpoint manager) can validate
    the restored state and still fall back to an older checkpoint with
    the live executor untouched."""
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    template = jax.tree_util.tree_map(_abstract, _state_tree(executor))
    state = ckptr.restore(str(path), template)
    return {
        "params": state["params"],
        "opt_state": state["opt_state"],
        "global_step": int(state["meta"]["global_step"]),
        "base_key": state["meta"]["base_key"],
    }


def load_sharded(executor, path):
    """Restore a sharded checkpoint into the executor, preserving each
    value's current device placement/sharding."""
    # reuse the single restore contract (Executor.load_state_dict)
    executor.load_state_dict(restore_sharded_state(executor, path))
    return executor
