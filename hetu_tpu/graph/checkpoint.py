"""Sharded checkpointing (orbax-backed).

The reference pickles name→numpy on rank 0 and PS-resident params via
SaveParam RPCs (executor.py:558-670).  `Executor.save/load` keeps that
single-file contract (plus RNG state for bitwise resume); this module adds
the multi-host path: each host writes only its addressable shards and
restores straight into the live sharding layout, which is how TPU-pod
checkpoints must work (a 100B-param state never materializes on one host).
"""

from __future__ import annotations

import os
import pickle
import re

import numpy as np

import jax
import jax.numpy as jnp


class CheckpointError(RuntimeError):
    """A checkpoint file or payload is torn, corrupt, or structurally
    invalid.  Raised instead of the opaque ``KeyError``/unpickle crash a
    garbage or stale file used to produce, so callers (and the rolling
    checkpoint manager's fallback scan) can tell "bad file" from "bug"."""


class GeometryMismatch(CheckpointError):
    """A checkpoint was written under a different geometry (mesh shape /
    per-param shardings) than the live executor's — a same-geometry
    restore would die inside orbax with a shape or topology error, so
    the mismatch is raised up front with BOTH geometries named.  Use
    :func:`restore_resharded` (or ``restore_latest(...,
    reshard=True)``) when the cross-geometry load is intended."""

    def __init__(self, message, saved=None, live=None):
        super().__init__(message)
        self.saved = saved
        self.live = live


# the single-file checkpoint contract (Executor.state_dict); "format" /
# "opt_meta" are optional so pre-tag checkpoints keep loading
REQUIRED_STATE_KEYS = frozenset(
    {"params", "opt_state", "global_step", "base_key"})
SUPPORTED_FORMAT_VERSIONS = (1,)


def validate_state(state, source="checkpoint"):
    """Check a checkpoint payload against the state_dict contract.

    Raises :class:`CheckpointError` naming exactly what is wrong
    (non-dict payload, missing required keys, format version from a
    newer writer) instead of letting ``load_state_dict`` die on an
    arbitrary ``KeyError`` deep inside the restore."""
    if not isinstance(state, dict):
        raise CheckpointError(
            f"{source}: payload is {type(state).__name__}, expected the "
            "dict produced by Executor.state_dict()")
    missing = sorted(REQUIRED_STATE_KEYS - set(state))
    if missing:
        raise CheckpointError(
            f"{source}: missing required keys {missing} — not an "
            "Executor checkpoint (or a torn/stale file)")
    if not isinstance(state["params"], dict):
        raise CheckpointError(
            f"{source}: 'params' is {type(state['params']).__name__}, "
            "expected a name->array dict")
    fmt = state.get("format")
    if fmt is not None:
        if not isinstance(fmt, dict):
            raise CheckpointError(
                f"{source}: 'format' is {type(fmt).__name__}, expected a "
                "dict tag")
        version = fmt.get("version")
        if version is not None and version not in SUPPORTED_FORMAT_VERSIONS:
            raise CheckpointError(
                f"{source}: format version {version} is newer than this "
                f"build supports ({SUPPORTED_FORMAT_VERSIONS}); upgrade "
                "hetu_tpu or re-save the checkpoint from the old version")
    return state


def atomic_write_bytes(blob, path):
    """Write ``blob`` to ``path`` via a same-directory temp file +
    ``os.replace``: a kill mid-write leaves the previous file intact and
    never a half-written one under the final name."""
    path = str(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return path


def atomic_pickle(state, path):
    """Pickle ``state`` to ``path`` torn-proof (tmp + ``os.replace``)."""
    return atomic_write_bytes(
        pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL), path)


def read_checkpoint(path):
    """Read + unpickle + validate a single-file checkpoint.

    Garbage, truncated, or non-checkpoint pickles surface as
    :class:`CheckpointError` with the path named; a missing file stays a
    ``FileNotFoundError`` (a different operator mistake)."""
    with open(path, "rb") as f:
        blob = f.read()
    try:
        state = pickle.loads(blob)
    except Exception as e:  # pickle raises a zoo of types on garbage
        raise CheckpointError(
            f"{path}: not a readable checkpoint "
            f"({type(e).__name__}: {e}) — torn write or corrupt file?"
        ) from e
    return validate_state(state, source=str(path))


def _state_tree(executor):
    return {
        "params": dict(executor.params),
        "opt_state": executor.opt_state,
        "meta": {
            "global_step": jnp.asarray(executor._global_step),
            "base_key": jax.random.key_data(executor._base_key),
        },
    }


def _abstract(leaf):
    """Restore template leaf: shape/dtype + the LIVE sharding so orbax
    reassembles each host's shards in place (no full-host materialization)."""
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                    sharding=leaf.sharding)
    arr = jnp.asarray(leaf)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def save_sharded(executor, path):
    """Write a sharded (orbax) checkpoint of params + optimizer state +
    RNG.  Safe to call from every process of a multi-host run."""
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(str(path), _state_tree(executor), force=True)
    ckptr.wait_until_finished()


def restore_sharded_state(executor, path):
    """Read a sharded (orbax) checkpoint back into a
    ``Executor.state_dict``-shaped payload WITHOUT mutating the
    executor — so callers (the rolling checkpoint manager) can validate
    the restored state and still fall back to an older checkpoint with
    the live executor untouched."""
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    template = jax.tree_util.tree_map(_abstract, _state_tree(executor))
    state = ckptr.restore(str(path), template)
    return {
        "params": state["params"],
        "opt_state": state["opt_state"],
        "global_step": int(state["meta"]["global_step"]),
        "base_key": state["meta"]["base_key"],
    }


def load_sharded(executor, path):
    """Restore a sharded checkpoint into the executor, preserving each
    value's current device placement/sharding."""
    # reuse the single restore contract (Executor.load_state_dict)
    executor.load_state_dict(restore_sharded_state(executor, path))
    return executor


# -- cross-geometry restore (elastic training) -----------------------------

def executor_geometry(executor):
    """JSON-able description of the geometry an executor's state lives
    under: mesh axis sizes, device count, and per-param partition
    specs.  Recorded in the rolling-checkpoint manifest at save time so
    a restore into a DIFFERENT geometry is a validated decision
    (:func:`restore_resharded`), never an orbax shape error halfway
    through a restore."""
    mesh = getattr(executor, "mesh", None)
    geom = {
        "mesh": ({k: int(v) for k, v in mesh.shape.items()}
                 if mesh is not None else None),
        "devices": int(mesh.devices.size) if mesh is not None else 1,
        "params": {},
    }
    for name, v in executor.params.items():
        spec = getattr(getattr(v, "sharding", None), "spec", None)
        geom["params"][name] = str(spec) if spec is not None else None
    return geom


def geometry_compatible(saved, live):
    """True when a checkpoint written under ``saved`` restores into
    ``live`` without resharding (same mesh axis sizes, device count,
    and param partition specs).  Missing evidence (legacy manifest
    entry) counts as compatible — the old behavior."""
    if not saved or not live:
        return True
    return (saved.get("mesh") == live.get("mesh")
            and saved.get("devices") == live.get("devices")
            and saved.get("params") == live.get("params"))


def describe_geometry(geom):
    """One-line human form of an :func:`executor_geometry` dict."""
    if not geom:
        return "<unknown geometry>"
    mesh = geom.get("mesh")
    axes = ("x".join(f"{k}={v}" for k, v in mesh.items())
            if mesh else "unmeshed")
    return f"mesh[{axes}] over {geom.get('devices', '?')} device(s)"


_SLOT_RE = re.compile(r"(?:^|/)slots/([^/]+)(?:/|$)")


def state_shardings(executor):
    """Target-sharding lookup for :func:`restore_resharded`, derived
    from a LIVE executor built under the TARGET geometry: a callable
    ``keypath -> Sharding | None`` over ``/``-joined state-tree paths.
    Params resolve by name, optimizer slots follow their parameter
    (the slot name is in the path, so the writer's optimizer-op naming
    doesn't matter), meta leaves stay unsharded (host)."""
    by_param = {}
    for name, v in executor.params.items():
        sh = getattr(v, "sharding", None)
        if sh is not None:
            by_param[name] = sh

    def lookup(keypath):
        parts = keypath.split("/")
        if parts[0] == "params" and len(parts) == 2:
            return by_param.get(parts[1])
        if parts[0] == "opt_state":
            m = _SLOT_RE.search(keypath)
            if m:
                return by_param.get(m.group(1))
        return None

    return lookup


def restore_resharded(path, target_shardings):
    """Restore an orbax checkpoint written under ANY source geometry
    into TARGET shardings — the elastic-training restore: the writer's
    mesh may be gone (a chip died), the reader's mesh is whatever
    survived.

    ``target_shardings``: a callable ``keypath -> Sharding | None``
    (see :func:`state_shardings`) or a dict keyed by ``/``-joined
    state-tree paths; ``None`` leaves a leaf on the host (replicated).

    Primary path: abstract-template restore — the template substitutes
    the TARGET ``NamedSharding`` per leaf (shape/dtype come from the
    checkpoint's own metadata, so no source executor is needed) and
    orbax reads each array straight into its target layout.  Fallback
    (an orbax build that refuses a cross-topology template): restore to
    host arrays, then ``jax.device_put`` per leaf — the host-gather
    path, always correct on CPU, just not zero-copy.

    Returns an ``Executor.state_dict``-shaped payload; a target
    sharding that cannot tile a leaf's shape falls back to replicated
    for that leaf (optimizer scalars riding a sharded param's slot
    dict)."""
    import orbax.checkpoint as ocp
    from jax.tree_util import tree_map_with_path

    ckptr = ocp.StandardCheckpointer()
    try:
        meta = ckptr.metadata(str(path))
    except Exception as e:
        raise CheckpointError(
            f"{path}: unreadable checkpoint metadata "
            f"({type(e).__name__}: {e})") from e
    if callable(target_shardings):
        lookup = target_shardings
    else:
        spec_map = dict(target_shardings or {})
        lookup = spec_map.get

    def _keystr(kp):
        return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in kp)

    def _target(kp, shape):
        sh = lookup(_keystr(kp))
        if sh is not None:
            try:
                sh.shard_shape(tuple(shape))
            except Exception:
                sh = None       # spec can't tile this leaf: replicate
        return sh

    def _template(kp, m, with_shardings):
        shape, dtype = tuple(m.shape), m.dtype
        sh = _target(kp, shape) if with_shardings else None
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)

    try:
        tmpl = tree_map_with_path(
            lambda kp, m: _template(kp, m, True), meta)
        state = ckptr.restore(str(path), tmpl)
    except Exception:
        # host-gather fallback: read every leaf replicated, then place
        tmpl = tree_map_with_path(
            lambda kp, m: _template(kp, m, False), meta)
        try:
            state = ckptr.restore(str(path), tmpl)
        except Exception as e:
            raise CheckpointError(
                f"{path}: unrestorable shard set "
                f"({type(e).__name__}: {e})") from e

        def _place(kp, v):
            sh = _target(kp, np.shape(v))
            return jax.device_put(np.asarray(v), sh) if sh is not None \
                else v
        state = tree_map_with_path(_place, state)
    return {
        "params": state["params"],
        "opt_state": state["opt_state"],
        "global_step": int(state["meta"]["global_step"]),
        "base_key": state["meta"]["base_key"],
    }
