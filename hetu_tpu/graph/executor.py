"""Executor: named subgraphs compiled to jitted XLA programs.

Reference: /root/reference/python/hetu/gpu_ops/executor.py — `Executor` holds
named subgraphs (train/validate/...) each run by a `SubExecutor` that topo
sorts, infers shapes, plans memory, and dispatches kernels per node per step.

TPU redesign: each named subgraph becomes ONE jitted pure function
``(params, opt_state, feeds, key) -> (outputs, new_params, new_opt_state)``.
XLA replaces the per-node dispatch loop, the stream/event machinery
(executor.py:351-380, :1227-1246), the memory planner (memory_pool.py — XLA's
buffer assignment does arena reuse), and shape inference (shapes specialize at
trace time; a new feed shape simply triggers a retrace, mirroring the
reference's re-plan on shape change at executor.py:938-1051).

Distribution hooks: when a `mesh` (parallel/mesh.py) is attached, parameter
and feed shardings are derived from node `dist_state` annotations and passed
to jit as in_shardings — GSPMD then inserts the collectives the reference
materialized by hand in its graph-rewrite pass (context.py:1469).
"""

from __future__ import annotations

import functools
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from .node import (Op, PlaceholderOp, VariableOp, find_topo_sort,
                   graph_variables)
from .trace import TraceContext, evaluate
from .. import telemetry as _telemetry


class SubExecutor:
    """One named subgraph compiled into a single jitted step function."""

    def __init__(self, name, eval_nodes, executor):
        self.name = name
        self.eval_nodes = list(eval_nodes)
        self.executor = executor
        self.topo = find_topo_sort(self.eval_nodes)
        self.placeholders = [n for n in self.topo
                             if isinstance(n, PlaceholderOp)]
        self.variables = [n for n in self.topo if isinstance(n, VariableOp)]
        self.opt_ops = [n for n in self.topo if n.is_stateful
                        and hasattr(n, "init_state")]
        # train/eval mode: training iff the subgraph optimizes or explicitly
        # differentiates, unless the subgraph name marks it as evaluation
        # (reference: inference flag on SubExecutor, executor.py:733).
        has_grads = any(hasattr(n, "_compute_with_env") for n in self.topo)
        self.training = executor.config.get(
            "training",
            (len(self.opt_ops) > 0 or has_grads)
            and name not in ("validate", "inference", "eval"))
        # PS-backed embeddings (ps/embedding.py PSRowsOp): gathered rows
        # enter as feeds; their grads leave as hidden outputs pushed to the
        # host store after the step (reference hybrid comm_mode, where
        # embedding params bypass the dense path via PS push/pull).
        self.ps_rows = [p for p in self.placeholders
                        if hasattr(p, "ps_embedding")]
        self._ps_grad_nodes = []
        if self.training and self.ps_rows:
            losses = [op.loss for op in self.opt_ops
                      if getattr(op, "loss", None) is not None]
            if losses:
                from .autodiff import gradients
                # PS rows may feed any optimized loss: differentiate their
                # sum (total sensitivity) so no server update is dropped
                total = losses[0]
                for extra in losses[1:]:
                    total = total + extra
                self._ps_grad_nodes = gradients(total, self.ps_rows)
        self._all_eval = self.eval_nodes + self._ps_grad_nodes
        if self._ps_grad_nodes:
            self.topo = find_topo_sort(self._all_eval)
        self._ps_pending = []
        self._jitted = None
        self._multi_jitted = None   # lazily-built run_steps program
        self._numerics_layers = None  # set by _build when a monitor rides
        self._numerics_sample = 1     # in-graph stats sampling cadence
        self._jitted_stats = None     # stats-bearing twin (sampled mode)
        # fast-path cache for steady-state training loops: the first
        # slow-path run() caches the feed pytree STRUCTURE — key set,
        # canonical names, declared dtypes, which placeholders are
        # dataloader-fed — so subsequent steps skip the per-call feed
        # validation/cast/dataloader-resolution walk and only swap leaf
        # buffers.  Keyed on structure, not dict identity: a prefetcher
        # handing over a fresh dict of device batches every step stays
        # on the fast path (in-place value swaps in one dict do too).
        self._fast_feed = None
        # monitor variables: non-trainable in-graph counters (e.g. the
        # BERT MLM bucket-overflow total) polled host-side every
        # monitor_interval steps — works on every platform, unlike host
        # callbacks (VERDICT r3 item 7)
        self._monitor_vars = [v for v in self.variables
                              if getattr(v, "monitor", None) is not None]
        self._monitor_interval = int(
            executor.config.get("monitor_interval", 200))
        self._runs = 0  # per-subgraph step count (monitor poll schedule)
        # runtime telemetry (telemetry/): instruments are near-free
        # no-ops until telemetry.enable() — the step path carries them
        # unconditionally (cost pinned by tests/test_telemetry.py)
        reg = _telemetry.get_registry()
        self._m_steps = reg.counter(
            "hetu_executor_steps_total",
            "Executor steps dispatched (run() calls + run_steps inner "
            "steps)", labels=("subgraph",)).labels(subgraph=name)
        self._m_step_time = reg.histogram(
            "hetu_executor_step_seconds",
            "Wall time of one run() call (feed prep + dispatch + guard "
            "check; device completion is asynchronous)",
            labels=("subgraph",)).labels(subgraph=name)
        self._m_multi = reg.counter(
            "hetu_executor_run_steps_calls_total",
            "run_steps() multi-step dispatches",
            labels=("subgraph",)).labels(subgraph=name)
        self._m_retrace = reg.counter(
            "hetu_executor_retraces_total",
            "Step-program (re)traces — >1 per subgraph after warmup "
            "means a shape/dtype change recompiled the step",
            labels=("subgraph",)).labels(subgraph=name)
        self._tr = _telemetry.get_tracer()

    def ps_synchronize(self):
        """Wait for all in-flight PS pushes (call before reading tables
        directly or checkpointing the host store)."""
        first_error = None
        for f in self._ps_pending:
            try:
                f.result()
            except Exception as e:   # drain everything, report once
                if first_error is None:
                    first_error = e
        self._ps_pending.clear()
        for p in self.ps_rows:
            p.ps_embedding.synchronize()
        if first_error is not None:
            raise first_error

    def _should_donate(self):
        """Donate params/opt-state only under real memory pressure.

        Donation halves peak parameter memory, but on current TPU XLA it
        also makes the compiler stage the param-update fusions in scoped
        memory (S(1)) and COPY every updated parameter back to its HBM
        buffer — measured 1.42 -> 2.18 ms/step on the W&D bench shapes
        (+13% HBM bytes), and the same pattern taxes every stage.  When
        the state comfortably fits HBM the copies buy nothing, so: donate
        iff params+opt bytes exceed a quarter of device memory (both
        copies plus activations still fit below ~50%), or the user forces
        it with ``Executor(..., donate_params=True/False)``.
        """
        cfg = self.executor.config.get("donate_params", "auto")
        if cfg != "auto":
            return bool(cfg)
        ex = self.executor
        # lazy-sparse (scatter) param updates NEED aliasing: a functional
        # .at[ids].set over a non-donated table forces XLA to copy the
        # whole [V, H] buffer first, turning the rowwise update back into
        # a full-table pass (measured 2.8 ms vs 1.0 ms on the W&D lazy
        # path).  The S(1) copy-back tax donation carries only hits the
        # DENSE params, which are small whenever someone bothered with a
        # sparse table.
        if any(getattr(op, "sparse", None) for op in self.opt_ops):
            return True
        state_bytes = sum(
            getattr(v, "nbytes", 0)
            for v in jax.tree_util.tree_leaves((ex.params, ex.opt_state)))
        limit = 16 * 1024 ** 3  # v5e/v5p-class HBM default
        try:
            stats = jax.devices()[0].memory_stats()
            if stats and stats.get("bytes_limit"):
                limit = stats["bytes_limit"]
        except Exception:
            pass
        # compare against ONE device's HBM: replicated state (plain DP)
        # costs its full global size on EVERY chip, and for sharded state
        # the global total over-counts per-device pressure — which only
        # errs toward donating, the memory-safe direction.
        return state_bytes > 0.25 * limit

    def _build(self):
        placeholders = self.placeholders
        eval_nodes = self._all_eval
        topo = self.topo
        training = self.training
        mesh = self.executor.mesh
        compute_dtype = self.executor.compute_dtype
        # resilience.StepGuard: traced INTO the step when attached, so
        # the sentinel reductions fuse with the updates they check
        guard = self.executor.config.get("step_guard")
        guard_losses = ([op.loss for op in self.opt_ops
                         if getattr(op, "loss", None) is not None]
                        if guard is not None else [])
        # telemetry.NumericsMonitor: like the guard sentinel, the
        # per-layer stats vector is traced INTO the step when a monitor
        # is attached, so each L2 reduce fuses with the grad/update
        # computation that produced the tensor.  The layer spec is
        # static (optimizer var lists, keyed by profiling.layer_of), so
        # the row order is fixed before any trace runs.
        numerics_groups = None
        numerics_sample = 1
        self._numerics_layers = None
        self._numerics_sample = 1
        if (self.executor.config.get("numerics") is not None
                and self.training and self.opt_ops):
            numerics_sample = max(1, int(getattr(
                self.executor.config["numerics"], "sample_every", 1)))
            self._numerics_sample = numerics_sample
            from ..telemetry.profiling import layer_of
            groups = {}
            for op in self.opt_ops:
                if not hasattr(op, "var_list"):
                    continue
                for var, gnode in zip(op.var_list,
                                      op.inputs[:len(op.var_list)]):
                    groups.setdefault(layer_of(var.name), []).append(
                        (var, gnode, None))
                for var, sites in getattr(op, "sparse", None) or []:
                    groups.setdefault(layer_of(var.name), []).append(
                        (var, None, sites))
            if groups:
                numerics_groups = list(groups.items())
                self._numerics_layers = tuple(groups)

        def cast(x):
            if compute_dtype is not None and jnp.issubdtype(
                    x.dtype, jnp.floating):
                return x.astype(compute_dtype)
            return x

        # skip the per-step key derivation entirely when nothing in the
        # subgraph draws random bits (dropout/noise ops) — the threefry
        # fold_in is small but pure overhead on RNG-free models (W&D,
        # ResNet eval, ...)
        needs_rng = any(getattr(n, "needs_rng", False) for n in topo)

        def step_fn(params, opt_state, feeds, base_key, step,
                    _stats="cond"):
            # host-side retrace witness: runs at TRACE time only, so the
            # counter ticks once per compiled program variant.
            # ``_stats`` is a python-level mode bound per program
            # variant (functools.partial below, never traced): None
            # emits no stats outputs (byte-identical to an unmonitored
            # step), "full" emits the row unconditionally, "cond"
            # emits it under the in-graph sample_every lax.cond
            # (run_steps' amortized path).
            self._m_retrace.inc()
            # the per-step key derives INSIDE the program from a
            # device-resident step counter — an eager fold_in per run()
            # would dispatch a separate device op each step (several ms
            # through a remote-tunnel link, dominating small models)
            key = (jax.random.fold_in(base_key, step) if needs_rng
                   else base_key)
            # mixed precision: forward/backward run in compute_dtype while
            # optimizers update the full-precision masters (the standard
            # TPU bf16-compute / f32-master-weights policy).
            ctx = TraceContext(key=key, training=training, mesh=mesh,
                               cp_impl=self.executor.config.get(
                                   "cp_impl", "ring"),
                               master_params=(params if compute_dtype
                                              is not None else None))
            ctx.opt_state = opt_state
            bindings = {}
            for v in self.variables:
                bindings[v] = cast(params[v.name])
            for p in placeholders:
                bindings[p] = cast(feeds[p.name])
            vals, env = evaluate(eval_nodes, bindings, ctx, topo=topo)
            new_params = dict(params)
            for var, val in ctx.updates.items():
                new_params[var.name] = val.astype(params[var.name].dtype)
            new_opt_state = dict(opt_state)
            new_opt_state.update(ctx.new_opt_state)
            nstats = None
            if numerics_groups is not None and _stats is not None:
                # fused per-layer stats: sums of squares of the grad,
                # the ATTEMPTED update delta (pre skip-select, so a
                # poisoned step shows its non-finite norms even when
                # the guard discards it), and the current params — one
                # [n_layers, 3] f32 row block per step.  Sqrt happens
                # host-side; NaN/inf propagate through the sums, so a
                # non-finite row IS the per-layer finite flag.
                def _sumsq(x):
                    x = x.astype(jnp.float32)
                    return jnp.sum(x * x)

                def _nstats():
                    rows = []
                    for _layer, entries in numerics_groups:
                        gsq = jnp.float32(0)
                        usq = jnp.float32(0)
                        psq = jnp.float32(0)
                        for var, gnode, sites in entries:
                            old = params[var.name]
                            psq = psq + _sumsq(old)
                            new = ctx.updates.get(var)
                            if new is not None:
                                usq = usq + _sumsq(
                                    new.astype(jnp.float32)
                                    - old.astype(jnp.float32))
                            if gnode is not None and gnode in env:
                                gsq = gsq + _sumsq(env[gnode])
                            for rnode, _ids in (sites or ()):
                                if rnode in env:
                                    # sparse tables: L2 over the batch's
                                    # touched row grads (dense rows are
                                    # 0)
                                    gsq = gsq + _sumsq(env[rnode])
                        rows.append(jnp.stack([gsq, usq, psq]))
                    return jnp.stack(rows)

                if _stats == "cond" and numerics_sample > 1:
                    # sampled cadence inside run_steps' fori_loop: the
                    # reductions run only on every sample_every-th
                    # inner step (real control flow, not a select);
                    # the loop carry keeps the latest SAMPLED row, so
                    # the zeros filler is never surfaced.  The single-
                    # step run() path never pays even the cond — it
                    # switches between the plain and "full" compiled
                    # programs host-side on the same cadence.
                    nstats = jax.lax.cond(
                        (step % jnp.uint32(numerics_sample)) == 0,
                        _nstats,
                        lambda: jnp.zeros((len(numerics_groups), 3),
                                          jnp.float32))
                else:
                    nstats = _nstats()
            if guard is not None:
                # fused guard sentinel: one scalar conjunction over loss
                # finiteness and every parameter update written this step
                # (optimizer slots are poisoned iff the param is, so
                # checking params covers both at half the reads).  The
                # loss sum doubles as the host-side spike signal.
                gloss = jnp.float32(0)
                seen = False
                for lnode in guard_losses:
                    if lnode in env:
                        gloss = gloss + jnp.sum(env[lnode]).astype(
                            jnp.float32)
                        seen = True
                if not seen:
                    # eval-only subgraph: guard its floating outputs
                    for v in vals:
                        if v is not None and jnp.issubdtype(
                                jnp.result_type(v), jnp.floating):
                            gloss = gloss + jnp.sum(v).astype(jnp.float32)
                gfin = jnp.isfinite(gloss)
                for var, val in ctx.updates.items():
                    if jnp.issubdtype(jnp.result_type(
                            new_params[var.name]), jnp.floating):
                        gfin = jnp.logical_and(
                            gfin, jnp.all(jnp.isfinite(
                                new_params[var.name])))
                if guard.policy == "skip":
                    # discard the poisoned update IN-GRAPH: params and
                    # opt-state roll forward only on a clean sentinel, so
                    # a NaN step can never corrupt persistent state
                    for var in ctx.updates:
                        new_params[var.name] = jnp.where(
                            gfin, new_params[var.name], params[var.name])
                    for k in ctx.new_opt_state:
                        new_opt_state[k] = jax.tree_util.tree_map(
                            lambda nv, ov: jnp.where(gfin, nv, ov),
                            new_opt_state[k], opt_state[k])
            # hidden trailing outputs, strip order (last-first in
            # _dispatch): [.., nstats][gfin, gloss]
            if nstats is not None:
                vals = list(vals) + [nstats]
            if guard is not None:
                vals = list(vals) + [gfin, gloss]
            return vals, new_params, new_opt_state, step + 1

        self._step_fn = step_fn   # run_steps builds its scan over this
        donate = ((0, 1, 4) if self.training and self._should_donate()
                  else (4,))
        # single-step program variants: on a sampled cadence the
        # steady-state program carries NO stats (the stats reductions
        # would otherwise pin the pre-update params live across the
        # update — a cond can't help, its operand liveness is static —
        # costing a params copy per step); the "full" twin runs only
        # on every sample_every-th dispatch.
        single = step_fn
        stats_fn = None
        if numerics_groups is not None:
            if numerics_sample == 1:
                single = functools.partial(step_fn, _stats="full")
            else:
                single = functools.partial(step_fn, _stats=None)
                stats_fn = functools.partial(step_fn, _stats="full")
        in_shardings = self.executor._input_shardings(self)
        self._jitted_stats = None
        if in_shardings is not None:
            # pin updated params/opt-state to their INPUT shardings: with
            # interior reshard constraints in the program, GSPMD may
            # otherwise emit new param values in a different layout,
            # which would mismatch the next call's in_shardings (and
            # defeat donation aliasing).  Eval outputs gather replicated
            # (reference reduceMean/gatherPredict, executor.py:680).
            from ..parallel.mesh import replicated
            rep = replicated(self.executor.mesh)
            param_sh, opt_sh, _, _, _ = in_shardings
            out_shardings = (rep, param_sh, opt_sh, rep)
            self._jitted = jax.jit(single, donate_argnums=donate,
                                   in_shardings=in_shardings,
                                   out_shardings=out_shardings)
            if stats_fn is not None:
                self._jitted_stats = jax.jit(
                    stats_fn, donate_argnums=donate,
                    in_shardings=in_shardings,
                    out_shardings=out_shardings)
        else:
            self._jitted = jax.jit(single, donate_argnums=donate)
            if stats_fn is not None:
                self._jitted_stats = jax.jit(stats_fn,
                                             donate_argnums=donate)

    def _fast_resolve(self, feed_dict):
        """Steady-state dispatch: swap leaf buffers into the cached feed
        structure.  Returns the canonical feeds dict, or None (disarming
        the cache) when the structure or value classes changed — a
        wrong-dtype device array must not silently retrace a new program
        variant, and numpy leaves still need the slow path's cast."""
        pairs, autos = self._fast_feed
        if len(feed_dict or {}) != len(pairs):
            self._fast_feed = None
            return None
        feeds = {}
        for key, name, want in pairs:
            v = feed_dict.get(key)
            if not isinstance(v, jax.Array) or (
                    want is not None and v.dtype != want):
                self._fast_feed = None
                return None
            feeds[name] = v
        for p, want in autos:
            # dataloader-fed: a device-prefetched batch in the declared
            # dtype passes straight through (no host round-trip); host
            # batches get the one cast the slow path would do
            v = p.auto_feed(self.name)
            if not isinstance(v, jax.Array) or (
                    want is not None and v.dtype != want):
                v = jnp.asarray(v, dtype=want)
            feeds[p.name] = v
        return feeds

    def run(self, feed_dict=None, convert_to_numpy_ret_vals=False):
        if not _telemetry.enabled():
            return self._run_impl(feed_dict, convert_to_numpy_ret_vals)
        t0 = time.perf_counter()
        try:
            return self._run_impl(feed_dict, convert_to_numpy_ret_vals)
        finally:
            self._m_steps.inc()
            self._m_step_time.observe(time.perf_counter() - t0)

    def _run_impl(self, feed_dict, convert_to_numpy_ret_vals):
        if self._jitted is None:
            # "compile" phase: program construction (graph walk + jit
            # wrapper build) — the goodput ledger's compile bucket.
            # XLA's lazy trace/compile on the first dispatch still
            # lands in that step's dispatch/device residual.
            with self._tr.span("compile"):
                self._build()
        ex = self.executor
        # "h2d" phase: everything between entry and the jitted call —
        # feed canonicalization, casts, uploads, PS row gathers
        with self._tr.span("h2d"):
            ps_ids = None
            feeds = (self._fast_resolve(feed_dict)
                     if self._fast_feed is not None else None)
            if feeds is None:
                feeds, ps_ids = self._slow_feeds(feed_dict)
        return self._dispatch(ex, feeds, ps_ids,
                              convert_to_numpy_ret_vals)

    def _slow_feeds(self, feed_dict):
        """Full per-call feed canonicalization walk; returns
        ``(feeds, ps_ids)`` and may arm the fast path for next step."""
        feeds = {}
        feed_dict = feed_dict or {}
        for node, value in feed_dict.items():
            name = node.name if isinstance(node, Op) else node
            feeds[name] = value
        # dataloader nodes: pull the next prefetched batch for any node the
        # user didn't feed explicitly (reference DataloaderOp streams)
        auto_names = set()
        for p in self.placeholders:
            if p.name not in feeds and hasattr(p, "auto_feed"):
                feeds[p.name] = p.auto_feed(self.name)
                auto_names.add(p.name)
        # PS embeddings: issue ASYNC row gathers through each table's
        # worker thread (ordered after the previous step's async grad
        # push), then resolve after the rest of feed prep — so host
        # store/cache traffic overlaps the still-running previous device
        # step (reference SparsePull prefetch path,
        # ParameterServerCommunicate.py:40-56 + executor.py:1541-1567)
        ps_ids = {}
        ps_futs = {}
        for p in self.ps_rows:
            ids_name = p.ids_node.name
            if ids_name not in feeds:
                raise ValueError(
                    f"PS embedding {p.name} needs ids feed '{ids_name}'")
            ids_val = np.asarray(feeds[ids_name])
            if p.inv_node is not None:
                # unique-feed: gather only the batch's unique rows (bucket-
                # padded with -1, which the store reads as zeros and drops
                # on push) and feed the gather indices alongside
                from ..ps.embedding import _bucket
                uniq, inv = np.unique(ids_val, return_inverse=True)
                keys = np.full(_bucket(uniq.size), -1, np.int64)
                keys[:uniq.size] = uniq
                feeds[p.inv_node.name] = inv.reshape(
                    ids_val.shape).astype(np.int32)
                ps_ids[p.name] = keys
                ps_futs[p.name] = p.ps_embedding.lookup_async(keys)
            else:
                ps_ids[p.name] = ids_val
                ps_futs[p.name] = p.ps_embedding.lookup_async(ids_val)
        for p in self.ps_rows:
            rows = ps_futs[p.name].result()
            if p.inv_node is not None:
                feeds[p.name] = rows
            else:
                ids_val = ps_ids[p.name]
                # shape follows the FED ids (a new batch size just
                # retraces, per the executor's shape contract above)
                feeds[p.name] = rows.reshape(
                    ids_val.shape + (p.ps_embedding.embedding_dim,))
        missing = [p.name for p in self.placeholders if p.name not in feeds]
        if missing:
            raise ValueError(f"missing feeds for placeholders: {missing}")
        # drop feeds that aren't placeholders of THIS subgraph (e.g. ids
        # consumed only by the PS lookup above): extra keys would change the
        # jit pytree and break against in_shardings
        names = {p.name for p in self.placeholders}
        feeds = {k: v for k, v in feeds.items() if k in names}
        # cast feeds to declared dtypes (reference DataloaderOp feeds float32)
        all_device = True
        dtypes = {}
        for p in self.placeholders:
            v = feeds[p.name]
            want = np.dtype(p.dtype) if p.dtype is not None else None
            dtypes[p.name] = want
            if not isinstance(v, jax.Array):
                if p.name not in auto_names:
                    all_device = False
                feeds[p.name] = jnp.asarray(v, dtype=p.dtype)
            elif want is not None and v.dtype != want:
                # wrong-dtype DEVICE array: cast (device-side) instead of
                # silently retracing a second program variant
                if p.name not in auto_names:
                    all_device = False
                feeds[p.name] = v.astype(want)
        self._arm_fast(feed_dict, feeds, names, dtypes, auto_names,
                       all_device)
        return feeds, ps_ids

    def _arm_fast(self, feed_dict, feeds, names, dtypes, auto_names,
                  all_device):
        """Cache the feed pytree structure so the NEXT step skips the
        canonicalization walk.  Armed when every user-fed leaf is a
        device array in its declared dtype (dataloader-fed leaves are
        resolved per step regardless) and nothing host-interactive (PS
        rows, extra keys) is involved."""
        if not all_device or self.ps_rows:
            return
        pairs = []
        for key in feed_dict:
            name = key.name if isinstance(key, Op) else key
            if name not in names or name in auto_names:
                return      # extra key or shadowing a dataloader node
            pairs.append((key, name, dtypes.get(name)))
        if len({nm for _, nm, _ in pairs}) != len(pairs):
            return          # two keys canonicalize to one placeholder
        if len(pairs) + len(auto_names) != len(feeds):
            return
        autos = [(p, dtypes[p.name]) for p in self.placeholders
                 if p.name in auto_names]
        self._fast_feed = (pairs, autos)

    def _dispatch(self, ex, feeds, ps_ids, convert_to_numpy_ret_vals):
        if ex._step_arr is None:
            ex._step_arr = jnp.uint32(ex._global_step)
        # numerics cadence for the step about to run (counter value
        # ex._global_step): off-cadence steps run the plain program —
        # zero stats cost, not even a cond — the sampled ones run the
        # stats-bearing twin
        has_stats = self._numerics_layers is not None and (
            self._numerics_sample == 1
            or ex._global_step % self._numerics_sample == 0)
        fn = (self._jitted_stats
              if has_stats and self._jitted_stats is not None
              else self._jitted)
        ex._global_step += 1
        # "dispatch" phase: the jitted call itself — asynchronous on
        # accelerators, so time spent HERE past the enqueue cost is
        # runtime back-pressure (in-flight queue full ≈ device-bound)
        with self._tr.span("dispatch"):
            vals, new_params, new_opt_state, ex._step_arr = fn(
                ex.params, ex.opt_state, feeds, ex._base_key,
                ex._step_arr)
        ex.params = new_params
        ex.opt_state = new_opt_state
        # guard sentinel scalars ride as the two trailing hidden outputs
        guard = ex.config.get("step_guard")
        guard_out = None
        if guard is not None:
            guard_out, vals = vals[-2:], vals[:-2]
        # the per-layer numerics stats block rides just before them
        # (only on the stats-bearing program — off-cadence dispatches
        # emit no row at all)
        nstats_out = None
        if has_stats:
            nstats_out, vals = vals[-1], vals[:-1]
        # poll monitor counters after this SUBGRAPH's first step and
        # every interval of ITS runs (a global-step schedule can
        # permanently miss a subgraph under alternating train/validate);
        # np.asarray syncs on a scalar — negligible at the interval.
        # Executor.check_monitors() is the final flush.
        self._runs += 1
        if self._monitor_vars and (
                self._runs == 1
                or self._runs % self._monitor_interval == 0):
            self.check_monitors()
        # push PS-embedding grads ASYNC: the device array goes straight to
        # the table's worker thread, which blocks on the device→host copy
        # there — run() returns without waiting for the step, so the push
        # (and the next step's lookups, queued behind it) hide under
        # device compute.  push-then-lookup ordering per table keeps the
        # consistency mode intact; pull_bound/push_bound staleness applies
        # as before inside the cache.
        if self._ps_grad_nodes:
            n_user = len(self.eval_nodes)
            for p, gval in zip(self.ps_rows, vals[n_user:]):
                # start the device→host copy NOW, non-blocking; by the
                # time the table worker materializes the array the bytes
                # are (mostly) already on the host — critical when the
                # device link has high round-trip latency
                try:
                    gval.copy_to_host_async()
                except AttributeError:
                    pass
                fut = p.ps_embedding.push_grad_async(
                    ps_ids[p.name], gval, deduped=p.inv_node is not None)
                self._ps_pending.append(fut)
            # surface worker-thread errors, keep the list bounded
            done = [f for f in self._ps_pending if f.done()]
            for f in done:
                f.result()
                self._ps_pending.remove(f)
            vals = vals[:n_user]
        if nstats_out is not None:
            # BEFORE the guard check, so a trip this step can attribute
            # its culprit layer from the freshly queued stats row
            with self._tr.span("numerics"):
                ex.config["numerics"].on_step(
                    ex, self._numerics_layers, ex._global_step,
                    nstats_out)
        if guard_out is not None:
            # after PS pushes so a rollback can't orphan in-flight grads;
            # may restore executor state or raise GuardTripped (abort)
            with self._tr.span("guard_check"):
                guard.on_step(ex, guard_out[0], guard_out[1])
        if convert_to_numpy_ret_vals:
            vals = [None if v is None else np.asarray(v) for v in vals]
        return vals

    def run_steps(self, feed_dict, n, convert_to_numpy_ret_vals=False):
        """Run ``n`` consecutive training steps on the SAME feeds in ONE
        device dispatch: an in-graph ``lax.fori_loop`` over the step
        function, returning the LAST step's values.

        Per-step host dispatch costs a device round trip (~0.5 ms over
        a remote link, tens of us locally) — for small models that
        dwarfs the step itself, so this amortizes it n-fold.  The
        device-resident step counter keeps per-step RNG identical to n
        ``run()`` calls; checkpoint state advances the same way.
        Requires pure device-side feeds (no PS embeddings / dataloader
        placeholders — those interact with the host every step).
        Sharded executors work: the fori_loop program carries the same
        param/opt-state/feed shardings as the single-step program, so
        GSPMD re-inserts the identical collectives inside the loop
        body."""
        if n < 1:
            raise ValueError(f"run_steps needs n >= 1, got {n}")
        if self._jitted is None:
            with self._tr.span("compile"):
                self._build()
        if self.ps_rows:
            raise ValueError("run_steps: PS-embedding subgraphs interact "
                             "with the host store every step; use run()")
        if any(hasattr(p, "auto_feed") for p in self.placeholders):
            raise ValueError("run_steps: dataloader placeholders pull a "
                             "new batch per step; use run()")
        ex = self.executor
        feeds = None
        if self._fast_feed is not None and not self._fast_feed[1]:
            # reuse the cached feed structure (run_steps never has
            # dataloader autos — the guard above raised)
            feeds = self._fast_resolve(feed_dict)
        if feeds is None:
            feeds = {}
            for node, value in (feed_dict or {}).items():
                name = node.name if isinstance(node, Op) else node
                feeds[name] = value
            names = {p.name for p in self.placeholders}
            feeds = {k: v for k, v in feeds.items() if k in names}
            missing = [p.name for p in self.placeholders
                       if p.name not in feeds]
            if missing:
                raise ValueError(
                    f"missing feeds for placeholders: {missing}")
            all_device = True
            dtypes = {}
            for p in self.placeholders:
                v = feeds[p.name]
                want = np.dtype(p.dtype) if p.dtype is not None else None
                dtypes[p.name] = want
                if not isinstance(v, jax.Array) or (
                        want is not None and v.dtype != want):
                    all_device = False
                    feeds[p.name] = jnp.asarray(v, dtype=p.dtype)
            self._arm_fast(feed_dict or {}, feeds, names, dtypes, set(),
                           all_device)
        if self._multi_jitted is None:
            step_fn = self._step_fn
            donate = ((0, 1, 4) if self.training
                      and self._should_donate() else (4,))
            # guard state at build time matches _build's: attach/detach
            # invalidate both compiled programs together
            guarded = ex.config.get("step_guard") is not None
            nlayers = len(self._numerics_layers or ())
            nsample = self._numerics_sample if nlayers else 1
            # the stats block rides before the two guard scalars
            stats_idx = -3 if guarded else -1

            def multi_fn(params, opt_state, feeds, base_key, step,
                         n_steps):
                # per-inner-step guard-trip accounting: the sentinel of
                # every inner step accumulates into a carried counter,
                # so trips are EXACT across the fori_loop instead of
                # detected only at the call boundary (ROADMAP item).
                # vals[-2] is the step's fused gfin sentinel.  The
                # numerics carry does the same per LAYER: an int32
                # [n_layers] count of inner steps whose stats row went
                # non-finite.  On the sampled cadence the latest
                # SAMPLED row is carried too, so the window's newest
                # real stats come back whichever inner step they
                # belong to (zeros filler rows are never surfaced).
                def nf_of(vals, nf):
                    row_ok = jnp.isfinite(
                        jnp.sum(vals[stats_idx], axis=1))
                    return nf + jnp.where(row_ok, 0, 1).astype(jnp.int32)

                def advance(carry):
                    params, opt_state, step, trips, nf, nrow = carry
                    prev = step
                    vals, params, opt_state, step = step_fn(
                        params, opt_state, feeds, base_key, step)
                    if guarded:
                        trips = trips + jnp.where(vals[-2], 0, 1).astype(
                            jnp.int32)
                    if nlayers:
                        nf = nf_of(vals, nf)
                        if nsample > 1:
                            nrow = jnp.where(
                                (prev % jnp.uint32(nsample)) == 0,
                                vals[stats_idx], nrow)
                    return vals, (params, opt_state, step, trips, nf,
                                  nrow)

                carry = (params, opt_state, step, jnp.int32(0),
                         jnp.zeros((nlayers,), jnp.int32),
                         jnp.zeros((nlayers, 3), jnp.float32))
                carry = jax.lax.fori_loop(
                    0, n_steps - 1,
                    lambda _, c: advance(c)[1], carry)
                # last step outside the loop so its values are returned
                vals, carry = advance(carry)
                params, opt_state, step, trips, nf, nrow = carry
                if nlayers and nsample > 1:
                    vals = list(vals)
                    vals[stats_idx] = nrow
                return vals, params, opt_state, step, trips, nf

            in_sh = ex._input_shardings(self)
            if in_sh is not None:
                # mirror _build: pin the carried params/opt-state to
                # their INPUT shardings so iteration i+1 of the loop —
                # and the next run_steps call — sees the layout its
                # executable expects; n_steps rides replicated
                from ..parallel.mesh import replicated
                rep = replicated(ex.mesh)
                param_sh, opt_sh = in_sh[0], in_sh[1]
                self._multi_jitted = jax.jit(
                    multi_fn, donate_argnums=donate,
                    in_shardings=in_sh + (rep,),
                    out_shardings=(rep, param_sh, opt_sh, rep, rep, rep))
            else:
                self._multi_jitted = jax.jit(multi_fn,
                                             donate_argnums=donate)
        if ex._step_arr is None:
            ex._step_arr = jnp.uint32(ex._global_step)
        ex._global_step += n
        with self._tr.span("dispatch"):
            (vals, ex.params, ex.opt_state, ex._step_arr,
             trips_arr, nf_arr) = self._multi_jitted(
                ex.params, ex.opt_state, feeds, ex._base_key,
                ex._step_arr, jnp.int32(n))
        self._m_steps.inc(n)
        self._m_multi.inc()
        guard = ex.config.get("step_guard")
        guard_out = None
        if guard is not None:
            guard_out, vals = vals[-2:], vals[:-2]
        if self._numerics_layers is not None:
            # the returned stats cover the FINAL inner step (latest
            # SAMPLED inner step on the sampled cadence); the carried
            # [n_layers] counter attributes every inner step's
            # non-finite rows exactly (mirroring inner_trips).  A
            # window too short to contain a sampled step delivers
            # nothing — the filler row carries no information.
            nstats_out, vals = vals[-1], vals[:-1]
            ns = self._numerics_sample
            s0 = ex._global_step - n
            if ns == 1 or ((s0 + n - 1) // ns) * ns >= s0:
                with self._tr.span("numerics"):
                    ex.config["numerics"].on_step(
                        ex, self._numerics_layers, ex._global_step,
                        nstats_out, n=n, inner_nf=nf_arr)
        if guard_out is not None:
            # the returned sentinel covers the FINAL inner step; the
            # carried counter reports every inner step's trip exactly
            # (the 'skip' policy's in-graph select still protects every
            # inner step; rollback/abort act at the call boundary)
            with self._tr.span("guard_check"):
                guard.on_step(ex, guard_out[0], guard_out[1], n=n,
                              inner_trips=trips_arr)
        self._runs += n
        if self._monitor_vars:
            self.check_monitors()
        if convert_to_numpy_ret_vals:
            vals = [None if v is None else np.asarray(v) for v in vals]
        return vals

    def check_monitors(self):
        """Warn on any tripped monitor counter (MLM overflow etc.)."""
        import warnings
        for v in self._monitor_vars:
            msg = v.monitor(float(np.asarray(self.executor.params[v.name])))
            if msg:
                warnings.warn(msg)

    def profile(self, feed_dict=None, repeats=10):
        """Wall-clock a compiled step (reference SubExecutor.profile)."""
        self.run(feed_dict)  # compile
        start = time.perf_counter()
        for _ in range(repeats):
            out = self.run(feed_dict)
        jax.block_until_ready([o for o in out if o is not None])
        return (time.perf_counter() - start) / repeats

    def _abstract_args(self, feed_dict=None):
        """The jitted step's argument tree as ShapeDtypeStructs.  Feed
        shapes come from ``feed_dict`` values when given, else from the
        placeholders' declared shapes."""
        ex = self.executor

        def abstract(a):
            return jax.ShapeDtypeStruct(jnp.shape(a), jnp.result_type(a))

        fed = {}
        if feed_dict:
            for node, value in feed_dict.items():
                name = node.name if isinstance(node, Op) else node
                fed[name] = value
        feeds = {}
        for p in self.placeholders:
            if p.name in fed:
                feeds[p.name] = jax.ShapeDtypeStruct(
                    jnp.shape(fed[p.name]), p.dtype)
            else:
                assert p.shape is not None, \
                    f"cost_analysis needs a feed or declared shape for " \
                    f"{p.name}"
                feeds[p.name] = jax.ShapeDtypeStruct(tuple(p.shape),
                                                     p.dtype)
        return (jax.tree_util.tree_map(abstract, ex.params),
                jax.tree_util.tree_map(abstract, ex.opt_state),
                feeds,
                jax.ShapeDtypeStruct((), ex._base_key.dtype),
                jax.ShapeDtypeStruct((), jnp.uint32))

    def lower_compiled(self, feed_dict=None):
        """The compiled (AOT) step program for analysis.  Pure: no step
        executes, no state mutates; XLA reuses its compilation cache, so
        after the first ``run()`` this costs a lowering only."""
        if self._jitted is None:
            self._build()
        return self._jitted.lower(*self._abstract_args(feed_dict)).compile()

    def cost_analysis(self, feed_dict=None):
        """XLA's static cost model for the compiled step (flops, HBM
        bytes accessed, ...) — the single-program analogue of the
        reference's per-op timer_subexecutor breakdown: XLA has already
        fused across op boundaries, so costs are whole-program.

        Pure analysis: no step executes, no state mutates.  Returns the
        version-normalized dict (see ``platform.compiled_cost_analysis``).
        """
        from ..platform import compiled_cost_analysis
        return compiled_cost_analysis(self.lower_compiled(feed_dict))

    def memory_analysis(self, feed_dict=None):
        """XLA's memory ledger for the compiled step (argument/output/
        temp bytes), version-normalized to a plain dict — the workspace
        side of the HBM accounting in ``telemetry.profiling``."""
        from ..platform import compiled_memory_analysis
        return compiled_memory_analysis(self.lower_compiled(feed_dict))


def _tree_nbytes(tree):
    """Total bytes of every array leaf in a pytree (0 for scalars and
    non-array leaves)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


class Executor:
    """Multi-subgraph session (reference executor.py:430).

    ``eval_node_dict`` may be a list (single anonymous subgraph) or a dict
    {name: eval_node_list}.  ``dist_strategy`` (parallel/strategies) annotates
    the graph with shardings before compilation; ``mesh`` selects the device
    mesh.  ``seed`` drives variable init and per-step RNG (dropout replay on
    checkpoint resume is preserved by saving the step counter, like the
    reference's seed+seqnum scheme in random.py).
    """

    def __init__(self, eval_node_dict, ctx=None, seed=0, mesh=None,
                 dist_strategy=None, comm_mode=None, compute_dtype=None,
                 **kwargs):
        if isinstance(eval_node_dict, (list, tuple)):
            eval_node_dict = {"default": list(eval_node_dict)}
        self.eval_node_dict = {k: list(v) for k, v in eval_node_dict.items()}
        self.mesh = mesh
        self.comm_mode = comm_mode
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        self.config = kwargs

        all_nodes = [n for lst in self.eval_node_dict.values() for n in lst]
        # reference comm_mode semantics (executor.py:278-306):
        #   'AllReduce' — dense grads allreduced across data-parallel
        #     replicas; here that's the DataParallel strategy (GSPMD emits
        #     the psum over the dp axis).
        #   'PS'/'Hybrid' — embedding tables live behind the parameter
        #     store (ps.PSEmbedding feeds/pushes rows); dense params stay
        #     on-device.  Selecting the mode without any PS-backed table
        #     in the graph is almost certainly a mistake — flag it.
        if comm_mode is not None:
            mode = str(comm_mode).lower()
            if mode == "allreduce":
                if dist_strategy is None and mesh is None:
                    from ..parallel.strategies import DataParallel
                    dist_strategy = DataParallel(ndev=len(jax.devices()))
            elif mode in ("ps", "hybrid"):
                has_ps = any(hasattr(n, "ps_embedding")
                             for n in find_topo_sort(all_nodes))
                if not has_ps:
                    import warnings
                    warnings.warn(
                        f"comm_mode={comm_mode!r} but no PSEmbedding-backed "
                        "table reaches this executor; dense parameters "
                        "always train on-device (use ps.PSEmbedding for "
                        "host-store tables)")
                if mode == "hybrid" and dist_strategy is None \
                        and mesh is None and len(jax.devices()) > 1:
                    from ..parallel.strategies import DataParallel
                    dist_strategy = DataParallel(ndev=len(jax.devices()))
            else:
                raise ValueError(f"unknown comm_mode {comm_mode!r}")
        if dist_strategy is not None:
            dist_strategy.annotate(all_nodes)
            if mesh is None and getattr(dist_strategy, "mesh", None) is not None:
                self.mesh = dist_strategy.mesh
        self.all_topo = find_topo_sort(all_nodes)
        self.variables = [n for n in self.all_topo if isinstance(n, VariableOp)]
        by_name = {}
        for v in self.variables:
            if by_name.setdefault(v.name, v) is not v:
                raise ValueError(
                    f"two distinct variables named {v.name!r} reach this "
                    "executor; give the models distinct `name=`s or build "
                    "them under separate `ht.name_scope()`s")

        # rng_impl="rbg" maps dropout/noise ops onto the TPU's hardware RNG
        # (threefry, the default, burns real FLOPs generating bits —
        # measurable on dropout-heavy training; rbg is the TPU-native
        # choice when bit-exact cross-platform replay isn't required)
        self._base_key = jax.random.key(seed,
                                        impl=kwargs.get("rng_impl", None))
        self._global_step = 0
        self._step_arr = None  # device-resident step counter (lazy)
        self.params = {}
        init_key = jax.random.fold_in(self._base_key, 0x5EED)
        for v in self.variables:
            # fold in the NAME, not the global op id: op ids count every
            # node any earlier code in the process built, so two
            # same-seed executors would init differently depending on
            # what ran before them (ADVICE r5 — the torch-parity gate
            # was suite-order-dependent).  Names are unique per executor
            # (checked above) and stable across processes.
            salt = np.uint32(zlib.crc32(v.name.encode("utf-8")))
            self.params[v.name] = self._place(
                v, v.initializer(jax.random.fold_in(init_key, salt),
                                 v.shape, jnp.dtype(v.dtype)))

        self.opt_state = {}
        self._opt_ops = {}  # name -> op, in graph (construction) order
        for n in self.all_topo:
            if n.is_stateful and hasattr(n, "init_state"):
                self.opt_state[n.name] = n.init_state(self.params)
                self._opt_ops[n.name] = n

        # HBM accounting: register the two big live pools this executor
        # owns with the process-wide ledger (telemetry.profiling).  The
        # ledger always tracks — the hetu_hbm_bytes{pool=} gauge only
        # moves once telemetry is enabled — and close() releases both.
        led = _telemetry.get_hbm_ledger()
        tag = f"executor:{id(self):x}"
        self._hbm_handles = [
            led.alloc("params", _tree_nbytes(self.params),
                      owner=f"{tag}:params"),
            led.alloc("opt_state", _tree_nbytes(self.opt_state),
                      owner=f"{tag}:opt_state")]

        if "pipeline" in self.config:
            # graph-driven pipeline over inhomogeneous stages (raw_ctx /
            # `with ht.stage(i)` annotations), reference context.py:1430
            from ..parallel.graph_pipeline import PipelineSubExecutor
            self.subexecutor = {
                name: PipelineSubExecutor(name, nodes, self)
                for name, nodes in self.eval_node_dict.items()}
        else:
            self.subexecutor = {name: SubExecutor(name, nodes, self)
                                for name, nodes in self.eval_node_dict.items()}
        # resilience.StepGuard passed as Executor(..., step_guard=guard):
        # bind it so policy actions (rollback/abort) can reach this state
        if self.config.get("step_guard") is not None:
            self.config["step_guard"]._bind(self)
        # telemetry.NumericsMonitor passed as Executor(..., numerics=mon):
        # bind so escalation can find the guard through this executor
        if self.config.get("numerics") is not None:
            self.config["numerics"]._executor = self

    # -- sharding hooks (filled in by parallel layer) ----------------------
    def _place(self, var, value):
        if self.mesh is not None and var.dist_state is not None:
            from ..parallel.mesh import to_named_sharding
            return jax.device_put(value, to_named_sharding(self.mesh,
                                                           var.dist_state))
        return value

    def _input_shardings(self, subexec):
        if self.mesh is None:
            return None
        from ..parallel.mesh import to_named_sharding, replicated
        param_sh = {}
        for v in subexec.variables:
            if v.dist_state is not None:
                param_sh[v.name] = to_named_sharding(self.mesh, v.dist_state)
            else:
                param_sh[v.name] = replicated(self.mesh)
        feed_sh = {}
        for p in subexec.placeholders:
            if p.dist_state is not None:
                feed_sh[p.name] = to_named_sharding(self.mesh, p.dist_state)
            else:
                feed_sh[p.name] = replicated(self.mesh)
        opt_sh = jax.tree_util.tree_map(
            lambda _: replicated(self.mesh), self.opt_state)
        # parameter-sharded optimizer slots follow their parameter
        for opname, state in self.opt_state.items():
            if opname in opt_sh and "slots" in state:
                for vname in state["slots"]:
                    if vname in param_sh:
                        opt_sh[opname]["slots"][vname] = jax.tree_util.tree_map(
                            lambda _: param_sh[vname], state["slots"][vname])
        return (param_sh, opt_sh, feed_sh, replicated(self.mesh),
                replicated(self.mesh))

    # -- reference-compatible API -----------------------------------------
    def run(self, name_or_feed=None, feed_dict=None,
            convert_to_numpy_ret_vals=False, **kwargs):
        if isinstance(name_or_feed, str):
            name = name_or_feed
        else:
            name = next(iter(self.subexecutor))
            if feed_dict is None:
                feed_dict = name_or_feed
        return self.subexecutor[name].run(
            feed_dict=feed_dict,
            convert_to_numpy_ret_vals=convert_to_numpy_ret_vals)

    def run_steps(self, name, feed_dict, n,
                  convert_to_numpy_ret_vals=False):
        """Run ``n`` steps of subgraph ``name`` on the same feeds in ONE
        device dispatch (see SubExecutor.run_steps)."""
        return self.subexecutor[name].run_steps(
            feed_dict, n,
            convert_to_numpy_ret_vals=convert_to_numpy_ret_vals)

    def ps_synchronize(self):
        """Drain in-flight PS embedding traffic across all subgraphs
        (reference worker barriers before SaveParam, executor.py:589)."""
        for sub in self.subexecutor.values():
            if hasattr(sub, "ps_synchronize"):
                sub.ps_synchronize()

    def close(self):
        """Release this executor's HBM-ledger entries (params/opt_state
        pools).  Idempotent; the arrays themselves stay valid and are
        reclaimed by ordinary GC — this only ends the accounting."""
        for h in getattr(self, "_hbm_handles", ()):
            h.free()
        self._hbm_handles = []

    def profile(self, name=None, feed_dict=None, repeats=10,
                trace_dir=None):
        """Wall-clock ``repeats`` compiled steps of subgraph ``name``
        (reference Executor.profile, executor.py:501).

        With ``trace_dir``, the timed steps run under
        ``jax.profiler.trace`` and per-op aggregates (the
        timer_subexecutor.logOut role) are written to
        ``<trace_dir>/op_aggregates.json`` — see hetu_tpu/timeline.py.
        Returns ``(avg_seconds_per_step, aggregates_or_None)`` —
        always a pair, so callers passing trace_dir conditionally
        don't have to switch on the return shape."""
        if name is None:
            name = next(iter(self.subexecutor))
        sub = self.subexecutor[name]
        if trace_dir is None:
            return sub.profile(feed_dict, repeats=repeats), None
        # compile + warm OUTSIDE the capture — and BLOCK, so no async
        # warmup work leaks in: the aggregates cover exactly `repeats`
        # steps (matching meta)
        out = sub.run(feed_dict)
        jax.block_until_ready([o for o in out if o is not None])
        with jax.profiler.trace(trace_dir):
            start = time.perf_counter()
            for _ in range(repeats):
                out = sub.run(feed_dict)
            jax.block_until_ready([o for o in out if o is not None])
            dt = (time.perf_counter() - start) / repeats
        from ..timeline import write_aggregates
        aggs = write_aggregates(trace_dir, extra={
            "subgraph": name, "repeats": repeats,
            "avg_step_seconds": dt})
        return dt, aggs

    def check_monitors(self):
        """Final flush of monitor counters across all subgraphs (also
        called from state_dict so a run that checkpoints before the next
        poll interval still surfaces tripped counters)."""
        for sub in self.subexecutor.values():
            if hasattr(sub, "check_monitors"):
                sub.check_monitors()

    # -- checkpoint (reference executor.py:558-670) ------------------------
    def state_dict(self):
        self.check_monitors()
        host = jax.tree_util.tree_map(np.asarray, self.params)
        opt = jax.tree_util.tree_map(np.asarray, self.opt_state)
        # kept outside opt_state so the jitted step never sees string
        # leaves; load_state_dict uses it to pair optimizer instances by
        # construction order + class instead of by sorted-name luck
        meta = {name: {"class": type(op.optimizer).__name__, "order": i}
                for i, (name, op) in enumerate(self._opt_ops.items())
                if hasattr(op, "optimizer")}
        return {"params": host, "opt_state": opt, "opt_meta": meta,
                # machine-checkable layout tag: 4-D conv kernels are
                # HWIO (TPU-native).  Without it, an OIHW-era checkpoint
                # whose kernel dims are all equal (e.g. a 3x3 conv with
                # 3->3 channels) would load silently transposed — the
                # shape guard in load_state_dict can't see those.
                "format": {"conv_layout": "HWIO", "version": 1},
                "global_step": self._global_step,
                "base_key": np.asarray(jax.random.key_data(self._base_key))}

    def save(self, path):
        # atomic: tmp in the same directory + os.replace, so a kill
        # mid-save (preemption!) never destroys the previous checkpoint
        from .checkpoint import atomic_pickle
        atomic_pickle(self.state_dict(), path)

    def load(self, path):
        # read_checkpoint turns garbage/truncated/stale files into a
        # CheckpointError naming the path, not an opaque unpickle crash
        from .checkpoint import read_checkpoint
        self.load_state_dict(read_checkpoint(path))

    def load_state_dict(self, state):
        from .checkpoint import validate_state
        validate_state(state, source="state_dict payload")
        fmt = state.get("format")
        layout = (fmt or {}).get("conv_layout")
        if layout not in (None, "HWIO"):
            raise ValueError(
                f"checkpoint declares conv_layout={layout!r}; this "
                "executor expects HWIO kernels — convert with "
                "Conv2d.load_oihw (see MIGRATION.md)")
        if fmt is None and any(
                np.ndim(v) == 4 for v in state["params"].values()):
            import warnings
            warnings.warn(
                "checkpoint predates the conv-layout tag: 4-D kernels "
                "are assumed HWIO; an OIHW-era checkpoint whose kernel "
                "dims are all equal cannot be shape-detected — if this "
                "is one, convert with Conv2d.load_oihw (MIGRATION.md)",
                stacklevel=2)
        var_by_name = {v.name: v for v in self.variables}
        extra = sorted(set(state["params"]) - set(var_by_name))
        absent = sorted(set(var_by_name) - set(state["params"]))
        if extra or absent:
            # loading only the intersection is legitimate (fine-tuning a
            # new head) but must never be SILENT: a "restored" run that
            # actually re-initialized half its params diverges quietly.
            # Classic cause: rebuilding the same model outside
            # ht.name_scope(), which suffixes every name with _1.
            import warnings
            warnings.warn(
                f"partial restore: {len(absent)} graph param(s) not in "
                f"the checkpoint (keep their init: {absent[:4]}...), "
                f"{len(extra)} checkpoint param(s) unused "
                f"({extra[:4]}...) — if a full restore was intended, "
                "check that the model was rebuilt under the same "
                "ht.name_scope()", stacklevel=2)
        for name, value in state["params"].items():
            if name in var_by_name:
                v = var_by_name[name]
                value = jnp.asarray(value)
                if v.shape is not None and tuple(value.shape) != tuple(
                        v.shape):
                    hint = ""
                    if value.ndim == 4 and tuple(value.shape) == (
                            v.shape[3], v.shape[2], v.shape[0], v.shape[1]):
                        hint = (" — this looks like an OIHW conv kernel; "
                                "layers.Conv2d stores HWIO (TPU-native); "
                                "convert with Conv2d.load_oihw")
                    raise ValueError(
                        f"checkpoint param {name!r} has shape "
                        f"{tuple(value.shape)} but the graph expects "
                        f"{tuple(v.shape)}{hint}")
                self.params[name] = self._place(v, value)
        saved_opt = state["opt_state"]
        if (set(saved_opt) != set(self.opt_state)
                and len(saved_opt) == len(self.opt_state)):
            # optimizer-op names carry a process-wide counter (a second
            # optimizer instance in the same process gets `optimizer_2`);
            # remap by construction order.  Slot variable-name sets alone
            # can't disambiguate two optimizers over the same variables
            # (same vars under different hyperparams), so also pair by the
            # checkpoint's recorded construction order + class when
            # available, and refuse a pairing order can't resolve.
            meta = state.get("opt_meta")
            if meta is not None and set(meta) == set(saved_opt):
                # construction order on BOTH sides
                sv_order = sorted(saved_opt, key=lambda n: meta[n]["order"])
                cur_order = list(self._opt_ops)
            else:
                # legacy checkpoint: pair sorted-vs-sorted (the old
                # behavior — consistent on both sides, unlike zipping
                # construction order against sorted names, which
                # mispairs once 'optimizer_10' sorts before
                # 'optimizer_2')
                sv_order = sorted(saved_opt)
                cur_order = sorted(self.opt_state)
                slot_sets = [frozenset(s.get("slots", {}))
                             for s in self.opt_state.values()]
                if len(set(slot_sets)) != len(slot_sets):
                    raise ValueError(
                        "checkpoint has no optimizer construction-order "
                        "metadata and this graph has multiple optimizers "
                        "over identical variable sets — the pairing is "
                        "ambiguous; re-save the checkpoint with this "
                        "version or load opt state manually")
            remap = {}
            for cur_name, sv_name in zip(cur_order, sv_order):
                cur, sv = self.opt_state[cur_name], saved_opt[sv_name]
                if set(cur.get("slots", {})) != set(sv.get("slots", {})):
                    raise ValueError(
                        f"checkpoint optimizer state {sv_name!r} does not "
                        f"match this graph's {cur_name!r} (different "
                        "variable sets)")
                if meta is not None and sv_name in meta:
                    cur_op = self._opt_ops[cur_name]
                    cur_cls = type(getattr(cur_op, "optimizer",
                                           cur_op)).__name__
                    if meta[sv_name]["class"] != cur_cls:
                        raise ValueError(
                            f"checkpoint optimizer {sv_name!r} is a "
                            f"{meta[sv_name]['class']} but this graph's "
                            f"{cur_name!r} is a {cur_cls}")
                remap[cur_name] = sv
            saved_opt = remap
        self.opt_state = jax.tree_util.tree_map(jnp.asarray, saved_opt)
        self._global_step = state["global_step"]
        self._step_arr = None  # re-materializes from _global_step
        self._base_key = jax.random.wrap_key_data(
            jnp.asarray(state["base_key"]),
            impl=self.config.get("rng_impl", None))

    def get_params(self):
        return dict(self.params)
