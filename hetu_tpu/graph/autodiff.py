"""Trace-time autodiff: ``gradients(loss, xs)`` as graph nodes.

The reference builds the backward graph symbolically at define time with
hand-written per-op gradient rules (/root/reference/python/hetu/gpu_ops/
executor.py:1265 `gradients()` — reverse topo walk calling `node.gradient`).
Here gradient nodes are thin wrappers that, when the graph is traced, rebase
the loss subgraph on ``xs`` and call ``jax.vjp`` — so every op differentiates
for free (including future Pallas kernels via their custom VJPs), and XLA CSE
dedupes the re-traced forward against the primal forward.  The user-facing
contract matches the reference: ``gradients`` returns one graph node per x,
usable as inputs to optimizer ops or comm ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .node import Op, PlaceholderOp, VariableOp, find_topo_sort
from .trace import TraceContext, evaluate


class GradientsBundleOp(Op):
    """Internal: computes all d loss / d xs in one vjp call.

    ``fuses_primal`` marks that the vjp's forward pass produces the loss
    value itself: when the loss subgraph is stateless, `evaluate`
    (trace.py) computes this bundle FIRST and injects the vjp primal as
    the loss's value, so the forward is traced exactly once — measured on
    TPU v5e, the old evaluate-loss-then-vjp structure cost 25% extra
    FLOPs/step on BERT-base because XLA CSE does NOT reliably merge the
    primal forward with the vjp's re-trace (and cannot across Pallas
    custom_vjp boundaries).
    """

    fuses_primal = True

    def __init__(self, loss, xs, grad_out=None):
        self.xs = list(xs)
        self.grad_out = grad_out
        inputs = [loss] + self.xs + ([grad_out] if grad_out is not None else [])
        super().__init__(*inputs, name=f"grads_of_{loss.name}")
        self.loss = loss

    # evaluated via _compute_with_env (special-cased by trace/executor)
    def _compute_with_env(self, env, ctx: TraceContext, want_primal=False):
        sub_topo = find_topo_sort([self.loss])
        x_set = set(self.xs)
        # Rebase on true graph leaves only; everything between leaves and loss
        # is re-traced with xs overridden (xs may be intermediate nodes, e.g.
        # stage-boundary activations for pipeline partitioning).  Binding any
        # already-computed interior node would cut the path from xs to loss.
        leaves = [n for n in sub_topo
                  if isinstance(n, (PlaceholderOp, VariableOp))
                  and n not in x_set]

        # stateful updates (batchnorm running stats, assigns) surface as
        # the vjp's aux so the primal-fusion path can record them; on the
        # non-fused path they're discarded (the primal forward already
        # recorded them).  RNG is shared either way, so dropout masks
        # replay identically.
        node_by_name = {}  # aux pytree keys must sort; map names back

        def f(x_vals):
            inner = TraceContext(key=ctx.key, training=ctx.training,
                                 mesh=ctx.mesh,
                                 master_params=ctx.master_params)
            bind = {n: env[n] for n in leaves if n in env}
            bind.update(dict(zip(self.xs, x_vals)))
            (loss_val,), _ = evaluate([self.loss], bind, inner)
            node_by_name.update({v.name: v for v in inner.updates})
            return loss_val, {v.name: val
                              for v, val in inner.updates.items()}

        primals = [env[x] for x in self.xs]
        loss_val, vjp_fn, updates = jax.vjp(f, primals, has_aux=True)
        if self.grad_out is not None:
            ct = env[self.grad_out]
        else:
            ct = jnp.ones_like(loss_val)
        (grads,) = vjp_fn(ct)
        if want_primal:
            return loss_val, tuple(grads), {node_by_name[k]: v
                                            for k, v in updates.items()}
        return tuple(grads)

    def _compute(self, input_vals, ctx):
        raise RuntimeError("GradientsBundleOp is evaluated with env access")


class GradientSliceOp(Op):
    """Selects one gradient out of a GradientsBundleOp."""

    def __init__(self, bundle, idx, of):
        super().__init__(bundle, name=f"grad_{of.name}")
        self.idx = idx
        self.of = of  # the x this is the gradient of

    def _compute(self, input_vals, ctx):
        return input_vals[0][self.idx]


def gradients(loss, node_list, grad_out=None, return_all=False):
    """Build gradient nodes of ``loss`` w.r.t. each node in ``node_list``.

    API-compatible with reference executor.py:1265.  ``return_all`` returns
    (grads, backward2forward, forward2backward) maps used by the pipeline
    partitioner; here the maps are {x: grad_node} / {grad_node: x}.
    """
    node_list = list(node_list)
    bundle = GradientsBundleOp(loss, node_list, grad_out=grad_out)
    grads = [GradientSliceOp(bundle, i, x) for i, x in enumerate(node_list)]
    if return_all:
        f2b = {x: g for x, g in zip(node_list, grads)}
        b2f = {g: x for x, g in zip(node_list, grads)}
        return grads, b2f, f2b
    return grads
