"""Goodput ledger: attribute wall-time x chips into exhaustive buckets.

Every chaos/fault PR (2, 5, 6, 17) proved the system RECOVERS; none
answered what the failure COST.  The ledger answers it with the PR 4
phases-sum-to-wall discipline lifted to the whole process: a window of
``wall_s * chips`` chip-seconds is attributed into buckets that sum to
1.0 BY CONSTRUCTION — measured sinks first, the residual is idle, and
when concurrent measured sinks oversubscribe the wall (threaded
serving) every measured bucket is scaled down proportionally so the
identity holds instead of silently breaking.

Buckets (:data:`GOODPUT_BUCKETS`):

* useful — ``useful_train`` (executor step time minus compile and
  guard-tripped steps), ``useful_prefill`` / ``useful_decode``
  (serving span time minus failover replay);
* lost, by mechanism — ``compile`` (program build span),
  ``data_wait`` (input stall spans), ``checkpoint_save`` /
  ``checkpoint_restore`` (histograms), ``rollback`` (guard-tripped
  step time + the rollback-restore span), ``failover_replay``
  (replayed tokens x measured per-token decode cost, carved out of the
  serving spans), ``kv_migration`` (live-migration span), ``reshard``
  (the elastic trainer's re-plan + rebuild + resharded-restore span —
  what a capacity change costs end to end),
  ``brownout_shed`` (shed requests x measured mean request cost,
  bounded by the idle residual — capacity we chose not to spend),
  ``idle`` (the residual).

Everything is fed from EXISTING spans/counters — no new probes in hot
paths; the only new spans this PR adds are ``compile`` (executor
program build), ``rollback_restore`` (guard), and ``kv_migrate``
(fleet), each on an already-cold path.  Per-trainer / per-replica
attribution rides the label sets the counters already carry: the
report splits ``useful_train`` by subgraph step-time share and
``useful_decode`` by scheduler token share.

Disabled by default like every PR 4 instrument: :meth:`begin` /
:meth:`account` while disabled are one flag check (<20 us/op, pinned
by ``tests/test_timeseries.py``).
"""

from __future__ import annotations

import time

__all__ = ["GoodputLedger", "GOODPUT_BUCKETS", "USEFUL_BUCKETS",
           "LOST_CAUSES"]

#: every bucket the ledger can attribute chip-time to (fractions sum to 1)
GOODPUT_BUCKETS = ("useful_train", "useful_prefill", "useful_decode",
                   "compile", "data_wait", "checkpoint_save",
                   "checkpoint_restore", "rollback", "failover_replay",
                   "kv_migration", "reshard", "brownout_shed", "idle")

USEFUL_BUCKETS = ("useful_train", "useful_prefill", "useful_decode")

#: the lost-capacity causes (everything that is not useful or idle)
LOST_CAUSES = tuple(b for b in GOODPUT_BUCKETS
                    if b not in USEFUL_BUCKETS)


def _csum(snap, name):
    m = snap.get(name)
    if m is None:
        return 0.0
    return float(sum(s["value"] for s in m["samples"]))


def _hsum(snap, name):
    m = snap.get(name)
    if m is None:
        return 0.0
    return float(sum(s["sum"] for s in m["samples"]))


def _hcount(snap, name):
    m = snap.get(name)
    if m is None:
        return 0
    return int(sum(s["count"] for s in m["samples"]))


def _by_label(snap, name, field="value"):
    """{label_str: value} per series of one metric."""
    m = snap.get(name)
    if m is None:
        return {}
    out = {}
    for s in m["samples"]:
        key = ",".join(f"{k}={v}" for k, v in sorted(s["labels"].items()))
        out[key] = out.get(key, 0.0) + float(s[field])
    return out


class GoodputLedger:
    """Windowed chip-time attribution over the process registry+tracer.

    :meth:`begin` pins the window start (a cumulative-sink baseline);
    :meth:`account` attributes everything since.  Ledgers are cheap —
    make one per trainer / replica / chaos stage for scoped windows;
    the ``name`` label keeps their gauges apart."""

    def __init__(self, registry=None, tracer=None, *, name="process",
                 chips=1, clock=None, enabled=False):
        if chips < 1:
            raise ValueError(f"chips must be >= 1, got {chips}")
        self._registry = registry
        self._tracer = tracer
        self.name = str(name)
        self.chips = int(chips)
        self.enabled = bool(enabled)
        self._clock = clock if clock is not None else time.perf_counter
        self._base = None           # (t0, sinks) window baseline
        self._m_goodput = None
        self._m_lost = None

    # -- the cumulative sinks ---------------------------------------------
    def _sinks(self):
        snap = self._registry.snapshot() if self._registry else {}
        agg = self._tracer.aggregate() if self._tracer else {}

        def span(n):
            return float(agg.get(n, {}).get("total_s", 0.0))

        return {
            "train_wall": _hsum(snap, "hetu_executor_step_seconds"),
            "train_steps": _hcount(snap, "hetu_executor_step_seconds"),
            "train_by": _by_label(snap, "hetu_executor_step_seconds",
                                  field="sum"),
            "compile": span("compile"),
            "data_wait": span("data_wait") + span("prefetch_h2d"),
            "ckpt_save": _hsum(snap, "hetu_checkpoint_save_seconds"),
            "restore": _hsum(snap, "hetu_checkpoint_restore_seconds"),
            "rollback_restore": span("rollback_restore"),
            "guard_trips": (_csum(snap, "hetu_guard_trips_total")
                            + _csum(snap, "hetu_guard_inner_trips_total")),
            "prefill": span("serve_prefill"),
            "decode": span("serve_decode"),
            "tokens": _csum(snap, "hetu_serving_tokens_total"),
            "tokens_by": _by_label(snap, "hetu_serving_tokens_total"),
            "replayed": _csum(snap, "hetu_serving_replayed_tokens_total"),
            "kv_migration": span("kv_migrate"),
            # the elastic recover protocol's span, plus the checkpoint
            # flush/restore it contains (those also hit the save/
            # restore histograms — carved back out in account() the
            # way rollback_restore is, so no second is counted twice)
            "reshard": span("elastic_reshard"),
            "elastic_save": span("elastic_ckpt_save"),
            "elastic_restore": span("elastic_ckpt_restore"),
            "rejections": (_csum(snap, "hetu_serving_rejections_total")
                           + _csum(snap,
                                   "hetu_slo_admission_rejects_total")),
            "finished": _csum(snap, "hetu_serving_requests_total"),
        }

    @staticmethod
    def _delta(cur, base):
        d = {}
        for k, v in cur.items():
            if isinstance(v, dict):
                b = base.get(k, {}) if base else {}
                d[k] = {kk: max(0.0, vv - b.get(kk, 0.0))
                        for kk, vv in v.items()}
            else:
                b = base.get(k, 0.0) if base else 0.0
                d[k] = max(0.0, v - b)
        return d

    # -- windowing ---------------------------------------------------------
    def begin(self, now=None):
        """Pin the attribution window start; no-op while disabled."""
        if not self.enabled:
            return None
        t = self._clock() if now is None else float(now)
        self._base = (t, self._sinks())
        return t

    # -- attribution -------------------------------------------------------
    def account(self, wall_s=None, chips=None, now=None,
                update_gauges=True):
        """Attribute the window since :meth:`begin` (or since the
        ledger was enabled) into :data:`GOODPUT_BUCKETS`.

        Returns ``{"wall_chip_s", "buckets" (seconds), "fractions"
        (sum to 1 exactly), "goodput_fraction", "lost", "replicas"}``;
        ``{"enabled": False}`` while disabled."""
        if not self.enabled:
            return {"enabled": False}
        t = self._clock() if now is None else float(now)
        if self._base is None:
            self.begin(now=t)
        t0, base = self._base
        d = self._delta(self._sinks(), base)
        wall = float(wall_s) if wall_s is not None else max(0.0, t - t0)
        chips = self.chips if chips is None else int(chips)
        cap = wall * chips

        # training: step wall minus the compile span it contains, minus
        # guard-tripped steps (each trip wasted ~one mean step)
        mean_step = (d["train_wall"] / d["train_steps"]
                     if d["train_steps"] else 0.0)
        train_pool = max(0.0, d["train_wall"] - d["compile"])
        tripped = min(train_pool, d["guard_trips"] * mean_step)
        useful_train = train_pool - tripped
        # rollback = tripped step time + the measured restore span; the
        # restore HISTOGRAM also observed that span, so the plain
        # checkpoint_restore bucket is the histogram minus it (same for
        # the elastic recover protocol's flush/restore, which belong to
        # the reshard bucket)
        rollback = tripped + d["rollback_restore"]
        ckpt_restore = max(0.0, d["restore"] - d["rollback_restore"]
                           - d["elastic_restore"])
        ckpt_save = max(0.0, d["ckpt_save"] - d["elastic_save"])
        # serving: failover replay re-derives tokens that were already
        # paid for once — cost ~= replayed tokens at the measured
        # per-token decode cost, carved out of decode then prefill
        per_tok = d["decode"] / d["tokens"] if d["tokens"] > 0 else 0.0
        replay_s = min(d["decode"] + d["prefill"],
                       d["replayed"] * per_tok)
        replay_decode = min(d["decode"], replay_s)
        replay_prefill = min(d["prefill"], replay_s - replay_decode)
        useful_decode = d["decode"] - replay_decode
        useful_prefill = d["prefill"] - replay_prefill

        buckets = {
            "useful_train": useful_train,
            "useful_prefill": useful_prefill,
            "useful_decode": useful_decode,
            "compile": d["compile"],
            "data_wait": d["data_wait"],
            "checkpoint_save": ckpt_save,
            "checkpoint_restore": ckpt_restore,
            "rollback": rollback,
            "failover_replay": replay_decode + replay_prefill,
            "kv_migration": d["kv_migration"],
            "reshard": d["reshard"],
            "brownout_shed": 0.0,
        }
        measured = sum(buckets.values())
        scaled = False
        if cap > 0 and measured > cap:
            # concurrent measured sinks oversubscribed the wall
            # (threaded serving): scale proportionally so the sum-to-1
            # identity survives instead of silently breaking
            f = cap / measured
            buckets = {k: v * f for k, v in buckets.items()}
            measured = cap
            scaled = True
        idle = max(0.0, cap - measured)
        # brownout shed is capacity we REFUSED to spend — it can only
        # come out of the idle residual, priced at the measured mean
        # cost of a finished request
        mean_req = ((useful_decode + useful_prefill) / d["finished"]
                    if d["finished"] > 0 else 0.0)
        shed = min(idle, d["rejections"] * mean_req)
        buckets["brownout_shed"] = shed
        idle -= shed
        buckets["idle"] = idle

        if cap > 0:
            fractions = {k: v / cap for k, v in buckets.items()}
            # the residual in FRACTION space: exact sum-to-1
            fractions["idle"] = 1.0 - sum(
                v for k, v in fractions.items() if k != "idle")
        else:
            fractions = {k: 0.0 for k in buckets}
            fractions["idle"] = 1.0
        goodput = sum(fractions[k] for k in USEFUL_BUCKETS)
        lost = {k: fractions[k] for k in LOST_CAUSES}

        if update_gauges:
            self._set_gauges(goodput, lost)
        return {"ledger": self.name,
                "wall_chip_s": round(cap, 6),
                "chips": chips,
                "window_s": round(wall, 6),
                "scaled_to_wall": scaled,
                "buckets_s": {k: round(v, 6)
                              for k, v in buckets.items()},
                "fractions": {k: round(v, 9)
                              for k, v in fractions.items()},
                "goodput_fraction": round(goodput, 9),
                "lost": {k: round(v, 9) for k, v in lost.items()},
                "replicas": self._replica_split(d, fractions)}

    def _replica_split(self, d, fractions):
        """Label-share attribution of the useful fractions: train by
        subgraph step-time share, decode by scheduler token share."""
        out = {}
        total_t = sum(d["train_by"].values())
        if total_t > 0:
            out["useful_train"] = {
                k: round(fractions["useful_train"] * v / total_t, 9)
                for k, v in d["train_by"].items()}
        total_k = sum(d["tokens_by"].values())
        if total_k > 0:
            out["useful_decode"] = {
                k: round(fractions["useful_decode"] * v / total_k, 9)
                for k, v in d["tokens_by"].items()}
        return out

    def _set_gauges(self, goodput, lost):
        reg = self._registry
        if reg is None:
            return
        if self._m_goodput is None:
            self._m_goodput = reg.gauge(
                "hetu_goodput_fraction",
                "Fraction of wall x chips spent on useful work "
                "(train steps + prefill/decode tokens) in the last "
                "accounted window", labels=("ledger",))
            self._m_lost = reg.gauge(
                "hetu_goodput_lost_fraction",
                "Fraction of wall x chips lost to one cause in the "
                "last accounted window", labels=("ledger", "cause"))
        self._m_goodput.labels(ledger=self.name).set(goodput)
        for cause, frac in lost.items():
            self._m_lost.labels(ledger=self.name, cause=cause).set(frac)

    def report_block(self):
        """The ``/goodput`` debug payload + ``telemetry.report()``
        block: the window since :meth:`begin` (telemetry.enable pins
        it), gauges untouched."""
        if not self.enabled:
            return {"enabled": False}
        return dict(self.account(update_gauges=False), enabled=True)
