"""Time-series plane: a bounded ring of periodic registry snapshots.

The registry (PR 4) answers "what is the process doing RIGHT NOW"; the
flight recorder answers "what happened at the trip".  Nothing so far
answers "what changed over the last N ticks" — the question every
trend-driven consumer (SLO burn-rate alerting in ``alerts.py``, the
goodput ledger in ``goodput.py``, the PR 11/18 control loops) actually
asks.  :class:`TimeSeriesStore` closes that gap: ``tick()`` appends one
compact snapshot of every registered metric (cumulative counter values,
gauge samples, histogram count/sum/bucket counts) stamped on an
INJECTABLE clock, into a fixed-capacity ring with resolution-halving
downsampling — old history gets coarser, never unbounded.

Query API works in the same shapes Prometheus users expect:

* ``series(name, labels, window)`` — ``[(t, value)]`` points; with
  ``labels=None`` matching label sets are SUMMED (the fleet-wide view);
* ``delta()`` / ``rate()`` — counter movement over a window;
* ``last()`` — the newest sample;
* ``tail()`` — the last-N points an alert incident carries.

Persistence goes through the one :class:`~.registry.JsonlWriter` path
(``write_jsonl`` dumps the retained ring; ``configure(writer=)``
streams one line per tick).  Like every PR 4 instrument the store is
DISABLED by default: ``tick()`` while disabled is one flag check
(pinned <20 us/op by ``tests/test_timeseries.py``), so control loops
carry their tick hooks unconditionally.

There is NO collector thread: ticks are driven by whoever owns a
cadence (``FleetController.tick`` via an attached
:class:`~.alerts.AlertManager`, bench chaos stages on a manual clock) —
the no-leaked-threads gate stays intact and tests get determinism
for free.
"""

from __future__ import annotations

import threading
import time

__all__ = ["TimeSeriesStore"]


def _label_key(labels):
    """Canonical hashable key for one label set ({} -> ())."""
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _key_str(key):
    return ",".join(f"{k}={v}" for k, v in key) if key else ""


class TimeSeriesStore:
    """Bounded ring of periodic :class:`MetricsRegistry` snapshots.

    ``capacity`` bounds RETAINED ticks; past it the oldest half is
    downsampled 2:1 (every second tick dropped), so the ring holds a
    long coarse past plus a fine recent window.  ``clock`` defaults to
    ``time.perf_counter`` and is injectable for deterministic tests /
    chaos probes.  ``min_interval_s`` rate-limits callers that tick on
    a hot cadence (a 20 Hz controller loop should not snapshot the
    registry 20 times a second)."""

    def __init__(self, registry=None, capacity=512, clock=None,
                 enabled=False, min_interval_s=0.0):
        if capacity < 4:
            raise ValueError(f"capacity must be >= 4, got {capacity}")
        self._registry = registry
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._clock = clock if clock is not None else time.perf_counter
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._ticks = []            # [(t, {name: (kind, {key: value})})]
        self.tick_count = 0         # ticks ever taken
        self.downsampled = 0        # ticks dropped by compaction
        self.compactions = 0
        self._writer = None
        self._m_ticks = None
        self._m_retained = None
        self._m_dropped = None

    # -- configuration -----------------------------------------------------
    def configure(self, writer=None, min_interval_s=None):
        """Attach a :class:`~.registry.JsonlWriter` (one line per tick)
        and/or adjust the tick rate limit."""
        if writer is not None:
            self._writer = writer
        if min_interval_s is not None:
            self.min_interval_s = float(min_interval_s)
        return self

    def clear(self):
        with self._lock:
            self._ticks = []
            self.tick_count = 0
            self.downsampled = 0
            self.compactions = 0

    def __len__(self):
        with self._lock:
            return len(self._ticks)

    # -- collection --------------------------------------------------------
    def _capture(self):
        """One compact frame of the registry: {name: (kind,
        {label_key: scalar | (count, sum, bucket_counts)})}."""
        snap = self._registry.snapshot()
        frame = {}
        for name, m in snap.items():
            samples = {}
            for s in m["samples"]:
                key = _label_key(s["labels"])
                if m["type"] == "histogram":
                    samples[key] = (s["count"], s["sum"],
                                    tuple(n for _, n in s["buckets"]))
                else:
                    samples[key] = float(s["value"])
            frame[name] = (m["type"], samples)
        return frame

    def tick(self, now=None):
        """Append one snapshot frame; no-op while disabled.  Returns
        the frame timestamp, or None when disabled / rate-limited."""
        if not self.enabled or self._registry is None:
            return None
        t = self._clock() if now is None else float(now)
        with self._lock:
            if (self._ticks and self.min_interval_s > 0.0
                    and t - self._ticks[-1][0] < self.min_interval_s):
                return None
        frame = self._capture()
        dropped = 0
        with self._lock:
            self._ticks.append((t, frame))
            self.tick_count += 1
            if len(self._ticks) > self.capacity:
                # resolution-halving compaction: drop every second tick
                # of the OLDEST half — the recent window stays fine-
                # grained, the deep past gets coarser instead of gone
                half = len(self._ticks) // 2
                old = self._ticks[:half]
                kept = old[::2]
                dropped = len(old) - len(kept)
                self._ticks = kept + self._ticks[half:]
                self.downsampled += dropped
                self.compactions += 1
            retained = len(self._ticks)
        self._self_metrics(retained, dropped)
        if self._writer is not None:
            self._writer.write({"kind": "timeseries_tick", "t": t,
                                "metrics": self._json_frame(frame)})
        return t

    def _self_metrics(self, retained, dropped):
        reg = self._registry
        if self._m_ticks is None:
            self._m_ticks = reg.counter(
                "hetu_timeseries_ticks_total",
                "Registry snapshots appended to the time-series ring")
            self._m_retained = reg.gauge(
                "hetu_timeseries_ticks_retained",
                "Snapshots currently retained in the time-series ring")
            self._m_dropped = reg.counter(
                "hetu_timeseries_ticks_downsampled_total",
                "Old snapshots dropped by resolution-halving compaction")
        self._m_ticks.inc()
        self._m_retained.set(retained)
        if dropped:
            self._m_dropped.inc(dropped)

    # -- queries -----------------------------------------------------------
    def _frames(self, window=None, now=None):
        with self._lock:
            ticks = list(self._ticks)
        if window is None or not ticks:
            return ticks
        t1 = ticks[-1][0] if now is None else float(now)
        return [f for f in ticks if f[0] >= t1 - float(window)]

    @staticmethod
    def _sample_value(kind, v, field):
        if kind != "histogram":
            return v
        if field in (None, "count"):
            return float(v[0])
        if field == "sum":
            return float(v[1])
        raise ValueError(f"histogram field must be 'count' or 'sum', "
                         f"got {field!r}")

    def series(self, name, labels=None, window=None, field=None,
               now=None):
        """``[(t, value)]`` for one metric over ``window`` seconds
        (None: the whole retained ring).  ``labels=None`` sums every
        label set of the metric — the fleet-wide aggregate; a dict
        selects one series exactly.  ``field``: ``count``/``sum`` for
        histograms.  Ticks predating a cumulative metric's first
        appearance count as 0 (counters are born at zero); for gauges
        such ticks are skipped — absence is not zero."""
        want = None if labels is None else _label_key(labels)
        out = []
        absent = []     # frames predating the metric's first appearance
        kind = None
        for t, frame in self._frames(window, now):
            m = frame.get(name)
            if m is None:
                if not out:
                    absent.append(t)
                continue
            kind, samples = m
            if want is None:
                vals = [self._sample_value(kind, v, field)
                        for v in samples.values()]
                if not vals:
                    if not out:
                        absent.append(t)
                    continue
                out.append((t, float(sum(vals))))
            elif want in samples:
                out.append((t, float(self._sample_value(
                    kind, samples[want], field))))
            elif not out:
                absent.append(t)
        # cumulative metrics start life at zero: a counter born mid-
        # window at value N is N increments of real movement, so pre-
        # birth frames contribute 0 rather than vanishing (otherwise a
        # rate rule can never fire on a fault that CREATES its counter).
        # Gauges keep skip semantics — absence is not zero for them.
        if out and absent and kind in ("counter", "histogram"):
            out = [(t, 0.0) for t in absent] + out
        return out

    def last(self, name, labels=None, field=None):
        pts = self.series(name, labels=labels, field=field)
        return pts[-1][1] if pts else None

    def delta(self, name, labels=None, window=None, field=None,
              now=None):
        """last - first over the window; None with <2 points (no
        movement evidence is different from zero movement)."""
        pts = self.series(name, labels, window, field, now)
        if len(pts) < 2:
            return None
        return pts[-1][1] - pts[0][1]

    def rate(self, name, labels=None, window=None, field=None,
             now=None):
        """Per-second rate of a cumulative series over the window;
        None with <2 points or a zero time base."""
        pts = self.series(name, labels, window, field, now)
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        return (pts[-1][1] - pts[0][1]) / dt

    def mean(self, name, labels=None, window=None, field=None,
             now=None):
        pts = self.series(name, labels, window, field, now)
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)

    def tail(self, name, labels=None, n=16, field=None):
        """The last ``n`` points — what an alert incident carries as
        the offending series window."""
        return self.series(name, labels=labels, field=field)[-int(n):]

    def names(self):
        """Metric names present in the newest frame."""
        with self._lock:
            if not self._ticks:
                return []
            return sorted(self._ticks[-1][1])

    # -- export ------------------------------------------------------------
    @staticmethod
    def _json_frame(frame):
        out = {}
        for name, (kind, samples) in frame.items():
            rows = []
            for key, v in samples.items():
                row = {"labels": _key_str(key)}
                if kind == "histogram":
                    row.update(count=v[0], sum=v[1], buckets=list(v[2]))
                else:
                    row["value"] = v
                rows.append(row)
            out[name] = {"type": kind, "samples": rows}
        return out

    def write_jsonl(self, writer):
        """Dump the retained ring as one record through a
        :class:`~.registry.JsonlWriter` (or any ``write(record)``)."""
        with self._lock:
            ticks = list(self._ticks)
        writer.write({"kind": "timeseries",
                      "tick_count": self.tick_count,
                      "downsampled": self.downsampled,
                      "ticks": [{"t": t, "metrics": self._json_frame(f)}
                                for t, f in ticks]})

    def report_block(self):
        """The ``/timeseries`` debug payload + ``telemetry.report()``
        block: ring occupancy, span, and the live series index."""
        with self._lock:
            ticks = list(self._ticks)
        return {"enabled": self.enabled,
                "ticks_retained": len(ticks),
                "tick_count": self.tick_count,
                "downsampled": self.downsampled,
                "compactions": self.compactions,
                "capacity": self.capacity,
                "span_s": (round(ticks[-1][0] - ticks[0][0], 6)
                           if len(ticks) >= 2 else 0.0),
                "series": self.names()}
