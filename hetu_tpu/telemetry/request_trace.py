"""Per-request lifecycle timelines, stitched across fleet failover.

The registry answers "how many requests finished"; this module answers
the question the chaos/fleet benches kept re-deriving from scattered
records — "what happened to request e0-17, and why was it slow?".
Every rid accumulates a typed, monotonic-clock event timeline:

    queued, admitted, prefill_start, prefill_chunk, prefill_end,
    decode_iter, hot_hit, host_pull, watchdog_trip, harvested,
    failover_replay, expired, cancelled, finish

``decode_iter`` is ONE event per engine iteration per request (slot +
token count), not one per token emission call, so a 64-token request
costs 64 small events, not a flood.  Timelines are keyed by the
CLUSTER rid: a fleet failover re-submits the same rid on a sibling,
so its events (tagged with the sibling's engine instance) append to
the same timeline — one stitched history per accepted request, with
``failover_replay`` marking the seam.  Embedding requests reuse the
same vocabulary with per-tier ``hot_hit``/``host_pull`` lookup events.

Cost model (the PR 4 contract): disabled by default, and ``event()``
is one flag check + return while disabled, so the serving hot paths
carry their probes unconditionally.  Storage is bounded twice over —
per-rid event cap and a total-rid cap with oldest-terminal-first
eviction — and every drop is counted (surfaced as registry gauges by
``telemetry.report()``; silent loss is invisible loss).

Export faces: ``export_jsonl`` (one record per rid through the shared
:class:`~.registry.JsonlWriter` path), ``inflight()`` (the ``/requests``
debug endpoint's live table), and ``chrome_rows()`` — trace-event rows
(one pid per engine, one tid per rid) that merge into the
``SpanTracer.chrome_trace`` view so request lifecycles land next to the
host phase spans in one Perfetto load.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

__all__ = ["RequestTrace", "EVENT_TYPES"]

#: the full event vocabulary (tests pin additions to the doc)
EVENT_TYPES = ("queued", "admitted", "prefill_start", "prefill_chunk",
               "prefill_end", "decode_iter", "hot_hit", "host_pull",
               "watchdog_trip", "harvested", "failover_replay",
               "migrated", "expired", "cancelled", "finish")

#: attempt-level finish reasons that do NOT end the cluster timeline
#: (the fleet re-homes the rid; more events follow)
_NONTERMINAL_FINISH = ("failover",)


class _Timeline:
    __slots__ = ("events", "engine", "deadline", "dropped")

    def __init__(self):
        self.events = []
        self.engine = None      # last engine instance seen
        self.deadline = None    # absolute, on the serving monotonic clock
        self.dropped = 0


class RequestTrace:
    """Bounded per-rid event timelines (see module doc)."""

    def __init__(self, max_rids=4096, events_per_rid=512, enabled=False):
        if max_rids < 1 or events_per_rid < 2:
            raise ValueError(
                f"need max_rids >= 1 and events_per_rid >= 2, got "
                f"{max_rids}/{events_per_rid}")
        self.max_rids = int(max_rids)
        self.events_per_rid = int(events_per_rid)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._timelines = OrderedDict()     # rid -> _Timeline
        self._epoch = time.perf_counter()
        self.dropped_events = 0     # events refused by the per-rid cap
        self.dropped_rids = 0       # whole timelines evicted by max_rids
        self._sink = None           # FlightRecorder.record, when wired

    # -- recording ---------------------------------------------------------
    def event(self, rid, etype, engine=None, **fields):
        """Append one typed event to ``rid``'s timeline.  No-op while
        disabled (one flag check).  ``engine`` tags the event with the
        engine instance that produced it — a failed-over rid's timeline
        carries every instance it touched."""
        if not self.enabled:
            return
        ev = {"e": etype, "t": time.perf_counter()}
        if engine is not None:
            ev["engine"] = engine
        if fields:
            # None-valued fields carry no information — keep events lean
            ev.update({k: v for k, v in fields.items()
                       if v is not None})
        with self._lock:
            tl = self._timelines.get(rid)
            if tl is None:
                if len(self._timelines) >= self.max_rids:
                    self._evict_locked()
                tl = self._timelines[rid] = _Timeline()
            if engine is not None:
                tl.engine = engine
            if etype == "queued" and fields.get("deadline") is not None:
                tl.deadline = float(fields["deadline"])
            if (len(tl.events) >= self.events_per_rid
                    and etype != "finish"):
                # keep the terminal event no matter what: completeness
                # ("did every accepted rid reach a terminal?") must
                # survive a chatty decode; drop the middle, not the end
                tl.dropped += 1
                self.dropped_events += 1
                return
            tl.events.append(ev)
        sink = self._sink
        if sink is not None:
            sink(dict(ev, rid=rid))

    def _evict_locked(self):
        """Make room for a new rid: evict the oldest FINISHED timeline,
        or the oldest outright when nothing has finished."""
        victim = None
        for rid, tl in self._timelines.items():
            if _done(tl.events):
                victim = rid
                break
        if victim is None:
            victim = next(iter(self._timelines))
        del self._timelines[victim]
        self.dropped_rids += 1

    def clear(self):
        with self._lock:
            self._timelines = OrderedDict()
            self.dropped_events = 0
            self.dropped_rids = 0
            self._epoch = time.perf_counter()

    # -- inspection --------------------------------------------------------
    def __len__(self):
        with self._lock:
            return len(self._timelines)

    def rids(self):
        with self._lock:
            return list(self._timelines)

    def timeline(self, rid):
        """Copies of ``rid``'s events (oldest first); [] for unknown."""
        with self._lock:
            tl = self._timelines.get(rid)
            return [dict(e) for e in tl.events] if tl else []

    def complete(self, rid):
        """True when the rid was accepted (timeline starts at queued/
        admitted) AND reached a cluster-terminal ``finish`` — the
        property the chaos/fleet benches assert for every accepted rid,
        stitched across however many failovers it survived."""
        with self._lock:
            tl = self._timelines.get(rid)
            events = tl.events if tl else ()
            if not events or events[0]["e"] not in ("queued", "admitted"):
                return False
            return _done(events)

    def inflight(self, now=None):
        """Live request table (the ``/requests`` endpoint): one row per
        un-finished rid — rid, last lifecycle state, age, deadline
        remaining, and the engine currently holding it."""
        now = time.perf_counter() if now is None else now
        rows = []
        with self._lock:
            for rid, tl in self._timelines.items():
                if not tl.events or _done(tl.events):
                    continue
                row = {"rid": rid,
                       "state": tl.events[-1]["e"],
                       "age_s": round(now - tl.events[0]["t"], 6),
                       "engine": tl.engine,
                       "events": len(tl.events)}
                # deadlines live on the SERVING clock (possibly a test's
                # ManualClock), not ours — report the raw bound and let
                # the caller difference it when the clocks coincide
                row["deadline_remaining_s"] = (
                    None if tl.deadline is None
                    else round(tl.deadline - now, 6))
                rows.append(row)
        return rows

    # -- export ------------------------------------------------------------
    def export_jsonl(self, writer, epoch=None):
        """One ``{"kind": "request_timeline", ...}`` record per rid via
        any ``write(record)`` object (:class:`~.registry.JsonlWriter`);
        timestamps relative to ``epoch`` (default: this trace's).
        Returns the number of records written."""
        epoch = self._epoch if epoch is None else epoch
        with self._lock:
            items = [(rid, tl.engine, tl.dropped,
                      [dict(e) for e in tl.events])
                     for rid, tl in self._timelines.items()]
        for rid, engine, dropped, events in items:
            for e in events:
                e["t"] = round(e["t"] - epoch, 9)
            writer.write({"kind": "request_timeline", "rid": rid,
                          "engine": engine, "complete": _done(events),
                          "dropped_events": dropped, "events": events})
        return len(items)

    def chrome_rows(self, epoch=None, pid_base=(1 << 20) + 1):
        """Trace-event rows for the merged chrome view: one pid per
        engine instance (``M`` process_name metadata), one tid per rid
        (``M`` thread_name), and one ``X`` event per lifecycle event
        whose duration runs to the rid's next event — so a request reads
        as a contiguous lane and a failover visibly jumps lanes.
        ``epoch`` should be the SpanTracer's epoch when merging
        (``telemetry.chrome_trace`` passes it)."""
        epoch = self._epoch if epoch is None else epoch
        with self._lock:
            items = [(rid, [dict(e) for e in tl.events])
                     for rid, tl in self._timelines.items()]
        pids, tids, rows = {}, {}, []
        for rid, events in items:
            tid = tids.setdefault(rid, len(tids) + 1)
            for i, ev in enumerate(events):
                engine = ev.pop("engine", None) or "engine?"
                pid = pids.get(engine)
                if pid is None:
                    pid = pids[engine] = pid_base + len(pids)
                    rows.append({"ph": "M", "pid": pid,
                                 "name": "process_name",
                                 "args": {"name": f"engine {engine}"}})
                rows.append({"ph": "M", "pid": pid, "tid": tid,
                             "name": "thread_name",
                             "args": {"name": f"rid {rid}"}})
                t = ev.pop("t")
                nxt = (events[i + 1]["t"] if i + 1 < len(events) else t)
                rows.append({"ph": "X", "pid": pid, "tid": tid,
                             "name": ev.pop("e"),
                             "ts": (t - epoch) * 1e6,
                             "dur": max(0.0, (nxt - t) * 1e6),
                             "args": dict(ev, rid=rid)})
        return rows


def _done(events):
    """A timeline is finished when its LAST event is a terminal
    ``finish`` — an attempt-level finish (reason "failover", or an
    "error" the fleet re-homes) is followed by more events, so the
    last-event test is exactly the stitched-timeline semantics.  A
    CLUSTER-level finish (the fleet's ``_finalize``) is authoritative
    wherever it sits: an abandoned replica's wedged step thread may
    unblock and append stale events after the fleet already finalized
    the rid, and those must not un-finish the timeline."""
    if not events:
        return False
    last = events[-1]
    if (last["e"] == "finish"
            and last.get("reason") not in _NONTERMINAL_FINISH):
        return True
    return any(e["e"] == "finish" and e.get("cluster")
               and e.get("reason") not in _NONTERMINAL_FINISH
               for e in reversed(events))
