"""Training numerics plane: per-layer gradient/update statistics.

The StepGuard reduces a whole step to one fused ok-scalar — a trip says
"something went non-finite" with no idea *which layer*, and the slow
failure modes that precede NaNs (exploding grad norms, vanishing
update-to-weight ratios, parameter drift) produce no signal at all.
This module is the host side of the per-layer numerics capture:

* **In-graph stats** — when a :class:`NumericsMonitor` is attached
  (``Executor(..., numerics=mon)`` or ``mon.attach(executor)``), the
  executor's jitted step emits ONE fused ``[n_layers, 3]`` float32
  array of per-layer sums of squares — gradient, update delta
  (attempted, pre-skip-select), and parameter — riding as a hidden
  trailing output exactly like the guard sentinel.  Each reduce fuses
  with the update computation that produced the tensor; layers are
  keyed by the same canonical name scopes
  :func:`~hetu_tpu.telemetry.profiling.layer_of` uses, so numerics
  rows line up with the PR 10 cost/memory attribution.
* **Deferred host reads** — like the guard, the monitor holds the
  device array and materializes on the ``check_interval`` cadence
  (one step late under ``defer``), so the step path stays sync-free.
  ``run_steps`` carries an exact per-inner-step non-finite count per
  layer through its fori_loop, mirroring ``inner_trips``.
* **Anomaly detection** — per-layer EWMAs with z-scores:
  ``spike`` (grad-norm z above ``z_threshold``), ``vanish`` (grad norm
  collapsed below ``vanish_factor x`` its EWMA), ``drift`` (param norm
  wandered more than ``drift_tolerance`` relative to its EWMA), and
  ``nonfinite`` (the layer's stats row is not finite).  Derived
  update-to-weight ratios ride along (the classic LR-sanity signal).
* **Culprit attribution** — :meth:`culprit` names the first-non-finite
  and largest-z layers; ``StepGuard._trip`` calls it so every
  ``guard_trip`` incident dump and :class:`GuardTripped` carries the
  layer that actually went bad.
* **Escalation** — with ``escalate_after=k``, a layer anomalous for
  ``k`` consecutive processed steps escalates into the guard's
  skip/rollback/abort policy *before* the NaN ever lands.

Everything is disabled-by-default: an executor without a monitor
traces zero extra ops, and the monitor's instruments are the usual
~100 ns no-ops until :func:`hetu_tpu.telemetry.enable`.
"""

from __future__ import annotations

import collections
import math
import threading
import time
import weakref

import numpy as np

from .registry import JsonlWriter

ANOMALY_KINDS = ("spike", "vanish", "drift", "nonfinite")

# live monitors, for telemetry.report()["numerics"] and /numerics
_LIVE = weakref.WeakSet()


def numerics_report():
    """Every live monitor's report block, keyed by monitor name (the
    ``/numerics`` debug payload and ``telemetry.report()["numerics"]``)."""
    return {m.name: m.report() for m in list(_LIVE)}


class NumericsMonitor:
    """Host-side consumer of the fused per-layer stats vector.

    Attach with ``Executor(..., numerics=mon)`` or ``mon.attach(ex)``
    (the latter invalidates compiled step programs so the stats get
    traced in).  The executor calls :meth:`on_step` with the DEVICE
    array; materialization is deferred per ``defer``/``check_interval``.
    """

    def __init__(self, name="train", check_interval=1, defer=True,
                 sample_every=1, ema_decay=0.9, z_threshold=6.0,
                 vanish_factor=1e-3, drift_tolerance=0.25, warmup=5,
                 history_path=None, history_cap=256, guard=None,
                 escalate_after=None, registry=None):
        self.name = str(name)
        self.check_interval = max(1, int(check_interval))
        self.defer = bool(defer)
        # in-graph sampling cadence: the stats row is COMPUTED only on
        # steps where global_step % sample_every == 0 (a lax.cond skips
        # the reductions entirely on the other steps).  1 = every step:
        # exact per-step non-finite attribution, at ~3 extra memory
        # passes over params/grads per step — cheap on TPU where the
        # reduces fuse into the update fusion, material on CPU.
        # Production loops that want trend monitoring at ~zero cost
        # sample (e.g. 256, the bench twin's pinned config); forensics
        # and the exactness tests use 1.
        # Changing it after attach requires re-attach (the compiled
        # step bakes the cadence in).
        self.sample_every = max(1, int(sample_every))
        self.ema_decay = float(ema_decay)
        self.z_threshold = float(z_threshold)
        self.vanish_factor = float(vanish_factor)
        self.drift_tolerance = float(drift_tolerance)
        self.warmup = int(warmup)
        self.history_path = history_path
        self.history_cap = int(history_cap)
        self.guard = guard
        self.escalate_after = (None if escalate_after is None
                               else max(1, int(escalate_after)))
        # (layers, step, stats_arr, n, inner_nf_arr_or_None)
        self._pending = collections.deque()
        self._epoch = time.perf_counter()
        self._lock = threading.Lock()
        self._executor = None
        self._writer = None
        self._in_culprit = False
        self._last_nonfinite = None   # (step, [layers in row order])
        self._last_step = None
        self.layers = {}              # layer -> per-layer state dict
        self.history = collections.deque(maxlen=self.history_cap)
        self.stats = {"steps": 0, "processed": 0, "anomalies": 0,
                      "nonfinite_rows": 0, "escalations": 0}
        if registry is None:
            from . import get_registry
            registry = get_registry()
        reg = registry

        def _m(kind, name, help, labels):
            return getattr(reg, kind)(name, help, labels=labels)

        self._g_grad = _m(
            "gauge", "hetu_numerics_grad_norm",
            "Latest per-layer gradient L2 norm", ("monitor", "layer"))
        self._g_update = _m(
            "gauge", "hetu_numerics_update_norm",
            "Latest per-layer parameter-update L2 norm (attempted "
            "update, pre-skip-select)", ("monitor", "layer"))
        self._g_param = _m(
            "gauge", "hetu_numerics_param_norm",
            "Latest per-layer parameter L2 norm", ("monitor", "layer"))
        self._g_ratio = _m(
            "gauge", "hetu_numerics_update_ratio",
            "Latest per-layer update-to-weight L2 ratio",
            ("monitor", "layer"))
        self._m_steps = _m(
            "counter", "hetu_numerics_steps_total",
            "Training steps whose per-layer numerics were processed "
            "(run_steps inner steps included)", ("monitor",)
        ).labels(monitor=self.name)
        self._m_anom = _m(
            "counter", "hetu_numerics_anomalies_total",
            "Per-layer numerics anomalies by kind "
            "(spike/vanish/drift/nonfinite)", ("monitor", "layer", "kind"))
        self._m_nonfinite = _m(
            "counter", "hetu_numerics_nonfinite_total",
            "Steps on which a layer's stats row was non-finite (exact "
            "across run_steps inner steps)", ("monitor", "layer"))
        self._m_escalations = _m(
            "counter", "hetu_numerics_escalations_total",
            "Sustained anomalies escalated into the StepGuard policy",
            ("monitor",)).labels(monitor=self.name)
        _LIVE.add(self)

    # -- wiring ------------------------------------------------------------
    def attach(self, executor):
        """Install on an already-built executor: compiled step programs
        are invalidated so the next run traces the stats vector in."""
        executor.config["numerics"] = self
        self._executor = executor
        for sub in executor.subexecutor.values():
            if hasattr(sub, "_jitted"):
                sub._jitted = None
            if hasattr(sub, "_multi_jitted"):
                sub._multi_jitted = None
        return self

    def detach(self, executor):
        """Remove the monitor (and the stats vector from the step)."""
        self.flush()
        executor.config.pop("numerics", None)
        for sub in executor.subexecutor.values():
            if hasattr(sub, "_jitted"):
                sub._jitted = None
            if hasattr(sub, "_multi_jitted"):
                sub._multi_jitted = None
        return self

    # -- per-step hook (called by SubExecutor) -----------------------------
    def on_step(self, executor, layers, step, stats_arr, n=1,
                inner_nf=None):
        """Receive one step's DEVICE stats array (``[n_layers, 3]``
        sums of squares: grad, update, param).  No host sync happens
        here — the array is queued and materialized on the
        ``check_interval`` cadence (one step late under ``defer``, by
        which time the buffer is ready and the read is a fetch, not a
        sync).  ``inner_nf``: run_steps' carried per-layer non-finite
        step count (device ``[n_layers]`` int32)."""
        self._executor = executor
        self._pending.append((tuple(layers), step, stats_arr, n,
                              inner_nf))
        keep = 1 if self.defer else 0
        if len(self._pending) >= self.check_interval + keep:
            while len(self._pending) > keep:
                self._process(*self._pending.popleft())

    def flush(self):
        """Materialize and process every pending stats row (call after
        the training loop).  Returns the stats dict."""
        while self._pending:
            self._process(*self._pending.popleft())
        return self.stats

    @property
    def pending_count(self):
        return len(self._pending)

    # -- internals ---------------------------------------------------------
    def _layer_state(self, layer):
        st = self.layers.get(layer)
        if st is None:
            st = {"grad": None, "update": None, "param": None,
                  "ratio": None, "z": None, "steps": 0,
                  "ema_grad": None, "var_grad": None, "ema_param": None,
                  "nonfinite_steps": 0, "anomaly_streak": 0,
                  "anomalies": {k: 0 for k in ANOMALY_KINDS},
                  # cached label children: .labels() re-resolution per
                  # step is the hot path's dominant host cost
                  "_h": (self._g_grad.labels(monitor=self.name,
                                             layer=layer),
                         self._g_update.labels(monitor=self.name,
                                               layer=layer),
                         self._g_param.labels(monitor=self.name,
                                              layer=layer),
                         self._g_ratio.labels(monitor=self.name,
                                              layer=layer),
                         self._m_nonfinite.labels(monitor=self.name,
                                                  layer=layer))}
            self.layers[layer] = st
        return st

    def _process(self, layers, step, stats_arr, n, inner_nf):
        rows = np.asarray(stats_arr, dtype=np.float64).tolist()
        nf = (None if inner_nf is None
              else np.asarray(inner_nf, dtype=np.int64).tolist())
        self.stats["steps"] += int(n)
        self.stats["processed"] += 1
        self._m_steps.inc(int(n))
        self._last_step = int(step)
        row_nonfinite = []
        hist_row = {}
        eps = 1e-12
        isfinite, sqrt = math.isfinite, math.sqrt
        for i, layer in enumerate(layers):
            st = self._layer_state(layer)
            st["steps"] += int(n)
            gsq, usq, psq = rows[i]
            gf, uf, pf = isfinite(gsq), isfinite(usq), isfinite(psq)
            finite = gf and uf and pf
            # norms from the fused sums of squares; NaN propagates so a
            # poisoned layer shows non-finite norms, not garbage
            g = sqrt(gsq) if gf and gsq > 0.0 else (
                0.0 if gf else float("nan"))
            u = sqrt(usq) if uf and usq > 0.0 else (
                0.0 if uf else float("nan"))
            p = sqrt(psq) if pf and psq > 0.0 else (
                0.0 if pf else float("nan"))
            ratio = (u / (p + eps)) if finite else float("nan")
            st["grad"], st["update"] = g, u
            st["param"], st["ratio"] = p, ratio
            nf_steps = (int(nf[i]) if nf is not None
                        else (0 if finite else 1))
            kinds = ()
            if nf_steps or not finite:
                st["nonfinite_steps"] += max(nf_steps, 1)
                st["_h"][4].inc(max(nf_steps, 1))
                row_nonfinite.append(layer)
                kinds = ("nonfinite",)
                st["z"] = None
            else:
                warm = st["steps"] > self.warmup
                ema, var = st["ema_grad"], st["var_grad"]
                if ema is None:
                    st["ema_grad"], st["var_grad"] = g, 0.0
                    st["z"] = 0.0
                else:
                    z = (g - ema) / (sqrt(max(var, 0.0)) + eps)
                    st["z"] = z
                    if warm and abs(z) > self.z_threshold and g > ema:
                        kinds += ("spike",)
                    if warm and g < self.vanish_factor * ema:
                        kinds += ("vanish",)
                    d = self.ema_decay
                    st["ema_grad"] = d * ema + (1.0 - d) * g
                    st["var_grad"] = (d * var
                                      + (1.0 - d) * (g - ema) ** 2)
                pema = st["ema_param"]
                if pema is None:
                    st["ema_param"] = p
                else:
                    if (warm and abs(p - pema)
                            > self.drift_tolerance * (abs(pema) + eps)):
                        kinds += ("drift",)
                    d = self.ema_decay
                    st["ema_param"] = d * pema + (1.0 - d) * p
                h = st["_h"]
                h[0].set(g), h[1].set(u), h[2].set(p), h[3].set(ratio)
            for k in kinds:
                st["anomalies"][k] += 1
                self.stats["anomalies"] += 1
                self._m_anom.labels(monitor=self.name, layer=layer,
                                    kind=k).inc()
            st["anomaly_streak"] = (st["anomaly_streak"] + 1 if kinds
                                    else 0)
            hist_row[layer] = {"grad": g, "update": u, "param": p,
                               "ratio": ratio, "z": st["z"],
                               "finite": finite,
                               "anomalies": list(kinds)}
        if row_nonfinite:
            self.stats["nonfinite_rows"] += 1
            self._last_nonfinite = (int(step), row_nonfinite)
        entry = {"step": int(step), "n": int(n), "layers": hist_row}
        self.history.append(entry)
        self._write_history(entry)
        self._maybe_escalate(step)

    def _write_history(self, entry):
        if self.history_path is None:
            return
        with self._lock:
            if self._writer is None:
                self._writer = JsonlWriter(self.history_path)
        # monotonic seconds since monitor creation (the flight
        # recorder's idiom) — wall-clock time.time() is gated out
        self._writer.write(dict(
            entry, t=round(time.perf_counter() - self._epoch, 6),
            monitor=self.name))

    def close(self):
        """Flush pending rows and close the JSONL history file."""
        self.flush()
        with self._lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None

    def _maybe_escalate(self, step):
        if self.escalate_after is None or self._in_culprit:
            return
        guard = self.guard
        if guard is None and self._executor is not None:
            guard = self._executor.config.get("step_guard")
        if guard is None:
            return
        for layer, st in self.layers.items():
            if st["anomaly_streak"] >= self.escalate_after:
                kinds = [k for k, c in st["anomalies"].items() if c]
                st["anomaly_streak"] = 0   # one escalation per streak
                self.stats["escalations"] += 1
                self._m_escalations.inc()
                guard._trip(
                    f"numerics escalation: layer '{layer}' anomalous "
                    f"({'/'.join(kinds)}) for {self.escalate_after} "
                    "consecutive checks", step, None)
                return   # a trip may have restored state; re-evaluate

    # -- attribution / reporting ------------------------------------------
    def culprit(self, step=None):
        """Layer attribution for a trip at ``step`` (or now): drains
        pending stats (the trip path is already synchronous), then
        names the first-non-finite layer of the most recent poisoned
        row and the largest-|z| layer overall.  Reentrancy-safe against
        the guard calling back in during an escalation trip."""
        self._in_culprit = True
        try:
            self.flush()
        finally:
            self._in_culprit = False
        first_nf, nf_layers = None, []
        if self._last_nonfinite is not None:
            nf_step, nf_layers = self._last_nonfinite
            first_nf = nf_layers[0]
        best, best_z = None, 0.0
        for layer, st in self.layers.items():
            z = st.get("z")
            if z is not None and np.isfinite(z) and abs(z) > abs(best_z):
                best, best_z = layer, float(z)
        return {"step": (int(step) if step is not None
                         else self._last_step),
                "first_nonfinite": first_nf,
                "nonfinite_layers": list(nf_layers),
                "largest_z": best,
                "z": (best_z if best is not None else None)}

    def report(self):
        """The ``/numerics`` block for this monitor: per-layer latest
        norms/ratios/z + anomaly counts, and the monitor totals."""
        return {
            "layers": {
                layer: {"grad_norm": st["grad"],
                        "update_norm": st["update"],
                        "param_norm": st["param"],
                        "update_ratio": st["ratio"],
                        "z": st["z"], "steps": st["steps"],
                        "nonfinite_steps": st["nonfinite_steps"],
                        "anomalies": dict(st["anomalies"])}
                for layer, st in self.layers.items()},
            "steps": self.stats["steps"],
            "processed": self.stats["processed"],
            "pending": len(self._pending),
            "anomalies": self.stats["anomalies"],
            "nonfinite_rows": self.stats["nonfinite_rows"],
            "escalations": self.stats["escalations"],
            "check_interval": self.check_interval,
            "sample_every": self.sample_every,
            "history_path": (str(self.history_path)
                             if self.history_path else None)}
