"""Per-program performance introspection + process-wide HBM accounting.

Two layers, both exported through the ``hetu_tpu.telemetry`` singletons:

**ProgramProfiler** — for every compiled program the system owns (the
executor train step, the serving prefill/decode pair, the embedding
scoring program) :meth:`~ProgramProfiler.capture` pulls XLA's
``cost_analysis`` / ``memory_analysis`` through the version-compat
helpers in :mod:`~hetu_tpu.platform`, attributes flops/bytes to graph
ops and model layers via name scopes (:func:`attribute_graph`), and
:meth:`~ProgramProfiler.observe` folds in measured step counts to
produce the derived signals of :mod:`~hetu_tpu.telemetry.perf_model`:
per-step MFU / roofline position, achieved vs peak bytes, serving
tokens/s-per-chip.  Exported three ways: ``hetu_profile_*`` registry
gauges, the ``profile`` block in ``telemetry.report()``, and the
``/profile`` debug endpoint on the metrics HTTP server.

**HbmLedger** — a live-buffer ledger over the device pools the system
allocates (``hetu_hbm_bytes{pool=params|opt_state|kv_cache|hot_cache|
workspace}``), fed by the SlotKVCache, the DeviceHotRowCache, and the
executor's param/opt-state allocations.  Pool totals equal the sum of
live tracked buffers by construction; ``allocs == frees`` after every
owner's ``close()`` (pinned by tests/test_profiling.py).  The snapshot
rides along in every FlightRecorder incident dump so OOM-adjacent
incidents carry memory forensics.

Disabled-mode contract: like every PR 4 instrument, the per-call paths
here are ~100 ns no-ops while telemetry is off (ledger alloc/free are
allocation-TIME bookkeeping — dict writes, never in a step loop — and
gauge mirrors go through the registry's no-op instruments).  Captures
are explicit, pull-based analysis: nothing here touches the executor /
engine hot paths.
"""

from __future__ import annotations

import threading

from . import perf_model

__all__ = ["HBM_POOLS", "HbmLedger", "ProgramProfiler",
           "attribute_graph", "layer_of"]

#: the device pools the ledger accounts (the hetu_hbm_bytes label set)
HBM_POOLS = ("params", "opt_state", "kv_cache", "hot_cache", "workspace")


class _HbmBuffer:
    """Handle for one tracked allocation; ``free()`` is idempotent."""

    __slots__ = ("pool", "owner", "nbytes", "_ledger", "live")

    def __init__(self, ledger, pool, owner, nbytes):
        self._ledger = ledger
        self.pool = pool
        self.owner = owner
        self.nbytes = int(nbytes)
        self.live = True

    def free(self):
        self._ledger._free(self)

    def close(self):
        self.free()


class HbmLedger:
    """Live-buffer ledger: who holds how many device bytes, by pool.

    Tracking is unconditional (allocation-time bookkeeping, not a hot
    path); the ``hetu_hbm_bytes{pool=}`` gauge mirror goes through the
    registry, so it costs the usual ~100 ns no-op while telemetry is
    disabled and appears in snapshots/scrapes once enabled."""

    def __init__(self, registry=None):
        self._registry = registry
        self._lock = threading.Lock()
        self._buffers = []          # live _HbmBuffer handles
        self.alloc_count = 0
        self.free_count = 0
        self._m_bytes = None

    def _gauge(self):
        reg = self._registry
        if reg is None:
            return None
        if self._m_bytes is None:
            self._m_bytes = reg.gauge(
                "hetu_hbm_bytes",
                "Live tracked device bytes, by allocation pool",
                labels=("pool",))
        return self._m_bytes

    def _sync_pool(self, pool):
        g = self._gauge()
        if g is not None:
            g.labels(pool=pool).set(self.live_bytes(pool))

    # -- tracking ----------------------------------------------------------
    def alloc(self, pool, nbytes, owner=None):
        """Track one live device allocation; returns the free() handle.
        Unknown pools raise — the label set is the documented contract.
        """
        if pool not in HBM_POOLS:
            raise ValueError(f"unknown HBM pool {pool!r}; "
                             f"one of {HBM_POOLS}")
        buf = _HbmBuffer(self, pool, owner, nbytes)
        with self._lock:
            self._buffers.append(buf)
            self.alloc_count += 1
        self._sync_pool(pool)
        return buf

    def _free(self, buf):
        with self._lock:
            if not buf.live:
                return
            buf.live = False
            self._buffers.remove(buf)
            self.free_count += 1
        self._sync_pool(buf.pool)

    def replace(self, handle, pool, nbytes, owner=None):
        """alloc() that first frees ``handle`` (None ok) — for owners
        that re-measure one logical buffer (workspace re-captures)."""
        if handle is not None:
            handle.free()
        return self.alloc(pool, nbytes, owner=owner)

    # -- views -------------------------------------------------------------
    def live_bytes(self, pool=None):
        with self._lock:
            return sum(b.nbytes for b in self._buffers
                       if pool is None or b.pool == pool)

    def live_buffers(self, pool=None):
        with self._lock:
            return [{"pool": b.pool, "owner": b.owner,
                     "nbytes": b.nbytes}
                    for b in self._buffers
                    if pool is None or b.pool == pool]

    def snapshot(self):
        """JSON-safe ledger state: per-pool totals (every pool present,
        0 when empty), the live buffer list, and the alloc/free balance.
        Pool totals equal the sum of the listed buffers by construction.
        """
        bufs = self.live_buffers()
        pools = {p: 0 for p in HBM_POOLS}
        for b in bufs:
            pools[b["pool"]] += b["nbytes"]
        with self._lock:
            allocs, frees = self.alloc_count, self.free_count
        for p in pools:
            self._sync_pool(p)
        return {"pools": pools,
                "total_bytes": sum(pools.values()),
                "buffers": bufs,
                "allocs": allocs,
                "frees": frees,
                "live": allocs - frees}

    def clear(self):
        with self._lock:
            for b in self._buffers:
                b.live = False
            self._buffers = []
            self.alloc_count = 0
            self.free_count = 0


# ---------------------------------------------------------------------------
# per-op / per-layer attribution


#: parameter-name suffixes that identify which LAYER a variable belongs
#: to (wdl_deep0_weight and wdl_deep0_bias are both layer "wdl_deep0")
_PARAM_SUFFIXES = ("_weight", "_bias", "_kernel", "_gamma", "_beta",
                   "_scale", "_wte", "_wpe")


def layer_of(var_name):
    """Layer key of one variable name: the name-scope prefix left after
    stripping the parameter-role suffix (``wdl_deep0_weight`` ->
    ``wdl_deep0``; a suffix-less table like ``wdl_emb`` is its own
    layer)."""
    name = str(var_name)
    for suf in _PARAM_SUFFIXES:
        if name.endswith(suf) and len(name) > len(suf):
            return name[:-len(suf)]
    return name


def attribute_graph(eval_nodes, feed_shapes=None, totals=None):
    """Per-layer flops/bytes attribution for one op graph.

    Per-op costs come from the analytic estimators in
    :mod:`~hetu_tpu.profiler` (shape inference via ``jax.eval_shape``);
    each compute op is attributed to the layer of its parameter inputs
    (variables carry the name-scope-stable layer names), ops without a
    parameter input inherit their first attributed producer, and
    anything else lands in ``(unattributed)``.  When ``totals`` (the
    XLA cost dict of the COMPILED program) is given, per-layer estimates
    are scaled so they sum to XLA's whole-program flops/bytes — the
    estimators give the split, XLA gives the magnitude (it has already
    fused across op boundaries, so per-op truth doesn't exist post-
    compile).

    Returns rows sorted by flops share:
    ``{"layer", "ops", "flops", "bytes", "flops_frac", "flops_est",
    "bytes_est"}``.
    """
    from ..graph.node import PlaceholderOp, VariableOp, find_topo_sort
    from ..profiler import estimate_flops, shape_map, tensor_bytes

    eval_nodes = list(eval_nodes)
    shapes = shape_map(eval_nodes, feed_shapes)
    topo = find_topo_sort(eval_nodes)
    layer = {}
    for node in topo:
        if isinstance(node, VariableOp):
            layer[node] = layer_of(node.name)
        elif isinstance(node, PlaceholderOp):
            layer[node] = None
        else:
            found = None
            for inp in node.inputs:         # params define the layer
                if isinstance(inp, VariableOp):
                    found = layer.get(inp)
                    break
            if found is None:
                for inp in node.inputs:     # else inherit the producer
                    if layer.get(inp):
                        found = layer[inp]
                        break
            layer[node] = found
    groups = {}
    for node in topo:
        if isinstance(node, (PlaceholderOp, VariableOp)):
            continue
        flops = float(estimate_flops(node, shapes))
        nbytes = float(sum(tensor_bytes(shapes.get(i))
                           for i in node.inputs)
                       + tensor_bytes(shapes.get(node)))
        key = layer.get(node) or "(unattributed)"
        row = groups.setdefault(key, {"layer": key, "ops": 0,
                                      "flops_est": 0.0, "bytes_est": 0.0})
        row["ops"] += 1
        row["flops_est"] += flops
        row["bytes_est"] += nbytes
    est_f = sum(r["flops_est"] for r in groups.values())
    est_b = sum(r["bytes_est"] for r in groups.values())
    tot_f = float((totals or {}).get("flops", 0.0) or 0.0)
    tot_b = float((totals or {}).get("bytes accessed", 0.0) or 0.0)
    scale_f = (tot_f / est_f) if tot_f > 0 and est_f > 0 else 1.0
    scale_b = (tot_b / est_b) if tot_b > 0 and est_b > 0 else 1.0
    rows = []
    for r in groups.values():
        rows.append({"layer": r["layer"], "ops": r["ops"],
                     "flops": round(r["flops_est"] * scale_f, 2),
                     "bytes": round(r["bytes_est"] * scale_b, 2),
                     "flops_frac": round(r["flops_est"] / est_f, 6)
                     if est_f > 0 else 0.0,
                     "flops_est": round(r["flops_est"], 2),
                     "bytes_est": round(r["bytes_est"], 2)})
    rows.sort(key=lambda r: -r["flops"])
    return rows


# ---------------------------------------------------------------------------
# the profiler


class ProgramProfiler:
    """Registry of :meth:`capture`-d program profiles + derived signals.

    Pull-based: owners (bench ``--profile``, tests, a notebook) capture
    explicitly; nothing instruments the step hot paths.  All state is
    JSON-safe and served live via ``telemetry.report()["profile"]`` and
    the ``/profile`` debug endpoint."""

    def __init__(self, registry=None, ledger=None):
        self._registry = registry
        self._ledger = ledger
        self._lock = threading.Lock()
        self._profiles = {}             # name -> profile dict
        self._workspace = {}            # name -> ledger handle
        self._peaks = None
        self._m = None
        self.cache_hits = 0             # signature-cache short-circuits

    def _metrics(self):
        """The hetu_profile_* instrument set (lazy; None w/o registry).
        Plain-name wrapper calls keep these visible to the METRICS.md
        drift gate's AST scanner."""
        reg = self._registry
        if reg is None:
            return None
        if self._m is None:
            def _m(kind, name, help, labels):
                return getattr(reg, kind)(name, help, labels=labels)

            self._m = {
                "captures": _m(
                    "counter", "hetu_profile_captures_total",
                    "Program profiles captured, by program kind",
                    ("kind",)),
                "flops": _m(
                    "gauge", "hetu_profile_flops_per_step",
                    "XLA cost-model flops per execution of the "
                    "profiled program", ("program",)),
                "bytes": _m(
                    "gauge", "hetu_profile_bytes_per_step",
                    "XLA cost-model HBM bytes accessed per execution "
                    "of the profiled program", ("program",)),
                "mfu": _m(
                    "gauge", "hetu_profile_mfu",
                    "Model flops utilization of the profiled program "
                    "(achieved / peak flops)", ("program",)),
                "ai": _m(
                    "gauge", "hetu_profile_arithmetic_intensity",
                    "Flops per HBM byte accessed (roofline x-axis)",
                    ("program",)),
                "items": _m(
                    "gauge", "hetu_profile_items_per_sec_per_chip",
                    "Serving throughput of the profiled program "
                    "(tokens/rows per second per chip)", ("program",)),
            }
        return self._m

    def peaks(self):
        """The chip peak table (cached after first sniff)."""
        if self._peaks is None:
            self._peaks = perf_model.chip_peaks()
        return self._peaks

    # -- capture -----------------------------------------------------------
    def capture(self, name, compiled=None, *, kind="program", cost=None,
                memory=None, eval_nodes=None, feed_shapes=None,
                signature=None):
        """Profile one compiled program.

        ``compiled`` is an XLA compiled object (``jitted.lower(...)
        .compile()``, ``SubExecutor.lower_compiled()``) analyzed through
        the :mod:`~hetu_tpu.platform` compat helpers; pre-normalized
        ``cost``/``memory`` dicts may be passed instead (tests, remote
        rounds).  ``eval_nodes`` (+ optional ``feed_shapes``) adds the
        per-layer attribution table.  Re-capturing a name replaces its
        profile (and its workspace ledger entry).

        ``signature=`` keys a capture CACHE: when the stored profile
        for ``name`` carries the same signature the stored profile is
        returned as-is (``cache_hits`` counts them) and ``compiled`` is
        never analyzed — pass a zero-arg factory as ``compiled`` to
        defer even BUILDING the program (an engine's AOT re-lower) to
        the cache-miss path.  That is what keeps continuous profiling
        under the SLO controller retrace-flat.  A changed or absent
        signature replaces the profile as before."""
        from ..platform import (compiled_cost_analysis,
                                compiled_memory_analysis)
        if signature is not None:
            with self._lock:
                prev = self._profiles.get(str(name))
            if prev is not None and prev.get("signature") == signature:
                self.cache_hits += 1
                return prev
        if compiled is not None and callable(compiled) \
                and not hasattr(compiled, "cost_analysis"):
            compiled = compiled()   # deferred build: cache missed
        if compiled is not None:
            cost = compiled_cost_analysis(compiled) if cost is None \
                else cost
            memory = compiled_memory_analysis(compiled) if memory is None \
                else memory
        cost = dict(cost or {})
        memory = dict(memory or {})
        layers = (attribute_graph(eval_nodes, feed_shapes, totals=cost)
                  if eval_nodes is not None else None)
        profile = {"name": str(name), "kind": str(kind),
                   "cost": {k: cost[k] for k in
                            ("flops", "bytes accessed", "transcendentals")
                            if k in cost},
                   "memory": memory,
                   "layers": layers,
                   "signature": signature,
                   "derived": perf_model.derive(cost, peaks=self.peaks())}
        with self._lock:
            self._profiles[str(name)] = profile
        temp = int(memory.get("temp_size_in_bytes", 0) or 0)
        if self._ledger is not None:
            self._workspace[str(name)] = self._ledger.replace(
                self._workspace.get(str(name)), "workspace", temp,
                owner=f"program:{name}")
        m = self._metrics()
        if m is not None:
            m["captures"].labels(kind=str(kind)).inc()
            m["flops"].labels(program=str(name)).set(
                float(cost.get("flops", 0.0) or 0.0))
            m["bytes"].labels(program=str(name)).set(
                float(cost.get("bytes accessed", 0.0) or 0.0))
        return profile

    def observe(self, name, steps=None, elapsed_s=None, tokens=None,
                items_name="tokens", n_chips=1):
        """Fold a MEASURED execution window into ``name``'s profile:
        ``steps`` executions over ``elapsed_s`` seconds (plus an
        optional item count for serving throughput) turn the static
        cost into MFU / roofline / achieved-bytes signals."""
        with self._lock:
            profile = self._profiles.get(str(name))
        if profile is None:
            raise KeyError(f"no captured profile named {name!r}")
        cost = dict(profile["cost"])
        profile["derived"] = perf_model.derive(
            cost, steps=steps, elapsed_s=elapsed_s, peaks=self.peaks(),
            n_chips=n_chips, tokens=tokens, items_name=items_name)
        d = profile["derived"]
        m = self._metrics()
        if m is not None:
            if "mfu" in d:
                m["mfu"].labels(program=str(name)).set(d["mfu"])
            ai = d["roofline"].get("arithmetic_intensity")
            if ai is not None:
                m["ai"].labels(program=str(name)).set(ai)
            key = f"{items_name}_per_sec_per_chip"
            if key in d:
                m["items"].labels(program=str(name)).set(d[key])
        return profile

    def attach_trace(self, name, trace_dir):
        """Attach MEASURED per-op device aggregates from a
        ``jax.profiler.trace`` capture dir to ``name``'s profile.  Uses
        :func:`~hetu_tpu.timeline.trace_aggregates` — on captures with
        a device-plane "XLA Ops" lane only those ops aggregate, so the
        measured table matches the cost model's device-side scope."""
        from ..timeline import trace_aggregates
        agg = trace_aggregates(trace_dir)
        with self._lock:
            profile = self._profiles.get(str(name))
        if profile is None:
            raise KeyError(f"no captured profile named {name!r}")
        profile["measured_ops"] = agg
        return agg

    # -- views -------------------------------------------------------------
    def profile(self, name):
        with self._lock:
            return self._profiles.get(str(name))

    def profiles(self):
        with self._lock:
            return dict(self._profiles)

    def layer_table(self):
        """The per-layer cost table across every captured program:
        ``{"program", "layer", "flops", "bytes", "flops_frac", "ops"}``
        rows, heaviest first."""
        rows = []
        with self._lock:
            profs = list(self._profiles.values())
        for p in profs:
            for r in (p["layers"] or ()):
                rows.append({"program": p["name"], "layer": r["layer"],
                             "flops": r["flops"], "bytes": r["bytes"],
                             "flops_frac": r["flops_frac"],
                             "ops": r["ops"]})
        rows.sort(key=lambda r: -r["flops"])
        return rows

    def calibration(self, name):
        """Measured per-layer cost evidence for the auto-parallel
        planner (``hetu_tpu/planner/calibrate.py``): the
        :meth:`observe`-d window's measured step time attributed over
        the program's layers by XLA flops fraction.  Requires a capture
        with ``eval_nodes=`` (the attribution table) and a measured
        window (``steps_per_sec``); rows are ``{"layer", "ms", "flops",
        "bytes", "flops_frac"}``, heaviest first."""
        p = self.profile(name)
        if p is None:
            raise KeyError(f"no captured profile named {name!r}")
        layers = p.get("layers")
        if not layers:
            raise ValueError(
                f"profile {name!r} has no layer attribution — capture "
                f"with eval_nodes=")
        sps = (p.get("derived") or {}).get("steps_per_sec")
        if not sps:
            raise ValueError(
                f"profile {name!r} has no measured window — observe() "
                f"it first")
        step_ms = 1e3 / float(sps)
        return [{"layer": r["layer"],
                 "ms": round(step_ms * r["flops_frac"], 6),
                 "flops": r["flops"], "bytes": r["bytes"],
                 "flops_frac": r["flops_frac"]} for r in layers]

    def report_block(self):
        """The ``profile`` block of ``telemetry.report()`` (also the
        ``/profile`` debug endpoint): every program's cost/memory/
        derived signals, the cross-program layer table, the chip peaks,
        and the HBM ledger snapshot."""
        with self._lock:
            programs = {n: dict(p) for n, p in self._profiles.items()}
        out = {"programs": programs,
               "layer_table": self.layer_table(),
               "peaks": self.peaks() if programs else None}
        if self._ledger is not None:
            out["hbm"] = self._ledger.snapshot()
        return out

    def clear(self):
        with self._lock:
            self._profiles = {}
            workspace, self._workspace = self._workspace, {}
        for handle in workspace.values():
            handle.free()
        self._peaks = None
