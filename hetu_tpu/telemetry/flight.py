"""Flight recorder: a crash black box for the serving stack.

A bounded process-wide ring holds the most recent request lifecycle
events (fed by :class:`~.request_trace.RequestTrace`) plus replica
health transitions.  On any TRIP — GuardTripped, a watchdog
quarantine, an engine crash or wedge, a circuit-breaker open,
FleetUnavailable, PSUnavailable — :meth:`incident` dumps what a
post-mortem needs while it is still true:

* the full registry snapshot at the moment of the trip,
* the last-N ring events (what the process was doing just before),
* per-replica health states (when the tripping layer knows them),
* the tripping rid's complete timeline (when a rid is implicated).

Dumps go through the shared :class:`~.registry.JsonlWriter` path, one
NEW file per incident under the no-clobber contract (an existing path
is never overwritten — the sequence number advances past it), and every
trip counts in ``hetu_incidents_total{kind=}``.  With no incident
directory configured the dump is kept in the in-memory index only —
tests and the ``/incidents`` endpoint read the index either way.

Like every PR 4 instrument this is disabled by default: ``record()``
and ``incident()`` are one flag check while disabled, so the trip
paths (guard, fleet, RPC client) carry their hooks unconditionally.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from .registry import JsonlWriter

__all__ = ["FlightRecorder", "INCIDENT_KINDS"]

#: every trip kind a dump can carry (documented in docs/INCIDENTS.md)
INCIDENT_KINDS = ("guard_trip", "watchdog", "engine_crash",
                  "engine_wedge", "breaker_open", "fleet_unavailable",
                  "ps_unavailable", "slo_scale", "slo_degrade",
                  "migrate_failed", "elastic_reshard", "alert")


class FlightRecorder:
    """Bounded ring of recent events + incident dumps (see module doc).

    ``registry=`` supplies the :class:`~.registry.MetricsRegistry` the
    ``hetu_incidents_total`` counter and dump snapshots come from
    (``hetu_tpu.telemetry`` wires the process-wide one)."""

    def __init__(self, capacity=2048, registry=None, enabled=False):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._registry = registry
        self._lock = threading.Lock()
        self._ring = deque(maxlen=self.capacity)
        self._epoch = time.perf_counter()
        self.recorded = 0           # events ever recorded
        self.incident_dir = None    # None: index-only (no files)
        self._seq = 0
        self._incidents = []        # index entries, oldest first
        self._request_trace = None  # wired by telemetry.enable()
        self._hbm = None            # callable -> HBM ledger snapshot
        self._pages = {}            # label -> callable -> page-pool occupancy
        self._m_incidents = None

    # -- configuration -----------------------------------------------------
    def configure(self, incident_dir=None, request_trace=None, hbm=None):
        """Set (or clear) the dump directory, the RequestTrace the
        tripping rid's timeline is pulled from, and the HBM-ledger
        snapshot callable included in every dump."""
        if incident_dir is not None:
            incident_dir = str(incident_dir)
            os.makedirs(incident_dir, exist_ok=True)
        self.incident_dir = incident_dir
        if request_trace is not None:
            self._request_trace = request_trace
        if hbm is not None:
            self._hbm = hbm
        return self

    def register_pages(self, label, fn):
        """Register a page-pool occupancy callable (``kv_cache.
        PagedKVCache`` wires itself here at construction); every
        incident dump then carries its live occupancy/fragmentation
        under ``pages[label]`` — page-starved admission stalls and
        fragmentation pathologies must be visible in the post-mortem,
        not reconstructed from metrics after the fact."""
        with self._lock:
            self._pages[str(label)] = fn

    def unregister_pages(self, label):
        """Drop a page-pool provider (idempotent; pools unregister on
        close so dumps never call into a torn-down cache)."""
        with self._lock:
            self._pages.pop(str(label), None)

    @property
    def dropped(self):
        """Events that fell off the ring (total recorded - retained)."""
        return max(0, self.recorded - self.capacity)

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self.recorded = 0
            self._incidents = []
            self._seq = 0
            self._epoch = time.perf_counter()

    # -- the ring ----------------------------------------------------------
    def record(self, ev):
        """Append one event dict to the ring; no-op while disabled."""
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(ev)
            self.recorded += 1

    def ring(self):
        """Retained events, oldest first (copies)."""
        with self._lock:
            return [dict(e) for e in self._ring]

    # -- incidents ---------------------------------------------------------
    def incident(self, kind, rid=None, health=None, extra=None):
        """Dump the black box for one trip.  Returns the index entry
        (or None while disabled).  ``health`` is the tripping layer's
        per-replica health snapshot when it has one (``fleet.health()``);
        ``extra`` is any JSON-safe context (exception text, step, loss).
        """
        if not self.enabled:
            return None
        reg = self._registry
        if reg is not None:
            if self._m_incidents is None:
                self._m_incidents = reg.counter(
                    "hetu_incidents_total",
                    "Flight-recorder incident dumps, by trip kind",
                    labels=("kind",))
            self._m_incidents.labels(kind=str(kind)).inc()
        now = time.perf_counter()
        rt = self._request_trace
        with self._lock:
            pages_fns = dict(self._pages)
        dump = {"kind": str(kind),
                "t": round(now - self._epoch, 9),
                "rid": rid,
                "events": self.ring(),
                "health": health,
                "timeline": (rt.timeline(rid)
                             if rt is not None and rid is not None
                             else None),
                "registry": reg.snapshot() if reg is not None else None,
                "hbm": self._hbm() if self._hbm is not None else None,
                "pages": ({lbl: fn() for lbl, fn in pages_fns.items()}
                          if pages_fns else None),
                "extra": extra}
        with self._lock:
            self._seq += 1
            seq = self._seq
        path = self._write_dump(seq, kind, dump)
        entry = {"seq": seq, "kind": str(kind), "rid": rid,
                 "t": dump["t"], "n_events": len(dump["events"]),
                 "path": path}
        with self._lock:
            self._incidents.append(entry)
        return entry

    def _write_dump(self, seq, kind, dump):
        if self.incident_dir is None:
            return None
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in str(kind))
        while True:
            path = os.path.join(self.incident_dir,
                                f"incident-{seq:04d}-{safe}.jsonl")
            if not os.path.exists(path):
                break
            seq += 1    # no-clobber: never overwrite an existing dump
        with JsonlWriter(path) as w:
            w.write(dump)
        return path

    def incidents(self):
        """The incident index (the ``/incidents`` endpoint), oldest
        first: seq, kind, rid, t, n_events, path."""
        with self._lock:
            return [dict(e) for e in self._incidents]

    def incident_count(self, kind=None):
        with self._lock:
            if kind is None:
                return len(self._incidents)
            return sum(1 for e in self._incidents if e["kind"] == kind)

    @staticmethod
    def load_dump(path):
        """Read one incident file back (single-record JSONL)."""
        with open(path, encoding="utf-8") as f:
            return json.loads(f.readline())
