"""Host-side step-phase span tracer: ring buffer + Chrome-trace export.

``jax.profiler`` answers "what did the DEVICE do"; this tracer answers
the question three bench rounds stalled on — "where does the HOST step
time go" (``host_gap`` reported as a bare ratio since r05).  Hot paths
open named spans around their phases (prefetch ``data_wait``/
``prefetch_h2d``, executor ``h2d``/``dispatch``/``guard_check``,
serving ``serve_prefill``/``serve_decode``); each span is two
``time.perf_counter()`` reads and one slot write into a fixed ring
buffer, so steady-state tracing never allocates unboundedly and never
syncs the device.

Disabled (the default), ``span()`` hands back a shared no-op context
manager — the whole per-span cost is one flag check plus the ``with``
protocol (~a hundred ns), cheap enough to leave in the executor step
path unconditionally (pinned by the micro-benchmark in
``tests/test_telemetry.py``).

Export: ``aggregate()`` for per-phase totals (the bench's host_gap
decomposition) and ``chrome_trace()`` for chrome://tracing /
Perfetto — optionally MERGED with a ``jax.profiler.trace`` capture's
events, so host phases and XLA device ops land in one viewer.  The two
event sets keep their own clock bases by default (jax's capture epoch
is not recoverable host-side); ``align_steps=True`` makes the merged
view time-accurate by shifting the k-th host step group onto the k-th
device step's clock base (anchor span k ↔ k-th jitted-step execution).
"""

from __future__ import annotations

import json
import threading
import time

__all__ = ["SpanTracer", "NULL_SPAN"]


class _NullSpan:
    """Shared do-nothing span (disabled tracer / allocation-free)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "_t0")

    def __init__(self, tracer, name):
        self._tracer = tracer
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer._record(self.name, self._t0, t1 - self._t0)
        return False


class SpanTracer:
    """Fixed-capacity ring of (name, start_s, dur_s) host spans."""

    def __init__(self, capacity=16384, enabled=False):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._buf = [None] * self.capacity
        self._n = 0                      # total spans ever recorded
        self._epoch = time.perf_counter()

    def span(self, name):
        """Context manager timing one phase; no-op while disabled."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name)

    def _record(self, name, t0, dur):
        with self._lock:
            self._buf[self._n % self.capacity] = (name, t0, dur)
            self._n += 1

    def __len__(self):
        return min(self._n, self.capacity)

    @property
    def dropped(self):
        """Spans that fell off the ring (total recorded - retained)."""
        return max(0, self._n - self.capacity)

    def clear(self):
        with self._lock:
            self._buf = [None] * self.capacity
            self._n = 0
            self._epoch = time.perf_counter()

    def spans(self):
        """Retained spans, oldest first: [(name, start_s, dur_s)]."""
        with self._lock:
            n, cap = self._n, self.capacity
            if n <= cap:
                return [s for s in self._buf[:n]]
            i = n % cap
            return self._buf[i:] + self._buf[:i]

    def aggregate(self):
        """{name: {total_s, count, mean_s}} over the retained spans."""
        agg = {}
        for name, _, dur in self.spans():
            slot = agg.setdefault(name, [0.0, 0])
            slot[0] += dur
            slot[1] += 1
        return {name: {"total_s": t, "count": c, "mean_s": t / c}
                for name, (t, c) in sorted(agg.items())}

    # -- Chrome-trace export ----------------------------------------------
    def chrome_trace(self, jax_trace_dir=None, pid=1 << 20,
                     align_steps=False, step_span="dispatch",
                     device_step_regex=r"jit"):
        """Trace-event JSON (``{"traceEvents": [...]}``) of the retained
        spans — complete ``X`` events in microseconds relative to the
        tracer epoch, on one process lane named ``hetu host spans``.

        ``jax_trace_dir``: a ``jax.profiler.trace`` output directory
        whose newest capture's events are merged in ahead of ours, so
        one chrome://tracing load shows XLA device lanes next to the
        host phases.

        The two event sets keep separate clock bases (jax's capture
        epoch is not recoverable host-side) — UNLESS ``align_steps=True``
        maps them per step: the k-th occurrence of the ``step_span``
        host span is shifted onto the k-th device-lane event whose name
        matches ``device_step_regex`` (the jitted step executions,
        sorted by timestamp), and every other host span takes the
        offset of its step's anchor.  With that, the merged view is
        TIME-ACCURATE per step: host ``dispatch`` k starts exactly where
        device step k starts, and the surrounding phases sit on the
        same per-step clock base.  Host steps beyond the captured device
        steps reuse the last known offset."""
        spans = self.spans()
        captured_events = []
        if jax_trace_dir is not None:
            import gzip
            from ..timeline import _latest_trace_json
            captured = json.loads(
                gzip.open(_latest_trace_json(jax_trace_dir)).read())
            captured_events = list(captured.get("traceEvents", []))
        offsets = None
        if align_steps and captured_events:
            import re
            pat = re.compile(device_step_regex)
            dev = sorted(
                (e for e in captured_events
                 if e.get("ph") == "X" and "ts" in e
                 and pat.search(str(e.get("name", "")))),
                key=lambda e: e["ts"])
            anchors = [(t0 - self._epoch) * 1e6
                       for name, t0, _ in spans if name == step_span]
            if dev and anchors:
                offsets = [dev[min(k, len(dev) - 1)]["ts"] - a
                           for k, a in enumerate(anchors)]
        events = [
            {"ph": "M", "pid": pid, "name": "process_name",
             "args": {"name": "hetu host spans"}},
            {"ph": "M", "pid": pid, "tid": 0, "name": "thread_name",
             "args": {"name": "step phases"}},
        ]
        k = -1      # step anchors passed so far
        for name, t0, dur in spans:
            ts = (t0 - self._epoch) * 1e6
            ev = {"ph": "X", "pid": pid, "tid": 0, "name": name,
                  "ts": ts, "dur": dur * 1e6}
            if offsets is not None:
                if name == step_span:
                    k += 1
                step = max(0, min(k, len(offsets) - 1))
                ev["ts"] = ts + offsets[step]
                ev["args"] = {"aligned_step": step}
            events.append(ev)
        return {"traceEvents": captured_events + events,
                "displayTimeUnit": "ms"}

    def export_chrome(self, path, jax_trace_dir=None, **kw):
        """Write :meth:`chrome_trace` to ``path``; returns the path."""
        doc = self.chrome_trace(jax_trace_dir=jax_trace_dir, **kw)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path
