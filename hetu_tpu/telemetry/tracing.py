"""Host-side step-phase span tracer: ring buffer + Chrome-trace export.

``jax.profiler`` answers "what did the DEVICE do"; this tracer answers
the question three bench rounds stalled on — "where does the HOST step
time go" (``host_gap`` reported as a bare ratio since r05).  Hot paths
open named spans around their phases (prefetch ``data_wait``/
``prefetch_h2d``, executor ``h2d``/``dispatch``/``guard_check``,
serving ``serve_prefill``/``serve_decode``); each span is two
``time.perf_counter()`` reads and one slot write into a fixed ring
buffer, so steady-state tracing never allocates unboundedly and never
syncs the device.

Disabled (the default), ``span()`` hands back a shared no-op context
manager — the whole per-span cost is one flag check plus the ``with``
protocol (~a hundred ns), cheap enough to leave in the executor step
path unconditionally (pinned by the micro-benchmark in
``tests/test_telemetry.py``).

Export: ``aggregate()`` for per-phase totals (the bench's host_gap
decomposition) and ``chrome_trace()`` for chrome://tracing /
Perfetto — optionally MERGED with a ``jax.profiler.trace`` capture's
events, so host phases and XLA device ops land in one viewer.  The two
event sets keep their own clock bases (jax's capture epoch is not
recoverable host-side); lanes align per step by span boundaries, not by
absolute timestamp.
"""

from __future__ import annotations

import json
import threading
import time

__all__ = ["SpanTracer", "NULL_SPAN"]


class _NullSpan:
    """Shared do-nothing span (disabled tracer / allocation-free)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "_t0")

    def __init__(self, tracer, name):
        self._tracer = tracer
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer._record(self.name, self._t0, t1 - self._t0)
        return False


class SpanTracer:
    """Fixed-capacity ring of (name, start_s, dur_s) host spans."""

    def __init__(self, capacity=16384, enabled=False):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._buf = [None] * self.capacity
        self._n = 0                      # total spans ever recorded
        self._epoch = time.perf_counter()

    def span(self, name):
        """Context manager timing one phase; no-op while disabled."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name)

    def _record(self, name, t0, dur):
        with self._lock:
            self._buf[self._n % self.capacity] = (name, t0, dur)
            self._n += 1

    def __len__(self):
        return min(self._n, self.capacity)

    @property
    def dropped(self):
        """Spans that fell off the ring (total recorded - retained)."""
        return max(0, self._n - self.capacity)

    def clear(self):
        with self._lock:
            self._buf = [None] * self.capacity
            self._n = 0
            self._epoch = time.perf_counter()

    def spans(self):
        """Retained spans, oldest first: [(name, start_s, dur_s)]."""
        with self._lock:
            n, cap = self._n, self.capacity
            if n <= cap:
                return [s for s in self._buf[:n]]
            i = n % cap
            return self._buf[i:] + self._buf[:i]

    def aggregate(self):
        """{name: {total_s, count, mean_s}} over the retained spans."""
        agg = {}
        for name, _, dur in self.spans():
            slot = agg.setdefault(name, [0.0, 0])
            slot[0] += dur
            slot[1] += 1
        return {name: {"total_s": t, "count": c, "mean_s": t / c}
                for name, (t, c) in sorted(agg.items())}

    # -- Chrome-trace export ----------------------------------------------
    def chrome_trace(self, jax_trace_dir=None, pid=1 << 20):
        """Trace-event JSON (``{"traceEvents": [...]}``) of the retained
        spans — complete ``X`` events in microseconds relative to the
        tracer epoch, on one process lane named ``hetu host spans``.

        ``jax_trace_dir``: a ``jax.profiler.trace`` output directory
        whose newest capture's events are merged in ahead of ours, so
        one chrome://tracing load shows XLA device lanes next to the
        host phases (clock bases differ; see module doc)."""
        events = [
            {"ph": "M", "pid": pid, "name": "process_name",
             "args": {"name": "hetu host spans"}},
            {"ph": "M", "pid": pid, "tid": 0, "name": "thread_name",
             "args": {"name": "step phases"}},
        ]
        for name, t0, dur in self.spans():
            events.append({"ph": "X", "pid": pid, "tid": 0,
                           "name": name,
                           "ts": (t0 - self._epoch) * 1e6,
                           "dur": dur * 1e6})
        if jax_trace_dir is not None:
            import gzip
            from ..timeline import _latest_trace_json
            captured = json.loads(
                gzip.open(_latest_trace_json(jax_trace_dir)).read())
            events = list(captured.get("traceEvents", [])) + events
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome(self, path, jax_trace_dir=None):
        """Write :meth:`chrome_trace` to ``path``; returns the path."""
        doc = self.chrome_trace(jax_trace_dir=jax_trace_dir)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path
