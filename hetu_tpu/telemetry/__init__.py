"""Unified runtime telemetry: one registry + one tracer per process.

The executor, prefetcher, resilience guard/checkpointer, serving engine,
and PS transport all instrument themselves against the singletons here.
Everything is DISABLED by default — the no-op instrument path costs
~100 ns per call (pinned by ``tests/test_telemetry.py``), so the hot
paths carry their probes unconditionally and a training run pays
nothing until someone calls :func:`enable`.

Four singletons: the :class:`MetricsRegistry` (counters/gauges/
histograms), the :class:`SpanTracer` (host step-phase spans), the
:class:`RequestTrace` (per-rid lifecycle timelines, stitched across
fleet failover), and the :class:`FlightRecorder` (recent-event ring +
incident dumps on any trip).

Typical wiring::

    from hetu_tpu import telemetry
    telemetry.enable(http_port=9100)      # /metrics /healthz /requests
                                          # /incidents live
    ... train / serve ...
    print(telemetry.report())             # snapshot + phase breakdown
    telemetry.shutdown()

``bench.py --telemetry`` drives exactly this around every stage and
appends :func:`report` to the stage's detail JSON.
"""

from __future__ import annotations

from .alerts import (ALERT_STATES, AbsenceRule, AlertManager,
                     BurnRateRule, ThresholdRule, slo_rules)
from .flight import FlightRecorder, INCIDENT_KINDS
from .goodput import (GOODPUT_BUCKETS, LOST_CAUSES, USEFUL_BUCKETS,
                      GoodputLedger)
from .numerics import ANOMALY_KINDS, NumericsMonitor, numerics_report
from .profiling import HBM_POOLS, HbmLedger, ProgramProfiler
from .registry import (DEFAULT_BUCKETS, Counter, Gauge, Histogram,
                       JsonlWriter, MetricsRegistry, MetricsServer,
                       start_http_server)
from .request_trace import EVENT_TYPES, RequestTrace
from .timeseries import TimeSeriesStore
from .tracing import NULL_SPAN, SpanTracer

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "JsonlWriter", "MetricsServer", "SpanTracer", "NULL_SPAN",
           "RequestTrace", "FlightRecorder", "EVENT_TYPES",
           "INCIDENT_KINDS", "DEFAULT_BUCKETS", "start_http_server",
           "HbmLedger", "ProgramProfiler", "HBM_POOLS",
           "NumericsMonitor", "numerics_report", "ANOMALY_KINDS",
           "TimeSeriesStore", "AlertManager", "ThresholdRule",
           "AbsenceRule", "BurnRateRule", "slo_rules", "ALERT_STATES",
           "GoodputLedger", "GOODPUT_BUCKETS", "USEFUL_BUCKETS",
           "LOST_CAUSES", "goodput_report",
           "get_registry", "get_tracer", "get_request_trace",
           "get_flight", "get_hbm_ledger", "get_profiler",
           "get_timeseries", "get_alerts", "get_goodput",
           "enabled", "enable", "disable", "shutdown",
           "report", "step_phase_report", "chrome_trace"]

_registry = MetricsRegistry(enabled=False)
_tracer = SpanTracer(capacity=65536, enabled=False)
_request_trace = RequestTrace(enabled=False)
_flight = FlightRecorder(registry=_registry, enabled=False)
_hbm = HbmLedger(registry=_registry)
_profiler = ProgramProfiler(registry=_registry, ledger=_hbm)
# the time-series plane (ISSUE 19): metric history ring, alert rules
# over it, and the goodput ledger — all disabled-by-default, all driven
# by whoever owns a cadence (no collector threads)
_timeseries = TimeSeriesStore(registry=_registry, enabled=False)
_alerts = AlertManager(_timeseries, registry=_registry, flight=_flight,
                       enabled=False)
_goodput = GoodputLedger(registry=_registry, tracer=_tracer,
                         name="process", enabled=False)
# every request event also lands in the flight ring (bounded; the
# recorder gates on its own enabled flag)
_request_trace._sink = _flight.record
# incident dumps carry the HBM ledger snapshot (memory forensics for
# OOM-adjacent trips)
_flight.configure(request_trace=_request_trace, hbm=_hbm.snapshot)
_server = None


def get_registry():
    """The process-wide :class:`MetricsRegistry`."""
    return _registry


def get_tracer():
    """The process-wide :class:`SpanTracer`."""
    return _tracer


def get_request_trace():
    """The process-wide :class:`RequestTrace`."""
    return _request_trace


def get_flight():
    """The process-wide :class:`FlightRecorder`."""
    return _flight


def get_hbm_ledger():
    """The process-wide :class:`HbmLedger` (live-buffer HBM accounting)."""
    return _hbm


def get_profiler():
    """The process-wide :class:`ProgramProfiler`."""
    return _profiler


def get_timeseries():
    """The process-wide :class:`TimeSeriesStore`."""
    return _timeseries


def get_alerts():
    """The process-wide :class:`AlertManager` (rules added by the
    operator / bench; nothing fires out of the box)."""
    return _alerts


def get_goodput():
    """The process-wide :class:`GoodputLedger` (window pinned at
    :func:`enable`)."""
    return _goodput


def goodput_report(**kw):
    """Attribute the process ledger's current window (see
    :meth:`GoodputLedger.account`); ``{"enabled": False}`` while
    telemetry is off."""
    return _goodput.account(**kw)


def enabled():
    return _registry.enabled


def _slo_block():
    """The /slo debug payload: every live FleetController's report.
    Lazy import — serving imports telemetry, never the reverse at
    module load."""
    from ..serving import control
    return control.slo_report()


def enable(http_port=None, host="127.0.0.1", incident_dir=None):
    """Turn instruments live; optionally start the HTTP exporter
    (``http_port=0`` binds an ephemeral port) and point the flight
    recorder at an incident-dump directory.  Returns the
    :class:`MetricsServer` when one is (already) running, else None."""
    global _server
    _registry.enable()
    _tracer.enabled = True
    _request_trace.enabled = True
    _flight.enabled = True
    _timeseries.enabled = True
    _alerts.enabled = True
    _goodput.enabled = True
    _goodput.begin()        # the process goodput window starts here
    if incident_dir is not None:
        _flight.configure(incident_dir=incident_dir)
    if http_port is not None and _server is None:
        _server = start_http_server(
            port=http_port, host=host, registry=_registry,
            debug_providers={
                "/requests": _request_trace.inflight,
                "/incidents": _flight.incidents,
                "/profile": _profiler.report_block,
                "/slo": _slo_block,
                "/numerics": numerics_report,
                "/timeseries": _timeseries.report_block,
                "/alerts": _alerts.report_block,
                "/goodput": _goodput.report_block,
            },
            health_extra=lambda: {"alerts": _alerts.summary()})
    return _server


def disable():
    """Freeze instruments (references stay valid; state is retained)."""
    _registry.disable()
    _tracer.enabled = False
    _request_trace.enabled = False
    _flight.enabled = False
    _timeseries.enabled = False
    _alerts.enabled = False
    _goodput.enabled = False


def shutdown():
    """Disable + stop the exporter (if any).  State is retained."""
    global _server
    disable()
    if _server is not None:
        _server.close()
        _server = None


def _sync_loss_gauges(reg=None, tr=None, rt=None, fl=None):
    """Mirror ring occupancy + drop counts into registry gauges so
    silent span/event loss shows up in every snapshot and scrape."""
    reg = reg if reg is not None else _registry
    tr = tr if tr is not None else _tracer
    rt = rt if rt is not None else _request_trace
    fl = fl if fl is not None else _flight
    reg.gauge("hetu_tracer_ring_spans",
              "Host spans retained in the SpanTracer ring").set(len(tr))
    reg.gauge("hetu_tracer_ring_capacity",
              "SpanTracer ring capacity").set(tr.capacity)
    reg.gauge("hetu_tracer_spans_dropped",
              "Host spans that fell off the SpanTracer ring"
              ).set(tr.dropped)
    reg.gauge("hetu_trace_rids_tracked",
              "Request timelines currently retained").set(len(rt))
    reg.gauge("hetu_trace_events_dropped",
              "Request-trace events refused by the per-rid cap"
              ).set(rt.dropped_events)
    reg.gauge("hetu_trace_rids_dropped",
              "Whole request timelines evicted by the rid cap"
              ).set(rt.dropped_rids)
    reg.gauge("hetu_flight_ring_events",
              "Events retained in the flight-recorder ring"
              ).set(len(fl))
    reg.gauge("hetu_flight_events_dropped",
              "Events that fell off the flight-recorder ring"
              ).set(fl.dropped)


# span names recorded INSIDE SubExecutor.run()'s wall time; everything
# else host-side (data_wait, prefetch_h2d) happens between run() calls
_RUN_PHASES = ("h2d", "dispatch", "numerics", "guard_check")
_LOOP_PHASES = ("data_wait", "prefetch_h2d")


def step_phase_report(registry=None, tracer=None):
    """Per-step host_gap decomposition from the executor step histogram
    + the tracer's phase spans.

    Returns ``{"steps", "wall_s_per_step", "phases": {...}}`` where the
    phases are ``data_wait`` / ``prefetch_h2d`` (between run() calls),
    ``h2d`` / ``dispatch`` / ``guard_check`` (inside run()), and
    ``device_and_wait`` — the residual of the run() wall time not
    attributable to host work, i.e. time spent inside the jitted call
    (device compute and runtime queue back-pressure).  The phases sum to
    ``wall_s_per_step`` by construction, so the breakdown IS the
    decomposition of the wall step time (host_gap's numerator).
    ``{"steps": 0}`` when no instrumented step has run."""
    reg = registry if registry is not None else _registry
    tr = tracer if tracer is not None else _tracer
    snap = reg.snapshot()
    hist = snap.get("hetu_executor_step_seconds")
    steps = 0
    wall_total = 0.0
    for sample in (hist or {}).get("samples", ()):
        steps += sample["count"]
        wall_total += sample["sum"]
    if steps == 0:
        return {"steps": 0}
    agg = tr.aggregate()
    phases = {}
    run_host = 0.0
    for name in _RUN_PHASES:
        t = agg.get(name, {}).get("total_s", 0.0) / steps
        phases[name] = t
        run_host += t
    run_wall = wall_total / steps
    phases["device_and_wait"] = max(0.0, run_wall - run_host)
    loop_extra = 0.0
    for name in _LOOP_PHASES:
        t = agg.get(name, {}).get("total_s", 0.0) / steps
        phases[name] = t
        loop_extra += t
    wall = max(run_wall, run_host) + loop_extra
    return {"steps": int(steps),
            "wall_s_per_step": round(wall, 9),
            "phases": {k: round(v, 9) for k, v in phases.items()},
            "spans_dropped": tr.dropped}


def report(registry=None, tracer=None):
    """Everything ``--telemetry`` appends to a bench detail JSON: the
    registry snapshot (with ring-occupancy/drop gauges synced first),
    the step-phase breakdown, the raw per-span aggregates (serving
    phases etc. that aren't executor steps), and the request-trace /
    incident summary."""
    reg = registry if registry is not None else _registry
    tr = tracer if tracer is not None else _tracer
    if reg is _registry:
        _sync_loss_gauges(reg, tr)
    return {"registry": reg.snapshot(),
            "phases": step_phase_report(reg, tr),
            "spans": {k: {"total_s": round(v["total_s"], 6),
                          "count": v["count"],
                          "mean_s": round(v["mean_s"], 9)}
                      for k, v in tr.aggregate().items()},
            "requests": {"tracked": len(_request_trace),
                         "events_dropped": _request_trace.dropped_events,
                         "rids_dropped": _request_trace.dropped_rids},
            "incidents": {"total": _flight.incident_count(),
                          "by_kind": {
                              k: _flight.incident_count(k)
                              for k in INCIDENT_KINDS
                              if _flight.incident_count(k)}},
            "profile": _profiler.report_block(),
            "numerics": numerics_report(),
            "timeseries": _timeseries.report_block(),
            "alerts": _alerts.report_block(),
            "goodput": _goodput.report_block()}


def chrome_trace(jax_trace_dir=None, **kw):
    """The merged Chrome-trace view: the SpanTracer's host phase lanes
    (optionally merged + step-aligned with a ``jax.profiler.trace``
    capture, see :meth:`SpanTracer.chrome_trace`) PLUS the per-rid
    request lifecycle lanes — one pid per engine, one tid per rid — on
    the tracer's clock base, so one Perfetto load shows device ops,
    host phases, and request lifecycles together."""
    doc = _tracer.chrome_trace(jax_trace_dir=jax_trace_dir, **kw)
    doc["traceEvents"].extend(
        _request_trace.chrome_rows(epoch=_tracer._epoch))
    return doc
