"""Unified runtime telemetry: one registry + one tracer per process.

The executor, prefetcher, resilience guard/checkpointer, serving engine,
and PS transport all instrument themselves against the singletons here.
Everything is DISABLED by default — the no-op instrument path costs
~100 ns per call (pinned by ``tests/test_telemetry.py``), so the hot
paths carry their probes unconditionally and a training run pays
nothing until someone calls :func:`enable`.

Typical wiring::

    from hetu_tpu import telemetry
    telemetry.enable(http_port=9100)      # /metrics + /healthz live
    ... train / serve ...
    print(telemetry.report())             # snapshot + phase breakdown
    telemetry.shutdown()

``bench.py --telemetry`` drives exactly this around every stage and
appends :func:`report` to the stage's detail JSON.
"""

from __future__ import annotations

from .registry import (DEFAULT_BUCKETS, Counter, Gauge, Histogram,
                       JsonlWriter, MetricsRegistry, MetricsServer,
                       start_http_server)
from .tracing import NULL_SPAN, SpanTracer

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "JsonlWriter", "MetricsServer", "SpanTracer", "NULL_SPAN",
           "DEFAULT_BUCKETS", "start_http_server", "get_registry",
           "get_tracer", "enabled", "enable", "disable", "shutdown",
           "report", "step_phase_report"]

_registry = MetricsRegistry(enabled=False)
_tracer = SpanTracer(capacity=65536, enabled=False)
_server = None


def get_registry():
    """The process-wide :class:`MetricsRegistry`."""
    return _registry


def get_tracer():
    """The process-wide :class:`SpanTracer`."""
    return _tracer


def enabled():
    return _registry.enabled


def enable(http_port=None, host="127.0.0.1"):
    """Turn instruments live; optionally start the HTTP exporter
    (``http_port=0`` binds an ephemeral port).  Returns the
    :class:`MetricsServer` when one is (already) running, else None."""
    global _server
    _registry.enable()
    _tracer.enabled = True
    if http_port is not None and _server is None:
        _server = start_http_server(port=http_port, host=host,
                                    registry=_registry)
    return _server


def disable():
    """Freeze instruments (references stay valid; state is retained)."""
    _registry.disable()
    _tracer.enabled = False


def shutdown():
    """Disable + stop the exporter (if any).  State is retained."""
    global _server
    disable()
    if _server is not None:
        _server.close()
        _server = None


# span names recorded INSIDE SubExecutor.run()'s wall time; everything
# else host-side (data_wait, prefetch_h2d) happens between run() calls
_RUN_PHASES = ("h2d", "dispatch", "guard_check")
_LOOP_PHASES = ("data_wait", "prefetch_h2d")


def step_phase_report(registry=None, tracer=None):
    """Per-step host_gap decomposition from the executor step histogram
    + the tracer's phase spans.

    Returns ``{"steps", "wall_s_per_step", "phases": {...}}`` where the
    phases are ``data_wait`` / ``prefetch_h2d`` (between run() calls),
    ``h2d`` / ``dispatch`` / ``guard_check`` (inside run()), and
    ``device_and_wait`` — the residual of the run() wall time not
    attributable to host work, i.e. time spent inside the jitted call
    (device compute and runtime queue back-pressure).  The phases sum to
    ``wall_s_per_step`` by construction, so the breakdown IS the
    decomposition of the wall step time (host_gap's numerator).
    ``{"steps": 0}`` when no instrumented step has run."""
    reg = registry if registry is not None else _registry
    tr = tracer if tracer is not None else _tracer
    snap = reg.snapshot()
    hist = snap.get("hetu_executor_step_seconds")
    steps = 0
    wall_total = 0.0
    for sample in (hist or {}).get("samples", ()):
        steps += sample["count"]
        wall_total += sample["sum"]
    if steps == 0:
        return {"steps": 0}
    agg = tr.aggregate()
    phases = {}
    run_host = 0.0
    for name in _RUN_PHASES:
        t = agg.get(name, {}).get("total_s", 0.0) / steps
        phases[name] = t
        run_host += t
    run_wall = wall_total / steps
    phases["device_and_wait"] = max(0.0, run_wall - run_host)
    loop_extra = 0.0
    for name in _LOOP_PHASES:
        t = agg.get(name, {}).get("total_s", 0.0) / steps
        phases[name] = t
        loop_extra += t
    wall = max(run_wall, run_host) + loop_extra
    return {"steps": int(steps),
            "wall_s_per_step": round(wall, 9),
            "phases": {k: round(v, 9) for k, v in phases.items()},
            "spans_dropped": tr.dropped}


def report(registry=None, tracer=None):
    """Everything ``--telemetry`` appends to a bench detail JSON: the
    registry snapshot, the step-phase breakdown, and the raw per-span
    aggregates (serving phases etc. that aren't executor steps)."""
    reg = registry if registry is not None else _registry
    tr = tracer if tracer is not None else _tracer
    return {"registry": reg.snapshot(),
            "phases": step_phase_report(reg, tr),
            "spans": {k: {"total_s": round(v["total_s"], 6),
                          "count": v["count"],
                          "mean_s": round(v["mean_s"], 9)}
                      for k, v in tr.aggregate().items()}}
