"""Process-wide metrics registry: counters, gauges, histograms.

Hetu's reference stack treats measurement as a subsystem (per-op replay
profiling + logger aggregation feed experiment tracking and the
auto-parallel search, SURVEY §5 P16/P18); this is the RUNTIME half of
that role for the TPU port.  The offline half (``timeline.py``,
``profiler.py``) answers "what did that trace contain"; the registry
answers "what is the process doing right now" — executor step counters,
prefetch queue depth, guard trips, serving occupancy — through one
surface with three faces:

* ``snapshot()`` — a JSON-safe dict (bench detail files, tests);
* ``to_prometheus()`` — text exposition v0.0.4, served by the
  stdlib-only HTTP exporter (``start_http_server``: ``/metrics`` +
  ``/healthz``) so a TPU VM exposes live metrics with zero extra deps;
* ``JsonlWriter`` — the one append-a-JSON-line serialization path,
  shared with ``hetu_tpu.logger.HetuLogger``.

Cost model: the registry is DISABLED by default and every instrument
checks the registry flag before touching state, so an un-enabled
``counter.inc()`` is two attribute loads and a branch (~100 ns) — cheap
enough to leave in executor/prefetch/serving hot paths unconditionally.
Instruments are cached by name: two subsystems asking for the same
metric share one time series (label sets distinguish them).

Durations everywhere in this module come from ``time.perf_counter()``
(monotonic); wall-clock ``time.time()`` is banned for timing by the
AST gate in ``tests/test_no_wallclock_timing.py``.
"""

from __future__ import annotations

import bisect
import json
import threading
import time

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "JsonlWriter", "start_http_server", "MetricsServer",
           "DEFAULT_BUCKETS"]

# seconds-scale latency buckets: 100 us .. 10 s covers everything from a
# no-op dispatch to a slow checkpoint restore
DEFAULT_BUCKETS = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2,
                   5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _fmt(v):
    """Prometheus sample value: integral floats print as integers."""
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _esc(s):
    return (str(s).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


class _Child:
    """One labeled time series of a metric (pre-resolved label values,
    so the hot-path call is flag-check + locked update only)."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric, key):
        self._metric = metric
        self._key = key


class _CounterChild(_Child):
    def inc(self, n=1):
        m = self._metric
        if not m._registry.enabled:
            return
        if n < 0:
            raise ValueError(f"counter {m.name} cannot decrease (n={n})")
        with m._lock:
            m._values[self._key] += n


class _GaugeChild(_Child):
    def set(self, v):
        m = self._metric
        if not m._registry.enabled:
            return
        with m._lock:
            m._values[self._key] = float(v)

    def inc(self, n=1):
        m = self._metric
        if not m._registry.enabled:
            return
        with m._lock:
            m._values[self._key] += n

    def dec(self, n=1):
        self.inc(-n)


class _HistogramChild(_Child):
    def observe(self, v):
        m = self._metric
        if not m._registry.enabled:
            return
        v = float(v)
        i = bisect.bisect_left(m.buckets, v)
        with m._lock:
            slot = m._values[self._key]
            if i < len(m.buckets):
                slot["buckets"][i] += 1
            slot["sum"] += v
            slot["count"] += 1


class _Metric:
    kind = None
    _child_cls = _Child

    def __init__(self, name, help, label_names, registry):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._registry = registry
        self._lock = threading.Lock()
        self._values = {}       # label-values tuple -> value/state
        self._children = {}
        self._default_child = None

    def _zero(self):
        return 0.0

    def labels(self, **labelvals):
        if set(labelvals) != set(self.label_names):
            raise ValueError(
                f"metric {self.name} takes labels {self.label_names}, "
                f"got {tuple(sorted(labelvals))}")
        key = tuple(str(labelvals[k]) for k in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._child_cls(self, key)
                self._children[key] = child
                self._values[key] = self._zero()
        return child

    def _default(self):
        """The unlabeled series (metrics declared without label names);
        cached so hot-path ``metric.inc()`` skips label resolution."""
        child = self._default_child
        if child is None:
            if self.label_names:
                raise ValueError(
                    f"metric {self.name} has labels {self.label_names}; "
                    "resolve a series with .labels(...) first")
            child = self._default_child = self.labels()
        return child

    def _samples(self):
        with self._lock:
            return [(dict(zip(self.label_names, key)), value)
                    for key, value in sorted(self._values.items())]


class Counter(_Metric):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, n=1):
        self._default().inc(n)


class Gauge(_Metric):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, v):
        self._default().set(v)

    def inc(self, n=1):
        self._default().inc(n)

    def dec(self, n=1):
        self._default().dec(n)


class Histogram(_Metric):
    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, name, help, label_names, registry,
                 buckets=DEFAULT_BUCKETS):
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"histogram {name} buckets must be sorted and unique, "
                f"got {buckets!r}")
        self.buckets = tuple(float(b) for b in buckets)
        super().__init__(name, help, label_names, registry)

    def _zero(self):
        return {"buckets": [0] * len(self.buckets), "sum": 0.0,
                "count": 0}

    def observe(self, v):
        self._default().observe(v)

    def _samples(self):
        with self._lock:
            out = []
            for key, slot in sorted(self._values.items()):
                out.append((dict(zip(self.label_names, key)),
                            {"buckets": list(slot["buckets"]),
                             "sum": slot["sum"],
                             "count": slot["count"]}))
            return out


class MetricsRegistry:
    """Named metric instruments + the three export faces.

    ``enabled=False`` (the default for the process-wide registry) makes
    every instrument a near-free no-op; flip with ``enable()`` /
    ``disable()`` at any point — call sites keep their references.
    """

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self, enabled=False):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._metrics = {}
        self._t0 = time.perf_counter()

    def enable(self):
        self.enabled = True
        return self

    def disable(self):
        self.enabled = False
        return self

    # -- instrument constructors (cached by name) -------------------------
    def _get(self, cls, name, help, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, labels, self, **kw)
                self._metrics[name] = m
                return m
        if type(m) is not cls or m.label_names != tuple(labels):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind} with "
                f"labels {m.label_names}; cannot re-register as "
                f"{cls.kind} with labels {tuple(labels)}")
        if "buckets" in kw:
            want = tuple(float(b) for b in kw["buckets"])
            if m.buckets != want:
                # instruments are cached by name, so two callers asking
                # for one histogram with different ladders would
                # SILENTLY share whichever registered first — the
                # per-deployment override (buckets= threaded through
                # InferenceEngine/EngineFleet) must instead fail loudly
                raise ValueError(
                    f"histogram {name!r} already registered with "
                    f"buckets {m.buckets}; cannot re-register with "
                    f"{want} — pick one ladder per deployment")
        return m

    def counter(self, name, help="", labels=()):
        return self._get(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()):
        return self._get(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(),
                  buckets=DEFAULT_BUCKETS):
        """``buckets=`` sets the ladder at FIRST registration (the
        per-deployment override path); later registrations must agree."""
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def reset(self):
        """Drop every registered metric (tests; NOT the enabled flag)."""
        with self._lock:
            self._metrics = {}

    # -- export faces ------------------------------------------------------
    def snapshot(self):
        """JSON-safe deep copy: {name: {type, help, samples: [{labels,
        value|count/sum/buckets}]}}.  Isolated — later updates do not
        mutate an already-taken snapshot."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in metrics:
            samples = []
            for labels, value in m._samples():
                entry = {"labels": labels}
                if m.kind == "histogram":
                    entry["count"] = value["count"]
                    entry["sum"] = value["sum"]
                    entry["buckets"] = [
                        [le, n] for le, n in zip(m.buckets,
                                                 value["buckets"])]
                else:
                    entry["value"] = value
                samples.append(entry)
            out[m.name] = {"type": m.kind, "help": m.help,
                           "samples": samples}
        return out

    def to_prometheus(self):
        """Text exposition format v0.0.4 (what Prometheus scrapes)."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
        for m in metrics:
            lines.append(f"# HELP {m.name} {_esc(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for labels, value in m._samples():
                base = ",".join(f'{k}="{_esc(v)}"'
                                for k, v in labels.items())
                if m.kind == "histogram":
                    cum = 0
                    for le, n in zip(m.buckets, value["buckets"]):
                        cum += n
                        lb = (base + "," if base else "") + \
                            f'le="{_fmt(float(le))}"'
                        lines.append(
                            f"{m.name}_bucket{{{lb}}} {cum}")
                    lb = (base + "," if base else "") + 'le="+Inf"'
                    lines.append(
                        f"{m.name}_bucket{{{lb}}} {value['count']}")
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{m.name}_sum{suffix} "
                                 f"{_fmt(value['sum'])}")
                    lines.append(f"{m.name}_count{suffix} "
                                 f"{value['count']}")
                else:
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{m.name}{suffix} {_fmt(value)}")
        return "\n".join(lines) + "\n"

    def write_jsonl(self, writer):
        """Append one snapshot record through a :class:`JsonlWriter`
        (or any object with ``write(record)``)."""
        writer.write({"kind": "metrics_snapshot",
                      "uptime_s": round(time.perf_counter() - self._t0,
                                        3),
                      "metrics": self.snapshot()})


class JsonlWriter:
    """THE append-a-JSON-line path (logger records, registry snapshots):
    one place that owns the file handle, flush policy, and close —
    ``HetuLogger`` delegates here instead of keeping its own ``open``.
    Context-manager; ``close()`` is idempotent."""

    def __init__(self, path):
        self.path = str(path)
        self._f = open(self.path, "a")
        self._lock = threading.Lock()

    def write(self, record):
        with self._lock:
            if self._f is None:
                raise ValueError(f"JsonlWriter({self.path}) is closed")
            self._f.write(json.dumps(record) + "\n")
            self._f.flush()

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MetricsServer:
    """Handle for a running exporter: ``.port``, ``.url``, ``close()``."""

    def __init__(self, httpd, thread):
        self._httpd = httpd
        self._thread = thread
        self.host, self.port = httpd.server_address[:2]

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def start_http_server(port=0, host="127.0.0.1", registry=None,
                      debug_providers=None, health_extra=None):
    """Serve ``/metrics`` (Prometheus text) + ``/healthz`` (JSON) from a
    daemon thread — stdlib only, so it runs on a bare TPU VM.  Returns a
    :class:`MetricsServer` (``port=0`` binds an ephemeral port).

    ``debug_providers``: ``{path: callable}`` extra JSON endpoints —
    each callable returns a JSON-safe value, rendered on GET.  This is
    how ``telemetry.enable()`` mounts ``/requests`` (the live in-flight
    request table) and ``/incidents`` (the flight-recorder dump index)
    without this module importing them.

    ``health_extra``: callable returning a JSON-safe dict merged into
    the ``/healthz`` body — how the alert manager's one-line summary
    (``firing: N``) reaches external probes without a /metrics scrape.
    A raising callable degrades the body, never the endpoint."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry
    providers = dict(debug_providers or {})
    t0 = time.perf_counter()

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                body = reg.to_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/healthz":
                doc = {"status": "ok", "telemetry_enabled": reg.enabled,
                       "uptime_s": round(time.perf_counter() - t0, 3)}
                if health_extra is not None:
                    try:
                        doc.update(health_extra())
                    except Exception as e:
                        doc["status"] = "degraded"
                        doc["error"] = f"{type(e).__name__}: {e}"
                body = json.dumps(doc).encode()
                ctype = "application/json"
            elif path in providers:
                try:
                    body = json.dumps(providers[path]()).encode()
                except Exception as e:
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(
                        f"{type(e).__name__}: {e}".encode())
                    return
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):   # keep scrapes off stderr
            pass

    if reg is None:
        raise ValueError("start_http_server needs a registry= (use "
                         "hetu_tpu.telemetry.enable(http_port=...) for "
                         "the process-wide one)")
    httpd = ThreadingHTTPServer((host, int(port)), _Handler)
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever, daemon=True,
                              name="hetu-metrics-exporter")
    thread.start()
    return MetricsServer(httpd, thread)
