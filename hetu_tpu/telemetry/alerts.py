"""Declarative alerting over the time-series ring.

Rules evaluate against a :class:`~.timeseries.TimeSeriesStore` — never
against a single instantaneous sample — so alerting is trend-driven by
construction.  Three rule shapes:

* :class:`ThresholdRule` — a reduced window statistic (``last`` /
  ``rate`` / ``delta`` / ``mean``) compared against a bound;
* :class:`AbsenceRule` — "this counter stopped moving": a counter that
  HAS moved before shows zero delta across the window (optionally only
  while a gate series says there is work to move it — an idle system
  is not stuck);
* :class:`BurnRateRule` — the SRE multi-window error-budget burn: the
  bad/good event fraction over a FAST and a SLOW window, both expressed
  as multiples of the declared budget; fires only when both windows
  burn (fast-only is a blip, slow-only is already-old news).

:func:`slo_rules` derives the standard rule set from a declared
``serving.control.SLO`` — deadline-miss budget burn (fast + slow),
attainment floor, HBM headroom, watchdog / migration-failure /
engine-crash / guard-trip / overload-shed rates, numerics anomaly
streaks, and a stuck-token absence detector — so a fleet gets paging
coverage from the same object its controller already steers by.

Every rule runs a pending -> firing -> resolved state machine
(``for_ticks`` consecutive bad evaluations arm it; one good evaluation
after firing resolves it).  Entering ``firing`` emits a flight-recorder
``alert`` incident carrying the offending series tail, flips the
``hetu_alerts_firing{rule=}`` gauge, and counts a transition; the
:class:`~..serving.control.FleetController` can consume
:meth:`AlertManager.firing` as a scale/brownout input next to its
EWMAs (the ``alerts=`` hook).

Disabled by default like every PR 4 instrument: :meth:`evaluate` /
:meth:`poll` while disabled are one flag check (<20 us/op, pinned by
``tests/test_timeseries.py``).  No evaluator thread — the owner of a
cadence (controller tick, bench stage, operator loop) calls
:meth:`poll`.
"""

from __future__ import annotations

import threading
import time

__all__ = ["AlertManager", "ThresholdRule", "AbsenceRule",
           "BurnRateRule", "slo_rules", "ALERT_STATES"]

#: the per-rule state machine (resolved relaxes to inactive next eval)
ALERT_STATES = ("inactive", "pending", "firing", "resolved")

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


class _Rule:
    """Shared shape: ``check(store, now) -> (active|None, observed)``.
    ``None`` means no evidence either way (metric absent, <2 points) —
    the state machine treats it as not-active without claiming health.
    """

    kind = None

    def __init__(self, name, *, window=None, for_ticks=2,
                 severity="page"):
        self.name = str(name)
        self.window = None if window is None else float(window)
        self.for_ticks = max(1, int(for_ticks))
        self.severity = str(severity)

    def check(self, store, now):
        raise NotImplementedError

    def describe(self):
        return {"name": self.name, "kind": self.kind,
                "window_s": self.window, "for_ticks": self.for_ticks,
                "severity": self.severity}

    def tail_series(self):
        """(metric, labels, field) whose tail the incident carries."""
        raise NotImplementedError


class ThresholdRule(_Rule):
    """``reduce(metric over window) op threshold``."""

    kind = "threshold"
    REDUCERS = ("last", "rate", "delta", "mean")

    def __init__(self, name, metric, *, op=">", threshold=0.0,
                 reduce="rate", labels=None, field=None, **kw):
        super().__init__(name, **kw)
        if op not in _OPS:
            raise ValueError(f"op must be one of {tuple(_OPS)}, "
                             f"got {op!r}")
        if reduce not in self.REDUCERS:
            raise ValueError(f"reduce must be one of {self.REDUCERS}, "
                             f"got {reduce!r}")
        self.metric = str(metric)
        self.op = op
        self.threshold = float(threshold)
        self.reduce = reduce
        self.labels = labels
        self.field = field

    def check(self, store, now):
        fn = getattr(store, self.reduce)
        if self.reduce == "last":
            v = fn(self.metric, labels=self.labels, field=self.field)
        else:
            v = fn(self.metric, labels=self.labels, window=self.window,
                   field=self.field, now=now)
        if v is None:
            return None, None
        return _OPS[self.op](v, self.threshold), v

    def describe(self):
        d = super().describe()
        d.update(metric=self.metric, op=self.op,
                 threshold=self.threshold, reduce=self.reduce)
        return d

    def tail_series(self):
        return self.metric, self.labels, self.field


class AbsenceRule(_Rule):
    """A counter that has moved before shows zero delta over the
    window — the stuck detector.  ``while_metric`` gates the rule on a
    load signal (e.g. tokens stuck only counts while queue depth > 0),
    so an idle system never pages."""

    kind = "absence"

    def __init__(self, name, metric, *, labels=None, field=None,
                 while_metric=None, while_op=">", while_threshold=0.0,
                 while_labels=None, **kw):
        kw.setdefault("window", 5.0)
        super().__init__(name, **kw)
        if kw.get("window") is None and self.window is None:
            raise ValueError("AbsenceRule needs a window")
        self.metric = str(metric)
        self.labels = labels
        self.field = field
        self.while_metric = while_metric
        self.while_op = while_op
        self.while_threshold = float(while_threshold)
        self.while_labels = while_labels

    def check(self, store, now):
        total = store.last(self.metric, labels=self.labels,
                           field=self.field)
        if not total:
            return None, None       # never moved: nothing to be stuck
        if self.while_metric is not None:
            gate = store.last(self.while_metric,
                              labels=self.while_labels)
            if gate is None or not _OPS[self.while_op](
                    gate, self.while_threshold):
                return False, 0.0   # no load: idle, not stuck
        d = store.delta(self.metric, labels=self.labels,
                        window=self.window, field=self.field, now=now)
        if d is None:
            return None, None
        return d == 0.0, d

    def describe(self):
        d = super().describe()
        d.update(metric=self.metric, while_metric=self.while_metric)
        return d

    def tail_series(self):
        return self.metric, self.labels, self.field


class BurnRateRule(_Rule):
    """Multi-window error-budget burn: ``bad/good`` fraction over a
    fast AND a slow window, each as a multiple of ``budget``.  Fires
    when ``burn_fast > fast_factor`` and ``burn_slow > slow_factor``
    simultaneously — the standard SRE page condition that ignores both
    blips and stale history.  ``window`` doubles as the slow window;
    ``fast_window`` defaults to a quarter of it."""

    kind = "burn_rate"

    def __init__(self, name, bad_metric, good_metric, budget, *,
                 fast_window=None, fast_factor=6.0, slow_factor=1.0,
                 bad_labels=None, good_labels=None, **kw):
        kw.setdefault("window", 20.0)
        super().__init__(name, **kw)
        if budget <= 0 or budget > 1:
            raise ValueError(f"budget must be in (0, 1], got {budget}")
        self.bad_metric = str(bad_metric)
        self.good_metric = str(good_metric)
        self.budget = float(budget)
        self.fast_window = (self.window / 4.0 if fast_window is None
                            else float(fast_window))
        self.fast_factor = float(fast_factor)
        self.slow_factor = float(slow_factor)
        self.bad_labels = bad_labels
        self.good_labels = good_labels

    def _burn(self, store, window, now):
        bad = store.delta(self.bad_metric, labels=self.bad_labels,
                          window=window, now=now)
        good = store.delta(self.good_metric, labels=self.good_labels,
                           window=window, now=now)
        if bad is None or good is None or good <= 0:
            return None
        return (bad / good) / self.budget

    def check(self, store, now):
        fast = self._burn(store, self.fast_window, now)
        slow = self._burn(store, self.window, now)
        if fast is None or slow is None:
            return None, None
        return (fast > self.fast_factor
                and slow > self.slow_factor), fast

    def describe(self):
        d = super().describe()
        d.update(bad_metric=self.bad_metric,
                 good_metric=self.good_metric, budget=self.budget,
                 fast_window_s=self.fast_window,
                 fast_factor=self.fast_factor,
                 slow_factor=self.slow_factor)
        return d

    def tail_series(self):
        return self.bad_metric, self.bad_labels, None


class _RuleState:
    __slots__ = ("state", "bad_ticks", "since", "observed",
                 "transitions", "fired")

    def __init__(self):
        self.state = "inactive"
        self.bad_ticks = 0
        self.since = None
        self.observed = None
        self.transitions = []       # [(to_state, t)], bounded
        self.fired = 0


class AlertManager:
    """Rules + per-rule state machines over one TimeSeriesStore.

    ``poll()`` = ``store.tick()`` + :meth:`evaluate` — the one call a
    cadence owner makes.  Rules are explicit (:meth:`add`,
    :func:`slo_rules`); nothing fires out of the box."""

    MAX_TRANSITIONS = 64            # per rule, newest kept

    def __init__(self, store, rules=(), *, registry=None, flight=None,
                 clock=None, enabled=False):
        self.store = store
        self.enabled = bool(enabled)
        self._registry = registry
        self._flight = flight
        self._clock = clock if clock is not None else time.perf_counter
        self._lock = threading.Lock()
        self._rules = {}
        self._states = {}
        self.evals = 0
        self._m_firing = None
        self._m_transitions = None
        self._m_evals = None
        for r in rules:
            self.add(r)

    def add(self, rule):
        with self._lock:
            if rule.name in self._rules:
                raise ValueError(f"alert rule {rule.name!r} already "
                                 "registered")
            self._rules[rule.name] = rule
            self._states[rule.name] = _RuleState()
        return rule

    def rules(self):
        with self._lock:
            return list(self._rules.values())

    def state(self, name):
        with self._lock:
            return self._states[name].state

    def transitions(self, name):
        """[(to_state, t)] — the no-flap audit trail for one rule."""
        with self._lock:
            return list(self._states[name].transitions)

    def firing(self):
        with self._lock:
            return tuple(n for n, s in self._states.items()
                         if s.state == "firing")

    # -- evaluation --------------------------------------------------------
    def poll(self, now=None):
        """Tick the store, then evaluate every rule.  One flag check
        while disabled."""
        if not self.enabled:
            return ()
        self.store.tick(now)
        return self.evaluate(now)

    def evaluate(self, now=None):
        """Advance every rule's state machine against the store.
        Returns the currently-firing rule names."""
        if not self.enabled:
            return ()
        t = self._clock() if now is None else float(now)
        self.evals += 1
        self._lazy_metrics()
        if self._m_evals is not None:
            self._m_evals.inc()
        with self._lock:
            items = list(self._rules.items())
        for name, rule in items:
            active, observed = rule.check(self.store, t)
            self._advance(name, rule, active, observed, t)
        return self.firing()

    def _advance(self, name, rule, active, observed, t):
        st = self._states[name]
        st.observed = observed
        if active:
            st.bad_ticks += 1
            if st.state in ("inactive", "resolved"):
                self._transition(st, name, "pending", t)
            if st.state == "pending" and st.bad_ticks >= rule.for_ticks:
                self._transition(st, name, "firing", t)
                st.fired += 1
                self._emit_incident(rule, st, observed, t)
        else:
            # None (no evidence) does not resolve a firing rule — only
            # a measured-good window does; it does clear a pending one
            st.bad_ticks = 0
            if st.state == "firing" and active is False:
                self._transition(st, name, "resolved", t)
            elif st.state == "pending":
                self._transition(st, name, "inactive", t)
            elif st.state == "resolved":
                self._transition(st, name, "inactive", t)

    def _transition(self, st, name, to, t):
        st.state = to
        st.since = t
        st.transitions.append((to, t))
        del st.transitions[:-self.MAX_TRANSITIONS]
        if self._m_transitions is not None:
            self._m_transitions.labels(rule=name, to=to).inc()
        if self._m_firing is not None:
            self._m_firing.labels(rule=name).set(
                1.0 if to == "firing" else 0.0)

    def _emit_incident(self, rule, st, observed, t):
        if self._flight is None:
            return
        metric, labels, field = rule.tail_series()
        thr = getattr(rule, "threshold",
                      getattr(rule, "fast_factor", None))
        self._flight.incident(
            "alert",
            extra={"rule": rule.name, "kind": rule.kind,
                   "severity": rule.severity,
                   "window_s": rule.window,
                   "observed": observed, "threshold": thr,
                   "fired_total": st.fired,
                   "series": {"metric": metric,
                              "tail": self.store.tail(
                                  metric, labels=labels, field=field)}})

    def _lazy_metrics(self):
        if self._registry is None or self._m_firing is not None:
            return
        reg = self._registry
        self._m_firing = reg.gauge(
            "hetu_alerts_firing",
            "1 while the named alert rule is firing, else 0",
            labels=("rule",))
        self._m_transitions = reg.counter(
            "hetu_alerts_transitions_total",
            "Alert state-machine transitions, by rule and destination",
            labels=("rule", "to"))
        self._m_evals = reg.counter(
            "hetu_alerts_evals_total",
            "Full rule-set evaluation passes")

    # -- export ------------------------------------------------------------
    def summary(self):
        """The one-line /healthz block: ``firing: N`` + names."""
        firing = self.firing()
        return {"firing": len(firing),
                "summary": f"firing: {len(firing)}",
                "rules": sorted(firing)}

    def report_block(self):
        with self._lock:
            rows = {}
            for name, rule in self._rules.items():
                st = self._states[name]
                rows[name] = dict(rule.describe(), state=st.state,
                                  observed=st.observed, since=st.since,
                                  fired_total=st.fired,
                                  transitions=len(st.transitions))
        return {"enabled": self.enabled, "evals": self.evals,
                "firing": sorted(self.firing()), "rules": rows}


def slo_rules(slo=None, *, window=20.0, for_ticks=2,
              attainment_floor=0.9, hbm_headroom_floor_bytes=None,
              watchdog_rate=0.0, migration_failure_rate=0.0,
              engine_crash_rate=0.0, guard_trip_rate=0.0,
              overload_shed_rate=0.0, numerics_anomaly_rate=0.0,
              stuck_window=None):
    """The standard rule set, derived from a declared ``SLO``.

    Every chaos fault class maps to exactly one rule here (the bench
    acceptance contract): a nan training step -> ``guard_trips``, an
    engine crash -> ``engine_crashes``, a KV transfer fault ->
    ``migration_failures``, an overload burst -> ``overload_shed``.
    Rate thresholds default to 0 (any movement over the window pages);
    raise them for noisy fleets.  ``slo=None`` uses the default SLO
    budget for the burn-rate pair."""
    from ..serving.control import SLO
    slo = slo if slo is not None else SLO()
    w = float(window)
    rules = [
        # the SLO error budget, burned over fast+slow windows: bad =
        # deadline-expired retirements, good = all retirements
        BurnRateRule("slo_deadline_burn",
                     "hetu_serving_deadline_expired_total",
                     "hetu_serving_requests_total",
                     slo.deadline_miss_target,
                     window=w, for_ticks=for_ticks),
        ThresholdRule("slo_attainment_low", "hetu_slo_attainment",
                      reduce="last", op="<",
                      threshold=float(attainment_floor),
                      window=w, for_ticks=for_ticks),
        ThresholdRule("guard_trips", "hetu_guard_trips_total",
                      reduce="rate", op=">",
                      threshold=float(guard_trip_rate),
                      window=w, for_ticks=for_ticks),
        ThresholdRule("engine_crashes", "hetu_fleet_engine_crashes_total",
                      reduce="rate", op=">",
                      threshold=float(engine_crash_rate),
                      window=w, for_ticks=for_ticks),
        ThresholdRule("migration_failures", "hetu_migrate_failures_total",
                      reduce="rate", op=">",
                      threshold=float(migration_failure_rate),
                      window=w, for_ticks=for_ticks),
        ThresholdRule("overload_shed", "hetu_serving_rejections_total",
                      reduce="rate", op=">",
                      threshold=float(overload_shed_rate),
                      window=w, for_ticks=for_ticks),
        ThresholdRule("watchdog_trips",
                      "hetu_serving_watchdog_trips_total",
                      reduce="rate", op=">",
                      threshold=float(watchdog_rate),
                      window=w, for_ticks=for_ticks),
        ThresholdRule("numerics_anomaly_streak",
                      "hetu_numerics_anomalies_total",
                      reduce="rate", op=">",
                      threshold=float(numerics_anomaly_rate),
                      window=w, for_ticks=for_ticks),
        AbsenceRule("serving_tokens_stuck", "hetu_serving_tokens_total",
                    window=(w if stuck_window is None
                            else float(stuck_window)),
                    for_ticks=for_ticks,
                    while_metric="hetu_serving_queue_depth",
                    while_op=">", while_threshold=0.0),
    ]
    if hbm_headroom_floor_bytes is not None:
        rules.append(ThresholdRule(
            "hbm_headroom_low", "hetu_slo_hbm_headroom",
            reduce="last", op="<",
            threshold=float(hbm_headroom_floor_bytes),
            window=w, for_ticks=for_ticks))
    return rules
