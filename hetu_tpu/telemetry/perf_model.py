"""Peak-rate tables + MFU / roofline arithmetic for the profiling layer.

Pure functions over numbers the :mod:`~hetu_tpu.telemetry.profiling`
capture layer supplies (XLA cost-model flops/bytes, measured steps/s),
so every derived signal here is unit-testable without a device:

* :func:`chip_peaks` — per-chip peak flop rate and HBM bandwidth, from
  the device kind (published TPU specs; bf16 dense-matmul peaks), with
  ``HETU_PEAK_FLOPS`` / ``HETU_PEAK_HBM_BW`` env overrides for chips
  the table doesn't know (and for pinning CPU-quick rounds to a stable
  denominator).
* :func:`mfu` — model flops utilization: achieved flops/s over peak.
* :func:`roofline` — arithmetic intensity vs the ridge point, i.e.
  whether the program sits on the compute or the memory roof.
* :func:`derive` — the full per-program derived block bench/report use.

On CPU the table returns a NOMINAL host peak: the absolute MFU is
meaningless there (and flagged ``peak_source="nominal_cpu"``), but the
ratio is stable run-to-run, which is what the perf-regression harness
(tools/perf_diff.py) diffs.
"""

from __future__ import annotations

import os

__all__ = ["CHIP_PEAKS", "chip_peaks", "mfu", "roofline", "derive"]

#: device_kind substring -> (peak flops/s, HBM bytes/s).  Flop peaks are
#: the published bf16 MXU numbers; substrings are matched in order, so
#: "v5p" must precede "v5" etc.  The trailing "cpu" entry is nominal.
CHIP_PEAKS = (
    ("v6e", (918e12, 1640e9)),          # Trillium
    ("v5p", (459e12, 2765e9)),
    ("v5e", (197e12, 819e9)),           # aka v5 lite
    ("v4", (275e12, 1228e9)),
    ("v3", (123e12, 900e9)),
    ("v2", (45e12, 700e9)),
    ("cpu", (2e11, 5e10)),              # nominal host-order numbers
)

_DEFAULT_PEAKS = (2e14, 8e11)           # unknown accelerator: v4-order


def chip_peaks(device_kind=None):
    """``{"device_kind", "peak_flops", "peak_hbm_bytes_per_s",
    "peak_source"}`` for the current (or named) chip.

    ``device_kind=None`` sniffs ``jax.devices()[0].device_kind`` — lazy
    import, so the module stays importable without jax.  Env overrides
    ``HETU_PEAK_FLOPS`` / ``HETU_PEAK_HBM_BW`` win over the table.
    """
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = "unknown"
    kind_l = str(device_kind).lower()
    flops, bw = _DEFAULT_PEAKS
    source = "default_unknown_chip"
    for sub, (f, b) in CHIP_PEAKS:
        if sub in kind_l:
            flops, bw = f, b
            source = "nominal_cpu" if sub == "cpu" else "table"
            break
    env_f = os.environ.get("HETU_PEAK_FLOPS")
    env_b = os.environ.get("HETU_PEAK_HBM_BW")
    if env_f:
        flops, source = float(env_f), "env"
    if env_b:
        bw = float(env_b)
        source = source if env_f else "env"
    return {"device_kind": str(device_kind),
            "peak_flops": float(flops),
            "peak_hbm_bytes_per_s": float(bw),
            "peak_source": source}


def mfu(flops_per_step, steps_per_sec, peak_flops):
    """Model flops utilization: (flops/step x steps/s) / peak flops/s.

    0.0 when any input is missing/non-positive (never raises: profiling
    must degrade, not break, on backends without a cost model)."""
    if not flops_per_step or not steps_per_sec or not peak_flops:
        return 0.0
    if flops_per_step <= 0 or steps_per_sec <= 0 or peak_flops <= 0:
        return 0.0
    return float(flops_per_step) * float(steps_per_sec) / float(peak_flops)


def roofline(flops_per_step, bytes_per_step, peaks):
    """Roofline position of one program: arithmetic intensity (flops per
    HBM byte accessed) vs the chip's ridge point (peak_flops / peak_bw).
    ``bound`` is "compute" above the ridge, "memory" below, None when
    the inputs are missing."""
    peak_f = peaks["peak_flops"]
    peak_b = peaks["peak_hbm_bytes_per_s"]
    ridge = (peak_f / peak_b) if peak_b else None
    if not flops_per_step or not bytes_per_step or bytes_per_step <= 0:
        return {"arithmetic_intensity": None, "ridge_intensity": ridge,
                "bound": None}
    ai = float(flops_per_step) / float(bytes_per_step)
    bound = None
    if ridge is not None:
        bound = "compute" if ai >= ridge else "memory"
    return {"arithmetic_intensity": round(ai, 6),
            "ridge_intensity": round(ridge, 6) if ridge else None,
            "bound": bound}


def derive(cost, steps=None, elapsed_s=None, peaks=None, n_chips=1,
           tokens=None, items_name="tokens"):
    """The derived-signal block for one profiled program.

    ``cost`` is the normalized XLA cost dict (flops, "bytes accessed");
    ``steps``/``elapsed_s`` a measured execution count and wall window
    (None -> static-only signals); ``tokens`` an optional item count for
    serving-style throughput (items/s/chip under ``items_name``).
    Arithmetic is deliberately transparent —
    ``mfu == flops_per_step * steps_per_sec / peak_flops`` exactly —
    and pinned by tests/test_profiling.py.
    """
    peaks = peaks or chip_peaks()
    flops = float(cost.get("flops", 0.0) or 0.0)
    nbytes = float(cost.get("bytes accessed", 0.0) or 0.0)
    out = {"flops_per_step": flops, "bytes_per_step": nbytes,
           "roofline": roofline(flops, nbytes, peaks)}
    if steps and elapsed_s and elapsed_s > 0:
        sps = float(steps) / float(elapsed_s)
        out["steps"] = int(steps)
        out["elapsed_s"] = round(float(elapsed_s), 6)
        out["steps_per_sec"] = round(sps, 4)
        out["achieved_flops_per_sec"] = round(flops * sps, 2)
        out["achieved_bytes_per_sec"] = round(nbytes * sps, 2)
        out["mfu"] = round(mfu(flops, sps, peaks["peak_flops"]), 6)
        bw = peaks["peak_hbm_bytes_per_s"]
        out["hbm_frac"] = round(nbytes * sps / bw, 6) if bw else None
        if tokens:
            per_chip = float(tokens) / float(elapsed_s) / max(1, n_chips)
            out[f"{items_name}_per_sec_per_chip"] = round(per_chip, 2)
    return out
