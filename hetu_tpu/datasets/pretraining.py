"""BERT pretraining features: raw corpus -> MLM/NSP arrays.

Reference: examples/nlp/bert/create_pretraining_data.py:1 — documents
are split into sentence segments; segment runs are packed into
[CLS] A [SEP] B [SEP] pairs where B is the true continuation 50% of the
time and a random document otherwise (NSP), then ~15% of tokens are
masked 80/10/10 ([MASK] / keep / random word) for the MLM objective.

Fresh design notes (same recipe, TPU-shaped output):
  * emits dense rectangular numpy arrays — input_ids/token_type_ids/
    attention_mask [N, S], mlm_labels [N*S] with -1 everywhere except
    masked positions, nsp_labels [N] — exactly the feed contract of
    ``models.BertForPreTraining.loss`` (the reference writes HDF5 of
    positions+ids instead; our MLM head buckets positions in-graph).
  * one ``np.random.default_rng`` drives every choice, so a (corpus,
    seed) pair reproduces bit-identical features across runs/hosts.
"""

from __future__ import annotations

import numpy as np


def documents_from_text_file(path, tokenizer):
    """Read the reference input format (one sentence per line; blank
    lines delimit documents) into token-id documents, dropping empties.

    Returns list of documents; each document is a list of segments;
    each segment is a list of token ids."""
    docs, cur = [], []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                if cur:
                    docs.append(cur)
                cur = []
                continue
            toks = tokenizer.tokenize(line)
            if toks:
                cur.append(tokenizer.convert_tokens_to_ids(toks))
    if cur:
        docs.append(cur)
    return docs


def mask_tokens(ids, special_mask, rng, vocab_size, mask_id, *,
                masked_lm_prob=0.15, max_predictions=None):
    """Apply the 80/10/10 MLM recipe to one sequence (ids: int array).

    Returns (masked_ids, labels) where labels[j] = original id at
    masked positions and -1 elsewhere (the BertForPreTraining
    contract)."""
    ids = np.asarray(ids)
    cand = np.nonzero(~special_mask)[0]
    rng.shuffle(cand)
    n_pred = max(1, int(round(len(ids) * masked_lm_prob)))
    if max_predictions is not None:
        n_pred = min(n_pred, max_predictions)
    picked = cand[:n_pred]
    out = ids.copy()
    labels = np.full(ids.shape, -1, np.int64)
    labels[picked] = ids[picked]
    roll = rng.random(len(picked))
    mask_pos = picked[roll < 0.8]
    rand_pos = picked[roll >= 0.9]
    out[mask_pos] = mask_id
    out[rand_pos] = rng.integers(0, vocab_size, rand_pos.shape)
    return out, labels


def create_pretraining_arrays(documents, tokenizer, *, max_seq_length=128,
                              dupe_factor=1, short_seq_prob=0.1,
                              masked_lm_prob=0.15,
                              max_predictions_per_seq=None, seed=0):
    """Documents (token-id segments) -> MLM/NSP feature arrays.

    Packing follows the reference recipe (create_instances_from_document,
    create_pretraining_data.py:191): accumulate segments to a target
    length, split the chunk at a random point into A, then B is either
    the rest of the chunk (NSP label 0 = "is next") or a random span
    from another document (label 1 = "random"), with unused segments
    pushed back.  ``dupe_factor`` repeats the corpus with different
    masking (reference --dupe_factor)."""
    rng = np.random.default_rng(seed)
    vocab = tokenizer.vocab
    cls_id = vocab[tokenizer.cls_token]
    sep_id = vocab[tokenizer.sep_token]
    mask_id = vocab[tokenizer.mask_token]
    vocab_size = len(vocab)
    max_tokens = max_seq_length - 3

    rows = []
    for _ in range(dupe_factor):
        for d_idx, doc in enumerate(documents):
            target = max_tokens
            if rng.random() < short_seq_prob:
                target = int(rng.integers(2, max_tokens))
            chunk, chunk_len, i = [], 0, 0
            while i < len(doc):
                chunk.append(doc[i])
                chunk_len += len(doc[i])
                if i == len(doc) - 1 or chunk_len >= target:
                    if chunk:
                        rows.append(_pack_pair(
                            chunk, documents, d_idx, target, max_tokens,
                            rng))
                        # _pack_pair may push back unused segments
                        i -= rows[-1].pop("pushed_back")
                    chunk, chunk_len = [], 0
                i += 1

    n = len(rows)
    input_ids = np.zeros((n, max_seq_length), np.int32)
    token_type = np.zeros((n, max_seq_length), np.int32)
    attn = np.zeros((n, max_seq_length), np.float32)
    mlm_labels = np.full((n, max_seq_length), -1, np.int64)
    nsp = np.zeros((n,), np.int32)
    for r, row in enumerate(rows):
        a, b = row["a"], row["b"]
        seq = [cls_id] + a + [sep_id] + b + [sep_id]
        types = [0] * (len(a) + 2) + [1] * (len(b) + 1)
        special = np.zeros(len(seq), bool)
        special[0] = special[len(a) + 1] = special[-1] = True
        masked, labels = mask_tokens(
            np.asarray(seq, np.int64), special, rng, vocab_size, mask_id,
            masked_lm_prob=masked_lm_prob,
            max_predictions=max_predictions_per_seq)
        L = len(seq)
        input_ids[r, :L] = masked
        token_type[r, :L] = types
        attn[r, :L] = 1.0
        mlm_labels[r, :L] = labels
        nsp[r] = row["is_random"]
    return {"input_ids": input_ids, "token_type_ids": token_type,
            "attention_mask": attn,
            "mlm_labels": mlm_labels.reshape(-1),
            "nsp_labels": nsp}


def _pack_pair(chunk, documents, d_idx, target, max_tokens, rng):
    """Split a segment chunk into an (A, B) pair per the NSP recipe."""
    a_end = 1
    if len(chunk) >= 2:
        a_end = int(rng.integers(1, len(chunk)))
    a = [t for seg in chunk[:a_end] for t in seg]
    pushed_back = 0
    if len(chunk) == 1 or rng.random() < 0.5:
        # random next: B comes from another document
        is_random = 1
        other = d_idx
        if len(documents) > 1:
            for _ in range(10):
                other = int(rng.integers(0, len(documents)))
                if other != d_idx:
                    break
        if other == d_idx:
            is_random = 0
            b = [t for seg in chunk[a_end:] for t in seg]
        else:
            b = []
            odoc = documents[other]
            start = int(rng.integers(0, len(odoc)))
            for seg in odoc[start:]:
                b.extend(seg)
                if len(b) >= target - len(a):
                    break
            pushed_back = len(chunk) - a_end  # unused segments: replay
    else:
        is_random = 0
        b = [t for seg in chunk[a_end:] for t in seg]
    if not b:   # degenerate single-segment doc: split A itself
        half = max(1, len(a) // 2)
        a, b, is_random = a[:half], a[half:] or a[:1], 0
    # longest-first pair truncation, trimming front/back at random
    # (reference truncate_seq_pair)
    while len(a) + len(b) > max_tokens:
        longer = a if len(a) >= len(b) else b
        if rng.random() < 0.5:
            longer.pop(0)
        else:
            longer.pop()
    return {"a": a, "b": b, "is_random": is_random,
            "pushed_back": pushed_back}
