"""GLUE task processors: TSV -> tokenized rectangular feature arrays.

Reference: examples/nlp/bert/glue_processor/glue.py:1 — per-task
``DataProcessor`` subclasses reading the published GLUE TSV layouts into
``InputExample``s, then ``convert_examples_to_features`` building
CLS/SEP/segment/pad features.  This module keeps the same task coverage
and TSV column contracts (so downloaded GLUE data drops in unchanged)
but emits dense numpy arrays directly — the shape TPU feeds want.

Usage:
    proc = GLUE_PROCESSORS["sst-2"]()
    train = proc.train_examples(data_dir)
    feats = convert_examples_to_arrays(train, proc.labels(), tokenizer,
                                       max_seq_length=128)
    # feats.input_ids [N, S] int32, .token_type_ids, .attention_mask,
    # .label_ids [N]
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass

import numpy as np


@dataclass
class GlueExample:
    guid: str
    text_a: str
    text_b: str | None = None
    label: str | None = None


@dataclass
class GlueFeatures:
    """Rectangular batch-of-everything arrays (device-upload ready)."""

    input_ids: np.ndarray       # [N, S] int32
    token_type_ids: np.ndarray  # [N, S] int32
    attention_mask: np.ndarray  # [N, S] float32
    label_ids: np.ndarray       # [N] int32 (or float32 for regression)

    def __len__(self):
        return self.input_ids.shape[0]

    def batches(self, batch_size, *, shuffle=False, seed=0,
                drop_remainder=True):
        """Yield dict feeds of size ``batch_size``."""
        n = len(self)
        order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(order)
        stop = n - batch_size + 1 if drop_remainder else n
        for i in range(0, max(stop, 0), batch_size):
            sl = order[i:i + batch_size]
            yield {"input_ids": self.input_ids[sl],
                   "token_type_ids": self.token_type_ids[sl],
                   "attention_mask": self.attention_mask[sl],
                   "label_ids": self.label_ids[sl]}


def fetch_glue_task(data_dir, task, base_url, files=None, **kw):
    """Download one GLUE task's TSVs into ``data_dir`` through the
    resilient fetch path (atomic write + retry/backoff via
    ``resilience.retry``), using the task processor's declared file
    names so the layout matches ``train_examples``/``dev_examples``.
    The caller supplies the mirror ``base_url`` (zero-egress default);
    existing files are reused.  Returns the downloaded paths."""
    from ._io import fetch
    proc = GLUE_PROCESSORS[task.lower()]()
    names = tuple(files) if files else (proc.train_file, proc.dev_file)
    os.makedirs(data_dir, exist_ok=True)
    return [fetch(f"{base_url.rstrip('/')}/{name}",
                  os.path.join(data_dir, name), **kw)
            for name in names]


def _read_tsv(path, quotechar=None):
    with open(path, "r", encoding="utf-8") as f:
        return list(csv.reader(f, delimiter="\t", quotechar=quotechar))


class GlueProcessor:
    """Base: subclasses define the TSV column layout of one GLUE task."""

    train_file = "train.tsv"
    dev_file = "dev.tsv"

    def labels(self):
        raise NotImplementedError

    def _examples(self, rows, set_type):
        raise NotImplementedError

    def train_examples(self, data_dir):
        return self._examples(
            _read_tsv(os.path.join(data_dir, self.train_file)), "train")

    def dev_examples(self, data_dir):
        return self._examples(
            _read_tsv(os.path.join(data_dir, self.dev_file)), "dev")


class MrpcProcessor(GlueProcessor):
    """MRPC: paraphrase pairs; label col 0, sentences cols 3/4."""

    def labels(self):
        return ["0", "1"]

    def _examples(self, rows, set_type):
        return [GlueExample(f"{set_type}-{i}", r[3], r[4], r[0])
                for i, r in enumerate(rows) if i > 0]


class Sst2Processor(GlueProcessor):
    """SST-2: single sentence col 0, label col 1."""

    def labels(self):
        return ["0", "1"]

    def _examples(self, rows, set_type):
        return [GlueExample(f"{set_type}-{i}", r[0], None, r[1])
                for i, r in enumerate(rows) if i > 0]


class ColaProcessor(GlueProcessor):
    """CoLA: no header; sentence col 3, label col 1."""

    def labels(self):
        return ["0", "1"]

    def _examples(self, rows, set_type):
        return [GlueExample(f"{set_type}-{i}", r[3], None, r[1])
                for i, r in enumerate(rows)]


class MnliProcessor(GlueProcessor):
    """MNLI: premise/hypothesis cols 8/9, label last col."""

    dev_file = "dev_matched.tsv"

    def labels(self):
        return ["contradiction", "entailment", "neutral"]

    def _examples(self, rows, set_type):
        return [GlueExample(f"{set_type}-{r[0]}", r[8], r[9], r[-1])
                for i, r in enumerate(rows) if i > 0]


GLUE_PROCESSORS = {
    "mrpc": MrpcProcessor,
    "sst-2": Sst2Processor,
    "sst2": Sst2Processor,
    "cola": ColaProcessor,
    "mnli": MnliProcessor,
}


def convert_examples_to_arrays(examples, label_list, tokenizer,
                               max_seq_length):
    """Tokenize + featurize into rectangular arrays.

    Mirrors the reference's convert_examples_to_features contract
    (glue_processor/glue.py:230): [CLS] a [SEP] (b [SEP]), longest-first
    pair truncation (tokenizer.encode), zero-padded to max_seq_length.
    """
    label_map = {lab: i for i, lab in enumerate(label_list)}
    n = len(examples)
    ids = np.zeros((n, max_seq_length), np.int32)
    types = np.zeros((n, max_seq_length), np.int32)
    mask = np.zeros((n, max_seq_length), np.float32)
    labels = np.zeros((n,), np.int32)
    for i, ex in enumerate(examples):
        a, t, m = tokenizer.encode(ex.text_a, ex.text_b,
                                   max_len=max_seq_length)
        ids[i], types[i], mask[i] = a, t, m
        if ex.label is not None:
            labels[i] = label_map[ex.label]
    return GlueFeatures(ids, types, mask, labels)
