"""Real-format CTR dataset ingestion: Criteo display-advertising TSV and
Avazu click-through CSV.

Reference: examples/ctr/models/load_data.py (download_criteo /
process_dense_feats / process_sparse_feats / process_all_criteo_data —
the raw-TSV → dense[N,13] + global-id sparse[N,26] + labels contract)
and tools/EmbeddingMemoryCompression/models/load_data.py (Avazu).  The
published preprocessing recipe is reimplemented numpy-only (no
pandas/sklearn):

- dense I1..I13: missing → 0, then ``log(x+1) if x > -1 else -1``;
- sparse C14..C39: missing → "-1", per-field label encoding over the
  SORTED unique values (sklearn LabelEncoder's order), then each field
  offset by the cumulative unique counts so ids index ONE unified
  embedding table (full Criteo: 33.76M features — the scale documented
  in tools/EmbeddingMemoryCompression/README.md);
- shuffled split with the last 10% held out for evaluation.

Download steps are intentionally absent (zero-egress environment); point
the loaders at a local ``train.txt``/``train.gz`` shard.  A vendored
sample shard ships at examples/ctr/datasets/criteo_sample.txt so the
pipeline is exercisable offline end-to-end.
"""

from __future__ import annotations

import gzip
import os

import numpy as np

CRITEO_NUM_DENSE = 13
CRITEO_NUM_SPARSE = 26
AVAZU_NUM_SPARSE = 22      # all columns but id/click are categorical

_CACHE_FILES = ["train_dense_feats.npy", "train_sparse_feats.npy",
                "train_labels.npy", "test_dense_feats.npy",
                "test_sparse_feats.npy", "test_labels.npy"]


def _open_text(path):
    if str(path).endswith(".gz"):
        return gzip.open(path, "rt", encoding="utf-8", errors="replace")
    return open(path, encoding="utf-8", errors="replace")


def read_criteo_tsv(path, nrows=None):
    """Parse the raw Criteo TSV (``label\\tI1..I13\\tC14..C39``, no
    header, empty fields for missing values; .gz transparent).

    Returns (labels[N] float32, dense_raw[N,13] float64 with NaN for
    missing, sparse_raw[N,26] '<U8' with '-1' for missing)."""
    labels, dense, sparse = [], [], []
    with _open_text(path) as f:
        for i, line in enumerate(f):
            if nrows is not None and i >= nrows:
                break
            cols = line.rstrip("\n").split("\t")
            if len(cols) != 1 + CRITEO_NUM_DENSE + CRITEO_NUM_SPARSE:
                continue        # malformed/truncated line
            labels.append(np.float32(cols[0]))
            dense.append([float(c) if c else np.nan
                          for c in cols[1:1 + CRITEO_NUM_DENSE]])
            sparse.append([c if c else "-1"
                           for c in cols[1 + CRITEO_NUM_DENSE:]])
    return (np.asarray(labels, np.float32),
            np.asarray(dense, np.float64),
            np.asarray(sparse))


def process_dense_feats(dense_raw):
    """Reference recipe: missing → 0, then log1p for x > -1 else -1."""
    d = np.nan_to_num(dense_raw, nan=0.0)
    out = np.full_like(d, -1.0)
    np.log1p(d, where=d > -1, out=out)      # masked: no warning at x<=-1
    return out.astype(np.float32)


def encode_sparse_feats(sparse_raw):
    """Per-field label encoding (sorted unique, sklearn order) + field
    offsets by cumulative unique counts → GLOBAL ids into one table.

    Returns (ids[N,F] int32, field_dims list[int], num_features)."""
    n, num_fields = sparse_raw.shape
    ids = np.empty((n, num_fields), np.int64)
    field_dims = []
    offset = 0
    for f in range(num_fields):
        uniq, inv = np.unique(sparse_raw[:, f], return_inverse=True)
        ids[:, f] = inv + offset
        field_dims.append(len(uniq))
        offset += len(uniq)
    return ids.astype(np.int32), field_dims, offset


def process_criteo(path, nrows=None, return_val=True, seed=0,
                   cache_dir=None):
    """Raw TSV → the reference's processed-array contract.

    With ``return_val`` (the default):
    ``((train_dense, test_dense), (train_sparse, test_sparse),
    (train_labels, test_labels)), num_features`` — a shuffled 90/10
    split, matching process_all_criteo_data's return shape.  Without:
    ``(dense, sparse, labels), num_features``.

    ``cache_dir``: reuse/write the reference's .npy cache file set
    (train_dense_feats.npy, ...) so repeated runs skip the parse."""
    if cache_dir and all(os.path.exists(os.path.join(cache_dir, f))
                         for f in _CACHE_FILES + ["num_features.npy"]):
        a = [np.load(os.path.join(cache_dir, f)) for f in _CACHE_FILES]
        num_features = int(np.load(os.path.join(cache_dir,
                                                "num_features.npy")))
        if return_val:
            return ((a[0], a[3]), (a[1], a[4]), (a[2], a[5])), num_features
        dense = np.concatenate([a[0], a[3]])
        sparse = np.concatenate([a[1], a[4]])
        labels = np.concatenate([a[2], a[5]])
        return (dense, sparse, labels), num_features

    labels, dense_raw, sparse_raw = read_criteo_tsv(path, nrows)
    dense = process_dense_feats(dense_raw)
    sparse, _, num_features = encode_sparse_feats(sparse_raw)
    if not return_val:
        return (dense, sparse, labels), num_features
    n = len(labels)
    perm = np.random.default_rng(seed).permutation(n)
    n_test = max(1, n // 10)
    tr, te = perm[:-n_test], perm[-n_test:]
    split = ((dense[tr], dense[te]), (sparse[tr], sparse[te]),
             (labels[tr], labels[te]))
    if cache_dir:
        os.makedirs(cache_dir, exist_ok=True)
        arrays = [split[0][0], split[1][0], split[2][0],
                  split[0][1], split[1][1], split[2][1]]
        for fname, arr in zip(_CACHE_FILES, arrays):
            np.save(os.path.join(cache_dir, fname), arr)
        np.save(os.path.join(cache_dir, "num_features.npy"),
                np.int64(num_features))
    return split, num_features


def read_avazu_csv(path, nrows=None):
    """Parse the raw Avazu CSV (header ``id,click,hour,C1,...``; all
    feature columns categorical; .gz transparent).

    Returns (labels[N] float32, sparse_raw[N,22] strings)."""
    labels, sparse = [], []
    with _open_text(path) as f:
        header = f.readline().rstrip("\n").split(",")
        assert header[:2] == ["id", "click"], \
            f"not an Avazu CSV (header starts {header[:2]})"
        n_fields = len(header) - 2
        for i, line in enumerate(f):
            if nrows is not None and i >= nrows:
                break
            cols = line.rstrip("\n").split(",")
            if len(cols) != len(header):
                continue
            labels.append(np.float32(cols[1]))
            sparse.append([c if c else "-1" for c in cols[2:]])
    out = np.asarray(sparse)
    assert out.shape[1] == n_fields
    return np.asarray(labels, np.float32), out


def process_avazu(path, nrows=None, return_val=True, seed=0):
    """Raw Avazu CSV → global-id sparse arrays (no dense features).

    Returns ``((train_sparse, test_sparse), (train_labels,
    test_labels)), num_features`` (or unsplit without return_val)."""
    labels, sparse_raw = read_avazu_csv(path, nrows)
    sparse, _, num_features = encode_sparse_feats(sparse_raw)
    if not return_val:
        return (sparse, labels), num_features
    n = len(labels)
    perm = np.random.default_rng(seed).permutation(n)
    n_test = max(1, n // 10)
    tr, te = perm[:-n_test], perm[-n_test:]
    return ((sparse[tr], sparse[te]),
            (labels[tr], labels[te])), num_features


def make_sample_shard(path, n=2000, seed=0, kind="criteo"):
    """Write a synthetic shard in the EXACT raw format (for offline
    pipelines/tests; the vendored examples/ctr/datasets/criteo_sample.txt
    came from this with the default seed).  Labels carry real signal —
    a logistic model over latent feature effects — so held-out AUC is a
    meaningful pipeline check, and missing values appear exactly as in
    the wild (empty TSV fields / empty CSV cells)."""
    rng = np.random.default_rng(seed)
    if kind == "criteo":
        n_dense, n_sparse = CRITEO_NUM_DENSE, CRITEO_NUM_SPARSE
        card = rng.integers(4, 40, n_sparse)
    else:
        n_dense, n_sparse = 0, AVAZU_NUM_SPARSE
        card = rng.integers(4, 30, n_sparse)
    w_dense = rng.normal(0, 0.6, n_dense)
    effects = [rng.normal(0, 0.8, c) for c in card]
    lines = []
    if kind == "avazu":
        lines.append("id,click,hour," + ",".join(
            f"C{i}" for i in range(1, n_sparse)))
    for i in range(n):
        dense_raw = rng.poisson(3.0, n_dense).astype(np.float64)
        cats = [int((rng.zipf(1.5) - 1) % c) for c in card]
        logit = (np.log1p(dense_raw) @ w_dense * 0.5
                 + sum(e[c] for e, c in zip(effects, cats)) * 0.4
                 - 1.0)
        y = int(rng.random() < 1.0 / (1.0 + np.exp(-logit)))
        dmiss = rng.random(n_dense) < 0.1
        smiss = rng.random(n_sparse) < 0.05
        if kind == "criteo":
            dcols = ["" if m else str(int(v))
                     for v, m in zip(dense_raw, dmiss)]
            scols = ["" if m else format(0x10000 + c * 97 + f * 7919,
                                         "08x")
                     for f, (c, m) in enumerate(zip(cats, smiss))]
            lines.append("\t".join([str(y)] + dcols + scols))
        else:
            scols = ["" if m else f"v{c:04d}"
                     for c, m in zip(cats, smiss)]
            lines.append(",".join([format(i, "019d"), str(y),
                                   f"{14102100 + cats[0]:d}"] + scols[1:]))
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    return path
