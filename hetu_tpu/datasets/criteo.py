"""Real-format CTR dataset ingestion: Criteo display-advertising TSV and
Avazu click-through CSV.

Reference: examples/ctr/models/load_data.py (download_criteo /
process_dense_feats / process_sparse_feats / process_all_criteo_data —
the raw-TSV → dense[N,13] + global-id sparse[N,26] + labels contract)
and tools/EmbeddingMemoryCompression/models/load_data.py (Avazu).  The
published preprocessing recipe is reimplemented numpy-only (no
pandas/sklearn):

- dense I1..I13: missing → 0, then ``log(x+1) if x > -1 else -1``;
- sparse C14..C39: missing → "-1", per-field label encoding over the
  SORTED unique values (sklearn LabelEncoder's order), then each field
  offset by the cumulative unique counts so ids index ONE unified
  embedding table (full Criteo: 33.76M features — the scale documented
  in tools/EmbeddingMemoryCompression/README.md);
- shuffled split with the last 10% held out for evaluation.

Download steps are intentionally absent (zero-egress environment); point
the loaders at a local ``train.txt``/``train.gz`` shard.  A vendored
sample shard ships at examples/ctr/datasets/criteo_sample.txt so the
pipeline is exercisable offline end-to-end.
"""

from __future__ import annotations

import itertools
import json
import os

import numpy as np

from ._io import open_text

CRITEO_NUM_DENSE = 13
CRITEO_NUM_SPARSE = 26
AVAZU_NUM_SPARSE = 22      # all columns but id/click are categorical

_CACHE_FILES = ["train_dense_feats.npy", "train_sparse_feats.npy",
                "train_labels.npy", "test_dense_feats.npy",
                "test_sparse_feats.npy", "test_labels.npy"]


def _open_text(path):
    return open_text(path, errors="replace")


def fetch_criteo(dest, url, **kw):
    """Download a raw Criteo/Avazu shard to ``dest`` through the
    resilient fetch path (atomic ``.part`` + ``os.replace`` write,
    retry/backoff via ``resilience.retry``).  Zero-egress by default:
    the caller supplies the mirror URL; an existing ``dest`` is reused.
    Point the loaders above at the returned path."""
    from ._io import fetch
    return fetch(url, dest, **kw)


def _read_blocks(f, sep, ncols, nrows, block):
    """Yield [k, ncols] fixed-width numpy string arrays from a line
    iterator, ``block`` lines at a time.

    Chunking bounds the transient Python-object overhead to one block:
    the full Criteo train.txt is 45.8M rows, and accumulating per-row
    Python lists for all of it costs tens of GB before any array
    exists.  Each block's list-of-lists is converted by ``np.array``
    into a compact fixed-width string matrix and freed."""
    remaining = nrows if nrows is not None else float("inf")
    while remaining > 0:
        lines = list(itertools.islice(f, int(min(block, remaining))))
        if not lines:
            return
        rows = [cols for cols in (ln.rstrip("\n").split(sep)
                                  for ln in lines)
                if len(cols) == ncols]     # drop malformed lines
        if rows:
            yield np.array(rows)
        remaining -= len(lines)


def read_criteo_tsv(path, nrows=None, block=524_288):
    """Parse the raw Criteo TSV (``label\\tI1..I13\\tC14..C39``, no
    header, empty fields for missing values; .gz transparent), in
    bounded-memory blocks.

    Returns (labels[N] float32, dense_raw[N,13] float64 with NaN for
    missing, sparse_raw[N,26] strings with '-1' for missing)."""
    ncols = 1 + CRITEO_NUM_DENSE + CRITEO_NUM_SPARSE
    labels, dense, sparse = [], [], []
    with _open_text(path) as f:
        for a in _read_blocks(f, "\t", ncols, nrows, block):
            labels.append(a[:, 0].astype(np.float32))
            d = a[:, 1:1 + CRITEO_NUM_DENSE]
            dense.append(np.where(d == "", "nan", d).astype(np.float64))
            s = a[:, 1 + CRITEO_NUM_DENSE:]
            sparse.append(np.where(s == "", "-1", s))
    if not labels:
        return (np.empty(0, np.float32),
                np.empty((0, CRITEO_NUM_DENSE), np.float64),
                np.empty((0, CRITEO_NUM_SPARSE), "U2"))
    return (np.concatenate(labels), np.concatenate(dense),
            np.concatenate(sparse))


def process_dense_feats(dense_raw):
    """Reference recipe: missing → 0, then log1p for x > -1 else -1."""
    d = np.nan_to_num(dense_raw, nan=0.0)
    out = np.full_like(d, -1.0)
    np.log1p(d, where=d > -1, out=out)      # masked: no warning at x<=-1
    return out.astype(np.float32)


def encode_sparse_feats(sparse_raw):
    """Per-field label encoding (sorted unique, sklearn order) + field
    offsets by cumulative unique counts → GLOBAL ids into one table.

    Returns (ids[N,F] int32, field_dims list[int], num_features)."""
    n, num_fields = sparse_raw.shape
    ids = np.empty((n, num_fields), np.int64)
    field_dims = []
    offset = 0
    for f in range(num_fields):
        uniq, inv = np.unique(sparse_raw[:, f], return_inverse=True)
        ids[:, f] = inv + offset
        field_dims.append(len(uniq))
        offset += len(uniq)
    return ids.astype(np.int32), field_dims, offset


def _cache_key(path, nrows, seed):
    mtime = None
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        pass
    return {"path": os.path.abspath(path), "mtime": mtime,
            "nrows": nrows, "seed": seed}


def _cache_matches(cache_dir, path, nrows, seed):
    manifest_p = os.path.join(cache_dir, "manifest.json")
    if not all(os.path.exists(os.path.join(cache_dir, f))
               for f in _CACHE_FILES + ["num_features.npy"]):
        return False
    try:
        with open(manifest_p) as f:
            have = json.load(f)
    except (OSError, ValueError):
        return False    # missing/truncated manifest: re-parse, don't crash
    want = _cache_key(path, nrows, seed)
    if (have.get("path") != want["path"]
            or have.get("nrows") != want["nrows"]
            or have.get("seed") != want["seed"]):
        return False
    # source gone (cache copied to another box): trust the manifest;
    # source changed underneath: re-parse
    return want["mtime"] is None or have.get("mtime") == want["mtime"]


def process_criteo(path, nrows=None, return_val=True, seed=0,
                   cache_dir=None):
    """Raw TSV → the reference's processed-array contract.

    With ``return_val`` (the default):
    ``((train_dense, test_dense), (train_sparse, test_sparse),
    (train_labels, test_labels)), num_features`` — a shuffled 90/10
    split, matching process_all_criteo_data's return shape.  Without:
    ``(dense, sparse, labels), num_features``.

    ``cache_dir``: reuse/write the reference's .npy cache file set
    (train_dense_feats.npy, ...) so repeated runs skip the parse.  The
    cache carries a manifest keyed on (source path, mtime, nrows, seed)
    and is bypassed — re-parsed — when the request doesn't match it, so
    a stale cache can't silently substitute the wrong data."""
    if cache_dir and _cache_matches(cache_dir, path, nrows, seed):
        a = [np.load(os.path.join(cache_dir, f)) for f in _CACHE_FILES]
        num_features = int(np.load(os.path.join(cache_dir,
                                                "num_features.npy")))
        if return_val:
            return ((a[0], a[3]), (a[1], a[4]), (a[2], a[5])), num_features
        # the cache stores the SHUFFLED 90/10 split (train ++ test ==
        # raw[perm]); invert the split permutation so a cache-served
        # return_val=False read yields raw-file row order, identical to
        # a fresh parse (ADVICE r5: row order must not depend on
        # whether a prior return_val=True run populated the cache)
        dense = np.concatenate([a[0], a[3]])
        sparse = np.concatenate([a[1], a[4]])
        labels = np.concatenate([a[2], a[5]])
        perm = np.random.default_rng(seed).permutation(len(labels))
        inv = np.empty_like(perm)
        inv[perm] = np.arange(len(perm))
        return (dense[inv], sparse[inv], labels[inv]), num_features

    labels, dense_raw, sparse_raw = read_criteo_tsv(path, nrows)
    dense = process_dense_feats(dense_raw)
    sparse, _, num_features = encode_sparse_feats(sparse_raw)
    if not return_val:
        return (dense, sparse, labels), num_features
    n = len(labels)
    perm = np.random.default_rng(seed).permutation(n)
    n_test = max(1, n // 10)
    tr, te = perm[:-n_test], perm[-n_test:]
    split = ((dense[tr], dense[te]), (sparse[tr], sparse[te]),
             (labels[tr], labels[te]))
    if cache_dir:
        os.makedirs(cache_dir, exist_ok=True)
        # invalidate FIRST: if this rewrite dies midway, a stale
        # manifest must not validate the new/partial arrays
        try:
            os.remove(os.path.join(cache_dir, "manifest.json"))
        except OSError:
            pass
        arrays = [split[0][0], split[1][0], split[2][0],
                  split[0][1], split[1][1], split[2][1]]
        for fname, arr in zip(_CACHE_FILES, arrays):
            np.save(os.path.join(cache_dir, fname), arr)
        np.save(os.path.join(cache_dir, "num_features.npy"),
                np.int64(num_features))
        tmp = os.path.join(cache_dir, "manifest.json.tmp")
        with open(tmp, "w") as f:
            json.dump(_cache_key(path, nrows, seed), f)
        os.replace(tmp, os.path.join(cache_dir, "manifest.json"))
    return split, num_features


def read_avazu_csv(path, nrows=None, block=524_288):
    """Parse the raw Avazu CSV (header ``id,click,hour,C1,...``; all
    feature columns categorical; .gz transparent), in bounded-memory
    blocks (the full set is 40.4M rows).

    Returns (labels[N] float32, sparse_raw[N,F] strings)."""
    labels, sparse = [], []
    with _open_text(path) as f:
        header = f.readline().rstrip("\n").split(",")
        assert header[:2] == ["id", "click"], \
            f"not an Avazu CSV (header starts {header[:2]})"
        n_fields = len(header) - 2
        for a in _read_blocks(f, ",", len(header), nrows, block):
            labels.append(a[:, 1].astype(np.float32))
            s = a[:, 2:]
            sparse.append(np.where(s == "", "-1", s))
    if not labels:
        return (np.empty(0, np.float32), np.empty((0, n_fields), "U2"))
    return np.concatenate(labels), np.concatenate(sparse)


def process_avazu(path, nrows=None, return_val=True, seed=0):
    """Raw Avazu CSV → global-id sparse arrays (no dense features).

    Returns ``((train_sparse, test_sparse), (train_labels,
    test_labels)), num_features`` (or unsplit without return_val)."""
    labels, sparse_raw = read_avazu_csv(path, nrows)
    sparse, _, num_features = encode_sparse_feats(sparse_raw)
    if not return_val:
        return (sparse, labels), num_features
    n = len(labels)
    perm = np.random.default_rng(seed).permutation(n)
    n_test = max(1, n // 10)
    tr, te = perm[:-n_test], perm[-n_test:]
    return ((sparse[tr], sparse[te]),
            (labels[tr], labels[te])), num_features


def make_sample_shard(path, n=2000, seed=0, kind="criteo"):
    """Write a synthetic shard in the EXACT raw format (for offline
    pipelines/tests; the vendored examples/ctr/datasets/criteo_sample.txt
    came from this with the default seed).  Labels carry real signal —
    a logistic model over latent feature effects — so held-out AUC is a
    meaningful pipeline check, and missing values appear exactly as in
    the wild (empty TSV fields / empty CSV cells)."""
    rng = np.random.default_rng(seed)
    if kind == "criteo":
        n_dense, n_sparse = CRITEO_NUM_DENSE, CRITEO_NUM_SPARSE
        card = rng.integers(4, 40, n_sparse)
    else:
        n_dense, n_sparse = 0, AVAZU_NUM_SPARSE
        card = rng.integers(4, 30, n_sparse)
    w_dense = rng.normal(0, 0.6, n_dense)
    effects = [rng.normal(0, 0.8, c) for c in card]
    lines = []
    if kind == "avazu":
        lines.append("id,click,hour," + ",".join(
            f"C{i}" for i in range(1, n_sparse)))
    for i in range(n):
        dense_raw = rng.poisson(3.0, n_dense).astype(np.float64)
        cats = [int((rng.zipf(1.5) - 1) % c) for c in card]
        logit = (np.log1p(dense_raw) @ w_dense * 0.5
                 + sum(e[c] for e, c in zip(effects, cats)) * 0.4
                 - 1.0)
        y = int(rng.random() < 1.0 / (1.0 + np.exp(-logit)))
        dmiss = rng.random(n_dense) < 0.1
        smiss = rng.random(n_sparse) < 0.05
        if kind == "criteo":
            dcols = ["" if m else str(int(v))
                     for v, m in zip(dense_raw, dmiss)]
            scols = ["" if m else format(0x10000 + c * 97 + f * 7919,
                                         "08x")
                     for f, (c, m) in enumerate(zip(cats, smiss))]
            lines.append("\t".join([str(y)] + dcols + scols))
        else:
            scols = ["" if m else f"v{c:04d}"
                     for c, m in zip(cats, smiss)]
            lines.append(",".join([format(i, "019d"), str(y),
                                   f"{14102100 + cats[0]:d}"] + scols[1:]))
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    return path
