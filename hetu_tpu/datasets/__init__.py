"""NLP dataset pipelines: GLUE task processors + BERT pretraining features.

Reference: examples/nlp/bert/glue_processor/glue.py (task processors →
InputFeatures) and examples/nlp/bert/create_pretraining_data.py (corpus →
MLM/NSP training instances).  Re-designed as framework modules producing
dense numpy arrays ready for device upload (TPU feeds want rectangular
batches, not per-example Python objects).
"""

from .glue import (GlueExample, GlueFeatures, GLUE_PROCESSORS,
                   MrpcProcessor, Sst2Processor, ColaProcessor,
                   MnliProcessor, convert_examples_to_arrays)
from .pretraining import (create_pretraining_arrays,
                          documents_from_text_file, mask_tokens)
from .criteo import (read_criteo_tsv, process_criteo, read_avazu_csv,
                     process_avazu, process_dense_feats,
                     encode_sparse_feats, make_sample_shard)
from .prefetch import DevicePrefetcher, prefetch_feeds

__all__ = [
    "GlueExample", "GlueFeatures", "GLUE_PROCESSORS", "MrpcProcessor",
    "Sst2Processor", "ColaProcessor", "MnliProcessor",
    "convert_examples_to_arrays", "create_pretraining_arrays",
    "documents_from_text_file", "mask_tokens",
    "read_criteo_tsv", "process_criteo", "read_avazu_csv",
    "process_avazu", "process_dense_feats", "encode_sparse_feats",
    "make_sample_shard", "DevicePrefetcher", "prefetch_feeds",
]
