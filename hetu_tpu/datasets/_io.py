"""Shared ingestion helpers for the dataset loaders: transparent-gzip
text open and a resilient, atomic ``fetch``."""

from __future__ import annotations

import gzip
import os
import shutil
import urllib.request


def open_text(path, errors="strict"):
    """Open a text file, transparently gunzipping ``*.gz``."""
    if str(path).endswith(".gz"):
        return gzip.open(path, "rt", encoding="utf-8", errors=errors)
    return open(path, encoding="utf-8", errors=errors)


def fetch(url, dest, *, attempts=4, backoff=0.5, timeout=30.0,
          expected_bytes=None, overwrite=False):
    """Download ``url`` to ``dest`` atomically with retry/backoff.

    Transient I/O errors (reset connections, timeouts, 5xx) back off
    through ``resilience.retry`` instead of failing the run; the bytes
    land in a same-directory ``.part`` file and only an intact transfer
    is ``os.replace``d into place, so a torn download never masquerades
    as the dataset.  ``expected_bytes`` (when the mirror publishes it)
    turns a truncated transfer into a retryable error.  An existing
    ``dest`` short-circuits unless ``overwrite``.  Returns ``dest``.
    """
    from ..resilience.retry import retry

    dest = str(dest)
    if not overwrite and os.path.exists(dest):
        return dest
    parent = os.path.dirname(os.path.abspath(dest))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{dest}.part.{os.getpid()}"

    def _once():
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r, \
                    open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            size = os.path.getsize(tmp)
            if expected_bytes is not None and size != int(expected_bytes):
                raise OSError(
                    f"{url}: got {size} bytes, expected {expected_bytes} "
                    "— truncated transfer")
            os.replace(tmp, dest)
            return dest
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass    # partial already gone; nothing to clean

    # URLError/HTTPError/TimeoutError are all OSError subclasses
    return retry(_once, attempts=attempts, backoff=backoff, factor=2.0,
                 max_backoff=30.0, jitter=0.25,
                 retry_on=(OSError, ConnectionError))
