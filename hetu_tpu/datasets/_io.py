"""Shared text-ingestion helpers for the dataset loaders."""

from __future__ import annotations

import gzip


def open_text(path, errors="strict"):
    """Open a text file, transparently gunzipping ``*.gz``."""
    if str(path).endswith(".gz"):
        return gzip.open(path, "rt", encoding="utf-8", errors=errors)
    return open(path, encoding="utf-8", errors=errors)
