"""Async device-prefetch input pipeline: keep the host off the step path.

Round-5 benchmarks showed the framework winning on-device but losing
end-to-end (wdl 0.972x wall vs 1.082x device) — the host-side feed walk
and synchronous uploads sat on the critical path, the classic
recsys/input-bound profile (DLRM inference is dominated by input
handling, not FLOPs; arXiv:2512.05831).  Reference analogue:
hetu/dataloader.py prefetches batches through queues so workers never
wait on ingestion.

``DevicePrefetcher`` wraps ANY iterator of host batches and runs a
background thread that eagerly ``jax.device_put``s each one — with the
step's committed sharding when given, so dp/tp layouts land sharded
exactly as the compiled program expects and GSPMD never re-lays them
out.  A bounded queue (``depth``) provides back-pressure; ``close()``
shuts the thread down cleanly.  Under ``JAX_PLATFORMS=cpu`` (tests,
laptops) it falls back to synchronous puts by default — host "uploads"
to host memory buy nothing there and the thread only adds jitter.

``prefetch_feeds`` binds a prefetcher to an executor subgraph: leaves
are cast to each placeholder's declared dtype and placed with the
subgraph's committed input shardings, so the executor's steady-state
fast path (graph/executor.py) accepts them without any per-step
canonicalization — no host round-trip anywhere in the step.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .. import telemetry as _telemetry

_STOP = ("stop", None)


class DevicePrefetcher:
    """Double-buffered device uploader over any batch iterator.

    ``iterator`` yields host batches: a dict {key: array} (keys may be
    graph nodes or names — ready to pass to ``Executor.run`` as
    ``feed_dict``), a tuple/list of arrays, or a single array.
    ``sharding``/``dtype`` mirror the batch structure: a dict keyed like
    the batch (node or name), a per-position tuple, or one value for
    all leaves.  ``depth`` bounds how many device batches sit ready
    ahead of the consumer.  ``sync=None`` auto-selects: threaded on
    accelerators, synchronous under the CPU platform.
    """

    def __init__(self, iterator, depth=2, sharding=None, dtype=None,
                 sync=None):
        import jax
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source = iterator
        self._it = iter(iterator)
        self.depth = int(depth)
        self._sharding = sharding
        self._dtype = dtype
        if sync is None:
            sync = jax.default_backend() == "cpu"
        self.sync = bool(sync)
        self._queue = None
        self._thread = None
        self._stop = threading.Event()
        self._exhausted = False
        # runtime telemetry: queue depth was invisible through three
        # bench rounds ("does the producer keep up?") — now it's a live
        # gauge, with wait-time counters on both sides of the queue
        reg = _telemetry.get_registry()
        self._m_depth = reg.gauge(
            "hetu_prefetch_queue_depth",
            "Device batches ready ahead of the consumer")
        self._m_consumer_wait = reg.counter(
            "hetu_prefetch_consumer_wait_seconds_total",
            "Time the training loop spent waiting on the prefetch queue")
        self._m_producer_wait = reg.counter(
            "hetu_prefetch_producer_wait_seconds_total",
            "Time the producer thread spent blocked on a full queue")
        self._m_starved = reg.counter(
            "hetu_prefetch_starvation_total",
            "Consumer arrivals that found the queue empty (producer "
            "behind — the input pipeline is on the critical path)")
        self._m_batches = reg.counter(
            "hetu_prefetch_batches_total", "Batches handed to consumers")
        self._tr = _telemetry.get_tracer()

    # -- leaf placement ---------------------------------------------------
    @staticmethod
    def _lookup(spec, key):
        if spec is None or not isinstance(spec, dict):
            return spec
        if key in spec:
            return spec[key]
        name = getattr(key, "name", key)
        return spec.get(name)

    def _put_leaf(self, value, key=None):
        import jax
        import jax.numpy as jnp
        want = self._lookup(self._dtype, key)
        want = np.dtype(want) if want is not None else None
        if not isinstance(value, jax.Array) or (
                want is not None and value.dtype != want):
            value = jnp.asarray(value, dtype=want)
        sh = self._lookup(self._sharding, key)
        if sh is not None:
            value = jax.device_put(value, sh)
        return value

    def _put(self, batch):
        if isinstance(batch, dict):
            return {k: self._put_leaf(v, k) for k, v in batch.items()}
        if isinstance(batch, (tuple, list)):
            return type(batch)(self._put_leaf(v, i)
                               for i, v in enumerate(batch))
        return self._put_leaf(batch)

    # -- producer ---------------------------------------------------------
    def _enqueue(self, item):
        t0 = time.perf_counter()
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                self._m_producer_wait.inc(time.perf_counter() - t0)
                return
            except queue.Full:
                continue

    def _producer(self):
        while not self._stop.is_set():
            try:
                batch = next(self._it)
            except StopIteration:
                self._enqueue(_STOP)
                return
            except Exception as e:          # surface at the consumer
                self._enqueue(("err", e))
                return
            try:
                dev = self._put(batch)
            except Exception as e:
                self._enqueue(("err", e))
                return
            self._enqueue(("ok", dev))

    def skip_to_step(self, k):
        """Fast-forward to global batch ``k`` before the first pull —
        the elastic trainer's resume hook.  Delegates to the wrapped
        source's own ``skip_to_step`` when it has one (Dataloader: O(1),
        seed-stable); otherwise the wrapped iterator is advanced lazily
        with islice (O(k) pulls, skipped batches never uploaded)."""
        if self._thread is not None or self._queue is not None:
            raise RuntimeError(
                f"prefetcher: skip_to_step({k}) after the stream "
                "started — position the stream before the first pull")
        if k < 0:
            raise ValueError(f"skip_to_step: k must be >= 0, got {k}")
        skip = getattr(self._source, "skip_to_step", None)
        if callable(skip):
            skip(int(k))
            self._it = iter(self._source)
        else:
            import itertools
            self._it = itertools.islice(self._it, int(k), None)
        return self

    def start(self):
        if self.sync or self._thread is not None:
            return self
        self._queue = queue.Queue(maxsize=self.depth)
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()
        return self

    # -- consumer ---------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        if self.sync:
            # sync fallback: the iterator pull is data_wait, the
            # device_put is an honest host->device phase of its own
            with self._tr.span("data_wait"):
                try:
                    batch = next(self._it)
                except StopIteration:
                    self._exhausted = True
                    raise
            with self._tr.span("prefetch_h2d"):
                dev = self._put(batch)
            self._m_batches.inc()
            return dev
        self.start()
        if self._queue.empty():
            self._m_starved.inc()
        t0 = time.perf_counter()
        # bounded wait + liveness check: a producer that died WITHOUT
        # enqueuing a sentinel (killed worker, OOM, SystemExit escaping
        # the except Exception) must surface here within one step, not
        # hang the training loop forever on queue.get()
        with self._tr.span("data_wait"):
            while True:
                try:
                    kind, val = self._queue.get(timeout=0.2)
                    break
                except queue.Empty:
                    t = self._thread
                    if t is not None and t.is_alive():
                        continue
                    try:  # it may have enqueued between timeout and check
                        kind, val = self._queue.get_nowait()
                        break
                    except queue.Empty:
                        self._exhausted = True
                        raise RuntimeError(
                            "prefetch producer thread died without a "
                            "result or error sentinel (killed worker?) — "
                            "restart the prefetcher to resume") from None
        self._m_consumer_wait.inc(time.perf_counter() - t0)
        self._m_depth.set(self._queue.qsize())
        if kind == "stop":
            self._exhausted = True
            raise StopIteration
        if kind == "err":
            self._exhausted = True
            raise val
        self._m_batches.inc()
        return val

    next_batch = __next__    # Dataloader-style alias

    def close(self):
        """Stop the producer thread and release its queue slots.  Safe to
        call twice; after close the prefetcher raises StopIteration."""
        self._stop.set()
        self._exhausted = True
        t = self._thread
        if t is not None:
            try:                       # unblock a producer stuck on put()
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=2.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def prefetch_feeds(executor, iterator, subgraph=None, depth=2, sync=None):
    """Bind a ``DevicePrefetcher`` to an executor subgraph's committed
    input layout.

    ``iterator`` yields feed dicts ``{placeholder_or_name: host_batch}``.
    Each leaf is cast to its placeholder's declared dtype and
    ``device_put`` with the sharding the compiled step expects (the same
    in_shardings jit receives — mesh-aware for dp×tp layouts), so the
    upload overlaps the previous step and the executor's fast path swaps
    the buffers in without any per-step canonicalization::

        pf = prefetch_feeds(ex, batches(), "train", depth=2)
        for _ in range(steps):
            ex.run("train", feed_dict=next(pf))
        pf.close()
    """
    if subgraph is None:
        subgraph = next(iter(executor.subexecutor))
    sub = executor.subexecutor[subgraph]
    placeholders = getattr(sub, "placeholders", [])
    dtypes = {p.name: p.dtype for p in placeholders}
    sharding = None
    shardings = executor._input_shardings(sub)
    if shardings is not None:
        sharding = dict(shardings[2])       # feed shardings, by name
    return DevicePrefetcher(iterator, depth=depth, sharding=sharding,
                            dtype=dtypes, sync=sync)
