"""Data pipeline: prefetching, DP-aware batch feeding as graph nodes.

Reference: /root/reference/python/hetu/dataloader.py — `Dataloader` (:125)
slices the dataset by dp_rank/dp_nrank and prefetches batches through
multiprocess queues; `DataloaderOp` (:289) is a graph node whose value the
executor pulls per step (per named subgraph: 'default'/'train'/'validate').

TPU redesign: feeding is host-side (no kernels involved).  Plain batch
slicing runs on a background *thread* + bounded queue — numpy slicing
releases the GIL and the XLA step fully overlaps it; the queue depth plays
the role of the reference's batch_num prefetch window.  A Python
``transform`` (augmentation, tokenization) is GIL-BOUND, so
``num_workers>0`` switches to the reference's architecture (worker
processes + shared memory, dataloader.py:125): the dataset is published
once into a SharedMemory block, workers apply the transform and write
batches into a fixed ring of shared-memory slots (slot i%S guarded by an
empty/filled semaphore pair), and the consumer drains the ring in batch
order — deterministic regardless of worker timing.  `DataloaderOp`
follows the executor's placeholder-autofill protocol (same hook as
ps/embedding.PSRowsOp): the executor asks the node for the next batch
instead of requiring a feed.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from .graph.node import PlaceholderOp


def _mp_worker(worker_id, num_workers, start, stop, data_shm_name,
               data_shape, data_dtype, out_shm_name, out_shape, out_dtype,
               slots, empty_sems, filled_sems, batch_size, num_batches,
               shuffle, seed, transform):
    """Worker process body: handles batches i with i % num_workers ==
    worker_id, writing each into ring slot i % slots.  ``start`` shifts
    the global counter so a fast-forwarded stream (skip_to_step) resumes
    mid-epoch without replaying skipped batches."""
    from multiprocessing import shared_memory
    data_shm = shared_memory.SharedMemory(name=data_shm_name)
    out_shm = shared_memory.SharedMemory(name=out_shm_name)
    try:
        data = np.ndarray(data_shape, dtype=data_dtype, buffer=data_shm.buf)
        ring = np.ndarray((slots,) + out_shape, dtype=out_dtype,
                          buffer=out_shm.buf)
        # GLOBAL batch counter g (continuous across epochs): the consumer
        # drains slot g % slots in g order, so the slot index must come
        # from g, not the within-epoch index — the within-epoch form
        # collides as soon as num_batches % slots != 0.  The first g this
        # worker owns at/after ``start`` keeps the g % W == worker shard
        # assignment identical to a never-skipped run.
        g = start + ((worker_id - start) % num_workers)
        order, order_epoch = None, -1
        while not stop.is_set():
            epoch, i = divmod(g, num_batches)
            if epoch != order_epoch:
                # every worker derives the SAME per-epoch order from the
                # seed, so index-sharding keeps global order deterministic
                order = (np.random.default_rng((seed, epoch))
                         .permutation(data_shape[0])
                         if shuffle else np.arange(data_shape[0]))
                order_epoch = epoch
            sel = order[i * batch_size:(i + 1) * batch_size]
            batch = data[sel]
            if transform is not None:
                batch = np.asarray(transform(batch), dtype=out_dtype)
            slot = g % slots
            while not stop.is_set():
                if empty_sems[slot].acquire(timeout=0.1):
                    break
            else:
                return
            ring[slot] = batch
            filled_sems[slot].release()
            g += num_workers
    finally:
        data_shm.close()
        out_shm.close()


class _MPEngine:
    """Worker processes + shared-memory ring (reference dataloader.py:125
    multiprocess queues, rebuilt on SharedMemory instead of pickled Queue
    traffic — one copy out of the ring per batch, zero per-batch pickling)."""

    def __init__(self, data, batch_size, num_batches, shuffle, seed,
                 num_workers, prefetch, transform, start=0):
        import multiprocessing as mp
        from multiprocessing import shared_memory
        # spawn: never fork a process that may hold a live XLA client
        self._mp = mp.get_context("spawn")
        self.num_batches = num_batches
        if data.shape[0] < num_batches * batch_size:
            # a ragged tail batch can't share the fixed-shape ring slots
            # (and XLA would retrace on it anyway)
            raise ValueError(
                "num_workers > 0 requires drop_last=True (ragged final "
                f"batch: {data.shape[0]} rows, batch {batch_size})")
        # ring slots: >= the worker fan-out (a worker blocking on a slot
        # must not deadlock the ring) AND a MULTIPLE of num_workers — the
        # consumer's slot-(g % slots) discipline assumes slot s is always
        # refilled by the same worker ((g + slots) % W == g % W); with an
        # indivisible slot count a fast worker could steal a slot one
        # epoch ahead and the consumer would read the wrong batch
        slots = max(2 * num_workers, int(prefetch))
        slots += (-slots) % num_workers
        probe = data[:batch_size]
        if transform is not None:
            probe = np.asarray(transform(probe))
        self._out_shape = probe.shape
        self._out_dtype = probe.dtype
        self._data_shm = shared_memory.SharedMemory(
            create=True, size=data.nbytes)
        np.ndarray(data.shape, data.dtype,
                   buffer=self._data_shm.buf)[...] = data
        self._out_shm = shared_memory.SharedMemory(
            create=True, size=int(np.prod((slots,) + probe.shape)
                                  * probe.dtype.itemsize))
        self._ring = np.ndarray((slots,) + probe.shape, probe.dtype,
                                buffer=self._out_shm.buf)
        self._slots = slots
        self._stop = self._mp.Event()
        self._empty = [self._mp.Semaphore(1) for _ in range(slots)]
        self._filled = [self._mp.Semaphore(0) for _ in range(slots)]
        self._procs = [
            self._mp.Process(
                target=_mp_worker,
                args=(w, num_workers, int(start), self._stop,
                      self._data_shm.name, data.shape, data.dtype,
                      self._out_shm.name, probe.shape, probe.dtype, slots,
                      self._empty, self._filled, batch_size, num_batches,
                      shuffle, seed, transform),
                daemon=True)
            for w in range(num_workers)]
        for p in self._procs:
            p.start()
        self._cursor = int(start)

    def next_batch(self):
        slot = self._cursor % self._slots
        self._filled[slot].acquire()
        batch = self._ring[slot].copy()
        self._empty[slot].release()
        self._cursor += 1
        return batch

    def stop(self):
        self._stop.set()
        for p in self._procs:
            p.join(timeout=2.0)
            if p.is_alive():
                p.terminate()
        for shm in (self._data_shm, self._out_shm):
            try:
                shm.close()
                shm.unlink()
            except FileNotFoundError:
                pass


class Dataloader:
    """Batched, optionally shuffled, DP-sliced iterator with prefetch.

    ``raw_data``: numpy array [N, ...].  ``dp_rank``/``dp_nrank`` shard the
    dataset like the reference (each data-parallel worker sees its slice).
    ``drop_last`` keeps shapes static for XLA (the reference re-plans on
    shape change; we default to dropping the ragged tail and only retrace
    when the user opts into it).
    """

    def __init__(self, raw_data, batch_size, shuffle=False, drop_last=True,
                 dp_rank=0, dp_nrank=1, seed=0, prefetch=2, name="data",
                 device_prefetch=False, dtype=None, transform=None,
                 num_workers=0, sharding=None):
        data = np.asarray(raw_data)
        if dp_nrank > 1:
            # contiguous equal shards; tail dropped so every rank agrees
            per = data.shape[0] // dp_nrank
            data = data[dp_rank * per:(dp_rank + 1) * per]
        self.data = data
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.name = name
        # device_prefetch: the producer thread uploads each batch with
        # jax.device_put as soon as it's sliced, so the host->device copy
        # overlaps the previous step instead of landing on the critical
        # path (on a remote-tunnel chip a per-step synchronous upload
        # costs a full link round trip; on TPU-VM it's PCIe time).
        # ``sharding``: the committed layout for the batch (a
        # jax.sharding.Sharding) — under a dp/tp mesh the upload lands
        # sharded exactly as the compiled step's in_shardings expect,
        # instead of single-device + GSPMD reshard.
        self.device_prefetch = device_prefetch
        self.sharding = sharding
        self.dtype = dtype
        # transform: per-batch augmentation/tokenization callable.  Pure
        # Python transforms are GIL-bound — pair with num_workers>0 to
        # run them in worker processes (reference dataloader.py:125);
        # must be picklable (module-level function) in that case.
        self.transform = transform
        self.num_workers = int(num_workers)
        self._seed = seed + dp_rank
        self._prefetch = prefetch
        self._queue = queue.Queue(maxsize=prefetch)
        self._thread = None
        self._engine = None
        self._stop = threading.Event()
        self._start_batch = 0
        if self.num_batches == 0:
            raise ValueError(
                f"dataloader '{name}': shard of {data.shape[0]} rows "
                f"(dp_rank {dp_rank}/{dp_nrank}) yields no "
                f"batches of size {batch_size}")

    @property
    def num_batches(self):
        n = self.data.shape[0]
        return n // self.batch_size if self.drop_last else \
            (n + self.batch_size - 1) // self.batch_size

    # reference API names ---------------------------------------------------
    def get_batch_num(self, name=None):
        return self.num_batches

    def _epoch_perm(self, epoch):
        # keyed by (seed, epoch) — the exact stream the MP workers use, so
        # thread and process engines yield identical batch sequences
        return (np.random.default_rng((self._seed, epoch))
                .permutation(self.data.shape[0])
                if self.shuffle else np.arange(self.data.shape[0]))

    def skip_to_step(self, k):
        """Fast-forward the stream to global batch ``k`` in O(1) — the
        elastic trainer's resume hook: batch k of a skipped stream is
        bitwise the batch k an uninterrupted run would have produced,
        because every batch is a pure function of (seed, k) via the
        per-epoch permutation.  Must be called before the stream starts
        (no replaying a live queue)."""
        if self._thread is not None or self._engine is not None:
            raise RuntimeError(
                f"dataloader '{self.name}': skip_to_step({k}) after the "
                "stream started — position the stream before the first "
                "next_batch()/start()")
        if k < 0:
            raise ValueError(f"skip_to_step: k must be >= 0, got {k}")
        self._start_batch = int(k)
        return self

    def _producer(self):
        epoch, start_i = divmod(self._start_batch, self.num_batches)
        while not self._stop.is_set():
            order = self._epoch_perm(epoch)
            epoch += 1
            for i in range(start_i, self.num_batches):
                if self._stop.is_set():
                    return
                sel = order[i * self.batch_size:(i + 1) * self.batch_size]
                batch = self.data[sel]
                if self.transform is not None:
                    batch = np.asarray(self.transform(batch))
                if self.device_prefetch:
                    batch = self._to_device(batch)
                while not self._stop.is_set():
                    try:
                        self._queue.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
            start_i = 0

    def start(self):
        if self.num_workers > 0:
            if self._engine is None:
                self._engine = _MPEngine(
                    self.data, self.batch_size, self.num_batches,
                    self.shuffle, self._seed, self.num_workers,
                    self._prefetch, self.transform,
                    start=self._start_batch)
            return self
        if self._thread is None:
            self._thread = threading.Thread(target=self._producer,
                                            daemon=True)
            self._thread.start()
        return self

    def _to_device(self, batch):
        import jax
        import jax.numpy as jnp
        batch = jnp.asarray(batch, dtype=self.dtype)
        if self.sharding is not None:
            return jax.device_put(batch, self.sharding)
        return jax.device_put(batch)

    def next_batch(self):
        self.start()
        if self._engine is not None:
            batch = self._engine.next_batch()
            if self.device_prefetch:
                batch = self._to_device(batch)
            return batch
        return self._queue.get()

    def stop(self):
        self._stop.set()
        if self._engine is not None:
            self._engine.stop()
            self._engine = None

    @property
    def batch_shape(self):
        """[batch, ...] shape AFTER the transform (what the graph sees)."""
        base = (self.batch_size,) + self.data.shape[1:]
        if self.transform is None:
            return base
        return np.asarray(
            self.transform(self.data[:self.batch_size])).shape

    def __iter__(self):
        """Single-epoch iteration without the prefetch machinery (eval
        loops); honors a prior :meth:`skip_to_step` by yielding the
        remainder of the positioned epoch."""
        epoch, start_i = divmod(self._start_batch, self.num_batches)
        order = self._epoch_perm(epoch)
        for i in range(start_i, self.num_batches):
            sel = order[i * self.batch_size:(i + 1) * self.batch_size]
            batch = self.data[sel]
            if self.transform is not None:
                batch = np.asarray(self.transform(batch))
            yield batch


class DataloaderOp(PlaceholderOp):
    """Graph node auto-fed from a Dataloader (reference DataloaderOp :289).

    ``dataloaders``: either one Dataloader or {subgraph_name: Dataloader}
    (the reference keys batch streams by named subexecutor: train/validate).
    The executor recognizes the ``auto_feed`` hook and pulls the next batch
    when the user did not feed the node explicitly.
    """

    __slots__ = ("dataloaders",)

    def __init__(self, dataloaders, dtype=np.float32, name=None):
        if not isinstance(dataloaders, dict):
            dataloaders = {"default": dataloaders}
        self.dataloaders = dataloaders
        some = next(iter(dataloaders.values()))
        super().__init__(name or f"dataloader_{some.name}",
                         shape=tuple(some.batch_shape), dtype=dtype)

    def auto_feed(self, subgraph_name):
        dl = self.dataloaders.get(subgraph_name)
        if dl is None:
            dl = self.dataloaders.get("default")
        if dl is None:
            raise ValueError(
                f"DataloaderOp {self.name} has no stream for subgraph "
                f"'{subgraph_name}' (streams: {list(self.dataloaders)})")
        return dl.next_batch()


def dataloader_op(dataloaders, dtype=np.float32, name=None):
    return DataloaderOp(dataloaders, dtype=dtype, name=name)
