"""Data pipeline: prefetching, DP-aware batch feeding as graph nodes.

Reference: /root/reference/python/hetu/dataloader.py — `Dataloader` (:125)
slices the dataset by dp_rank/dp_nrank and prefetches batches through
multiprocess queues; `DataloaderOp` (:289) is a graph node whose value the
executor pulls per step (per named subgraph: 'default'/'train'/'validate').

TPU redesign: feeding is host-side (no kernels involved), so the pipeline is
a background *thread* + bounded queue per dataloader — processes buy nothing
here because batch assembly is numpy slicing (GIL-releasing) and the XLA
step fully overlaps it; the queue depth plays the role of the reference's
batch_num prefetch window.  `DataloaderOp` follows the executor's
placeholder-autofill protocol (same hook as ps/embedding.PSRowsOp): the
executor asks the node for the next batch instead of requiring a feed.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from .graph.node import PlaceholderOp


class Dataloader:
    """Batched, optionally shuffled, DP-sliced iterator with prefetch.

    ``raw_data``: numpy array [N, ...].  ``dp_rank``/``dp_nrank`` shard the
    dataset like the reference (each data-parallel worker sees its slice).
    ``drop_last`` keeps shapes static for XLA (the reference re-plans on
    shape change; we default to dropping the ragged tail and only retrace
    when the user opts into it).
    """

    def __init__(self, raw_data, batch_size, shuffle=False, drop_last=True,
                 dp_rank=0, dp_nrank=1, seed=0, prefetch=2, name="data",
                 device_prefetch=False, dtype=None):
        data = np.asarray(raw_data)
        if dp_nrank > 1:
            # contiguous equal shards; tail dropped so every rank agrees
            per = data.shape[0] // dp_nrank
            data = data[dp_rank * per:(dp_rank + 1) * per]
        self.data = data
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.name = name
        # device_prefetch: the producer thread uploads each batch with
        # jax.device_put as soon as it's sliced, so the host->device copy
        # overlaps the previous step instead of landing on the critical
        # path (on a remote-tunnel chip a per-step synchronous upload
        # costs a full link round trip; on TPU-VM it's PCIe time)
        self.device_prefetch = device_prefetch
        self.dtype = dtype
        self._rng = np.random.default_rng(seed + dp_rank)
        self._queue = queue.Queue(maxsize=prefetch)
        self._epoch_order = None
        self._cursor = 0
        self._thread = None
        self._stop = threading.Event()
        if self.num_batches == 0:
            raise ValueError(
                f"dataloader '{name}': shard of {data.shape[0]} rows "
                f"(dp_rank {dp_rank}/{dp_nrank}) yields no "
                f"batches of size {batch_size}")

    @property
    def num_batches(self):
        n = self.data.shape[0]
        return n // self.batch_size if self.drop_last else \
            (n + self.batch_size - 1) // self.batch_size

    # reference API names ---------------------------------------------------
    def get_batch_num(self, name=None):
        return self.num_batches

    def _producer(self):
        while not self._stop.is_set():
            order = (self._rng.permutation(self.data.shape[0])
                     if self.shuffle else np.arange(self.data.shape[0]))
            for i in range(self.num_batches):
                if self._stop.is_set():
                    return
                sel = order[i * self.batch_size:(i + 1) * self.batch_size]
                batch = self.data[sel]
                if self.device_prefetch:
                    import jax
                    import jax.numpy as jnp
                    batch = jax.device_put(
                        jnp.asarray(batch, dtype=self.dtype))
                while not self._stop.is_set():
                    try:
                        self._queue.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._producer,
                                            daemon=True)
            self._thread.start()
        return self

    def next_batch(self):
        self.start()
        return self._queue.get()

    def stop(self):
        self._stop.set()

    def __iter__(self):
        """Single-epoch iteration without the prefetch thread (eval loops)."""
        order = (self._rng.permutation(self.data.shape[0])
                 if self.shuffle else np.arange(self.data.shape[0]))
        for i in range(self.num_batches):
            sel = order[i * self.batch_size:(i + 1) * self.batch_size]
            yield self.data[sel]


class DataloaderOp(PlaceholderOp):
    """Graph node auto-fed from a Dataloader (reference DataloaderOp :289).

    ``dataloaders``: either one Dataloader or {subgraph_name: Dataloader}
    (the reference keys batch streams by named subexecutor: train/validate).
    The executor recognizes the ``auto_feed`` hook and pulls the next batch
    when the user did not feed the node explicitly.
    """

    __slots__ = ("dataloaders",)

    def __init__(self, dataloaders, dtype=np.float32, name=None):
        if not isinstance(dataloaders, dict):
            dataloaders = {"default": dataloaders}
        self.dataloaders = dataloaders
        some = next(iter(dataloaders.values()))
        shape = (some.batch_size,) + some.data.shape[1:]
        super().__init__(name or f"dataloader_{some.name}", shape=shape,
                         dtype=dtype)

    def auto_feed(self, subgraph_name):
        dl = self.dataloaders.get(subgraph_name)
        if dl is None:
            dl = self.dataloaders.get("default")
        if dl is None:
            raise ValueError(
                f"DataloaderOp {self.name} has no stream for subgraph "
                f"'{subgraph_name}' (streams: {list(self.dataloaders)})")
        return dl.next_batch()


def dataloader_op(dataloaders, dtype=np.float32, name=None):
    return DataloaderOp(dataloaders, dtype=dtype, name=name)
