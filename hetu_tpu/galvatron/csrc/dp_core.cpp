// Native dynamic-programming core for per-layer hybrid-parallel strategy
// search (Galvatron-style).
//
// Reference behavior: tools/Hetu-Galvatron/csrc/dp_core.cpp:22
// `dynamic_programming_core` — a knapsack-style DP over
// (layer, memory-budget, strategy) minimizing estimated iteration time, with
// a per-layer intra-strategy cost, a strategy-transition (resharding) cost
// between adjacent layers, and integer per-layer memory consumption capping
// the budget.  The reference binds it with pybind11; pybind11 is not in this
// image, so this implementation exposes a plain C ABI loaded via ctypes
// (hetu_tpu/galvatron/build.py).  Code is original; only the DP recurrence
// semantics are kept for parity.
//
//   f[v][s]    = best total time for the processed prefix of layers, ending
//                in strategy s with v memory units consumed so far available
//   mark[i][v][s] = argmin predecessor strategy for backtracking
//
// Returns 0 on success (-1 if no feasible assignment fits max_mem); the
// chosen strategy per layer is written into res[], the optimal cost into
// *cost_out, and the leftover memory into *mem_left_out.

#include <algorithm>
#include <cstdint>
#include <limits>
#include <vector>

namespace {
constexpr double kInf = std::numeric_limits<double>::infinity();
}

extern "C" {

// layer_num   L
// max_mem     V   (integer memory budget, discretized units)
// strategy_num S
// mem_cost    [L*S]   int32  per-layer memory units under each strategy
// intra_cost  [L*S]   double per-layer compute(+comm) time under strategy
// inter_cost  [L*S*S] double transition cost layer i-1 (strategy si) -> layer i (strategy s)
//                     (inter_cost[i*S*S + si*S + s]; row i=0 is ignored)
// res         [L]     int32  out: chosen strategy per layer
int galvatron_dp_core(int64_t layer_num, int64_t max_mem, int64_t strategy_num,
                      const int32_t* mem_cost, const double* intra_cost,
                      const double* inter_cost, int32_t* res,
                      double* cost_out, int64_t* mem_left_out) {
  const int64_t L = layer_num, V = max_mem, S = strategy_num;
  if (L <= 0 || V <= 0 || S <= 0) return -1;

  // two explicit buffers (layer i-1 / layer i): a rolling array would alias
  // the row being written whenever a strategy's mem_cost is 0
  std::vector<double> f_prev(static_cast<size_t>(V) * S, 0.0);
  std::vector<double> f(static_cast<size_t>(V) * S, 0.0);
  std::vector<int32_t> mark(static_cast<size_t>(L) * V * S, -1);

  for (int64_t i = 0; i < L; ++i) {
    for (int64_t v = V - 1; v >= 0; --v) {
      for (int64_t s = 0; s < S; ++s) {
        const int32_t m = mem_cost[i * S + s];
        double* fvs = &f[v * S + s];
        if (v < m) {
          *fvs = kInf;
          continue;
        }
        const double* prev = &f_prev[(v - m) * S];
        double best = kInf;
        int32_t best_si = -1;
        if (i == 0) {
          // no predecessor layer: f starts at 0, no transition cost
          best = prev[s];
          best_si = static_cast<int32_t>(s);
        } else {
          for (int64_t si = 0; si < S; ++si) {
            const double cand = prev[si] + inter_cost[i * S * S + si * S + s];
            if (cand < best) {
              best = cand;
              best_si = static_cast<int32_t>(si);
            }
          }
        }
        if (best_si >= 0 && best < kInf) {
          *fvs = best + intra_cost[i * S + s];
          mark[(i * V + v) * S + s] = best_si;
        } else {
          *fvs = kInf;
        }
      }
    }
    std::swap(f_prev, f);
  }
  std::swap(f_prev, f);  // undo the last swap: f holds layer L-1

  // pick the best terminal strategy at full budget
  const double* last = &f[(V - 1) * S];
  int64_t cur = std::min_element(last, last + S) - last;
  double total = last[cur];
  if (!(total < kInf)) {
    *cost_out = kInf;
    *mem_left_out = -1;
    return -1;
  }

  int64_t v = V - 1;
  res[L - 1] = static_cast<int32_t>(cur);
  for (int64_t i = L - 1; i > 0; --i) {
    const int32_t prev_s = mark[(i * V + v) * S + cur];
    v -= mem_cost[i * S + cur];
    cur = prev_s;
    res[i - 1] = static_cast<int32_t>(cur);
  }
  v -= mem_cost[0 * S + cur];

  *cost_out = total;
  *mem_left_out = v;
  return 0;
}

}  // extern "C"
