"""Galvatron-equivalent per-layer hybrid-parallel layer (reference:
tools/Hetu-Galvatron — search in csrc/dp_core.cpp + galvatron/core, runtime
in galvatron/core/{parallel,pipeline,comm_groups}.py), re-designed for TPU
meshes: per-layer (tp, DDP|FSDP, checkpoint) strategies expressed as
PartitionSpecs on a binary-factorized mesh inside one SPMD program."""

from .build import dp_core, dp_core_auto, dp_core_numpy
from .config import HybridParallelConfig, layer_mesh_axes, tp_dp_axes
from .search import (CostModel, GalvatronSearch, LayerProfile,
                     ProfileError, Strategy,
                     load_profile, load_profile_doc, measure_ici_gbps,
                     profile_layers_analytic, profile_hp_layers,
                     save_profile,
                     strategy_space)
from .runtime import (HybridParallelModel, LayerShardings,
                      TransformerHPLayer, LlamaHPLayer, VocabEmbedHPSpec,
                      LMHeadHPSpec, lm_cross_entropy, lm_wrap_config,
                      make_lm_hybrid_model, build_mesh)

__all__ = [
    "dp_core", "dp_core_auto", "dp_core_numpy", "HybridParallelConfig", "layer_mesh_axes",
    "tp_dp_axes", "CostModel", "GalvatronSearch", "LayerProfile", "Strategy",
    "load_profile", "load_profile_doc", "measure_ici_gbps",
    "ProfileError",
    "profile_layers_analytic", "profile_hp_layers",
    "save_profile",
    "strategy_space", "HybridParallelModel", "LayerShardings",
    "TransformerHPLayer", "LlamaHPLayer", "VocabEmbedHPSpec",
    "LMHeadHPSpec", "lm_cross_entropy", "lm_wrap_config",
    "make_lm_hybrid_model", "build_mesh",
]
