"""Per-layer hybrid-parallel runtime on a factorized TPU mesh.

Reference: tools/Hetu-Galvatron/galvatron/core — the PyTorch runtime builds
per-layer TP×DP process groups (comm_groups.py:58-196), wraps each layer in
Megatron-TP modules + DDP/FSDP (parallel.py:166), inserts activation
redistribution between layers of different TP size (parallel.py:138,
redistribute.py), and drives GPipe/1F1B schedules (pipeline/pipeline.py:23).

TPU redesign — ONE SPMD program instead of process groups:

  * mesh = ("pp", "m0", ..., "m{k-1}") with k binary axes; a layer with
    tp=2^t takes t binary axes for tensor parallel and the rest for data
    parallel (config.tp_dp_axes).  Different layers → different
    PartitionSpecs, same program.
  * Megatron column/row-parallel matmuls need no hand-written collectives:
    weights carry shardings, activations carry with_sharding_constraint
    boundaries, and GSPMD inserts the all-reduce/all-gather — the manual
    f/g autograd functions of megatron mappings.py are the compiler's job.
  * DDP vs FSDP(zero-3) is purely a parameter-sharding choice: FSDP shards
    params over the dp axes too; XLA all-gathers at use and reduce-scatters
    gradients.
  * activation "redistribution" between adjacent layers of different tp
    = a sharding-constraint change.
  * checkpoint flag → jax.checkpoint on the layer body.
  * grad accumulation over `chunks` micro-batches via lax.scan; with
    pp_deg>1 and homogeneous stages the existing spmd pipeline
    (parallel/pipeline.py) provides the schedule.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .config import HybridParallelConfig, layer_mesh_axes, tp_dp_axes


def build_mesh(config: HybridParallelConfig, devices=None):
    if devices is None:
        devices = jax.devices()
    world = config.world or len(devices)
    k, maxes = layer_mesh_axes(world, config.pp_deg)
    names = ("pp",) + maxes
    sizes = (config.pp_deg,) + (2,) * k
    arr = np.array(devices[:world]).reshape(sizes)
    return Mesh(arr, names)


class LayerShardings:
    """PartitionSpecs for one layer under its searched strategy.

    ``mesh`` is the layer's execution mesh: the full (pp-less) mesh when
    pp_deg==1, or the layer's stage submesh (axes m0..mk-1) when the
    model is pipelined — per-layer TP×DP lives INSIDE a stage, exactly
    like the reference's per-layer groups within a pp rank range
    (comm_groups.py gen_tp_group_dist)."""

    def __init__(self, mesh, config, layer_idx):
        maxes = tuple(n for n in mesh.axis_names if n != "pp")
        k = len(maxes)
        tp = config.tp_sizes[layer_idx]
        consec = config.tp_consecutive[layer_idx]
        self.dp_axes, self.tp_axes = tp_dp_axes(k, maxes, tp, consec)
        self.fsdp = bool(config.dp_types[layer_idx])
        self.ckpt = bool(config.checkpoint_flags[layer_idx])
        # Megatron SP (reference transformer.py sequence_parallel): the
        # residual stream is seq-sharded over the tp axes; GSPMD turns the
        # entry to column-parallel matmuls into an all-gather and the exit
        # from row-parallel ones into a reduce-scatter (same ring bytes as
        # the plain-TP allreduce, 1/tp the LN/residual memory).
        self.sp = bool(config.sp_flags[layer_idx]) and bool(self.tp_axes)
        self.mesh = mesh

    def _axes(self, axes):
        return tuple(axes) if len(axes) != 1 else axes[0]

    def param_spec(self, tp_dim, ndim, fsdp_dim=None):
        """Spec for a parameter: shard ``tp_dim`` over the tp axes; under
        FSDP additionally shard ``fsdp_dim`` (default: first non-tp dim)
        over the dp axes."""
        spec = [None] * ndim
        if tp_dim is not None and self.tp_axes:
            spec[tp_dim] = self._axes(self.tp_axes)
        if self.fsdp and self.dp_axes:
            if fsdp_dim is None:
                fsdp_dim = next((d for d in range(ndim) if d != tp_dim), None)
            if fsdp_dim is not None and spec[fsdp_dim] is None:
                spec[fsdp_dim] = self._axes(self.dp_axes)
        return P(*spec)

    def act_spec(self, ndim, seq_shard=False):
        """Activations: batch over dp axes (+ optionally seq over tp axes =
        Megatron sequence parallelism for the LN/dropout segments)."""
        spec = [None] * ndim
        if self.dp_axes:
            spec[0] = self._axes(self.dp_axes)
        if seq_shard and self.tp_axes and ndim >= 2:
            spec[1] = self._axes(self.tp_axes)
        return P(*spec)

    def constrain(self, x, seq_shard=None):
        """Residual-stream constraint; seq_shard defaults to the layer's
        sequence-parallel flag."""
        if seq_shard is None:
            seq_shard = self.sp
        return lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, self.act_spec(x.ndim, seq_shard)))


def _layer_norm(x, g):
    """Shared LN (no bias): used by the transformer blocks AND the LM
    head so eps/dtype behavior can never drift between body and head."""
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * g


def _rms_norm(x, g):
    """Shared RMSNorm (f32 accumulation, Llama convention)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), -1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + 1e-5)).astype(x.dtype) * g


class TransformerHPLayer:
    """A Megatron-parallel transformer layer as an HP layer spec.

    Column-parallel QKV/FFN-in (shard output dim), row-parallel proj/FFN-out
    (shard input dim); GSPMD materializes the g/f collectives.
    Reference: galvatron/core/tensor_parallel/transformer.py.
    """

    def __init__(self, hidden, heads, ffn=None, dtype=jnp.float32):
        self.hidden, self.heads = hidden, heads
        self.ffn = ffn or 4 * hidden
        self.dtype = dtype

    def init(self, key):
        h, f = self.hidden, self.ffn
        ks = jax.random.split(key, 4)
        s = 0.02
        return {
            "wqkv": jax.random.normal(ks[0], (h, 3 * h), self.dtype) * s,
            "wo": jax.random.normal(ks[1], (h, h), self.dtype) * s,
            "w1": jax.random.normal(ks[2], (h, f), self.dtype) * s,
            "w2": jax.random.normal(ks[3], (f, h), self.dtype) * s,
            "ln1": jnp.ones((h,), self.dtype),
            "ln2": jnp.ones((h,), self.dtype),
        }

    # param name -> (tp_dim, fsdp_dim)
    tp_dims = {"wqkv": (1, 0), "wo": (0, 1), "w1": (1, 0), "w2": (0, 1),
               "ln1": (None, None), "ln2": (None, None)}

    def param_specs(self, sh: LayerShardings):
        # (None, None) marks the 1-D norm scales; everything else is a
        # 2-D projection.  Shared by subclasses whose tp_dims follow the
        # same convention (LlamaHPLayer).
        out = {}
        for name, (tp_dim, fsdp_dim) in self.tp_dims.items():
            ndim = 1 if (tp_dim, fsdp_dim) == (None, None) else 2
            out[name] = sh.param_spec(tp_dim if ndim > 1 else None, ndim,
                                      fsdp_dim if ndim > 1 else None)
        return out

    def _ln(self, x, g):
        return _layer_norm(x, g)

    def _attend(self, q, k, v, sh: LayerShardings):
        """[b, nh, t, hd] heads tp-sharded, batch dp-sharded.

        Long sequences route through the Pallas flash kernel inside a
        shard_map over the layer mesh (pallas_call is not GSPMD-
        partitionable, but attention is local per head, so a head/batch-
        sharded shard_map is exact); short sequences keep the jnp path."""
        b, nh, t, hd = q.shape
        mesh = sh.mesh
        tp = int(np.prod([mesh.shape[a] for a in sh.tp_axes] or [1]))
        dp = int(np.prod([mesh.shape[a] for a in sh.dp_axes] or [1]))
        if (t >= 128 and hd <= 512 and nh % tp == 0 and b % dp == 0):
            from ..ops.pallas.flash_attention import flash_attention
            from ..platform import shard_map
            spec = P(sh._axes(sh.dp_axes) if sh.dp_axes else None,
                     sh._axes(sh.tp_axes) if sh.tp_axes else None,
                     None, None)

            def body(q, k, v):
                o = flash_attention(q, k, v, causal=True)
                assert o is not None  # guaranteed by the shape pre-check
                return o

            return shard_map(body, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=spec, check_vma=False)(q, k, v)
        a = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(hd)
        mask = jnp.tril(jnp.ones((t, t), bool))
        a = jnp.where(mask, a, -1e9)
        a = jax.nn.softmax(a, axis=-1)
        return (a @ v).astype(v.dtype)

    def apply(self, params, x, sh: LayerShardings):
        b, t, h = x.shape
        nh = self.heads
        y = self._ln(x, params["ln1"])
        qkv = y @ params["wqkv"]                       # column-parallel
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, nh, h // nh).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, nh, h // nh).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, nh, h // nh).transpose(0, 2, 1, 3)
        o = self._attend(q, k, v, sh)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, h).astype(x.dtype)
        x = x + sh.constrain(o @ params["wo"])         # row-parallel + psum
        y = self._ln(x, params["ln2"])
        y = jax.nn.gelu(y @ params["w1"])              # column-parallel
        x = x + sh.constrain(y @ params["w2"])         # row-parallel + psum
        return sh.constrain(x)


class LlamaHPLayer(TransformerHPLayer):
    """A Llama decoder layer as an HP layer spec: RMSNorm, rotary q/k,
    optional GQA, SwiGLU FFN — the reference's Llama/Baichuan Galvatron
    tier (tools/Hetu-Galvatron/galvatron/models/llama/
    LlamaModel_tensor_parallel.py) rebuilt on shardings instead of
    Megatron process groups.  ``alibi=True`` gives the Baichuan-13B
    position scheme instead of RoPE (models/baichuan/)."""

    def __init__(self, hidden, heads, kv_heads=None, ffn=None,
                 rope_theta=10000.0, alibi=False, dtype=jnp.float32):
        self.hidden, self.heads = hidden, heads
        self.kv_heads = kv_heads or heads
        assert heads % self.kv_heads == 0
        self.ffn = ffn or int(hidden * 8 / 3)
        self.rope_theta = rope_theta
        self.alibi = alibi
        self.dtype = dtype

    def init(self, key):
        h, f = self.hidden, self.ffn
        kvd = self.kv_heads * (h // self.heads)
        ks = jax.random.split(key, 6)
        s = 0.02
        return {
            "wq": jax.random.normal(ks[0], (h, h), self.dtype) * s,
            "wkv": jax.random.normal(ks[1], (h, 2 * kvd), self.dtype) * s,
            "wo": jax.random.normal(ks[2], (h, h), self.dtype) * s,
            "wgate": jax.random.normal(ks[3], (h, f), self.dtype) * s,
            "wup": jax.random.normal(ks[4], (h, f), self.dtype) * s,
            "wdown": jax.random.normal(ks[5], (f, h), self.dtype) * s,
            "rms1": jnp.ones((h,), self.dtype),
            "rms2": jnp.ones((h,), self.dtype),
        }

    tp_dims = {"wq": (1, 0), "wkv": (1, 0), "wo": (0, 1),
               "wgate": (1, 0), "wup": (1, 0), "wdown": (0, 1),
               "rms1": (None, None), "rms2": (None, None)}

    def _rms(self, x, g):
        return _rms_norm(x, g)

    def apply(self, params, x, sh: LayerShardings):
        from ..ops.rotary import _rotary, _repeat_kv, _alibi_bias
        b, t, h = x.shape
        nh, kvh = self.heads, self.kv_heads
        hd = h // nh
        y = self._rms(x, params["rms1"])
        q = (y @ params["wq"]).reshape(b, t, nh, hd).transpose(0, 2, 1, 3)
        kv = y @ params["wkv"]                        # column-parallel
        k, v = jnp.split(kv, 2, axis=-1)
        k = k.reshape(b, t, kvh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, kvh, hd).transpose(0, 2, 1, 3)
        if not self.alibi:
            q = _rotary(q, theta=self.rope_theta)
            k = _rotary(k, theta=self.rope_theta)
        if kvh != nh:
            k = _repeat_kv(k, n_rep=nh // kvh)
            v = _repeat_kv(v, n_rep=nh // kvh)
        if self.alibi:
            bias = _alibi_bias(q, num_heads=nh)
            a = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(hd) + bias
            mask = jnp.tril(jnp.ones((t, t), bool))
            a = jax.nn.softmax(jnp.where(mask, a, -1e9), axis=-1)
            o = (a @ v).astype(v.dtype)
        else:
            o = self._attend(q, k, v, sh)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, h).astype(x.dtype)
        x = x + sh.constrain(o @ params["wo"])        # row-parallel + psum
        y = self._rms(x, params["rms2"])
        y = jax.nn.silu(y @ params["wgate"]) * (y @ params["wup"])
        x = x + sh.constrain(y @ params["wdown"])     # row-parallel + psum
        return sh.constrain(x)


class VocabEmbedHPSpec:
    """Token embedding as an HP 'layer': tokens [b, t] int32 → [b, t, h].

    tp shards the VOCAB dim of the table (Megatron VocabParallelEmbedding,
    reference site_package/megatron/core/tensor_parallel/layers.py — XLA's
    SPMD partitioner lowers the vocab-sharded gather to the same
    mask-local-rows + psum pattern Megatron hand-writes); an fsdp dp_type
    row — set from ``config.embed_sdp`` by ``lm_wrap_config`` — further
    shards it over the dp axes (the reference's embed_sdp flag,
    hybrid_parallel_config.py)."""

    def __init__(self, vocab, hidden, dtype=jnp.float32, init_scale=0.02):
        self.vocab, self.hidden = int(vocab), int(hidden)
        self.dtype, self.init_scale = dtype, init_scale

    def init(self, key):
        return {"wte": jax.random.normal(
            key, (self.vocab, self.hidden), self.dtype) * self.init_scale}

    def param_specs(self, sh: "LayerShardings"):
        return {"wte": sh.param_spec(0, 2, 1)}

    def apply(self, params, x, sh: "LayerShardings"):
        return sh.constrain(jnp.take(params["wte"], x, axis=0))


class LMHeadHPSpec:
    """Final norm + vocab-parallel LM head: [b, t, h] → logits [b, t, V]
    sharded over the tp axes on V (column-parallel; the CE loss reduces
    over the sharded vocab dim, GSPMD inserting the psum — logits are
    never unsharded, the point of Megatron's vocab-parallel CE).

    ``tied=True`` drops the head's own projection and reuses the
    embedding table (GPT-2/Megatron weight tying; the shared-table grad
    accumulates through the single vjp — no separate embedding-grad
    allreduce needed because pp_deg==1 keeps both on one submesh)."""

    def __init__(self, vocab, hidden, dtype=jnp.float32, norm="ln",
                 init_scale=0.02, tied=False):
        self.vocab, self.hidden = int(vocab), int(hidden)
        self.dtype, self.norm, self.init_scale = dtype, norm, init_scale
        self.tied = bool(tied)

    def init(self, key):
        p = {"gnorm": jnp.ones((self.hidden,), self.dtype)}
        if not self.tied:
            p["wlm"] = jax.random.normal(
                key, (self.hidden, self.vocab),
                self.dtype) * self.init_scale
        return p

    def param_specs(self, sh: "LayerShardings"):
        out = {"gnorm": sh.param_spec(None, 1)}
        if not self.tied:
            out["wlm"] = sh.param_spec(1, 2, 0)
        return out

    def apply(self, params, x, sh: "LayerShardings"):
        norm = _rms_norm if self.norm == "rms" else _layer_norm
        y = norm(x, params["gnorm"])
        if self.tied and "_tied_wte" not in params:
            raise KeyError(
                "tied LMHeadHPSpec.apply needs the shared table under "
                "'_tied_wte' (injected by HybridParallelModel._apply_range"
                "; pass the embedding table yourself when calling apply "
                "directly)")
        wlm = params["wlm"] if not self.tied else params["_tied_wte"].T
        logits = y @ wlm
        spec = [None] * 3
        if sh.dp_axes:
            spec[0] = sh._axes(sh.dp_axes)
        if sh.tp_axes:
            spec[2] = sh._axes(sh.tp_axes)
        return lax.with_sharding_constraint(
            logits, NamedSharding(sh.mesh, P(*spec)))


def lm_cross_entropy(logits, tokens):
    """Mean next-token CE over [b, t, V] logits vs [b, t] int targets.
    Works with vocab-sharded logits: the logsumexp reduction over V
    becomes a psum over the tp axes under GSPMD."""
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logits.astype(jnp.float32), tokens[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - picked)


def lm_wrap_config(cfg: HybridParallelConfig, embed_sdp=None):
    """Extend a searched per-transformer-layer config with embedding and
    LM-head rows on the first/last pipeline stage (the reference wraps
    model layers with embed/cls modules there, hybrid_parallel_config.py);
    ``embed_sdp`` (default: cfg.embed_sdp) makes both rows FSDP."""
    e = int(cfg.embed_sdp if embed_sdp is None else embed_sdp)
    div = list(cfg.pp_division)
    div[0] += 1
    div[-1] += 1   # pp_deg==1: same stage gets both rows
    return HybridParallelConfig(
        pp_deg=cfg.pp_deg,
        tp_sizes=[cfg.tp_sizes[0]] + cfg.tp_sizes + [cfg.tp_sizes[-1]],
        dp_types=[e] + cfg.dp_types + [e],
        tp_consecutive=([cfg.tp_consecutive[0]] + cfg.tp_consecutive
                        + [cfg.tp_consecutive[-1]]),
        checkpoint_flags=[0] + cfg.checkpoint_flags + [0],
        sp_flags=[0] + cfg.sp_flags + [0],
        pp_division=div, global_bsz=cfg.global_bsz, chunks=cfg.chunks,
        pipeline_type=cfg.pipeline_type,
        default_dp_type=cfg.default_dp_type, embed_sdp=e, world=cfg.world)


def make_lm_hybrid_model(vocab, layer_specs, cfg, embed_sdp=None,
                         norm="ln", dtype=jnp.float32, devices=None,
                         tie_embeddings=False):
    """Full-LM hybrid-parallel model (tokens → CE loss): embedding + the
    given transformer HP layers + vocab-parallel head under the searched
    config, matching the reference's Galvatron models
    (models/gpt/GPTModel_hybrid_parallel.py: embed and cls wrapped onto
    the first/last stage, embed_sdp honored).  ``tie_embeddings`` shares
    the table with the head (GPT-2 semantics) — pp_deg must be 1 so both
    live on one submesh; refused otherwise rather than silently untied."""
    if tie_embeddings and cfg.pp_deg > 1:
        raise ValueError(
            "tie_embeddings requires pp_deg == 1 (embedding and head must "
            "share a stage submesh); got pp_deg="
            f"{cfg.pp_deg}")
    hidden = layer_specs[0].hidden
    specs = ([VocabEmbedHPSpec(vocab, hidden, dtype=dtype)]
             + list(layer_specs)
             + [LMHeadHPSpec(vocab, hidden, dtype=dtype, norm=norm,
                             tied=tie_embeddings)])
    full = lm_wrap_config(cfg, embed_sdp)
    return HybridParallelModel(specs, full, loss_fn=lm_cross_entropy,
                               devices=devices)


class HybridParallelModel:
    """Applies a searched HybridParallelConfig to a stack of HP layers.

    pp_deg==1: all layers run inside one jitted step; per-layer shardings
    do the work the reference does with per-layer process groups.

    pp_deg>1: the searched ``pp_division`` is HONORED — layers partition
    into stages, each stage compiles its own forward and rematerializing
    backward over its pp-slice submesh (per-layer TP×DP/FSDP shardings
    intact inside the stage), and a host scheduler drives the searched
    ``config.pipeline_type`` schedule (gpipe or pipedream_flush/1F1B) over
    ``chunks`` micro-batches, transferring boundary activations/cotangents
    between stage device sets (the reference's pipeline/pipeline.py:133/343
    batched-p2p schedules).  JAX async dispatch overlaps stage programs —
    chunk m can be in stage 1 while chunk m+1 runs stage 0.
    """

    def __init__(self, layer_specs, config: HybridParallelConfig,
                 loss_fn=None, devices=None):
        assert len(layer_specs) == config.n_layers
        self.specs = layer_specs
        self.config = config
        self.mesh = build_mesh(config, devices)
        self.pp = config.pp_deg
        if self.pp > 1:
            rest = self.mesh.axis_names[1:]
            self.stage_meshes = [Mesh(self.mesh.devices[s], rest)
                                 for s in range(self.pp)]
            ranks = config.pp_ranks()
            self.stage_layers = [[i for i, r in enumerate(ranks) if r == s]
                                 for s in range(self.pp)]
            for s, idxs in enumerate(self.stage_layers):
                if not idxs:
                    raise ValueError(
                        f"pp_division {config.pp_division} leaves stage "
                        f"{s} empty — config cannot be honored")
            layer_mesh = lambda i: self.stage_meshes[ranks[i]]
        else:
            self.stage_meshes = [self.mesh]
            self.stage_layers = [list(range(config.n_layers))]
            layer_mesh = lambda i: self.mesh
        self.shardings = [LayerShardings(layer_mesh(i), config, i)
                          for i in range(config.n_layers)]
        self.loss_fn = loss_fn or (lambda out, tgt: jnp.mean((out - tgt) ** 2))
        self._stage_fwd = None

    def init_params(self, key):
        keys = jax.random.split(key, len(self.specs))
        params = []
        for spec, sh, k in zip(self.specs, self.shardings, keys):
            p = spec.init(k)
            pspecs = spec.param_specs(sh)
            p = {n: jax.device_put(v, NamedSharding(sh.mesh, pspecs[n]))
                 for n, v in p.items()}
            params.append(p)
        return params

    def save(self, path, params, opt_state=None):
        """Checkpoint the hybrid-parallel state: params gather to host
        numpy (shardings are a placement property, not data), alongside
        the searched config for load-time validation.  Reference:
        Galvatron's save_checkpoint over Megatron state dicts."""
        import pickle
        state = {
            "config": self.config.to_json(),
            "params": jax.tree_util.tree_map(np.asarray, params),
            "opt_state": (None if opt_state is None else
                          jax.tree_util.tree_map(np.asarray, opt_state)),
        }
        with open(path, "wb") as f:
            pickle.dump(state, f)

    def _expected_param_shapes(self):
        # abstract init: shapes without spending FLOPs
        key = jax.random.PRNGKey(0)
        return [jax.eval_shape(spec.init, key) for spec in self.specs]

    def load(self, path):
        """Restore (params, opt_state); params re-place onto each layer's
        searched shardings (a checkpoint written under one parallel config
        reloads under another — the host copy is layout-free).

        Optimizer state is pipeline-layout-bound: under pp_deg>1 it is a
        per-STAGE list whose grouping follows the saving config, so when
        the pipeline layout differs the load refuses it (reload with
        opt_state discarded, or keep the same pp layout)."""
        import pickle
        with open(path, "rb") as f:
            state = pickle.load(f)
        saved_layers = len(state["params"])
        if saved_layers != len(self.specs):
            raise ValueError(
                f"checkpoint has {saved_layers} layers, model has "
                f"{len(self.specs)}")
        expect = self._expected_param_shapes()
        for i, (p, exp) in enumerate(zip(state["params"], expect)):
            for n, v in p.items():
                if n not in exp or tuple(np.shape(v)) != tuple(exp[n].shape):
                    raise ValueError(
                        f"checkpoint layer {i} param {n!r} has shape "
                        f"{np.shape(v)}, model expects "
                        f"{tuple(exp[n].shape) if n in exp else 'absent'} "
                        "— wrong model for this checkpoint")
        shard_specs = []
        params = []
        for spec, sh, p in zip(self.specs, self.shardings,
                               state["params"]):
            pspecs = spec.param_specs(sh)
            shards = {n: NamedSharding(sh.mesh, pspecs[n]) for n in p}
            shard_specs.append(shards)
            params.append({n: jax.device_put(jnp.asarray(v), shards[n])
                           for n, v in p.items()})
        opt_state = state["opt_state"]
        if opt_state is not None:
            saved_cfg = state.get("config", {})
            cur_cfg = self.config.to_json()
            same_pp = (saved_cfg.get("pp_deg") == cur_cfg["pp_deg"] and
                       saved_cfg.get("pp_division")
                       == cur_cfg["pp_division"])
            if not same_pp:
                raise ValueError(
                    "checkpoint optimizer state was written under pipeline "
                    f"layout pp_deg={saved_cfg.get('pp_deg')}, this model "
                    f"uses pp_deg={self.config.pp_deg}; per-stage state "
                    "does not remap — load params only (save with "
                    "opt_state=None) or keep the pipeline layout")
            if self.pp == 1:
                # place optimizer subtrees that mirror the params tree
                # (adam mu/nu etc.) onto the params' shardings, so FSDP's
                # zero-3 memory sharding holds for the moments too
                param_td = jax.tree_util.tree_structure(params)
                flat_shards = [shard_specs[i][n]
                               for i in range(len(params))
                               for n in sorted(params[i])]

                def place(sub):
                    try:
                        leaves, td = jax.tree_util.tree_flatten(sub)
                    except Exception:
                        return None
                    if td != param_td:
                        return None
                    return jax.tree_util.tree_unflatten(
                        td, [jax.device_put(jnp.asarray(l), s)
                             for l, s in zip(leaves, flat_shards)])

                def walk(node):
                    placed = place(node)
                    if placed is not None:
                        return placed
                    if isinstance(node, (list, tuple)):
                        out = [walk(c) for c in node]
                        return (type(node)(*out)
                                if hasattr(node, "_fields")
                                else type(node)(out))
                    return jax.tree_util.tree_map(jnp.asarray, node)

                opt_state = walk(opt_state)
            else:
                # same pipeline layout: per-stage programs re-place the
                # state onto their submeshes on the first update
                opt_state = jax.tree_util.tree_map(jnp.asarray, opt_state)
        return params, opt_state

    def _apply_range(self, idxs, stage_params, x):
        for j, i in enumerate(idxs):
            spec, sh = self.specs[i], self.shardings[i]
            p = stage_params[j]
            if getattr(spec, "tied", False):
                # weight-tied LM head: borrow the embedding table from
                # layer 0 (make_lm_hybrid_model guarantees it shares this
                # stage); the vjp accumulates both uses into one grad
                if 0 not in idxs or "wte" not in stage_params[idxs.index(0)]:
                    raise ValueError(
                        "tied LM head requires a vocab-embedding spec as "
                        "layer 0 on the SAME pipeline stage (pp_deg == 1; "
                        "build via make_lm_hybrid_model)")
                p = dict(p)
                p["_tied_wte"] = stage_params[idxs.index(0)]["wte"]
            body = lambda p_, x_, spec_=spec, sh_=sh: spec_.apply(p_, x_, sh_)
            if sh.ckpt:
                body = jax.checkpoint(body)
            x = body(p, x)
        return x

    def apply(self, params, x):
        if self.pp == 1:
            return self._apply_range(self.stage_layers[0], params, x)
        for s, idxs in enumerate(self.stage_layers):
            x = self._to_stage(x, s)
            x = self._apply_range(idxs, [params[i] for i in idxs], x)
        return x

    def loss(self, params, x, tgt):
        return self.loss_fn(self.apply(params, x), tgt)

    # -- pipelined execution (pp_deg > 1) ---------------------------------
    def _to_stage(self, x, s):
        sh = self.shardings[self.stage_layers[s][0]]
        return jax.device_put(x, NamedSharding(
            self.stage_meshes[s], sh.act_spec(x.ndim)))

    def _build_stage_programs(self):
        self._stage_fwd, self._stage_bwd, self._stage_last_bwd = [], [], []
        for s, idxs in enumerate(self.stage_layers):
            last = s == self.pp - 1

            def fwd(sp, x, idxs=idxs):
                return self._apply_range(idxs, sp, x)

            self._stage_fwd.append(jax.jit(fwd))

            def bwd(sp, x, ct, idxs=idxs):
                _, vjp_fn = jax.vjp(
                    lambda p_, x_: self._apply_range(idxs, p_, x_), sp, x)
                return vjp_fn(ct)

            self._stage_bwd.append(jax.jit(bwd))
            if last:
                def last_bwd(sp, x, tgt, scale, idxs=idxs):
                    def f(p_, x_):
                        return self.loss_fn(
                            self._apply_range(idxs, p_, x_), tgt)
                    loss, vjp_fn = jax.vjp(f, sp, x)
                    gp, gx = vjp_fn(scale.astype(loss.dtype))
                    return loss, gp, gx

                self._stage_last_bwd = jax.jit(last_bwd)

    def grads(self, params, x, tgt):
        """(loss, grads) with micro-batch accumulation over config.chunks;
        pipelined across stages when pp_deg > 1."""
        chunks = max(1, self.config.chunks)
        if self.pp == 1:
            return self._grads_unstaged(params, x, tgt, chunks)
        return self._grads_pipelined(params, x, tgt, chunks)

    def _grads_unstaged(self, params, x, tgt, chunks):
        if chunks == 1:
            return jax.value_and_grad(self.loss)(params, x, tgt)
        b = x.shape[0]
        assert b % chunks == 0, f"batch {b} not divisible by chunks {chunks}"
        xs = x.reshape(chunks, b // chunks, *x.shape[1:])
        ts = tgt.reshape(chunks, b // chunks, *tgt.shape[1:])
        zero = jax.tree_util.tree_map(jnp.zeros_like, params)

        def micro(acc, xt):
            l, g = jax.value_and_grad(self.loss)(params, *xt)
            acc_l, acc_g = acc
            return (acc_l + l,
                    jax.tree_util.tree_map(jnp.add, acc_g, g)), None

        (tl, tg), _ = lax.scan(micro, (0.0, zero), (xs, ts))
        inv = 1.0 / chunks
        return tl * inv, jax.tree_util.tree_map(lambda g: g * inv, tg)

    def _grads_pipelined(self, params, x, tgt, chunks):
        """GPipe or pipedream-flush (1F1B) over ``chunks`` micro-batches,
        selected by ``config.pipeline_type`` (the searched schedule,
        reference pipeline/pipeline.py:133 pipedream_flush_forward_backward
        vs :343 gpipe_forward_backward).

        Both stash only boundary activations (stage inputs; intra-stage
        activations recompute in the vjp backward).  GPipe keeps all
        ``chunks`` of them live through the flush; pipedream-flush issues
        each chunk's full backward chain as soon as its forward leaves the
        last stage and frees that chunk's stash — at most ``pp`` chunks
        live, which is exactly what search.py's memory model
        (min(chunks, pp) live micro-batches) scores."""
        if self._stage_fwd is None:
            self._build_stage_programs()
        b = x.shape[0]
        assert b % chunks == 0, f"batch {b} not divisible by chunks {chunks}"
        schedule = self.config.pipeline_type
        mb = b // chunks
        xs = [x[m * mb:(m + 1) * mb] for m in range(chunks)]
        ts = [tgt[m * mb:(m + 1) * mb] for m in range(chunks)]
        sparams = [[params[i] for i in idxs] for idxs in self.stage_layers]

        stage_in = [[None] * self.pp for _ in range(chunks)]
        # d(mean over chunks)/dloss seed; losses stay device-resident —
        # a float() per chunk would sync the host mid-pipeline.  f32 here
        # (x may be int tokens for the LM tier); last_bwd casts it to the
        # loss dtype before seeding the vjp
        scale = jnp.asarray(1.0 / chunks, jnp.float32)
        grad_acc = [None] * self.pp
        losses = []
        self._live_chunks_hwm = 0

        def note_live():
            live = sum(any(a is not None for a in sl) for sl in stage_in)
            self._live_chunks_hwm = max(self._live_chunks_hwm, live)

        def backward(m):
            tgt_m = self._to_stage(ts[m], self.pp - 1) \
                if ts[m].ndim else ts[m]
            loss_m, gp, ct = self._stage_last_bwd(
                sparams[-1], stage_in[m][self.pp - 1], tgt_m, scale)
            losses.append(loss_m)
            grad_acc[-1] = gp if grad_acc[-1] is None else \
                jax.tree_util.tree_map(jnp.add, grad_acc[-1], gp)
            for s in reversed(range(self.pp - 1)):
                ct = self._to_stage(ct, s)
                gp, ct = self._stage_bwd[s](sparams[s], stage_in[m][s], ct)
                grad_acc[s] = gp if grad_acc[s] is None else \
                    jax.tree_util.tree_map(jnp.add, grad_acc[s], gp)
            stage_in[m] = [None] * self.pp   # chunk m's stash is consumed

        # forward wavefront: (chunk+stage) diagonal issue order; JAX async
        # dispatch overlaps stage programs across their device sets
        order = sorted(((m, s) for m in range(chunks)
                        for s in range(self.pp)),
                       key=lambda t: (t[0] + t[1], t[1]))
        for m, s in order:
            src = xs[m] if s == 0 else stage_in[m][s]
            xin = self._to_stage(src, s)   # ICI transfer between stages
            stage_in[m][s] = xin
            if s < self.pp - 1:
                stage_in[m][s + 1] = self._stage_fwd[s](sparams[s], xin)
                note_live()
            elif schedule == "pipedream_flush":
                backward(m)
                note_live()
            else:
                note_live()
        if schedule == "gpipe":
            for m in reversed(range(chunks)):
                backward(m)

        loss = losses[0]
        for l in losses[1:]:
            loss = loss + l
        grads = [None] * self.config.n_layers
        for s, idxs in enumerate(self.stage_layers):
            for j, i in enumerate(idxs):
                grads[i] = grad_acc[s][j]
        return loss * scale.astype(loss.dtype), grads

    def make_train_step(self, optimizer=None, lr=1e-3):
        """Returns (step_fn, opt_state_init).

        pp_deg==1: step_fn is one jitted program.  pp_deg>1: step_fn is a
        host-orchestrated pipeline step (per-stage programs overlap via
        async dispatch); updates apply per stage on its submesh."""
        if optimizer is None:
            def apply_updates(params, opt_state, g):
                new = jax.tree_util.tree_map(lambda p, gg: p - lr * gg,
                                             params, g)
                return new, opt_state
            init = lambda params: ()
        else:
            import optax

            def apply_updates(params, opt_state, g):
                updates, opt_state = optimizer.update(g, opt_state, params)
                return optax.apply_updates(params, updates), opt_state
            init = optimizer.init

        if self.pp == 1:
            def step(params, opt_state, x, tgt):
                loss, g = self.grads(params, x, tgt)
                params, opt_state = apply_updates(params, opt_state, g)
                return params, opt_state, loss
            return jax.jit(step, donate_argnums=(0, 1)), init

        # pipelined: per-stage jitted update keeps each stage's params on
        # its own submesh (grads already live there); donate params AND
        # slots so old/new optimizer state never coexist in HBM
        stage_update = jax.jit(apply_updates, donate_argnums=(0, 1))

        def step(params, opt_state, x, tgt):
            loss, g = self.grads(params, x, tgt)
            new_params = list(params)
            new_opt = list(opt_state) if isinstance(opt_state, list) \
                else [opt_state] * self.pp
            for s, idxs in enumerate(self.stage_layers):
                sp = [params[i] for i in idxs]
                sg = [g[i] for i in idxs]
                np_, no_ = stage_update(sp, new_opt[s], sg)
                for j, i in enumerate(idxs):
                    new_params[i] = np_[j]
                new_opt[s] = no_
            return new_params, new_opt, loss

        def init_pp(params):
            return [init([params[i] for i in idxs])
                    for idxs in self.stage_layers]

        return step, init_pp
