"""Galvatron-style per-layer hybrid-parallel strategy search.

Reference: tools/Hetu-Galvatron — the search core is a DP over
(layer, memory budget, strategy) minimizing estimated iteration time
(csrc/dp_core.cpp:22), fed by profiled per-layer compute times and a
hardware bandwidth profile (core/profiler.py:8, configs/
computation_profiling_*.json).  The outer loop enumerates pipeline degrees
and micro-batch counts; the inner DP picks per-layer (tp size, DDP|FSDP,
checkpoint) under the per-device memory budget.

This module keeps the profile→search→JSON-config contract, with costs
re-derived for a TPU mesh: TP comm = 2 allreduces of activations per layer
over the tp axes (ICI), FSDP adds a param all-gather per layer, DP grad
sync = one reduce-scatter+all-gather of params per step amortized over
layers, transition cost between adjacent layers with different layouts =
activation resharding bytes / ICI bandwidth.
"""

from __future__ import annotations

import itertools
import json
import os

import numpy as np

from .build import dp_core_auto
from .config import HybridParallelConfig


class LayerProfile:
    """Per-layer measurements driving the cost model.

    compute_ms   : forward time of the full (unsharded) layer for ONE sample
                   (profiled time / profiled batch size — profiler contract).
    param_bytes  : total parameter bytes of the layer.
    act_bytes    : activation bytes entering/leaving the layer per sample —
                   the BOUNDARY tensor, used by the TP/resharding comm terms.
    act_mem_bytes: MEASURED per-sample activation memory of the compiled
                   fwd+bwd (XLA temp-bytes slope over batch; includes qkv,
                   probs, ffn intermediates).  None → the memory model
                   falls back to its analytic heuristic on act_bytes.
    """

    def __init__(self, compute_ms, param_bytes, act_bytes,
                 act_mem_bytes=None):
        self.compute_ms = float(compute_ms)
        self.param_bytes = float(param_bytes)
        self.act_bytes = float(act_bytes)
        self.act_mem_bytes = (None if act_mem_bytes is None
                              else float(act_mem_bytes))

    def to_json(self):
        return {"compute_ms": self.compute_ms, "param_bytes": self.param_bytes,
                "act_bytes": self.act_bytes,
                "act_mem_bytes": self.act_mem_bytes}

    @classmethod
    def from_json(cls, d):
        return cls(d["compute_ms"], d["param_bytes"], d["act_bytes"],
                   d.get("act_mem_bytes"))


#: the profile artifact is a versioned contract: the planner
#: (hetu_tpu/planner) loads it across sessions, so a torn or
#: foreign-schema file must fail loudly, not search on garbage
PROFILE_SCHEMA = "galvatron_profile"
PROFILE_VERSION = 1


class ProfileError(ValueError):
    """A profile artifact failed schema/version validation on load."""


def save_profile(path, layers, ici_gbps=100.0, dcn_gbps=10.0, meta=None):
    """computation_profiling_*.json equivalent.  Atomic (tmp +
    ``os.replace``, the checkpoint-writer convention): a crash mid-write
    leaves the previous artifact intact instead of a torn JSON that a
    later search would load as garbage.  ``meta`` carries calibration
    provenance (platform, shapes, window) verbatim."""
    doc = {"schema": PROFILE_SCHEMA, "version": PROFILE_VERSION,
           "layers": [l.to_json() for l in layers],
           "ici_gbps": ici_gbps, "dcn_gbps": dcn_gbps}
    if meta:
        doc["meta"] = dict(meta)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def load_profile_doc(path):
    """The validated raw artifact dict, or :class:`ProfileError`."""
    try:
        with open(path) as fh:
            d = json.load(fh)
    except (OSError, ValueError) as e:
        raise ProfileError(f"unreadable profile artifact {path}: {e}")
    if not isinstance(d, dict):
        raise ProfileError(f"profile artifact {path} is not an object")
    if d.get("schema") != PROFILE_SCHEMA:
        raise ProfileError(
            f"profile artifact {path}: schema "
            f"{d.get('schema')!r} != {PROFILE_SCHEMA!r}")
    if d.get("version") != PROFILE_VERSION:
        raise ProfileError(
            f"profile artifact {path}: version "
            f"{d.get('version')!r} != {PROFILE_VERSION}")
    layers = d.get("layers")
    if not isinstance(layers, list) or not layers:
        raise ProfileError(f"profile artifact {path}: empty layers")
    for i, l in enumerate(layers):
        missing = {"compute_ms", "param_bytes", "act_bytes"} - set(
            l if isinstance(l, dict) else ())
        if missing:
            raise ProfileError(
                f"profile artifact {path}: layer {i} missing "
                f"{sorted(missing)}")
    return d


def load_profile(path):
    d = load_profile_doc(path)
    return ([LayerProfile.from_json(l) for l in d["layers"]],
            d.get("ici_gbps", 100.0), d.get("dcn_gbps", 10.0))


class Strategy:
    """One inner-DP strategy: (tp, dp_type, ckpt, sp) on a per-stage submesh
    of ``per_stage`` devices (dp degree = per_stage // tp).  ``sp`` =
    Megatron sequence parallelism: residual/LN activations seq-sharded over
    the tp group (reference tensor_parallel/transformer.py
    sequence_parallel flag)."""

    __slots__ = ("tp", "dp_type", "ckpt", "sp")

    def __init__(self, tp, dp_type, ckpt, sp=0):
        self.tp, self.dp_type, self.ckpt, self.sp = tp, dp_type, ckpt, sp

    def __repr__(self):
        kind = "fsdp" if self.dp_type else "ddp"
        tag = ",sp" if self.sp else ""
        return f"(tp={self.tp},{kind},ckpt={self.ckpt}{tag})"


def strategy_space(per_stage, with_ckpt=True):
    out = []
    tp = 1
    while tp <= per_stage:
        dp = per_stage // tp
        dp_types = [0, 1] if dp > 1 else [0]
        ckpts = [0, 1] if with_ckpt else [0]
        sps = [0, 1] if tp > 1 else [0]
        for dt, ck, sp in itertools.product(dp_types, ckpts, sps):
            out.append(Strategy(tp, dt, ck, sp))
        tp *= 2
    return out


class CostModel:
    """Estimated per-layer per-MICRO-BATCH time (ms) and per-device memory
    (bytes) under a strategy.

    ``micro_bsz`` is the GLOBAL micro-batch size (one pipeline chunk across
    all dp replicas); the model divides by dp for the per-device share, so
    strategies with different dp degrees are compared at equal per-step
    work.  bwd compute is modeled as 2x fwd; checkpointing adds one extra
    fwd.  Bytes-over-ICI use ring-collective cost (n-1)/n * bytes.
    Per-step costs (DP grad sync) are amortized over ``chunks``
    micro-batches; per-micro costs (TP activation allreduce, FSDP param
    gathers) are not.
    """

    def __init__(self, layers, per_stage, micro_bsz, chunks=1,
                 ici_gbps=100.0, fsdp_overlap=0.5):
        self.layers = layers
        self.per_stage = per_stage
        self.micro_bsz = micro_bsz
        self.chunks = max(1, chunks)
        self.ici = ici_gbps * 1e9 / 1e3      # bytes per ms
        self.fsdp_overlap = fsdp_overlap     # fraction of FSDP gather hidden

    def _coll_ms(self, nbytes, n):
        if n <= 1:
            return 0.0
        return (nbytes * (n - 1) / n) / self.ici

    def _local_bsz(self, st):
        dp = self.per_stage // st.tp
        return self.micro_bsz / dp

    def intra_ms(self, i, st):
        L = self.layers[i]
        dp = self.per_stage // st.tp
        lb = self._local_bsz(st)
        fwd = L.compute_ms * lb / st.tp
        bwd = 2.0 * fwd
        recompute = fwd if st.ckpt else 0.0
        act = L.act_bytes * lb
        # Megatron TP: allreduce activations in fwd + bwd (2 each).  Under
        # sp the allreduce becomes all-gather + reduce-scatter with the
        # same total ring bytes, so the comm term is unchanged — sp is a
        # pure memory lever (mem_bytes), exactly why the search should
        # prefer it whenever tp > 1 and memory binds.
        tp_comm = 4.0 * self._coll_ms(act, st.tp)
        # DP grad sync once per step: reduce-scatter + all-gather of this
        # layer's param shard, amortized over the micro-batches
        dp_comm = 2.0 * self._coll_ms(L.param_bytes / st.tp, dp) / self.chunks
        # FSDP: params re-gathered per micro-batch in fwd and bwd
        fsdp_comm = (2.0 * self._coll_ms(L.param_bytes / st.tp, dp)
                     * (1.0 - self.fsdp_overlap)) if st.dp_type else 0.0
        return fwd + bwd + recompute + tp_comm + dp_comm + fsdp_comm

    def inter_ms(self, i, prev_st, st):
        """Activation-resharding cost entering layer i (reference
        relocate_activations / redistribution groups)."""
        if prev_st.tp == st.tp:
            return 0.0
        L = self.layers[i]
        # bytes held per device under the coarser of the two layouts cross
        # ICI once (all-gather + re-slice)
        lb = min(self._local_bsz(prev_st), self._local_bsz(st))
        n = max(prev_st.tp, st.tp)
        return self._coll_ms(L.act_bytes * lb, n)

    # fraction of a layer's activation bytes that live on the residual/LN
    # segments ([b, t, h] tensors) — tp-sharded ONLY under sequence
    # parallelism; the rest (qkv, probs, ffn intermediate) is tp-sharded
    # by plain Megatron TP already
    RESIDUAL_ACT_FRAC = 0.25

    def mem_bytes(self, i, st, n_micro_live=1):
        L = self.layers[i]
        dp = self.per_stage // st.tp
        lb = self._local_bsz(st)
        param_shard = L.param_bytes / st.tp / (dp if st.dp_type else 1)
        # params + grads + adam moments (m, v) in f32 masters ≈ 4x params
        state = 4.0 * param_shard
        res_shard = st.tp if st.sp else 1    # runtime act_spec(seq_shard)
        if L.act_mem_bytes is not None:
            # MEASURED split: boundary (residual) bytes are act_bytes;
            # everything else in the compiled fwd+bwd footprint is
            # internal and tp-sharded by plain Megatron TP already
            boundary = L.act_bytes
            internal = max(0.0, L.act_mem_bytes - L.act_bytes)
        else:
            # analytic heuristic: act_bytes stands in for the whole
            # footprint, split by RESIDUAL_ACT_FRAC
            boundary = L.act_bytes * self.RESIDUAL_ACT_FRAC
            internal = L.act_bytes * (1.0 - self.RESIDUAL_ACT_FRAC)
        if st.ckpt:
            # only stage-boundary activations survive — and those ARE the
            # residual stream, so plain TP cannot shard them; sp can.
            # Still one copy per in-flight micro-batch.  (Analytic mode
            # keeps the historical 0.2 * total fudge for continuity.)
            keep = (boundary if L.act_mem_bytes is not None
                    else L.act_bytes * 0.2)
            act = keep * lb / res_shard * n_micro_live
        else:
            act = ((internal / st.tp + boundary / res_shard)
                   * lb * n_micro_live)
        return state + act


class GalvatronSearch:
    """Outer loop over pp_deg (and chunks), inner native DP per layer.

    mem_budget_bytes is the per-device HBM budget.  Returns the best
    HybridParallelConfig.
    """

    def __init__(self, world, mem_budget_bytes, micro_bsz=1,
                 ici_gbps=100.0, mem_units=64, use_native=True,
                 pp_candidates=None, chunks_candidates=(1, 2, 4, 8)):
        self.world = world
        self.budget = float(mem_budget_bytes)
        self.micro_bsz = micro_bsz
        self.ici_gbps = ici_gbps
        self.mem_units = mem_units          # DP memory discretization
        self.use_native = use_native
        self.pp_candidates = pp_candidates
        self.chunks_candidates = chunks_candidates
        #: which DP core actually ran the last search ("native" csrc or
        #: the "numpy" oracle) — plan artifacts record it as provenance
        self.core_used = None
        self.best_cost_ms = None

    def _pp_list(self, n_layers):
        if self.pp_candidates is not None:
            return self.pp_candidates
        out, pp = [], 1
        while pp <= min(self.world, n_layers):
            out.append(pp)
            pp *= 2
        return out

    def search(self, layers, global_bsz=None):
        global_bsz = global_bsz or self.micro_bsz * max(self.chunks_candidates)
        best = (float("inf"), None)
        n_layers = len(layers)
        for pp in self._pp_list(n_layers):
            per_stage = self.world // pp
            if per_stage == 0 or per_stage * pp != self.world:
                continue
            if per_stage & (per_stage - 1):
                continue
            space = strategy_space(per_stage)
            for chunks in self.chunks_candidates:
                if global_bsz % chunks:
                    continue
                cost, cfg = self._search_inner(layers, pp, per_stage, space,
                                               chunks, global_bsz)
                if cost < best[0]:
                    best = (cost, cfg)
        # the winning estimate is the planner's predicted iteration time
        # (ms per step at global_bsz) — plan artifacts gate it against
        # the measured run
        self.best_cost_ms = best[0] if best[1] is not None else None
        return best[1]

    def _search_inner(self, layers, pp, per_stage, space, chunks, global_bsz):
        """Inner DP, run per pipeline stage so mem_budget_bytes is enforced
        per DEVICE (each device holds exactly one stage's layers).

        Step-time model: per-micro stage times t_s from the DP; a flush
        schedule (GPipe/1F1B) costs  chunks * max_s(t_s) + sum_{s != argmax}
        t_s  — steady state is bound by the slowest stage, the rest is
        fill/drain.  The cost tables depend on chunks (micro-batch size and
        grad-sync amortization), so they are rebuilt per (pp, chunks).

        ``pp_division`` is SEARCHED, not fixed: the uniform split and a
        balanced split (min-max stage time over each layer's best feasible
        strategy cost) both run through the per-stage DP; the cheaper
        feasible one wins — heterogeneous layer profiles get uneven stages,
        exactly what the reference's searched configs record in
        ``pp_division``.
        """
        micro_bsz = global_bsz // chunks
        if micro_bsz == 0:
            return float("inf"), None
        model = CostModel(layers, per_stage, micro_bsz, chunks=chunks,
                          ici_gbps=self.ici_gbps)
        L, S = len(layers), len(space)
        unit = self.budget / self.mem_units
        # gpipe keeps ~chunks micro-batch activations live; 1f1b keeps ≤ pp
        n_live = min(chunks, pp) if pp > 1 else 1
        # cost tables, built once per (pp, chunks) — division-independent
        mem = np.zeros((L, S), dtype=np.int32)
        intra = np.zeros((L, S))
        inter = np.zeros((L, S, S))
        feasible = np.zeros((L, S), dtype=bool)
        for i in range(L):
            for s, st in enumerate(space):
                mem[i, s] = max(1, int(np.ceil(
                    model.mem_bytes(i, st, n_live) / unit)))
                intra[i, s] = model.intra_ms(i, st)
                feasible[i, s] = mem[i, s] <= self.mem_units
                for sp, stp in enumerate(space):
                    inter[i, sp, s] = model.inter_ms(i, stp, st)

        best = (float("inf"), None)
        for division in self._candidate_divisions(pp, intra, feasible):
            total, cfg = self._eval_division(
                division, pp, space, chunks, global_bsz, mem, intra, inter)
            if total < best[0]:
                best = (total, cfg)
        return best

    def _candidate_divisions(self, pp, intra, feasible):
        """Uniform split plus (when it differs) the contiguous partition
        minimizing the max per-stage sum of best-case layer costs."""
        L = intra.shape[0]
        avg = L // pp
        uniform = [avg] * (pp - 1) + [L - avg * (pp - 1)]
        if pp == 1:
            return [uniform]
        # per-layer optimistic cost: cheapest feasible strategy (inf if none)
        c = np.where(feasible, intra, np.inf).min(axis=1)
        if not np.isfinite(c).all():
            return [uniform]
        # DP over contiguous partitions: f[k][i] = min over j of
        # max(f[k-1][j], sum c[j..i)) — classic min-max partition
        pre = np.concatenate([[0.0], np.cumsum(c)])
        f = np.full((pp + 1, L + 1), np.inf)
        cut = np.zeros((pp + 1, L + 1), dtype=np.int32)
        f[0, 0] = 0.0
        for k in range(1, pp + 1):
            for i in range(k, L - (pp - k) + 1):
                for j in range(k - 1, i):
                    v = max(f[k - 1, j], pre[i] - pre[j])
                    if v < f[k, i]:
                        f[k, i], cut[k, i] = v, j
        bounds = [L]
        for k in range(pp, 0, -1):
            bounds.append(int(cut[k, bounds[-1]]))
        bounds = bounds[::-1]
        balanced = [bounds[k + 1] - bounds[k] for k in range(pp)]
        if balanced == uniform or 0 in balanced:
            return [uniform]
        return [uniform, balanced]

    def _eval_division(self, division, pp, space, chunks, global_bsz,
                       mem, intra, inter):
        assignment, stage_times = [], []
        lo = 0
        for stage_len in division:
            hi = lo + stage_len
            (cost, stage_assign, _), self.core_used = dp_core_auto(
                np.ascontiguousarray(mem[lo:hi]),
                np.ascontiguousarray(intra[lo:hi]),
                np.ascontiguousarray(inter[lo:hi]), self.mem_units,
                use_native=self.use_native)
            if stage_assign is None:
                return float("inf"), None
            assignment += stage_assign
            stage_times.append(cost)
            lo = hi
        slowest = max(stage_times)
        total = chunks * slowest + (sum(stage_times) - slowest)
        cfg = HybridParallelConfig(
            pp_deg=pp,
            tp_sizes=[space[s].tp for s in assignment],
            dp_types=[space[s].dp_type for s in assignment],
            checkpoint_flags=[space[s].ckpt for s in assignment],
            sp_flags=[space[s].sp for s in assignment],
            pp_division=division,
            global_bsz=global_bsz, chunks=chunks, world=self.world,
            pipeline_type="pipedream_flush" if pp > 1 else "gpipe")
        return total, cfg


def measure_ici_gbps(mesh=None, nbytes=1 << 22, repeats=5):
    """MEASURED interconnect bandwidth for the search's cost model — the
    reference's hardware-profiling step (GalvatronProfiler
    profile_bandwidth drives nccl-tests, galvatron/core/profiler.py:405).

    Times a psum over the mesh's first axis with the collective
    micro-bench (profiler.CommProfiler) and returns GB/s calibrated to
    CostModel._coll_ms's ring convention ((n-1)/n * bytes / time), so
    plugging the result into GalvatronSearch(ici_gbps=...) makes the
    model's collective terms match this hardware.  None when
    unmeasurable (single device)."""
    import jax
    from jax.sharding import Mesh
    from ..profiler import CommProfiler

    if mesh is None:
        devs = np.array(jax.devices())
        if devs.size < 2:
            return None
        mesh = Mesh(devs, ("all",))
    axis = mesh.axis_names[0]
    n = int(mesh.shape[axis])
    if n < 2:
        return None
    t_s = CommProfiler(mesh).bench_collective("psum", nbytes=nbytes,
                                              axis=axis, repeats=repeats)
    if not t_s or t_s <= 0:
        return None
    # bench_collective shards its buffer P(axis): the psum'd payload is
    # the PER-DEVICE block, nbytes/n — credit exactly that, in the same
    # one-phase ring convention CostModel._coll_ms prices with
    payload = nbytes / n
    return (payload * (n - 1) / n) / t_s / 1e9


def profile_layers_analytic(n_layers, hidden, seq, ffn_mult=4, dtype_bytes=2,
                            chip_tflops=200.0):
    """Analytic LayerProfile for a transformer layer (used when no measured
    profile exists; the profiler contract replaces this with real timings)."""
    param_bytes = (4 * hidden * hidden + 2 * ffn_mult * hidden * hidden) * 4
    flops = 2 * (4 * hidden * hidden + 2 * ffn_mult * hidden * hidden) * seq \
        + 4 * seq * seq * hidden
    compute_ms = flops / (chip_tflops * 1e12) * 1e3
    act_bytes = seq * hidden * dtype_bytes
    return [LayerProfile(compute_ms, param_bytes, act_bytes)
            for _ in range(n_layers)]


def profile_hp_layers(specs, batch=2, seq=128, reps=5, devices=None):
    """MEASURED LayerProfile for each HP layer spec (TransformerHPLayer,
    LlamaHPLayer, ...) — the reference's computation-profiling step
    (tools/Hetu-Galvatron/galvatron/core/profiler.py:194-478 writes
    computation_profiling_*.json per layer type, which the search loads).

    Times the UNSHARDED layer forward on one device of the current
    backend (one profile per distinct spec type; same-typed layers share
    it, like the reference's layertype_* entries)."""
    import time
    import jax
    import jax.numpy as jnp
    from .runtime import LayerShardings
    from .config import HybridParallelConfig
    from jax.sharding import Mesh

    dev = (devices or jax.devices())[0]
    mesh = Mesh(np.asarray([dev]), ("m0",))
    by_type = {}
    out = []
    for spec in specs:
        key = (type(spec).__name__, spec.hidden,
               getattr(spec, "ffn", None), getattr(spec, "heads", None))
        if key not in by_type:
            cfg = HybridParallelConfig(pp_deg=1, tp_sizes=[1],
                                       dp_types=[0], world=1)
            sh = LayerShardings(mesh, cfg, 0)
            params = jax.device_put(spec.init(jax.random.PRNGKey(0)), dev)
            x = jax.device_put(
                jax.random.normal(jax.random.PRNGKey(1),
                                  (batch, seq, spec.hidden), spec.dtype),
                dev)
            fwd = jax.jit(lambda p, x: spec.apply(p, x, sh))
            np.asarray(fwd(params, x))           # compile + real sync
            t0 = time.perf_counter()
            for _ in range(reps):
                o = fwd(params, x)
            np.asarray(o)
            ms = (time.perf_counter() - t0) / reps * 1e3
            param_bytes = sum(v.size * v.dtype.itemsize
                              for v in jax.tree_util.tree_leaves(params))
            # boundary bytes (comm terms) stay analytic: [s, h] per sample
            act_bytes = seq * spec.hidden * jnp.dtype(spec.dtype).itemsize
            # activation MEMORY from XLA's own ledger: temp-bytes slope of
            # the compiled fwd+bwd over two batch sizes, isolating the
            # batch-scaling bytes (saved qkv/probs/ffn intermediates) from
            # weight-sized scratch — the reference's memory_profiling step
            # measured, not estimated (galvatron/core/profiler.py JSONs)
            act_mem = None
            try:
                def temp_at(b):
                    from ..platform import compiled_memory_analysis
                    xb = jax.ShapeDtypeStruct((b, seq, spec.hidden),
                                              spec.dtype)
                    vg = jax.jit(jax.value_and_grad(
                        lambda p, x: jnp.sum(spec.apply(p, x, sh))))
                    ma = compiled_memory_analysis(
                        vg.lower(params, xb).compile())
                    return float(ma.get("temp_size_in_bytes", 0) or 0)
                t1, t2 = temp_at(batch), temp_at(2 * batch)
                if t2 > t1 > 0:
                    act_mem = max(act_bytes, (t2 - t1) / batch)
            except Exception:
                pass                    # memory model falls back to analytic
            by_type[key] = LayerProfile(ms / batch, param_bytes, act_bytes,
                                        act_mem_bytes=act_mem)
        out.append(by_type[key])
    return out
