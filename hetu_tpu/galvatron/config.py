"""Per-layer hybrid-parallel strategy configs (Galvatron-style).

Reference: tools/Hetu-Galvatron/galvatron/core/hybrid_parallel_config.py —
a searched JSON carries ``pp_deg``, per-layer ``tp_sizes_enc``,
``tp_consecutive_flags``, ``dp_types_enc`` (DDP vs FSDP/zero-3),
``checkpoint`` flags, ``pp_division``, plus run hyper-params (global batch,
chunks, pipeline_type).  This module keeps that schema (so searched configs
are interchangeable in spirit) and re-targets the *meaning* at a TPU mesh:

  world = pp_deg * 2^k devices; the non-pp submesh is factorized into k
  binary axes ("m0".."m{k-1}").  A layer with tp = 2^t shards its weight
  tp-dims over t of those axes and does data parallel over the other k-t;
  ``tp_consecutive=1`` uses the *fastest-varying* (last, ICI-nearest) axes
  for TP, ``0`` the slowest.  FSDP additionally shards params over the dp
  axes (GSPMD all-gathers on use = zero-3).  Per-layer differences become
  just different PartitionSpecs inside ONE jitted SPMD program — the
  reference's per-layer process groups + activation redistribution
  (core/comm_groups.py:58-196, parallel.py:138) reduce to
  with_sharding_constraint boundaries that XLA lowers to collectives.
"""

from __future__ import annotations

import json

import numpy as np


def str2array(s):
    """Decode the reference's compact flag-string encoding ('1,1,2,2' or
    list) into a list of ints."""
    if isinstance(s, (list, tuple)):
        return [int(x) for x in s]
    return [int(x) for x in str(s).replace("[", "").replace("]", "").split(",")
            if x.strip() != ""]


def array2str(a):
    return ",".join(str(int(x)) for x in a)


class HybridParallelConfig:
    """Validated per-layer strategy assignment for ``n_layers`` layers on
    ``world`` devices."""

    def __init__(self, pp_deg, tp_sizes, dp_types, tp_consecutive=None,
                 checkpoint_flags=None, pp_division=None, global_bsz=None,
                 chunks=1, pipeline_type="gpipe", default_dp_type="ddp",
                 embed_sdp=0, world=None, sp_flags=None):
        n = len(tp_sizes)
        self.pp_deg = int(pp_deg)
        self.tp_sizes = [int(t) for t in tp_sizes]
        self.dp_types = [int(d) for d in dp_types]       # 0=ddp 1=fsdp
        self.tp_consecutive = ([int(c) for c in tp_consecutive]
                               if tp_consecutive is not None else [1] * n)
        self.checkpoint_flags = ([int(c) for c in checkpoint_flags]
                                 if checkpoint_flags is not None else [0] * n)
        # Megatron sequence parallelism per layer (reference
        # tensor_parallel/transformer.py sequence_parallel flag): the
        # residual/LN segments are sharded along the sequence dim over the
        # layer's tp axes.  Numerically identical to plain TP; a pure
        # memory win.  Meaningful only where tp > 1.
        self.sp_flags = ([int(s) for s in sp_flags]
                         if sp_flags is not None else [0] * n)
        if pp_division is None:
            avg = n // self.pp_deg
            pp_division = [avg] * (self.pp_deg - 1) + [n - avg * (self.pp_deg - 1)]
        self.pp_division = [int(x) for x in pp_division]
        self.global_bsz = global_bsz
        self.chunks = int(chunks)
        self.pipeline_type = pipeline_type
        self.default_dp_type = default_dp_type
        self.embed_sdp = int(embed_sdp)
        self.world = world
        self.validate()

    @property
    def n_layers(self):
        return len(self.tp_sizes)

    def validate(self):
        n = self.n_layers
        if self.pipeline_type not in ("gpipe", "pipedream_flush"):
            # refuse, don't silently rewrite — executing a different
            # schedule than searched breaks the search's memory model
            raise ValueError(
                f"unknown pipeline_type {self.pipeline_type!r}; this "
                "runtime honors 'gpipe' and 'pipedream_flush'")
        assert len(self.dp_types) == n and len(self.tp_consecutive) == n \
            and len(self.checkpoint_flags) == n and len(self.sp_flags) == n
        assert sum(self.pp_division) == n and len(self.pp_division) == self.pp_deg
        for t in self.tp_sizes:
            assert t >= 1 and (t & (t - 1)) == 0, f"tp size {t} not a power of 2"
        if self.world is not None:
            per_stage = self.world // self.pp_deg
            assert per_stage * self.pp_deg == self.world
            for t in self.tp_sizes:
                assert t <= per_stage, \
                    f"tp {t} exceeds per-stage devices {per_stage}"

    def pp_ranks(self):
        """Per-layer pipeline-stage index (reference get_pp_ranks_enc)."""
        out = []
        for stage, cnt in enumerate(self.pp_division):
            out += [stage] * cnt
        return out

    # -- JSON schema kept compatible with the reference's searched configs --
    def to_json(self):
        return {
            "pp_deg": self.pp_deg,
            "tp_sizes_enc": array2str(self.tp_sizes),
            "tp_consecutive_flags": array2str(self.tp_consecutive),
            "dp_types_enc": array2str(self.dp_types),
            "checkpoint": array2str(self.checkpoint_flags),
            "sp_flags_enc": array2str(self.sp_flags),
            "pp_division": array2str(self.pp_division),
            "global_bsz": self.global_bsz,
            "chunks": self.chunks,
            "pipeline_type": self.pipeline_type,
            "default_dp_type": self.default_dp_type,
            "embed_sdp": self.embed_sdp,
            "world": self.world,
        }

    def save(self, path):
        with open(path, "w") as fh:
            json.dump(self.to_json(), fh, indent=2)

    @classmethod
    def from_json(cls, cfg):
        return cls(
            pp_deg=cfg["pp_deg"],
            tp_sizes=str2array(cfg["tp_sizes_enc"]),
            dp_types=str2array(cfg["dp_types_enc"]),
            tp_consecutive=(str2array(cfg["tp_consecutive_flags"])
                            if "tp_consecutive_flags" in cfg else None),
            checkpoint_flags=(str2array(cfg["checkpoint"])
                              if "checkpoint" in cfg else None),
            sp_flags=(str2array(cfg["sp_flags_enc"])
                      if "sp_flags_enc" in cfg else None),
            pp_division=(str2array(cfg["pp_division"])
                         if "pp_division" in cfg else None),
            global_bsz=cfg.get("global_bsz"),
            chunks=cfg.get("chunks", 1),
            pipeline_type=cfg.get("pipeline_type", "gpipe"),
            default_dp_type=cfg.get("default_dp_type", "ddp"),
            embed_sdp=cfg.get("embed_sdp", 0),
            world=cfg.get("world"),
        )

    @classmethod
    def load(cls, path):
        with open(path) as fh:
            return cls.from_json(json.load(fh))

    @classmethod
    def uniform(cls, n_layers, world, pp_deg=1, tp=1, fsdp=False, ckpt=False,
                sp=False, **kw):
        """GLOBAL-mode equivalent: one strategy for every layer."""
        return cls(pp_deg=pp_deg, tp_sizes=[tp] * n_layers,
                   dp_types=[1 if fsdp else 0] * n_layers,
                   checkpoint_flags=[1 if ckpt else 0] * n_layers,
                   sp_flags=[1 if sp else 0] * n_layers,
                   world=world, **kw)

    def __repr__(self):
        return (f"HybridParallelConfig(pp={self.pp_deg}, tp={self.tp_sizes}, "
                f"dp_types={self.dp_types}, ckpt={self.checkpoint_flags}, "
                f"pp_division={self.pp_division})")


def layer_mesh_axes(world, pp_deg):
    """Binary factorization of the per-stage submesh: returns (k, axis
    names) with 2^k = world // pp_deg."""
    per_stage = world // pp_deg
    assert per_stage * pp_deg == world
    k = int(np.log2(per_stage))
    assert 2 ** k == per_stage, f"per-stage devices {per_stage} not a power of 2"
    return k, tuple(f"m{i}" for i in range(k))


def tp_dp_axes(k, axes, tp_size, consecutive=1):
    """Split the k binary axes into (dp_axes, tp_axes) for a layer.

    consecutive=1 → TP on the last (fastest-varying, ICI-nearest) axes,
    matching the reference's consecutive-rank TP groups
    (comm_groups.py gen_tp_group_dist).
    """
    t = int(np.log2(tp_size))
    assert 2 ** t == tp_size and t <= k
    if consecutive:
        return axes[: k - t], axes[k - t:]
    return axes[t:], axes[:t]
