"""Build + load the native Galvatron DP core (g++ → libgalvatron_dp.so).

Reference ships tools/Hetu-Galvatron/csrc/dp_core.cpp as a pybind11 module;
pybind11 is absent here so the core exposes a C ABI consumed via ctypes,
compiled on first use (same pattern as hetu_tpu/ps/build.py).
"""

from __future__ import annotations

import ctypes
import os
import warnings

import numpy as np

from ..native_build import NativeLib

_HERE = os.path.dirname(os.path.abspath(__file__))


def _declare(lib):
    i64 = ctypes.c_int64
    lib.galvatron_dp_core.restype = ctypes.c_int
    lib.galvatron_dp_core.argtypes = [
        i64, i64, i64,
        ctypes.POINTER(ctypes.c_int32),   # mem_cost [L*S]
        ctypes.POINTER(ctypes.c_double),  # intra_cost [L*S]
        ctypes.POINTER(ctypes.c_double),  # inter_cost [L*S*S]
        ctypes.POINTER(ctypes.c_int32),   # res [L]
        ctypes.POINTER(ctypes.c_double),  # cost_out
        ctypes.POINTER(i64),              # mem_left_out
    ]


_native = NativeLib(os.path.join(_HERE, "csrc", "dp_core.cpp"),
                    os.path.join(_HERE, "csrc", "libgalvatron_dp.so"),
                    declare=_declare)


def build():
    return _native.build()


def load():
    return _native.load()


def dp_core(mem_cost, intra_cost, inter_cost, max_mem):
    """Run the native DP.  mem_cost [L,S] int, intra_cost [L,S], inter_cost
    [L,S,S].  Returns (total_cost, per-layer strategy indices, mem_left);
    (inf, None, -1) if infeasible."""
    mem_cost = np.ascontiguousarray(mem_cost, dtype=np.int32)
    intra = np.ascontiguousarray(intra_cost, dtype=np.float64)
    inter = np.ascontiguousarray(inter_cost, dtype=np.float64)
    L, S = mem_cost.shape
    assert intra.shape == (L, S) and inter.shape == (L, S, S)
    res = np.zeros(L, dtype=np.int32)
    cost = ctypes.c_double(0.0)
    left = ctypes.c_int64(0)
    lib = load()
    rc = lib.galvatron_dp_core(
        L, int(max_mem), S,
        mem_cost.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        intra.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        inter.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        res.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.byref(cost), ctypes.byref(left))
    if rc != 0:
        return float("inf"), None, -1
    return float(cost.value), res.tolist(), int(left.value)


_fallback_warned = False


def dp_core_auto(mem_cost, intra_cost, inter_cost, max_mem,
                 use_native=True):
    """Run the DP on the native csrc core when it builds, the numpy
    oracle otherwise — and say WHICH ran: returns ``(result, core)``
    with ``core in ("native", "numpy")``.  A toolchain-less host must
    not silently search on a different code path than the one the
    committed plans were produced by, so the first native→numpy
    fallback warns with the build error."""
    global _fallback_warned
    if use_native:
        try:
            return dp_core(mem_cost, intra_cost, inter_cost,
                           max_mem), "native"
        except (RuntimeError, OSError) as e:
            if not _fallback_warned:
                _fallback_warned = True
                warnings.warn(
                    f"galvatron native dp_core unavailable "
                    f"({type(e).__name__}: {e}); searches run on the "
                    f"numpy oracle instead")
    return dp_core_numpy(mem_cost, intra_cost, inter_cost,
                         max_mem), "numpy"


def dp_core_numpy(mem_cost, intra_cost, inter_cost, max_mem):
    """Pure-numpy oracle of the same recurrence (test/fallback path)."""
    mem_cost = np.asarray(mem_cost, dtype=np.int64)
    intra = np.asarray(intra_cost, dtype=np.float64)
    inter = np.asarray(inter_cost, dtype=np.float64)
    L, S = mem_cost.shape
    V = int(max_mem)
    # two buffers, not a rolling array: mem_cost 0 would alias the row
    # being written (same fix as dp_core.cpp)
    f_prev = np.zeros((V, S))
    f = np.zeros((V, S))
    mark = -np.ones((L, V, S), dtype=np.int64)
    for i in range(L):
        for v in range(V - 1, -1, -1):
            for s in range(S):
                m = mem_cost[i, s]
                if v < m:
                    f[v, s] = np.inf
                    continue
                if i == 0:
                    best, best_si = f_prev[v - m, s], s
                else:
                    cands = f_prev[v - m, :] + inter[i, :, s]
                    best_si = int(np.argmin(cands))
                    best = cands[best_si]
                if np.isfinite(best):
                    f[v, s] = best + intra[i, s]
                    mark[i, v, s] = best_si
                else:
                    f[v, s] = np.inf
        f_prev, f = f, f_prev
    f_prev, f = f, f_prev  # undo the last swap: f holds layer L-1
    cur = int(np.argmin(f[V - 1]))
    total = f[V - 1, cur]
    if not np.isfinite(total):
        return float("inf"), None, -1
    res = [0] * L
    res[L - 1] = cur
    v = V - 1
    for i in range(L - 1, 0, -1):
        prev_s = int(mark[i, v, cur])
        v -= int(mem_cost[i, cur])
        cur = prev_s
        res[i - 1] = cur
    v -= int(mem_cost[0, cur])
    return float(total), res, v
