"""Platform capability probes.

Some PJRT plugins (notably the axon dev-tunnel used for single-chip TPU
access) implement the compute path but not host send/recv callbacks
(jax.debug.print / io_callback / pure_callback).  Backend NAME checks
can't detect this — the tunnel reports platform "tpu" — so capabilities
are feature-probed once per process and cached.
"""

from __future__ import annotations

_HOST_CALLBACKS = None


def force_platform_from_env():
    """Honor JAX_PLATFORMS through jax.config BEFORE any device use.

    A pre-registered accelerator plugin (the axon sitecustomize) wins
    over the env var — the config reads "axon,cpu" regardless — and with
    the tunnel down a default-backend init blocks forever.  Call this at
    the top of scripts that accept JAX_PLATFORMS (tests/conftest.py and
    bench.py stage children apply the same rule inline)."""
    import os
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def host_callbacks_supported() -> bool:
    """True iff jitted host callbacks (jax.debug.print et al) execute on
    the default backend.  Probes with a trivial jitted program once and
    caches the verdict for the process lifetime."""
    global _HOST_CALLBACKS
    if _HOST_CALLBACKS is None:
        import jax
        import jax.numpy as jnp
        if _in_trace():
            # called mid-trace with no cached verdict: a jit probe here
            # would STAGE into the enclosing program (omnistaging) and
            # "succeed" while smuggling the callback into the caller's
            # compiled program.  Answer conservatively and leave the
            # cache unset so an eager call can still establish the real
            # verdict.
            return False
        try:
            jax.block_until_ready(jax.jit(
                lambda x: (jax.debug.print("", ordered=False), x)[1]
            )(jnp.zeros(())))
            jax.effects_barrier()
            _HOST_CALLBACKS = True
        except Exception:
            _HOST_CALLBACKS = False
    return _HOST_CALLBACKS


def _in_trace() -> bool:
    """True when called under an active jax trace.

    jax.core.trace_state_clean was removed in newer jax; the portable
    detection is whether array CREATION gets staged to a Tracer (under
    omnistaging any op inside a trace context does)."""
    import jax
    import jax.numpy as jnp
    clean = getattr(jax.core, "trace_state_clean", None)
    if clean is not None:
        try:
            return not clean()
        except Exception:
            pass
    return isinstance(jnp.zeros(()) + 0, jax.core.Tracer)
