"""Platform selection helpers for the axon dev-tunnel environment."""

from __future__ import annotations


def _compat_shard_map():
    """`jax.shard_map` across jax versions: the top-level export (and its
    ``check_vma`` kwarg) arrived in 0.6; older jax ships the same
    function as ``jax.experimental.shard_map.shard_map`` with the kwarg
    named ``check_rep``.  Import ``shard_map`` from here, not jax."""
    try:
        from jax import shard_map as sm
        return sm
    except ImportError:
        import functools
        from jax.experimental.shard_map import shard_map as sm

        @functools.wraps(sm)
        def wrapper(f, *args, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            # the old replication checker false-positives on scan
            # carries ("mismatched replication types ... pass
            # check_rep=False" is jax's own suggested workaround), so
            # default it off; callers can still opt back in
            kwargs.setdefault("check_rep", False)
            return sm(f, *args, **kwargs)
        return wrapper


shard_map = _compat_shard_map()


def compiled_cost_analysis(compiled):
    """XLA ``cost_analysis`` as a plain ``{str: float}`` dict across jax
    versions: 0.4.x wraps the per-device dict in a list (one entry per
    partition), newer jax returns the dict directly.  Returns ``{}``
    when the backend provides no cost model."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost) if cost else {}


#: the CompiledMemoryStats fields the profiling layer consumes, in the
#: order they are reported (device-side only; host_* mirrors excluded)
_MEMORY_FIELDS = ("generated_code_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "alias_size_in_bytes",
                  "temp_size_in_bytes")


def compiled_memory_analysis(compiled):
    """XLA ``memory_analysis`` as a plain ``{str: int}`` dict across jax
    versions: 0.4.x returns a ``CompiledMemoryStats`` attribute object,
    newer jax a dict.  Returns ``{}`` when the backend can't say."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    if isinstance(ma, dict):
        return {k: int(ma[k]) for k in _MEMORY_FIELDS if k in ma}
    out = {}
    for field in _MEMORY_FIELDS:
        v = getattr(ma, field, None)
        if v is not None:
            out[field] = int(v)
    return out


def fp8_dtype():
    """The fp8 e4m3 dtype this jax build ships, or None.

    jax >= 0.4.9 re-exports ml_dtypes' ``float8_e4m3fn`` as
    ``jnp.float8_e4m3fn``; older builds don't define it.  The quantized
    serving plane (``ops/quant.py``) gates its fp8 codec on this —
    callers fall back to int8 (or skip, in tests) when it returns
    None rather than growing their own version probes."""
    import jax.numpy as jnp
    return getattr(jnp, "float8_e4m3fn", None)


def force_platform_from_env():
    """Honor JAX_PLATFORMS through jax.config BEFORE any device use.

    A pre-registered accelerator plugin (the axon sitecustomize) wins
    over the env var — the config reads "axon,cpu" regardless — and with
    the tunnel down a default-backend init blocks forever.  Call this at
    the top of scripts that accept JAX_PLATFORMS (tests/conftest.py and
    bench.py stage children apply the same rule inline)."""
    import os
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
