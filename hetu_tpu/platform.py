"""Platform selection helpers for the axon dev-tunnel environment."""

from __future__ import annotations



def force_platform_from_env():
    """Honor JAX_PLATFORMS through jax.config BEFORE any device use.

    A pre-registered accelerator plugin (the axon sitecustomize) wins
    over the env var — the config reads "axon,cpu" regardless — and with
    the tunnel down a default-backend init blocks forever.  Call this at
    the top of scripts that accept JAX_PLATFORMS (tests/conftest.py and
    bench.py stage children apply the same rule inline)."""
    import os
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
