"""Experiment logging (reference: python/hetu/logger.py — `HetuLogger`
aggregates scalars to rank 0 over NCCL (:53-71), `WandbLogger` (:90)).

TPU redesign: in SPMD each host already sees globally-reduced losses (pjit
outputs are replicated), so "aggregation" is a host-side mean over steps;
multi-controller reduction uses jax's multihost utils when present.  The
wandb backend is gated (not baked into this image) with a JSONL fallback so
runs are always recorded.
"""

from __future__ import annotations

import time

from .telemetry.registry import JsonlWriter


class HetuLogger:
    """Scalar logger: accumulate per-step values, emit per-interval means.

    JSONL records go through :class:`telemetry.registry.JsonlWriter` —
    the one append-a-JSON-line path in the tree — and the elapsed
    ``time`` field is monotonic (``perf_counter``), so a wall-clock jump
    (NTP step mid-run) can't produce negative intervals.  Context-
    manager use closes the file deterministically::

        with HetuLogger(path="run.jsonl") as lg:
            lg.log(loss=...)
    """

    def __init__(self, path=None, print_interval=10, printer=print):
        self.path = path
        self.print_interval = print_interval
        self.printer = printer
        self._acc = {}
        self._step = 0
        self._t0 = time.perf_counter()
        self._writer = JsonlWriter(path) if path else None

    def log(self, **scalars):
        self._step += 1
        for k, v in scalars.items():
            self._acc.setdefault(k, []).append(float(v))
        if self._step % self.print_interval == 0:
            self.flush()

    def flush(self):
        if not self._acc:
            return
        means = {k: sum(v) / len(v) for k, v in self._acc.items()}
        rec = {"step": self._step,
               "time": round(time.perf_counter() - self._t0, 3), **means}
        if self.printer is not None:
            self.printer(" ".join(
                [f"step {self._step}"]
                + [f"{k}={v:.6g}" for k, v in means.items()]))
        if self._writer is not None:
            self._writer.write(rec)
        self._acc = {}

    def close(self):
        self.flush()
        if self._writer is not None:
            self._writer.close()
            self._writer = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class WandbLogger(HetuLogger):
    """wandb-backed logger with JSONL fallback when wandb is unavailable
    (reference logger.py:90)."""

    def __init__(self, project="hetu_tpu", name=None, config=None,
                 path=None, print_interval=10):
        super().__init__(path=path, print_interval=print_interval)
        self._wandb = None
        try:  # wandb is not baked into this image; fall back silently
            import wandb  # type: ignore
            self._wandb = wandb
            wandb.init(project=project, name=name, config=config or {})
        except Exception:
            pass

    def log(self, **scalars):
        if self._wandb is not None:
            self._wandb.log(scalars)
        super().log(**scalars)

    def close(self):
        super().close()
        if self._wandb is not None:
            self._wandb.finish()
