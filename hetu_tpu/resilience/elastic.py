"""Elastic training: survive chip loss and preemption by re-planning.

On a real pod, capacity changes mid-run: a chip dies (the next
collective fails — no notification), or the scheduler preempts the job
(SIGTERM, seconds of notice).  The seed's answer was a human: notice
the crash, pick a new slice, edit the mesh, restart from the last
checkpoint by hand.  :class:`ElasticTrainer` closes the loop — it is a
supervisor around an :class:`~hetu_tpu.graph.executor.Executor` plus a
parallelization strategy, and on any capacity change it runs one
recover protocol:

1. **Flush or adopt** — on an explicit resize it flushes a final
   checkpoint through the :class:`~.checkpointer.RollingCheckpointManager`;
   after a device loss or a preemption flush it ADOPTS the newest good
   rolling checkpoint instead (the hook already saved, and a dead chip
   can't flush).
2. **Re-plan** — the auto-parallel planner searches the best plan
   *constrained to the survivors* (``emit_plan(devices=...)``); with no
   calibrated profile it falls back to the always-executable hand plan
   (``emit_fallback_plan``: pure DP over what's left).
3. **Resharded restore** — a sharded checkpoint written under the OLD
   geometry restores through
   :func:`~hetu_tpu.graph.checkpoint.restore_resharded` into the new
   executor's own target shardings; a pickle checkpoint re-places
   through ``load_state_dict`` under the new mesh.
4. **Resume** — the rebuilt executor continues from the checkpointed
   ``global_step``; the batch stream is a pure function of the step
   (``Dataloader.skip_to_step``), so when the DP degree is unchanged
   the continuation is bitwise-identical to an uninterrupted run, and
   under a shrunk geometry it is exact-step and finite.

Recovery time is priced honestly: the whole protocol runs inside an
``elastic_reshard`` tracer span (the GoodputLedger's ``reshard``
bucket), with the checkpoint save/restore inside carved out of their
steady-state buckets via nested ``elastic_ckpt_save`` /
``elastic_ckpt_restore`` spans.  Every recovery increments
``hetu_elastic_resizes_total{cause=}``, observes
``hetu_elastic_recovery_seconds``, updates ``hetu_elastic_world_size``,
and dumps an ``elastic_reshard`` flight-recorder incident.
"""

from __future__ import annotations

import signal
import time
import warnings

import numpy as np

from .. import telemetry as _telemetry
from .faults import DeviceLost

__all__ = ["ElasticTrainer"]


class ElasticTrainer:
    """Supervise an executor through capacity changes.

    ``build``: callable ``strategy -> Executor`` — rebuilds the SAME
    graph under a new strategy (use ``ht.name_scope()`` + fixed names
    so a rebuild is deterministic).  ``manager``: a
    :class:`~.checkpointer.RollingCheckpointManager` (``sharded=True``
    enables cross-geometry restores through orbax; pickle mode
    re-places through ``load_state_dict``).

    ``devices``: the initial device pool (default: the full fleet).
    ``strategy_fn``: optional ``devices -> Strategy`` override; without
    it the planner emits a plan constrained to the pool (``plan_args``
    = dict with ``layers``/``mem_budget_bytes``/... forwarded to
    ``emit_plan``) or the hand fallback, lowered through
    :class:`~hetu_tpu.parallel.strategies.PlannedParallel`.

    ``install_hook=True`` arms the manager's SIGTERM flush for the
    live executor (re-armed after every rebuild) with
    ``exit_on_save=False`` — the train loop sees ``manager.preempted``
    and recovers instead of dying."""

    def __init__(self, build, manager, *, subgraph="train", devices=None,
                 checkpoint_every=1, strategy_fn=None, plan_args=None,
                 install_hook=True, preempt_sig=signal.SIGTERM):
        import jax
        self.build = build
        self.manager = manager
        self.subgraph = subgraph
        self.checkpoint_every = int(checkpoint_every)
        self.strategy_fn = strategy_fn
        self.plan_args = dict(plan_args) if plan_args else None
        self.install_hook = bool(install_hook)
        self.preempt_sig = int(preempt_sig)
        self.devices = (list(devices) if devices is not None
                        else list(jax.devices()))
        self.resharded = 0          # completed recoveries
        self.recovery_s = []        # wall seconds per recovery
        self.last_plan = None       # the plan dict the live mesh came from
        reg = _telemetry.get_registry()
        self._m_resizes = reg.counter(
            "hetu_elastic_resizes_total",
            "Elastic geometry changes, by cause "
            "(device_lost / preempted / manual)", labels=("cause",))
        self._m_recovery = reg.histogram(
            "hetu_elastic_recovery_seconds",
            "Wall time of one elastic recovery (flush/adopt + re-plan "
            "+ resharded restore + rebuild)")
        self._m_world = reg.gauge(
            "hetu_elastic_world_size",
            "Devices the live executor currently trains over")
        self._tr = _telemetry.get_tracer()
        self.executor = self.build(self._strategy(self.devices))
        self._m_world.set(len(self.devices))
        if self.install_hook:
            self.manager.install_preemption_hook(
                self.executor, sig=self.preempt_sig, exit_on_save=False)

    # -- planning ----------------------------------------------------------
    @property
    def global_step(self):
        return int(self.executor._global_step)

    def _strategy(self, devices):
        """The strategy for a device pool: the user's override, the
        planner constrained to the pool, or the hand fallback."""
        if self.strategy_fn is not None:
            self.last_plan = None
            return self.strategy_fn(devices)
        from ..parallel.strategies import PlannedParallel
        from ..planner.plan import (PlanError, emit_fallback_plan,
                                    emit_plan)
        plan = None
        if self.plan_args:
            kw = dict(self.plan_args)
            layers = kw.pop("layers", None)
            try:
                if layers is None:
                    raise PlanError("plan_args without layers")
                plan = emit_plan(layers, devices=devices, **kw)
            except PlanError as e:
                warnings.warn(
                    f"elastic re-plan over {len(devices)} device(s) "
                    f"failed ({e}) — degrading to the hand fallback")
                plan = None
        if plan is None:
            plan = emit_fallback_plan(
                devices=len(devices),
                n_layers=(self.plan_args or {}).get("n_layers", 1))
        self.last_plan = plan
        return PlannedParallel(plan, devices=devices)

    def _surviving(self):
        lost = getattr(self.executor, "lost_devices", None) or []
        alive = [d for d in self.devices if d not in lost]
        if not alive:
            raise RuntimeError(
                "elastic recovery impossible: no surviving devices")
        return alive

    # -- the recover protocol ----------------------------------------------
    def _recover(self, devices, cause, flush=True):
        """Flush/adopt -> re-plan -> rebuild -> resharded restore.
        Returns the step training resumes from."""
        t0 = time.perf_counter()
        with self._tr.span("elastic_reshard"):
            if flush:
                try:
                    with self._tr.span("elastic_ckpt_save"):
                        self.manager.save(self.executor)
                except Exception as e:
                    # a half-dead executor may not flush — adopt the
                    # newest rolling checkpoint instead of dying here
                    warnings.warn(
                        f"elastic flush failed ({type(e).__name__}: {e})"
                        " — adopting the newest rolling checkpoint")
            strategy = self._strategy(devices)
            old = self.executor
            new = self.build(strategy)
            with self._tr.span("elastic_ckpt_restore"):
                if self.manager.sharded:
                    step = self.manager.restore_latest(new, reshard=True)
                else:
                    step = self.manager.restore_latest(new)
            try:
                old.close()
            except Exception as e:
                # best-effort: the old executor's mesh may already be
                # half-dead — the new one owns fresh buffers either way
                warnings.warn(
                    f"elastic: closing the old executor failed "
                    f"({type(e).__name__}: {e})")
            self.executor = new
            self.devices = list(devices)
        dt = time.perf_counter() - t0
        if self.install_hook:
            # re-arm for the NEW executor (in place — the manager's
            # hook registry prevents self-chaining double flushes)
            self.manager.install_preemption_hook(
                new, sig=self.preempt_sig, exit_on_save=False)
        self.resharded += 1
        self.recovery_s.append(dt)
        self._m_resizes.labels(cause=str(cause)).inc()
        self._m_recovery.observe(dt)
        self._m_world.set(len(self.devices))
        _telemetry.get_flight().incident(
            "elastic_reshard",
            extra={"cause": str(cause), "world": len(self.devices),
                   "step": int(step), "recovery_s": round(dt, 6)})
        return int(step)

    def resize(self, devices, cause="manual"):
        """Explicitly re-plan onto a new device pool (scale up when
        capacity returns, down ahead of a planned maintenance): flush,
        re-plan, restore, continue.  Returns the resume step."""
        return self._recover(list(devices), cause, flush=True)

    # -- the supervised loop -----------------------------------------------
    def train(self, n_steps, batch_fn):
        """Run ``n_steps`` global steps, surviving device loss and
        preemption along the way.  ``batch_fn(step) -> feed_dict`` must
        be a pure function of the global step (a
        ``Dataloader.skip_to_step``-positioned stream, or closed-over
        arrays) — that purity is what makes a recovered run land on
        exactly the batches an uninterrupted one would have seen.

        Returns ``{step: loss}`` for every step that RAN to completion
        (a step rolled back by a recovery re-runs and overwrites)."""
        losses = {}
        stalls, last_fault_step = 0, None
        while True:
            if self.manager.preempted:
                # the SIGTERM hook already flushed: adopt, don't re-save
                self.manager.preempted = False
                self._recover(self._surviving(), cause="preempted",
                              flush=False)
                continue
            i = self.global_step
            if i >= int(n_steps):
                break
            try:
                out = self.executor.run(
                    self.subgraph, feed_dict=batch_fn(i),
                    convert_to_numpy_ret_vals=True)
            except DeviceLost:
                # A real loss shrinks _surviving() every time, so the
                # pool empties (RuntimeError) before this can spin; a
                # phantom loss that shrinks nothing would retry the
                # same step forever — bound it at 3 no-progress
                # recoveries and surface the fault instead.
                if last_fault_step == i:
                    stalls += 1
                    if stalls >= 3:
                        raise
                else:
                    stalls = 0
                last_fault_step = i
                self._recover(self._surviving(), cause="device_lost",
                              flush=False)
                continue
            losses[i] = float(np.asarray(out[0]))
            self.manager.maybe_save(self.executor, self.checkpoint_every)
        return losses
