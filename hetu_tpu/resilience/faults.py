"""Deterministic, seed-driven fault injection for tests and the chaos
bench.

Every injector here is a thin, composable wrapper that makes ONE
specific failure happen at a KNOWN place, reproducibly:

* batch corruption   — ``nan_stream`` / ``corrupt_batch`` poison float
  leaves of the k-th batch (what the StepGuard's fused sentinel must
  catch);
* iterator failure   — ``raising_stream`` raises ``InjectedFault`` from
  the dataloader iterator (the prefetcher's err channel must carry it
  to the consumer);
* producer death     — ``killer_stream`` raises ``PrefetcherKilled``
  (``SystemExit``) INSIDE the prefetch producer thread: it escapes the
  producer's ``except Exception`` and threading swallows it silently,
  so the thread dies with no sentinel on the queue — the honest
  simulation of a segfaulted/OOM-killed worker, which the consumer's
  liveness check must surface within one step;
* PS RPC faults      — ``delay_rpc`` stalls calls, ``drop_rpc`` closes
  the client's pooled sockets mid-conversation so the transport's
  reconnect+retransmit (and the server's dedup cache) must absorb it;
* torn files         — ``tear_file`` truncates a checkpoint the way a
  killed writer would have (only possible pre-atomic-write; the
  restore path must skip it);
* preemption         — ``simulate_preemption`` raises SIGTERM in the
  current process, exercising the checkpoint manager's flush hook.

``FaultInjector`` adds seed-driven *placement*: the same seed always
injects at the same steps, so a chaos run is replayable.
"""

from __future__ import annotations

import os
import signal
import time

import numpy as np


class InjectedFault(RuntimeError):
    """An error deliberately raised by a fault injector."""


class DeviceLost(RuntimeError):
    """A device backing the executor's mesh dropped out mid-run.  On a
    real pod a dead chip surfaces exactly like this: the NEXT dispatch
    (a collective touching the chip) fails — there is no callback.
    Carries the lost device so a supervisor (``resilience/elastic``)
    can compute the surviving set."""

    def __init__(self, device=None):
        super().__init__(f"device lost: {device}")
        self.device = device


# Kills a prefetch producer thread SILENTLY when raised from the wrapped
# source iterator: SystemExit escapes the producer's `except Exception`
# and threading discards it with no traceback, so no error sentinel is
# enqueued — the consumer sees only a dead thread, like after a real
# worker crash.  Must be EXACTLY SystemExit (an alias, not a subclass):
# threading.excepthook silences only the exact class.
PrefetcherKilled = SystemExit


# -- batch corruption ------------------------------------------------------

def corrupt_batch(batch, keys=None, value=np.nan):
    """Return a copy of ``batch`` (dict / tuple / array) with float
    leaves poisoned by ``value`` in element 0.  Integer leaves (ids)
    are left alone — NaN has no integer encoding, and real corruption
    enters through the float path (labels, dense features, activations).
    ``keys`` restricts which dict leaves are hit."""
    def _poison(arr):
        arr = np.array(arr, copy=True)
        if np.issubdtype(arr.dtype, np.floating) and arr.size:
            arr.reshape(-1)[0] = value
        return arr

    if isinstance(batch, dict):
        return {k: (_poison(v) if keys is None or k in keys
                    or getattr(k, "name", None) in (keys or ()) else v)
                for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        return type(batch)(_poison(v) for v in batch)
    return _poison(batch)


def nan_stream(iterator, at, keys=None, value=np.nan):
    """Yield ``iterator``'s batches, poisoning the ones at 0-based
    indices in ``at`` (an int or a collection of ints)."""
    steps = {int(at)} if np.isscalar(at) else {int(a) for a in at}
    for i, batch in enumerate(iterator):
        yield corrupt_batch(batch, keys, value) if i in steps else batch


def raising_stream(iterator, at, exc=None):
    """Yield batches until index ``at``, then raise (default
    :class:`InjectedFault`) — a dataloader that dies mid-epoch."""
    for i, batch in enumerate(iterator):
        if i == int(at):
            raise exc if exc is not None else InjectedFault(
                f"injected dataloader failure at batch {at}")
        yield batch


def killer_stream(iterator, at):
    """Yield batches until index ``at``, then kill the consuming thread
    silently (see :class:`PrefetcherKilled`)."""
    for i, batch in enumerate(iterator):
        if i == int(at):
            raise PrefetcherKilled(
                f"injected producer death at batch {at}")
        yield batch


# -- PS RPC faults ---------------------------------------------------------

def delay_rpc(table, seconds, calls=1):
    """Stall the next ``calls`` RPCs of a ``RemoteTable`` by ``seconds``
    (a congested or GC-pausing server).  Returns an undo callable."""
    orig = table._call
    state = {"left": int(calls)}

    def wrapped(header, *arrays, **kw):
        if state["left"] > 0:
            state["left"] -= 1
            time.sleep(float(seconds))
        return orig(header, *arrays, **kw)

    table._call = wrapped
    return lambda: setattr(table, "_call", orig)


def drop_rpc(table, calls=1):
    """Close the client's pooled sockets immediately before each of the
    next ``calls`` RPCs: the request dies mid-wire and the transport's
    reconnect + retransmit path (with the server's dedup cache for
    non-idempotent verbs) must absorb it.  Returns an undo callable."""
    orig = table._call
    state = {"left": int(calls)}

    def wrapped(header, *arrays, **kw):
        if state["left"] > 0:
            state["left"] -= 1
            for c in table._pool:
                sock = c.sock
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass    # socket already dead — the goal anyway
        return orig(header, *arrays, **kw)

    table._call = wrapped
    return lambda: setattr(table, "_call", orig)


# -- serving faults --------------------------------------------------------
# The serving-engine counterparts of the training-path faults: each one
# makes a production failure of the continuous-batching engine happen at
# a KNOWN place (bench.py --chaos --serve and tests/test_serving_
# robustness.py drive them).

def poison_slot_kv(engine, slot, value=np.nan):
    """Poison one slot's K/V cache rows with ``value`` — a corrupted HBM
    row / overflowed activation deposited into the pooled cache.  The
    next decode step's logits for THAT slot (and only that slot — slots
    attend their own rows) go non-finite, which is exactly what the
    engine's in-graph watchdog sentinel must flag."""
    import jax.numpy as jnp

    slot = int(slot)
    engine.cache.k = engine.cache.k.at[slot].set(value)
    engine.cache.v = engine.cache.v.at[slot].set(value)
    return slot


def raising_engine_step(engine, at, exc=None):
    """Make the engine's ``at``-th decode-step CALL (0-based, counted
    from now) raise (default :class:`InjectedFault`) BEFORE dispatch —
    a poisoned executable / runtime failure the host sees as an
    exception, not a sentinel.  Returns an undo callable."""
    orig = engine._step_fn
    state = {"n": 0}

    def wrapped(*args, **kw):
        n = state["n"]
        state["n"] += 1
        if n == int(at):
            raise exc if exc is not None else InjectedFault(
                f"injected decode-step failure at call {at}")
        return orig(*args, **kw)

    engine._step_fn = wrapped
    return lambda: setattr(engine, "_step_fn", orig)


def leak_slot(engine):
    """Allocate a KV slot that NO request owns — the accounting leak a
    crashed request path leaves behind.  Without the engine's reconcile
    sweep the slot never returns to the pool and admission eventually
    starves; with it, the sweep frees the orphan within one iteration.
    Returns the leaked slot id (None if the pool is already full)."""
    return engine.cache.alloc(owner="__injected_leak__")


def stalling_consumer(seconds, collect=None, fail_after=None):
    """A stream callback that STALLS ``seconds`` on every delivery (a
    slow/blocked client holding the decode loop hostage) and, when
    ``fail_after`` is set, raises :class:`InjectedFault` from the
    ``fail_after``-th call onward (a disconnected client).  ``collect``
    (a list) receives the tokens that were delivered."""
    state = {"n": 0}

    def cb(tok, req):
        state["n"] += 1
        if collect is not None:
            collect.append(int(tok))
        if fail_after is not None and state["n"] > int(fail_after):
            raise InjectedFault(
                f"injected consumer failure at delivery {state['n']}")
        if seconds:
            time.sleep(float(seconds))

    return cb


# -- embedding-serving faults ----------------------------------------------
# The tiered embedding path's failure classes (serving/embedding/):
# where a slot fault poisons one LLM stream, these attack the HOT-ROW
# CACHE contract — rows going stale under it, and admission churn
# defeating it.

def stale_rows(table, keys, value=1.0):
    """Apply an update to ``keys`` on the HOST embedding tier, bumping
    their row versions — every device-cached copy of those rows is now
    stale, and a staleness-bounded cache must refresh them within its
    bound (bound 0: on the very next lookup).  Accepts a
    ``ps.EmbeddingTable`` (push) or ``ps.CacheSparseTable`` (update
    through the HET cache, then flushed so the backing table moves
    too).  Returns the updated keys."""
    keys = np.asarray(keys).reshape(-1).astype(np.int64)
    dim = table.dim
    grads = np.full((keys.size, dim), float(value), np.float32)
    if hasattr(table, "embedding_update"):       # CacheSparseTable
        table.embedding_update(keys, grads).result()
        table.flush()
    else:
        table.push(keys, grads)
    return keys


def thrash_cache(cache, n_keys, seed=0, lo=0, hi=None):
    """Flood a :class:`~hetu_tpu.serving.embedding.DeviceHotRowCache`
    with one-shot COLD keys — the adversarial anti-Zipf workload that
    defeats LFU/LRU admission and forces eviction churn (every flood
    key is a miss, and each one evicts a resident row once the cache is
    full).  Keys are drawn seeded from ``[lo, hi)`` (``hi`` defaults to
    10x the cache so floods barely repeat) in batches the cache can
    hold.  Returns the number of evictions the flood caused."""
    rng = np.random.default_rng(seed)
    hi = int(hi) if hi is not None else lo + 10 * cache.cache_rows
    ev0 = cache.evictions
    batch = max(1, cache.cache_rows // 2)
    keys = rng.integers(int(lo), hi, int(n_keys))
    for i in range(0, keys.size, batch):
        cache.lookup_slots(keys[i:i + batch])
    return cache.evictions - ev0


# -- fleet faults ----------------------------------------------------------
# Replica-level failures for the fleet layer (bench.py --chaos --serve
# --fleet and tests/test_fleet.py): where the serving faults above hit
# one slot/consumer, these take out a WHOLE engine — the blast radius
# the EngineFleet's quarantine/failover/restart machinery must contain.

def crash_engine(engine, at=0, exc=None):
    """Make the engine's ``at``-th ``step()`` CALL (0-based, counted
    from now) raise OUTSIDE the watchdog's try blocks — the engine-loop
    bug / runtime abort that kills the whole engine, not one slot.  The
    fleet driver sees the exception escape ``step()`` and quarantines
    the replica.  Returns an undo callable."""
    orig = engine.step
    state = {"n": 0}

    def wrapped(*args, **kw):
        n = state["n"]
        state["n"] += 1
        if n == int(at):
            raise exc if exc is not None else InjectedFault(
                f"injected engine crash at step call {at}")
        return orig(*args, **kw)

    engine.step = wrapped
    return lambda: setattr(engine, "step", orig)


def wedge_engine(engine, seconds, at=0):
    """Make the engine's ``at``-th decode-step call STALL ``seconds``
    before dispatch — a hung device call / deadlocked runtime.  The
    driver thread is stuck inside ``step()``, so the replica's
    heartbeat goes stale and the fleet supervisor must quarantine it
    from OUTSIDE (it cannot get the lock).  Bounded, so the zombie
    daemon thread eventually exits.  Returns an undo callable."""
    orig = engine._step_fn
    state = {"n": 0}

    def wrapped(*args, **kw):
        n = state["n"]
        state["n"] += 1
        if n == int(at):
            time.sleep(float(seconds))
        return orig(*args, **kw)

    engine._step_fn = wrapped
    return lambda: setattr(engine, "_step_fn", orig)


def slow_engine(engine, seconds):
    """Make EVERY decode-step call of this engine take an extra
    ``seconds`` — the straggler replica (thermal throttling, a noisy
    neighbor).  Not a fault the health machine trips on; the fleet's
    latency-aware dispatch must simply learn to route around it.
    Returns an undo callable."""
    orig = engine._step_fn

    def wrapped(*args, **kw):
        time.sleep(float(seconds))
        return orig(*args, **kw)

    engine._step_fn = wrapped
    return lambda: setattr(engine, "_step_fn", orig)


# -- KV transfer faults (serving/kv_transfer.py wire) -----------------------
# The fleet routes every migration blob through ``fleet.transfer_filter``
# when one is set; these injectors compose with whatever filter was
# already installed and return an undo callable like everything above.

def _wrap_transfer(fleet, fn):
    prev = fleet.transfer_filter

    def filt(blob):
        if prev is not None:
            blob = prev(blob)
            if blob is None:
                return None
        return fn(blob)

    fleet.transfer_filter = filt
    return lambda: setattr(fleet, "transfer_filter", prev)


def drop_transfer(fleet, at=0):
    """Make the fleet's ``at``-th KV migration transfer (0-based,
    counted from now) vanish in flight — the network ate it.  The
    receiver never sees bytes; the fleet must fall back to
    teacher-forced replay with zero stream divergence."""
    state = {"n": 0}

    def fn(blob):
        n = state["n"]
        state["n"] += 1
        return None if n == int(at) else blob

    return _wrap_transfer(fleet, fn)


def corrupt_transfer(fleet, at=0):
    """Flip one byte in the middle of the ``at``-th migration blob —
    bit rot in transit.  The CRC32 frame walk on the receiver must
    reject it loudly (TransferError), leaving both pools untouched."""
    state = {"n": 0}

    def fn(blob):
        n = state["n"]
        state["n"] += 1
        if n != int(at):
            return blob
        b = bytearray(blob)
        b[len(b) // 2] ^= 0xFF
        return bytes(b)

    return _wrap_transfer(fleet, fn)


#: keep enough bytes that the magic survives — the failure under test
#: is a TORN FRAME, not a non-blob
_TRANSFER_MAGIC = b"HTKV1"


def tear_transfer(fleet, at=0, frac=0.5):
    """Truncate the ``at``-th migration blob to ``frac`` of its bytes —
    the sender died mid-write.  The receiver's frame walk must reject
    the torn frame, never a partial splice."""
    state = {"n": 0}

    def fn(blob):
        n = state["n"]
        state["n"] += 1
        if n != int(at):
            return blob
        return blob[:max(len(_TRANSFER_MAGIC),
                         int(len(blob) * float(frac)))]

    return _wrap_transfer(fleet, fn)


# -- files & process -------------------------------------------------------

def tear_file(path, frac=0.5, keep_bytes=None):
    """Truncate ``path`` the way a killed non-atomic writer would have:
    keep the first ``keep_bytes`` (or ``frac`` of the file)."""
    size = os.path.getsize(path)
    keep = int(size * float(frac)) if keep_bytes is None else int(keep_bytes)
    with open(path, "r+b") as f:
        f.truncate(max(0, min(keep, size)))
    return path


def simulate_preemption(sig=signal.SIGTERM):
    """Deliver the pod scheduler's preemption notice to THIS process
    (synchronously, in the main thread)."""
    signal.raise_signal(sig)


# -- capacity loss ---------------------------------------------------------

def lose_device(executor, device=None):
    """Simulate losing one device of the executor's mesh: the NEXT
    dispatch of EVERY subgraph raises :class:`DeviceLost` (how a dead
    chip actually surfaces — a failed collective, not a notification),
    and the device is appended to ``executor.lost_devices`` so a
    supervisor can compute the surviving set.  Defaults to the mesh's
    last device.  Returns an undo callable (a supervisor that rebuilds
    the executor never needs it; a test that wants the "chip back"
    does)."""
    mesh = getattr(executor, "mesh", None)
    if device is None:
        if mesh is not None:
            device = list(mesh.devices.flat)[-1]
        else:
            import jax
            device = jax.devices()[-1]
    lost = getattr(executor, "lost_devices", None)
    if lost is None:
        lost = []
        executor.lost_devices = lost
    lost.append(device)
    orig = {}
    for name, sub in executor.subexecutor.items():
        orig[name] = sub.run

        def _raiser(*a, _d=device, **kw):
            raise DeviceLost(_d)
        sub.run = _raiser

    def undo():
        for name, sub in executor.subexecutor.items():
            if name in orig:
                sub.run = orig[name]
        lost = getattr(executor, "lost_devices", None)
        if lost is not None and device in lost:
            lost.remove(device)
    return undo


def preempt_during_save(mgr, sig=signal.SIGTERM, frac=0.5,
                        deliver=None):
    """Arm the NEXT ``mgr.save`` to be preempted MID-FLUSH: what lands
    on disk is exactly the wreckage a SIGTERM inside the write window
    leaves — a torn payload under the final checkpoint name (pickle
    mode) or a complete-looking shard directory with one truncated
    file and no manifest entry (sharded mode: one host of the pod
    never finished), the preemption notice is delivered, and the save
    raises :class:`InjectedFault` instead of returning.  The contract
    under test: ``restore_latest`` must still ADOPT the previous good
    checkpoint — the torn flush fails verification (the existing
    torn-manifest path) and falls over.

    ``deliver`` controls the actual SIGTERM: ``None`` (default) raises
    it only when a non-default handler is installed (a bare test
    process must not be killed); ``True``/``False`` force it.  One-
    shot; returns an undo callable that disarms an unfired injector."""
    orig = mgr.save
    prev_last = mgr.last_saved_step

    def _armed_save(executor, step=None):
        mgr.save = orig                      # one-shot: disarm first so a
        prev_handler = signal.getsignal(sig)  # chained flush hook still works
        if mgr.sharded:
            import shutil
            path = orig(executor, step=step)
            step_no = mgr.last_saved_step
            fname = os.path.basename(path)
            # rewind the manifest to before this save (the kill landed
            # before the manifest write) and tear the largest shard
            # file — a host that never finished its part
            entries = [e for e in mgr._read_manifest()
                       if e.get("file") != fname]
            mgr._write_manifest(entries)
            files = [os.path.join(dp, fn)
                     for dp, _dn, fns in os.walk(path) for fn in fns]
            data = [f for f in files if os.path.getsize(f) > 0]
            if data:
                tear_file(max(data, key=os.path.getsize), frac=frac)
            mgr.last_saved_step = prev_last
        else:
            import pickle as _pickle
            state = executor.state_dict()
            step_no = (int(state.get("global_step", 0))
                       if step is None else int(step))
            blob = _pickle.dumps(state,
                                 protocol=_pickle.HIGHEST_PROTOCOL)
            fname = f"{mgr.prefix}-{step_no:010d}.pkl"
            with open(os.path.join(mgr.directory, fname), "wb") as f:
                f.write(blob[:max(1, int(len(blob) * float(frac)))])
        want = deliver
        if want is None:
            want = (callable(prev_handler)
                    and prev_handler not in (signal.SIG_IGN,
                                             signal.SIG_DFL))
        if want:
            signal.raise_signal(sig)
        raise InjectedFault(
            f"preempted during checkpoint flush (step {step_no})")

    mgr.save = _armed_save

    def undo():
        if mgr.save is _armed_save:
            mgr.save = orig
    return undo


# -- seeded placement ------------------------------------------------------

class FaultInjector:
    """Seed-driven fault placement: the same seed plans the same faults
    at the same steps, so chaos runs replay exactly."""

    def __init__(self, seed=0):
        self.seed = int(seed)
        self.rng = np.random.default_rng(self.seed)

    def pick_steps(self, n_steps, n_faults=1, low=1):
        """``n_faults`` distinct 0-based step indices in
        ``[low, n_steps)``, sorted (deterministic per seed)."""
        lo, hi = int(low), int(n_steps)
        if hi - lo < int(n_faults):
            raise ValueError(
                f"cannot place {n_faults} faults in [{lo}, {hi})")
        picks = self.rng.choice(np.arange(lo, hi), size=int(n_faults),
                                replace=False)
        return sorted(int(p) for p in picks)

    # stream wrappers bound to this injector's plan
    def nan_batches(self, iterator, n_steps, n_faults=1, keys=None):
        at = self.pick_steps(n_steps, n_faults)
        return at, nan_stream(iterator, at, keys=keys)

    def kill_producer(self, iterator, n_steps):
        (at,) = self.pick_steps(n_steps, 1)
        return at, killer_stream(iterator, at)

    def raise_in_loader(self, iterator, n_steps, exc=None):
        (at,) = self.pick_steps(n_steps, 1)
        return at, raising_stream(iterator, at, exc=exc)
