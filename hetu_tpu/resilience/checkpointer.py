"""Rolling, torn-proof checkpoints + preemption flush.

On TPU pods preemption is routine: the scheduler sends SIGTERM and the
host has seconds to get state off the machine.  The seed's
``Executor.save`` truncated the target file in place — a kill mid-save
destroyed the PREVIOUS checkpoint too, turning a preemption into a
total loss.  ``RollingCheckpointManager`` closes that whole class:

* every write is atomic (same-directory temp + ``os.replace``, see
  ``graph/checkpoint.py``) — a torn write never shadows a good file;
* a ``MANIFEST.json`` (itself atomically replaced) records step, byte
  count, and CRC32 per checkpoint, so ``restore_latest`` can PROVE a
  file intact before unpickling it, and fall back to the previous good
  one when the newest is torn, truncated, or non-finite;
* keep-last-K retention bounds disk;
* ``install_preemption_hook`` flushes a final checkpoint from the
  SIGTERM handler, so a preempted run resumes bitwise (params, opt
  state, RNG key, and step counter all ride ``Executor.state_dict``);
* host-store PS embedding tables registered via ``register_ps_table``
  are snapshotted next to every checkpoint and restored with it, so a
  rollback rewinds the PS rows too — without this, ``restore_latest``
  rewound device state while the host store kept its post-fault rows
  and the "restored" model silently mixed two points in time.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import signal
import time
import warnings
import zlib

import numpy as np

from .. import telemetry as _telemetry
from ..graph.checkpoint import (CheckpointError, GeometryMismatch,
                                atomic_write_bytes, describe_geometry,
                                executor_geometry, geometry_compatible,
                                validate_state)

MANIFEST_NAME = "MANIFEST.json"
SHARDED_SUFFIX = ".orbax"


class RollingCheckpointManager:
    """Keep-last-K atomic checkpoints of an Executor under one directory.

    ``save(executor)`` writes ``<prefix>-<step>.pkl`` + manifest entry
    and prunes beyond ``keep``; ``restore_latest(executor)`` walks the
    manifest newest-first (plus any on-disk checkpoints a lost manifest
    forgot), skips torn/corrupt/non-finite files with a warning, and
    loads the first good one.

    ``sharded=True`` switches the payload from a single-host pickle to
    an orbax SHARD DIRECTORY (``<prefix>-<step>.orbax/``) written via
    ``graph.checkpoint.save_sharded`` — each host of a multi-host pod
    writes only its addressable shards, so a 100B-param state never
    materializes on one machine.  The manifest entry then covers the
    WHOLE shard set (every file in the directory, with bytes + CRC32),
    and ``restore_latest`` proves the full set intact before touching
    the executor: a torn set (file missing, truncated, or corrupt —
    e.g. a host preempted mid-save) fails that candidate over to an
    older checkpoint exactly like a torn pickle does.  Rolling
    retention, the preemption flush hook, and registered PS-table
    snapshots all work identically in both modes.
    """

    def __init__(self, directory, keep=3, prefix="ckpt", ps_tables=None,
                 sharded=False):
        if int(keep) < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = int(keep)
        self.prefix = str(prefix)
        self.sharded = bool(sharded)
        self.preempted = False
        self.last_saved_step = None
        self._prev_handlers = {}
        self._hooked = {}       # sig -> {"executor", "handler", "prev"}
        # host-store embedding tables (ps/store.py) snapshotted alongside
        # every checkpoint; anything with .save(path)/.load(path) works
        self.ps_tables = dict(ps_tables or {})
        reg = _telemetry.get_registry()
        self._m_saves = reg.counter(
            "hetu_checkpoint_saves_total", "Rolling checkpoints written")
        self._m_save_time = reg.histogram(
            "hetu_checkpoint_save_seconds",
            "Wall time of one rolling checkpoint save (incl. PS "
            "snapshots + manifest + retention)")
        self._m_restore_time = reg.histogram(
            "hetu_checkpoint_restore_seconds",
            "Wall time of restore_latest (incl. verify + fallbacks)")

    def register_ps_table(self, name, table):
        """Snapshot ``table`` (``save(path)``/``load(path)``, e.g. a
        ps.EmbeddingTable) with every checkpoint under key ``name``, and
        restore it in ``restore_latest`` — PS rows rewind with the
        device state."""
        for attr in ("save", "load"):
            if not callable(getattr(table, attr, None)):
                raise TypeError(
                    f"ps table {name!r} lacks a callable .{attr}(path)")
        self.ps_tables[str(name)] = table

    # -- manifest ----------------------------------------------------------
    def _manifest_path(self):
        return os.path.join(self.directory, MANIFEST_NAME)

    def _read_manifest(self):
        try:
            with open(self._manifest_path()) as f:
                m = json.load(f)
        except (OSError, ValueError):
            return []   # missing/torn manifest: the on-disk scan covers us
        entries = m.get("entries") if isinstance(m, dict) else None
        if not isinstance(entries, list):
            return []
        return [e for e in entries if isinstance(e, dict) and "file" in e]

    def _write_manifest(self, entries):
        blob = json.dumps({"version": 1, "entries": entries}).encode()
        atomic_write_bytes(blob, self._manifest_path())

    def _step_of(self, fname):
        for suffix in (".pkl", SHARDED_SUFFIX):
            if fname.endswith(suffix):
                stem = fname[len(self.prefix) + 1:-len(suffix)]
                break
        else:
            return -1
        try:
            return int(stem)
        except ValueError:
            return -1

    def entries(self):
        """Known checkpoints, NEWEST first.  Manifest entries carry
        byte/CRC evidence; bare files (or shard dirs) found on disk
        (manifest lost or stale) are still candidates, just unverifiable
        before unpickle/restore."""
        by_file = {e["file"]: e for e in self._read_manifest()}
        try:
            names = os.listdir(self.directory)
        except OSError:
            names = []
        for n in names:
            if (n.startswith(self.prefix + "-") and n not in by_file
                    and (n.endswith(".pkl")
                         or n.endswith(SHARDED_SUFFIX))):
                by_file[n] = {"file": n, "step": self._step_of(n)}
        return sorted(by_file.values(),
                      key=lambda e: (e.get("step", -1), e["file"]),
                      reverse=True)

    def latest_step(self):
        ents = self.entries()
        return int(ents[0].get("step", -1)) if ents else None

    # -- save --------------------------------------------------------------
    def _save_ps_snapshots(self, step):
        """Write each registered PS table next to the checkpoint
        (atomic: native save into a temp file + os.replace) and return
        the per-table manifest evidence."""
        out = {}
        for nm, tbl in self.ps_tables.items():
            fname = f"{self.prefix}-{int(step):010d}-ps-{nm}.bin"
            path = os.path.join(self.directory, fname)
            tmp = f"{path}.tmp.{os.getpid()}"
            try:
                tbl.save(tmp)
                with open(tmp, "rb") as f:
                    blob = f.read()
                os.replace(tmp, path)
            finally:
                if os.path.exists(tmp):
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
            out[nm] = {"file": fname, "bytes": len(blob),
                       "crc32": zlib.crc32(blob) & 0xFFFFFFFF}
        return out

    def _shard_files(self, path):
        """Per-file bytes + CRC32 evidence for every file under a shard
        directory — the manifest entry that lets ``restore_latest``
        prove a whole shard SET intact before restoring it."""
        out = {}
        for dirpath, _dirnames, files in os.walk(path):
            for fn in sorted(files):
                fp = os.path.join(dirpath, fn)
                rel = os.path.relpath(fp, path).replace(os.sep, "/")
                with open(fp, "rb") as f:
                    blob = f.read()
                out[rel] = {"bytes": len(blob),
                            "crc32": zlib.crc32(blob) & 0xFFFFFFFF}
        return out

    def save(self, executor, step=None):
        """Atomically checkpoint the executor (plus any registered PS
        tables); returns the file (or shard-directory) path."""
        t0 = time.perf_counter()
        if self.sharded:
            if step is None:
                step = int(executor._global_step)
            fname = f"{self.prefix}-{int(step):010d}{SHARDED_SUFFIX}"
            # orbax requires an absolute target path
            path = os.path.abspath(os.path.join(self.directory, fname))
            from ..graph.checkpoint import save_sharded
            save_sharded(executor, path)
            entry = {"step": int(step), "file": fname,
                     "kind": "sharded",
                     # the writing geometry (mesh axes + per-param
                     # partition specs): restore_latest validates a
                     # cross-geometry restore against this instead of
                     # guessing and dying inside orbax
                     "geometry": executor_geometry(executor),
                     "files": self._shard_files(path)}
        else:
            state = executor.state_dict()
            if step is None:
                step = int(state.get("global_step", 0))
            blob = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
            fname = f"{self.prefix}-{int(step):010d}.pkl"
            path = os.path.join(self.directory, fname)
            atomic_write_bytes(blob, path)
            entry = {"step": int(step), "file": fname,
                     "bytes": len(blob),
                     "crc32": zlib.crc32(blob) & 0xFFFFFFFF}
        if self.ps_tables:
            entry["ps"] = self._save_ps_snapshots(step)
        entries = [e for e in self._read_manifest()
                   if e.get("file") != fname]
        entries.append(entry)
        entries.sort(key=lambda e: (e.get("step", -1), e.get("file", "")))
        kept, dropped = entries[-self.keep:], entries[:-self.keep]
        # manifest first: a crash between the two steps leaves an extra
        # file on disk (harmless), never a manifest pointing at nothing
        self._write_manifest(kept)
        for e in dropped:
            victims = [e["file"]] + [p["file"]
                                     for p in e.get("ps", {}).values()]
            for vf in victims:
                vp = os.path.join(self.directory, vf)
                try:
                    if os.path.isdir(vp):
                        shutil.rmtree(vp, ignore_errors=True)
                    else:
                        os.remove(vp)
                except OSError:
                    pass    # already gone / shared-fs race: retention is
                    # best-effort, correctness lives in the manifest
        self.last_saved_step = int(step)
        self._m_saves.inc()
        self._m_save_time.observe(time.perf_counter() - t0)
        return path

    def maybe_save(self, executor, every):
        """Checkpoint when ``every`` steps have passed since the last
        save (call once per training step; cheap no-op otherwise)."""
        step = int(executor._global_step)
        if (self.last_saved_step is None
                or step - self.last_saved_step >= int(every)):
            return self.save(executor, step=step)
        return None

    # -- restore -----------------------------------------------------------
    @staticmethod
    def _check_finite_params(state):
        for name, v in state["params"].items():
            arr = np.asarray(v)
            if (np.issubdtype(arr.dtype, np.floating)
                    and not np.isfinite(arr).all()):
                raise CheckpointError(
                    f"param {name!r} has non-finite values — "
                    "checkpoint captured an already-corrupted run")

    def _read_verified(self, path, entry, check_finite):
        with open(path, "rb") as f:
            blob = f.read()
        if "bytes" in entry and len(blob) != entry["bytes"]:
            raise CheckpointError(
                f"size mismatch ({len(blob)} != {entry['bytes']} bytes) "
                "— torn write")
        if ("crc32" in entry
                and zlib.crc32(blob) & 0xFFFFFFFF != entry["crc32"]):
            raise CheckpointError("CRC mismatch — corrupt file")
        try:
            state = pickle.loads(blob)
        except Exception as e:
            raise CheckpointError(
                f"unreadable pickle ({type(e).__name__}: {e})") from e
        validate_state(state, source=path)
        if check_finite:
            self._check_finite_params(state)
        return state

    def _read_verified_sharded(self, executor, path, entry,
                               check_finite, reshard=False):
        """Prove the whole shard SET intact against the manifest (every
        file present, byte-exact, CRC-clean), then restore it to a
        host-side state WITHOUT touching the executor — a torn set
        (preempted host mid-save) fails this candidate over to an older
        checkpoint with the live state unharmed.  ``reshard=True``
        restores through :func:`graph.checkpoint.restore_resharded`
        into the executor's own (target) shardings, so the writing
        geometry doesn't have to match."""
        if not os.path.isdir(path):
            raise CheckpointError("shard directory missing")
        files = entry.get("files")
        if files:
            for rel, meta in files.items():
                fp = os.path.join(path, rel)
                try:
                    with open(fp, "rb") as f:
                        blob = f.read()
                except OSError as e:
                    raise CheckpointError(
                        f"shard file {rel} unreadable ({e}) — torn "
                        "shard set") from e
                if "bytes" in meta and len(blob) != meta["bytes"]:
                    raise CheckpointError(
                        f"shard file {rel} size mismatch ({len(blob)} "
                        f"!= {meta['bytes']}) — torn shard set")
                if ("crc32" in meta and zlib.crc32(blob) & 0xFFFFFFFF
                        != meta["crc32"]):
                    raise CheckpointError(
                        f"shard file {rel} CRC mismatch — corrupt "
                        "shard")
        else:
            warnings.warn(
                f"shard dir {entry['file']} has no manifest evidence "
                "(manifest lost?) — restoring unverified")
        from ..graph.checkpoint import (restore_resharded,
                                        restore_sharded_state,
                                        state_shardings)
        try:
            if reshard:
                state = restore_resharded(path,
                                          state_shardings(executor))
            else:
                state = restore_sharded_state(executor, path)
        except CheckpointError:
            raise
        except Exception as e:   # orbax raises a zoo on torn/invalid sets
            raise CheckpointError(
                f"unrestorable shard set "
                f"({type(e).__name__}: {e})") from e
        validate_state(state, source=path)
        if check_finite:
            self._check_finite_params(state)
        return state

    def _verify_ps_snapshots(self, entry):
        """Prove every registered table's snapshot for ``entry`` intact
        BEFORE anything is mutated; returns {name: path}.  A registered
        table with no snapshot in the entry (checkpoint predates
        registration) restores nothing for that table — warned, not
        fatal; a snapshot that is missing or corrupt on disk fails the
        whole candidate so restore falls back to an older one."""
        ps_meta = entry.get("ps", {})
        paths = {}
        for nm in self.ps_tables:
            meta = ps_meta.get(nm)
            if meta is None:
                warnings.warn(
                    f"checkpoint {entry['file']} has no PS snapshot for "
                    f"table {nm!r} (saved before registration?) — its "
                    "rows are NOT rewound")
                continue
            path = os.path.join(self.directory, meta["file"])
            with open(path, "rb") as f:     # OSError -> candidate fails
                blob = f.read()
            if "bytes" in meta and len(blob) != meta["bytes"]:
                raise CheckpointError(
                    f"PS snapshot {meta['file']} size mismatch "
                    f"({len(blob)} != {meta['bytes']}) — torn write")
            if ("crc32" in meta
                    and zlib.crc32(blob) & 0xFFFFFFFF != meta["crc32"]):
                raise CheckpointError(
                    f"PS snapshot {meta['file']} CRC mismatch — corrupt")
            paths[nm] = path
        return paths

    def restore_latest(self, executor, check_finite=True,
                       reshard=False):
        """Restore the newest INTACT checkpoint into ``executor`` (and
        its PS snapshots into the registered tables) and return its
        step.  Torn, corrupt, structurally invalid, or (by default)
        non-finite checkpoints are skipped with a warning; raises
        :class:`CheckpointError` when nothing survives.

        A sharded checkpoint whose manifest-recorded geometry differs
        from the live executor's raises a typed
        :class:`~hetu_tpu.graph.checkpoint.GeometryMismatch` naming
        both geometries — the checkpoint is fine, the executor is the
        wrong shape, so falling over to an older file would be wrong
        twice.  ``reshard=True`` makes the cross-geometry restore
        intentional: the state is read through ``restore_resharded``
        into the executor's own target shardings instead."""
        t0 = time.perf_counter()
        tried = []
        live_geom = None
        for entry in self.entries():
            path = os.path.join(self.directory, entry["file"])
            sharded = (entry.get("kind") == "sharded"
                       or entry["file"].endswith(SHARDED_SUFFIX))
            if sharded and not reshard:
                saved_geom = entry.get("geometry")
                if saved_geom:
                    if live_geom is None:
                        live_geom = executor_geometry(executor)
                    if not geometry_compatible(saved_geom, live_geom):
                        raise GeometryMismatch(
                            f"checkpoint {entry['file']} was written "
                            f"under {describe_geometry(saved_geom)} but "
                            f"the live executor is "
                            f"{describe_geometry(live_geom)} — restore "
                            "with reshard=True (or "
                            "graph.checkpoint.restore_resharded) for an "
                            "intentional cross-geometry load",
                            saved=saved_geom, live=live_geom)
            try:
                if sharded:
                    state = self._read_verified_sharded(
                        executor, path, entry, check_finite,
                        reshard=reshard)
                else:
                    state = self._read_verified(path, entry,
                                                check_finite)
                ps_paths = self._verify_ps_snapshots(entry)
            except (CheckpointError, OSError) as e:
                tried.append(f"{entry['file']}: {e}")
                warnings.warn(
                    f"skipping bad checkpoint {entry['file']}: {e}")
                continue
            executor.load_state_dict(state)
            for nm, ps_path in ps_paths.items():
                self.ps_tables[nm].load(ps_path)
            self._m_restore_time.observe(time.perf_counter() - t0)
            return int(state["global_step"])
        detail = ("; ".join(tried) if tried
                  else "directory has no checkpoints")
        raise CheckpointError(
            f"no restorable checkpoint in {self.directory} ({detail})")

    # -- preemption --------------------------------------------------------
    def install_preemption_hook(self, executor, sig=signal.SIGTERM,
                                exit_on_save=True, callback=None):
        """Flush a final checkpoint when ``sig`` (default SIGTERM — the
        pod scheduler's preemption notice) arrives, then exit (default)
        or chain to the previously-installed handler.

        ``exit_on_save=False`` keeps the process alive after the flush
        (tests, chaos bench) — ``self.preempted`` flips True either way
        so a training loop can drain and stop cleanly.  Main thread
        only (CPython restriction on ``signal.signal``).

        A previously-installed callable handler (a user's, or another
        manager's) is CHAINED after this manager's flush, never
        silently replaced — two managers both get their final
        checkpoint out of a single SIGTERM.  Idempotent per (manager,
        executor) pair: re-installing for the same executor returns
        the live handler unchanged, and re-arming the same manager for
        a NEW executor (elastic rebuild) replaces its own hook in
        place instead of chaining to itself (which would double-flush
        every preemption)."""
        sig = int(sig)
        current = signal.getsignal(sig)
        mine = self._hooked.get(sig)
        if mine is not None and current is mine["handler"]:
            if mine["executor"] is executor:
                return mine["handler"]      # already armed for this pair
            prev = mine["prev"]             # re-arm in place, not on top
        else:
            prev = current

        def _handler(signum, frame):
            self.save(executor)
            self.preempted = True
            if callback is not None:
                callback(signum)
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)
            elif exit_on_save:
                raise SystemExit(128 + signum)

        signal.signal(sig, _handler)
        self._prev_handlers[sig] = prev
        self._hooked[sig] = {"executor": executor, "handler": _handler,
                             "prev": prev}
        return _handler

    def uninstall_preemption_hook(self, sig=signal.SIGTERM):
        sig = int(sig)
        self._hooked.pop(sig, None)
        prev = self._prev_handlers.pop(sig, None)
        if prev is not None:
            signal.signal(sig, prev)
