"""Resilient training runtime: step guard, rolling checkpoints, fault
injection, and the shared retry policy.

The reference Hetu assumes a healthy cluster — a NaN step, a torn
checkpoint, or a preempted host kills the run and loses everything
since the last manual save.  This package makes the executor-level
training loop survive those, at near-zero steady-state cost:

* :class:`StepGuard` (guard.py) — a non-finite sentinel FUSED into the
  jitted step plus a loss-spike watchdog, with ``skip`` / ``rollback``
  / ``abort`` policies;
* :class:`RollingCheckpointManager` (checkpointer.py) — atomic
  (tmp + ``os.replace``) keep-last-K checkpoints with a CRC manifest,
  a ``restore_latest`` that skips torn files, and a SIGTERM preemption
  hook that flushes a final checkpoint so a killed run resumes bitwise;
* :mod:`faults` — deterministic, seed-driven fault injection (NaN
  batches, dataloader errors, silent prefetch-producer death, PS RPC
  delay/drop, torn files, simulated preemption) backing the tests and
  ``bench.py --chaos``;
* :func:`retry` (retry.py) — the one backoff/jitter/deadline retry
  policy shared by the PS transport and dataset fetch paths;
* :class:`ElasticTrainer` (elastic.py) — the capacity-change
  supervisor: on chip loss or preemption it re-plans the parallel
  geometry over the survivors and resumes from a resharded rolling
  checkpoint (same-DP recoveries are bitwise vs an uninterrupted run).
"""

from __future__ import annotations

from ..graph.checkpoint import CheckpointError, GeometryMismatch
from .retry import retry
from .guard import GuardTripped, StepGuard
from .checkpointer import RollingCheckpointManager
from . import faults
from .faults import (DeviceLost, FaultInjector, InjectedFault,
                     PrefetcherKilled)
from .elastic import ElasticTrainer

__all__ = [
    "CheckpointError", "DeviceLost", "ElasticTrainer", "FaultInjector",
    "GeometryMismatch", "GuardTripped", "InjectedFault",
    "PrefetcherKilled", "RollingCheckpointManager", "StepGuard", "faults",
    "retry",
]
