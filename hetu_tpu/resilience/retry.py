"""One retry policy for every transient-failure loop in the tree.

The seed grew ad-hoc backoff loops wherever I/O could flake — the PS
transport's reconnect-and-retransmit (ps/rpc.py), dataset fetches, the
bench's per-stage retry.  Each had its own idea of backoff, deadline,
and when to give up, and none had jitter (synchronized retries from a
pod's worth of workers hammer a recovering server in lockstep —
ps-lite's resender staggers for the same reason).  ``retry`` is the one
shared policy: exponential backoff with a cap, optional multiplicative
jitter, bounded by attempts and/or a wall-clock deadline, with a
``giveup`` escape hatch for errors that retrying cannot fix.
"""

from __future__ import annotations

import random
import time


def retry(fn, *, attempts=None, deadline=None, backoff=0.05, factor=2.0,
          max_backoff=2.0, jitter=0.0, retry_on=(Exception,), giveup=None,
          on_retry=None, sleep=time.sleep, clock=time.monotonic, rng=None):
    """Call ``fn()`` until it returns, retrying failures with backoff.

    * ``attempts`` — max calls to ``fn`` (None = unbounded in count).
    * ``deadline`` — wall-clock seconds from now after which the last
      error is raised instead of retried (None = unbounded in time).
      At least one of ``attempts``/``deadline`` must be set: an
      unbounded retry loop turns an outage into a silent hang.
    * ``backoff``/``factor``/``max_backoff`` — first pause, growth, cap.
    * ``jitter`` — pause is scaled by ``1 + jitter * U[0, 1)`` so a
      fleet of clients desynchronizes (``rng`` overrides the source for
      deterministic tests).
    * ``retry_on`` — exception classes worth retrying; anything else
      propagates immediately.
    * ``giveup(exc) -> bool`` — per-error veto (e.g. "the client was
      closed underneath us"): a True return re-raises immediately.
    * ``on_retry(exc, attempt, pause)`` — hook between attempts
      (cleanup, logging).

    On exhaustion the LAST exception is re-raised, so callers keep their
    original error type (and can wrap it with context of their own).
    """
    if attempts is None and deadline is None:
        raise ValueError(
            "retry() needs attempts= and/or deadline= — an unbounded "
            "retry loop hides outages as hangs")
    if rng is None:
        rng = random
    deadline_t = None if deadline is None else clock() + float(deadline)
    delay = float(backoff)
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except retry_on as e:
            if giveup is not None and giveup(e):
                raise
            remaining = (None if deadline_t is None
                         else deadline_t - clock())
            if attempts is not None and attempt >= attempts:
                raise
            if remaining is not None and remaining <= 0:
                raise
            pause = delay
            if jitter:
                pause *= 1.0 + jitter * rng.random()
            if remaining is not None:
                pause = min(pause, remaining)
            if on_retry is not None:
                on_retry(e, attempt, pause)
            if pause > 0:
                sleep(pause)
            delay = min(delay * factor, max_backoff)
