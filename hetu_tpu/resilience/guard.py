"""Step guard: fused non-finite sentinel + loss-spike watchdog.

Long runs hit non-finite steps in practice (a bad batch, an overflowed
bf16 reduction, a poisoned embedding row) and the reference framework
simply trains on: the NaN propagates into every parameter within one
step and the run is dead from that point even though it keeps printing
losses.  ``StepGuard`` makes the step itself defensive at ~zero cost:

* **Fused sentinel** — the executor's jitted step computes ONE scalar
  conjunction inside the program: finiteness of the summed loss and of
  every parameter update written this step (each ``isfinite``-reduce
  fuses with the update computation that produced the tensor, so the
  guard reads nothing twice).  The sentinel and the summed loss come
  back as two hidden scalar outputs.
* **Policies** —
  - ``skip``: the poisoned update is discarded *in-graph* (a scalar
    select between new and old params/opt-state, fused into the update
    writes), so parameters are never corrupted and training continues
    on the next batch;
  - ``rollback``: parameters did take the hit (or a loss spike means
    the update was finite but suspect) — restore the last good rolling
    checkpoint via the attached
    :class:`~hetu_tpu.resilience.checkpointer.RollingCheckpointManager`
    and keep going, losing at most the checkpoint cadence;
  - ``abort``: raise :class:`GuardTripped` and let the caller decide.
* **Deferred checking** — reading a device scalar costs a host
  round-trip, so by default the guard holds the sentinel as a device
  array and materializes it one step later (by then the step has long
  finished and the read is a ready-buffer fetch, not a sync).
  ``check_interval=k`` batches the reads further: detection lags at
  most ``k+1`` steps, amortizing the round-trip k-fold — rollback
  semantics already tolerate that lag by construction.  ``flush()``
  drains whatever is still pending (call it after the loop).
* **Loss-spike watchdog** — host-side EMA over confirmed-finite
  losses; ``spike_factor=s`` trips when a loss exceeds ``s x`` the EMA
  after ``spike_warmup`` steps.  A spike's update is finite and already
  applied, so under ``skip`` it only warns+counts; ``rollback``/
  ``abort`` treat it like any other trip.
"""

from __future__ import annotations

import collections
import warnings

import numpy as np

from .. import telemetry as _telemetry


class GuardTripped(RuntimeError):
    """The step guard detected a fault it was told not to absorb.

    ``culprit`` carries the NumericsMonitor's layer attribution when
    one is attached to the same executor: a dict with
    ``first_nonfinite`` (first layer whose stats row went non-finite)
    and ``largest_z`` (layer with the largest grad-norm z-score)."""

    def __init__(self, reason, step, loss=None, culprit=None):
        msg = f"step guard tripped at step {step}: {reason}"
        if loss is not None:
            msg += f" (loss={loss!r})"
        if culprit is not None:
            layer = culprit.get("first_nonfinite") or culprit.get(
                "largest_z")
            if layer:
                msg += f" [culprit layer: {layer}]"
        super().__init__(msg)
        self.reason = reason
        self.step = step
        self.loss = loss
        self.culprit = culprit


class StepGuard:
    """Attach with ``Executor(..., step_guard=guard)`` or
    ``guard.attach(executor)`` (the latter invalidates already-compiled
    step programs so the sentinel gets traced in)."""

    POLICIES = ("skip", "rollback", "abort")

    def __init__(self, policy="skip", manager=None, spike_factor=None,
                 spike_warmup=10, ema_decay=0.9, defer=True,
                 check_interval=1, max_rollbacks=8):
        if policy not in self.POLICIES:
            raise ValueError(
                f"policy must be one of {self.POLICIES}, got {policy!r}")
        if policy == "rollback" and manager is None:
            raise ValueError(
                "rollback policy needs a RollingCheckpointManager "
                "(manager=) to restore from")
        self.policy = policy
        self.manager = manager
        self.spike_factor = spike_factor
        self.spike_warmup = int(spike_warmup)
        self.ema_decay = float(ema_decay)
        self.defer = bool(defer)
        self.check_interval = max(1, int(check_interval))
        self.max_rollbacks = int(max_rollbacks)
        # (step, ok_arr, loss_arr, n, inner_trips_arr_or_None)
        self._pending = collections.deque()
        self._ema = None
        self._executor = None
        self.stats = {"steps": 0, "nonfinite": 0, "spikes": 0,
                      "skipped": 0, "rollbacks": 0, "inner_trips": 0,
                      "trip_steps": [], "restored_steps": []}
        reg = _telemetry.get_registry()
        self._m_trips = reg.counter(
            "hetu_guard_trips_total",
            "StepGuard trips (non-finite sentinel or loss spike)",
            labels=("policy",)).labels(policy=policy)
        self._m_rollbacks = reg.counter(
            "hetu_guard_rollbacks_total",
            "Checkpoint rollbacks executed by the guard")
        self._m_inner = reg.counter(
            "hetu_guard_inner_trips_total",
            "Per-inner-step trips counted through the run_steps "
            "fori_loop carry (exact, not call-boundary)")

    # -- wiring ------------------------------------------------------------
    def attach(self, executor):
        """Install on an already-built executor: compiled step programs
        are invalidated so the next run traces the sentinel in."""
        executor.config["step_guard"] = self
        self._bind(executor)
        for sub in executor.subexecutor.values():
            if hasattr(sub, "_jitted"):
                sub._jitted = None
            if hasattr(sub, "_multi_jitted"):
                sub._multi_jitted = None
        return self

    def detach(self, executor):
        """Remove the guard (and the sentinel from the compiled step)."""
        self.flush()
        executor.config.pop("step_guard", None)
        for sub in executor.subexecutor.values():
            if hasattr(sub, "_jitted"):
                sub._jitted = None
            if hasattr(sub, "_multi_jitted"):
                sub._multi_jitted = None
        return self

    def _bind(self, executor):
        self._executor = executor
        unguarded = [name for name, sub in executor.subexecutor.items()
                     if not hasattr(sub, "_jitted")]
        if unguarded:
            # e.g. PipelineSubExecutor compiles per-stage programs the
            # sentinel isn't traced into — say so instead of silently
            # guarding nothing
            warnings.warn(
                f"StepGuard has no effect on subgraph(s) {unguarded}: "
                "their executor type does not trace the guard sentinel "
                "(pipeline executors are not guarded yet)")

    # -- per-step hook (called by SubExecutor) -----------------------------
    def on_step(self, executor, ok_arr, loss_arr, n=1, inner_trips=None):
        """Receive the step's DEVICE sentinel scalars.  Materialization
        is deferred per ``defer``/``check_interval`` (see module doc);
        a trip executes the policy — which may raise ``GuardTripped`` or
        restore executor state in place.  ``inner_trips``: run_steps'
        carried per-inner-step trip count (device scalar), materialized
        alongside the sentinel into ``stats['inner_trips']``."""
        self._executor = executor
        self._pending.append((executor._global_step, ok_arr, loss_arr, n,
                              inner_trips))
        keep = 1 if self.defer else 0
        if len(self._pending) >= self.check_interval + keep:
            while len(self._pending) > keep:
                self._process(*self._pending.popleft())

    def flush(self):
        """Materialize and check every pending sentinel (call after the
        training loop, and before checkpointing state you must trust).
        Returns the stats dict."""
        while self._pending:
            self._process(*self._pending.popleft())
        return self.stats

    # -- internals ---------------------------------------------------------
    def _process(self, step, ok_arr, loss_arr, n, inner_trips=None):
        ok = bool(np.asarray(ok_arr))
        loss = float(np.asarray(loss_arr))
        self.stats["steps"] += int(n)
        if inner_trips is not None:
            trips = int(np.asarray(inner_trips))
            self.stats["inner_trips"] += trips
            if trips:
                self._m_inner.inc(trips)
        if not ok:
            self.stats["nonfinite"] += 1
            self._trip("non-finite loss or parameter update", step, loss)
            return
        if self.spike_factor is not None and np.isfinite(loss):
            ema = self._ema
            if (ema is not None and self.stats["steps"] > self.spike_warmup
                    and loss > self.spike_factor * abs(ema) + 1e-12):
                self.stats["spikes"] += 1
                self._trip(
                    f"loss spike ({loss:.4g} > {self.spike_factor} x "
                    f"EMA {ema:.4g})", step, loss)
                return
            self._ema = (loss if ema is None
                         else self.ema_decay * ema
                         + (1.0 - self.ema_decay) * loss)

    def _culprit(self, step):
        """Layer attribution from the NumericsMonitor sharing this
        executor, if one rides: who went non-finite first, who has the
        largest grad-norm z-score.  None when no monitor is attached
        (attribution must never turn a trip into a second failure)."""
        ex = self._executor
        mon = ex.config.get("numerics") if ex is not None else None
        if mon is None:
            return None
        try:
            return mon.culprit(step)
        except Exception:
            return None

    def _trip(self, reason, step, loss):
        self.stats["trip_steps"].append(int(step))
        self._m_trips.inc()
        culprit = self._culprit(step)
        _telemetry.get_flight().incident(
            "guard_trip",
            extra={"reason": reason, "step": int(step),
                   "loss": (float(loss) if loss is not None
                            and np.isfinite(loss) else None),
                   "policy": self.policy, "culprit": culprit})
        if self.policy == "abort":
            raise GuardTripped(reason, step, loss, culprit=culprit)
        if self.policy == "skip":
            self.stats["skipped"] += 1
            if "spike" in reason:
                # a spike's update was finite and is already applied —
                # skip cannot un-apply it; only rollback can
                warnings.warn(
                    f"StepGuard(policy='skip') saw a {reason} at step "
                    f"{step}: the update is already applied (use "
                    "policy='rollback' to undo spikes)")
            return
        # rollback
        if self.stats["rollbacks"] >= self.max_rollbacks:
            raise GuardTripped(
                f"{reason} — exceeded max_rollbacks={self.max_rollbacks} "
                "(the fault is recurring; aborting instead of looping)",
                step, loss, culprit=culprit)
        # sentinels still queued describe the now-discarded timeline
        self._pending.clear()
        self._ema = None
        # "rollback_restore" span: the goodput ledger charges this
        # restore to the rollback bucket, not plain checkpoint_restore
        with _telemetry.get_tracer().span("rollback_restore"):
            restored = self.manager.restore_latest(self._executor)
        self.stats["rollbacks"] += 1
        self._m_rollbacks.inc()
        self.stats["restored_steps"].append(int(restored))
        warnings.warn(
            f"StepGuard rolled back: {reason} at step {step}; restored "
            f"checkpoint of step {restored} — batches in between replay "
            "from the data pipeline (skip the offending one)")
