"""Cluster launcher (reference: bin/heturun -> python/runner.py +
python/hetu/launcher.py + DistConfig, context.py:2204-2270).

The reference bootstraps MPI ranks + PS scheduler/server processes over ssh
and wires them with DMLC_* env vars.  On TPU pods the runtime contract is
jax.distributed: one process per host, all pointing at a coordinator
(chief), with the device topology discovered by the TPU runtime.  This
module keeps the reference's cluster-yaml schema and role model (workers +
parameter-store hosts + one chief) and emits/executes the per-host
commands; `launch_local` spawns in-process worker threads against a shared
PS store for single-host runs and tests (the reference's
launcher.py:18 multiprocess spawner plays this role).
"""

from __future__ import annotations

import os
import shlex
import socket
import subprocess
import threading

try:
    import yaml
    _HAS_YAML = True
except ImportError:  # pragma: no cover
    _HAS_YAML = False

_DEFAULT_PORT = 13030


class DistConfig:
    """Cluster topology (schema-compatible with the reference yaml:
    nodes: [{host, workers, servers, chief}])."""

    def __init__(self, file=None, num_local_servers=0, num_local_workers=1,
                 settings=None, port=_DEFAULT_PORT):
        if settings is not None:
            self.settings = settings
        elif file is None:
            assert num_local_workers > 0
            self.settings = {"nodes": [{
                "host": socket.gethostname(),
                "servers": num_local_servers,
                "workers": num_local_workers,
                "chief": True,
            }]}
        else:
            assert _HAS_YAML, "pyyaml is required to read cluster files"
            with open(file) as f:
                self.settings = yaml.safe_load(f.read())
        self.port = port
        allowed = {"host", "servers", "workers", "chief"}
        self.hosts, self.servers, self.workers = [], {}, {}
        chief = None
        for node in self.settings["nodes"]:
            assert set(node) <= allowed, f"bad node keys {set(node)}"
            self.hosts.append(node["host"])
            if node.get("servers", 0):
                self.servers[node["host"]] = node["servers"]
            if node.get("workers", 0):
                self.workers[node["host"]] = node["workers"]
            if node.get("chief", False):
                assert chief is None, "only one chief allowed"
                chief = node["host"]
        assert chief, "one node must set chief: true"
        self.chief = chief
        self.num_servers = sum(self.servers.values())
        self.num_workers = sum(self.workers.values())
        self.enable_PS = self.num_servers > 0

    def save(self, path):
        assert _HAS_YAML
        with open(path, "w") as f:
            yaml.safe_dump(self.settings, f)

    def __str__(self):
        return (f"Cluster {{ chief: {self.chief}, "
                f"servers({self.num_servers}): {self.servers}, "
                f"workers({self.num_workers}): {self.workers} }}")

    # -- jax.distributed env plumbing (replaces make_ps_config DMLC_*) ----
    def coordinator_address(self):
        return f"{self.chief}:{self.port}"

    def _worker_hosts(self):
        """Worker hosts with the chief FIRST: jax.distributed requires
        process 0 to live where the coordinator address points."""
        others = sorted(h for h in self.workers if h != self.chief)
        return ([self.chief] if self.chief in self.workers else []) + others

    def process_env(self, process_id):
        """Env for worker process `process_id` (process 0 is on the chief)."""
        return {
            "HETU_COORDINATOR": self.coordinator_address(),
            "HETU_NUM_PROCESSES": str(self.num_workers),
            "HETU_PROCESS_ID": str(process_id),
            "HETU_NUM_PS_HOSTS": str(len(self.servers)),
        }

    def worker_commands(self, script, args=()):
        """[(host, command)] bring-up plan, one command per worker process
        (the reference builds mpirun -H host:n); chief processes come first
        so process 0 can bind the coordinator port.  Remote hosts get ssh
        wrappers, local ones run directly."""
        out = []
        arg_str = " ".join(shlex.quote(a) for a in args)
        pid = 0
        local_names = (socket.gethostname(), "localhost", "127.0.0.1")
        for host in self._worker_hosts():
            for _ in range(self.workers[host]):
                env = self.process_env(pid)
                env_str = " ".join(f"{k}={v}" for k, v in env.items())
                cmd = (f"{env_str} python {shlex.quote(script)} "
                       f"{arg_str}").strip()
                if host not in local_names:
                    cmd = f"ssh {shlex.quote(host)} {shlex.quote(cmd)}"
                out.append((host, cmd))
                pid += 1
        return out


def initialize_from_env():
    """Call inside a launched worker: wires jax.distributed from the env
    set by `DistConfig.process_env` (no-op when single-process).

    ``HETU_PLATFORM`` (e.g. 'cpu') forces the jax platform first, tearing
    down any backend a sitecustomize pre-initialized — required because
    jax.distributed.initialize must run before backend bring-up."""
    import jax
    platform = os.environ.get("HETU_PLATFORM")
    if platform:
        try:
            from jax.extend import backend as _backend
            _backend.clear_backends()
        except Exception:
            pass
        jax.config.update("jax_platforms", platform)
    coord = os.environ.get("HETU_COORDINATOR")
    n = int(os.environ.get("HETU_NUM_PROCESSES", "1"))
    if coord and n > 1:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=n,
            process_id=int(os.environ["HETU_PROCESS_ID"]))
    return jax


def launch_local(worker_fn, num_workers, ps_tables=None):
    """Single-host launch: run `worker_fn(rank, nranks)` on N threads
    sharing this process's PS store / preduce scheduler (the TPU analogue of
    the reference's in-process scheduler/server/worker spawner).

    Returns the per-rank results.  Exceptions propagate.
    """
    results = [None] * num_workers
    errors = []

    def run(rank):
        try:
            results[rank] = worker_fn(rank, num_workers)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append((rank, e))

    threads = [threading.Thread(target=run, args=(r,), daemon=True)
               for r in range(num_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        rank, err = errors[0]
        raise RuntimeError(f"worker {rank} failed: {err!r}") from err
    return results


def launch(config: DistConfig, script, args=(), dry_run=False):
    """Bring up the cluster: emit (and unless dry_run, execute) one command
    per worker host.  Returns the [(host, cmd)] plan."""
    plan = config.worker_commands(script, args)
    if not dry_run:
        procs = [subprocess.Popen(cmd, shell=True) for _, cmd in plan]
        for p in procs:
            p.wait()
    return plan


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="heturun", description="hetu_tpu cluster launcher")
    ap.add_argument("-c", "--config", help="cluster yaml", default=None)
    ap.add_argument("-w", "--workers", type=int, default=1,
                    help="local workers when no config file")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the bring-up plan without executing")
    ap.add_argument("script")
    ap.add_argument("args", nargs=argparse.REMAINDER,
                    help="arguments forwarded to the script verbatim")
    ns = ap.parse_args(argv)
    config = DistConfig(file=ns.config, num_local_workers=ns.workers)
    plan = launch(config, ns.script, ns.args, dry_run=ns.dry_run)
    for host, cmd in plan:
        print(f"[{host}] {cmd}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
