"""Distributed debugging: replica-consistency / collective-desync checks.

The reference has no sanitizers (SURVEY.md §5: stream-event discipline +
NCCL group calls are trusted); desync between data-parallel replicas (from
non-deterministic host input, stray RNG, or a missed grad sync) shows up
only as silent divergence.  These utilities make that failure loud:

  * `replica_divergence(arr)` — host-side: max |shard - shard0| across the
    addressable copies of a replicated jax.Array.
  * `check_params_replicated(executor)` — sweep every parameter.
  * `equal_across(x, axis)` — in-program (shard_map): max deviation of x
    from the mesh-axis mean; jit-friendly, psum-based, usable as an
    assertion signal every N steps.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def replica_divergence(arr):
    """Max abs difference across the addressable replicas of ``arr``.

    0.0 for a consistent replicated array; for a sharded-only array the
    comparison covers replicas within each shard index (none → 0.0).
    """
    arr = jax.device_put(arr) if not hasattr(arr, "addressable_shards") \
        else arr
    by_index = {}
    for s in arr.addressable_shards:
        by_index.setdefault(tuple((sl.start, sl.stop)
                                  for sl in s.index), []).append(
            np.asarray(s.data))
    worst = 0.0
    for copies in by_index.values():
        base = copies[0]
        for other in copies[1:]:
            worst = max(worst, float(np.max(np.abs(base - other))))
    return worst


def check_params_replicated(executor, tol=0.0):
    """Verify every executor parameter's replicas agree (a diverged DP
    replica means a missed grad sync or nondeterministic input).  Returns
    {name: divergence} for offenders; empty dict == consistent."""
    bad = {}
    for name, value in executor.params.items():
        d = replica_divergence(value)
        if d > tol:
            bad[name] = d
    return bad


def equal_across(x, axis_name):
    """Inside shard_map: max |x - mean_over_axis(x)| (0 ⇔ all members
    identical).  Use as a cheap desync canary on grads/params:

        dev = equal_across(grads_leaf, 'dp')
        # host side: assert float(dev) < 1e-6
    """
    # upcast: in bf16, divergences below ~8e-3 relative would round to
    # zero in the psum — the exact signal this canary exists to catch
    xf = x.astype(jnp.float32)
    n = lax.psum(jnp.ones((), jnp.float32), axis_name)
    mean = lax.psum(xf, axis_name) / n
    return lax.pmax(jnp.max(jnp.abs(xf - mean)), axis_name)


def fingerprint(tree):
    """Order-stable scalar fingerprint of a pytree: sum of float64 sums,
    accumulated on the host (jax defaults to 32-bit; f32 sums over
    millions of weights wash out exactly the small divergences this exists
    to catch).  Compare across hosts/steps to detect desync cheaply."""
    total = np.float64(0.0)
    for leaf in jax.tree_util.tree_leaves(tree):
        total += np.asarray(leaf, dtype=np.float64).sum()
    return float(total)
