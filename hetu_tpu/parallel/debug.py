"""Distributed debugging: replica-consistency / collective-desync checks.

The reference has no sanitizers (SURVEY.md §5: stream-event discipline +
NCCL group calls are trusted); desync between data-parallel replicas (from
non-deterministic host input, stray RNG, or a missed grad sync) shows up
only as silent divergence.  These utilities make that failure loud:

  * `replica_divergence(arr)` — host-side: max |shard - shard0| across the
    addressable copies of a replicated jax.Array.
  * `check_params_replicated(executor)` — sweep every parameter.
  * `equal_across(x, axis)` — in-program (shard_map): max deviation of x
    from the mesh-axis mean; jit-friendly, psum-based, usable as an
    assertion signal every N steps.
  * `sharding_spec(arr)` / `placement_summary(arr)` /
    `visualize_sharding(arr)` — placement introspection the sharded-
    serving tests assert against.  The pinned jax (0.4.37) only renders
    `jax.debug.visualize_array_sharding` when the optional `rich`
    dependency is installed, so `visualize_sharding` falls back to a
    plain-text rendering built from ``addressable_shards`` — same
    information, no new dependency.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def replica_divergence(arr):
    """Max abs difference across the addressable replicas of ``arr``.

    0.0 for a consistent replicated array; for a sharded-only array the
    comparison covers replicas within each shard index (none → 0.0).
    """
    arr = jax.device_put(arr) if not hasattr(arr, "addressable_shards") \
        else arr
    by_index = {}
    for s in arr.addressable_shards:
        by_index.setdefault(tuple((sl.start, sl.stop)
                                  for sl in s.index), []).append(
            np.asarray(s.data))
    worst = 0.0
    for copies in by_index.values():
        base = copies[0]
        for other in copies[1:]:
            worst = max(worst, float(np.max(np.abs(base - other))))
    return worst


def check_params_replicated(executor, tol=0.0):
    """Verify every executor parameter's replicas agree (a diverged DP
    replica means a missed grad sync or nondeterministic input).  Returns
    {name: divergence} for offenders; empty dict == consistent."""
    bad = {}
    for name, value in executor.params.items():
        d = replica_divergence(value)
        if d > tol:
            bad[name] = d
    return bad


def equal_across(x, axis_name):
    """Inside shard_map: max |x - mean_over_axis(x)| (0 ⇔ all members
    identical).  Use as a cheap desync canary on grads/params:

        dev = equal_across(grads_leaf, 'dp')
        # host side: assert float(dev) < 1e-6
    """
    # upcast: in bf16, divergences below ~8e-3 relative would round to
    # zero in the psum — the exact signal this canary exists to catch
    xf = x.astype(jnp.float32)
    n = lax.psum(jnp.ones((), jnp.float32), axis_name)
    mean = lax.psum(xf, axis_name) / n
    return lax.pmax(jnp.max(jnp.abs(xf - mean)), axis_name)


def sharding_spec(arr):
    """The PartitionSpec of a jax.Array as a plain tuple (None entries
    = replicated dims), or None when the array carries no named
    sharding — a stable assertion surface across jax versions (the
    Sharding object reprs drift; the spec tuple does not)."""
    spec = getattr(getattr(arr, "sharding", None), "spec", None)
    return None if spec is None else tuple(spec)


def placement_summary(arr):
    """``{device_id: shard_shape}`` for a jax.Array — what actually
    lives where.  A replicated array maps every device to the full
    shape; a dim-d sharded array shows shape[d] / axis_size per
    device.  This is the machine-checkable sibling of
    :func:`visualize_sharding` (which is for humans)."""
    arr = jax.device_put(arr) if not hasattr(arr, "addressable_shards") \
        else arr
    return {int(s.device.id): tuple(s.data.shape)
            for s in arr.addressable_shards}


def _fmt_slice(sl, dim):
    start = 0 if sl.start is None else int(sl.start)
    stop = dim if sl.stop is None else int(sl.stop)
    return ":" if (start, stop) == (0, dim) else f"{start}:{stop}"


def visualize_sharding(arr, prefer_rich=True):
    """Render an array's device placement as text.

    Uses ``jax.debug.visualize_array_sharding`` when it can actually
    run (it imports ``rich`` lazily on the pinned jax and raises
    without it, and it only handles rank <= 2); every other case falls
    back to one ``devN: [slices]`` line per shard built from
    ``addressable_shards``.  Always RETURNS the fallback text so tests
    and logs can assert on it regardless of which path printed."""
    arr = jax.device_put(arr) if not hasattr(arr, "addressable_shards") \
        else arr
    if prefer_rich and arr.ndim in (1, 2):
        try:
            jax.debug.visualize_array_sharding(arr)
        except Exception:
            prefer_rich = False   # no rich / unsupported layout: text only
    lines = []
    for s in sorted(arr.addressable_shards, key=lambda s: s.device.id):
        idx = ", ".join(_fmt_slice(sl, dim)
                        for sl, dim in zip(s.index, arr.shape))
        lines.append(f"dev{int(s.device.id)}: [{idx}]")
    return "\n".join(lines)


def fingerprint(tree):
    """Order-stable scalar fingerprint of a pytree: sum of float64 sums,
    accumulated on the host (jax defaults to 32-bit; f32 sums over
    millions of weights wash out exactly the small divergences this exists
    to catch).  Compare across hosts/steps to detect desync cheaply."""
    total = np.float64(0.0)
    for leaf in jax.tree_util.tree_leaves(tree):
        total += np.asarray(leaf, dtype=np.float64).sum()
    return float(total)
