"""Context/sequence parallelism for long sequences.

The reference has NO ring attention / Ulysses / blockwise CP (SURVEY.md §5:
verified absent; only Megatron-style SP in Galvatron).  These are designed
fresh for TPU:

* **Ring attention** (`ring_attention`): sequence sharded over a 'cp' mesh
  axis; Q stays local while K/V blocks rotate around the ICI ring via
  `ppermute`, combined with online-softmax accumulation (flash-attention
  style m/l/o running stats).  Communication fully overlaps compute on TPU
  since XLA schedules the ppermute DMA concurrently with the matmuls.
* **Ulysses attention** (`ulysses_attention`): all_to_all head↔sequence
  resharding — attention itself stays local per device but over all tokens
  of a subset of heads (DeepSpeed-Ulysses scheme), one a2a before and after.
* **Megatron-SP** is subsumed by GSPMD: annotating activations
  P('dp', 'tp', None) around LN/dropout gives the scatter/gather pairs
  (tools/Hetu-Galvatron .../transformer.py sequence_parallel flag) without
  explicit code.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..platform import shard_map

from .collectives import varying


def _block_attend(q, k, v, m, l, o, q_off, k_off, scale, causal):
    """One flash block: update running (m, l, o) with K/V block.

    q: [B,H,Sq,D]; k,v: [B,H,Sk,D]; m,l: [B,H,Sq]; o: [B,H,Sq,D].
    q_off/k_off are global sequence offsets of the local blocks.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        iq = q_off + jnp.arange(q.shape[-2])[:, None]
        ik = k_off + jnp.arange(k.shape[-2])[None, :]
        s = jnp.where(iq >= ik, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows (m_new = -inf): keep them at zero weight
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = alpha * l + jnp.sum(p, axis=-1)
    o_new = alpha[..., None] * o + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def ring_attention_shard(q, k, v, axis_name, n_shards, causal=True,
                         scale=None):
    """Per-shard ring attention body (inside shard_map).

    q,k,v: local [B, H, S/cp, D] blocks, sequence-sharded on `axis_name`.
    Returns local attention output [B, H, S/cp, D].
    """
    seq_block = q.shape[-2]
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    my = lax.axis_index(axis_name)
    q_off = my * seq_block

    # scan carries start replicated but become shard-dependent
    m = varying(jnp.full(q.shape[:-1], -jnp.inf, dtype=jnp.float32),
                (axis_name,))
    l = varying(jnp.zeros(q.shape[:-1], dtype=jnp.float32), (axis_name,))
    o = varying(jnp.zeros(q.shape, dtype=jnp.float32), (axis_name,))

    def step(carry, r):
        k_blk, v_blk, m, l, o = carry
        # K/V block currently held came from shard (my - r) mod n
        src = jnp.mod(my - r, n_shards)
        k_off = src * seq_block
        m, l, o = _block_attend(q, k_blk, v_blk, m, l, o, q_off, k_off,
                                scale, causal)
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, l, o), None

    (k, v, m, l, o), _ = lax.scan(step, (k, v, m, l, o),
                                  jnp.arange(n_shards))
    l = jnp.maximum(l, 1e-20)
    return (o / l[..., None]).astype(q.dtype)


# -- flash ring attention --------------------------------------------------
# Same ring schedule, but each (Q-local, K-block) pair runs through the
# Pallas flash kernels (ops/pallas/flash_attention.py blockwise API):
# per-pair HBM traffic stays O(S·d) instead of the jnp path's O(S_local²)
# score tensors, which is what makes long local sequences feasible.  The
# backward is a second ring pass: dq accumulates locally from the combined
# lse, while (dk, dv) accumulators travel WITH their K/V block around the
# ring and arrive home after n steps holding every shard's contribution.


def _ring_rotate(xs, axis_name, n_shards):
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    return [lax.ppermute(x, axis_name, perm) for x in xs]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, n_shards, causal, scale):
    o, _ = _ring_flash_fwd_impl(q, k, v, axis_name, n_shards, causal,
                                scale)
    return o


def _ring_flash_fwd_impl(q, k, v, axis_name, n_shards, causal, scale):
    from ..ops.pallas.flash_attention import flash_attention_block
    sq = q.shape[-2]
    my = lax.axis_index(axis_name)
    q_off = my * sq
    o0 = varying(jnp.zeros(q.shape, jnp.float32), (axis_name,))
    lse0 = varying(jnp.full(q.shape[:-1], -1e30, jnp.float32),
                   (axis_name,))

    def step(carry, r):
        k_blk, v_blk, o, lse = carry
        src = jnp.mod(my - r, n_shards)
        o_blk, lse_blk = flash_attention_block(
            q, k_blk, v_blk, q_off, src * sq, causal=causal, scale=scale)
        lse_new = jnp.logaddexp(lse, lse_blk)
        o = (o * jnp.exp(lse - lse_new)[..., None]
             + o_blk.astype(jnp.float32)
             * jnp.exp(lse_blk - lse_new)[..., None])
        k_blk, v_blk = _ring_rotate([k_blk, v_blk], axis_name, n_shards)
        return (k_blk, v_blk, o, lse_new), None

    (_, _, o, lse), _ = lax.scan(step, (k, v, o0, lse0),
                                 jnp.arange(n_shards))
    return o.astype(q.dtype), lse


def _ring_flash_fwd(q, k, v, axis_name, n_shards, causal, scale):
    o, lse = _ring_flash_fwd_impl(q, k, v, axis_name, n_shards, causal,
                                  scale)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(axis_name, n_shards, causal, scale, res, g):
    from ..ops.pallas.flash_attention import flash_attention_block_bwd
    q, k, v, o, lse = res
    sq = q.shape[-2]
    my = lax.axis_index(axis_name)
    q_off = my * sq
    dq0 = varying(jnp.zeros(q.shape, jnp.float32), (axis_name,))
    dk0 = varying(jnp.zeros(k.shape, jnp.float32), (axis_name,))
    dv0 = varying(jnp.zeros(v.shape, jnp.float32), (axis_name,))

    def step(carry, r):
        k_blk, v_blk, dk_blk, dv_blk, dq = carry
        src = jnp.mod(my - r, n_shards)
        dq_c, dk_c, dv_c = flash_attention_block_bwd(
            q, k_blk, v_blk, o, lse, g, q_off, src * sq,
            causal=causal, scale=scale)
        dq = dq + dq_c.astype(jnp.float32)
        dk_blk = dk_blk + dk_c.astype(jnp.float32)
        dv_blk = dv_blk + dv_c.astype(jnp.float32)
        k_blk, v_blk, dk_blk, dv_blk = _ring_rotate(
            [k_blk, v_blk, dk_blk, dv_blk], axis_name, n_shards)
        return (k_blk, v_blk, dk_blk, dv_blk, dq), None

    (_, _, dk, dv, dq), _ = lax.scan(step, (k, v, dk0, dv0, dq0),
                                     jnp.arange(n_shards))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(mesh, q, k, v, *, axis="cp", causal=True, scale=None,
                   batch_axis="dp"):
    """Host-level: q,k,v [B, H, S, D] with S sharded over `axis`.

    Uses the Pallas blockwise flash kernels when the per-shard shapes fit
    the kernel envelope (128-multiple local seq, 8-aligned d ≤ 512);
    otherwise the jnp online-softmax path.  On a combined mesh the batch
    dim stays sharded over ``batch_axis`` (if present) — attention is
    batch-local, so dp shards pass straight through the shard_map."""
    from ..ops.pallas.flash_attention import blockwise_supported
    n = mesh.shape[axis]
    b_ax = batch_axis if (batch_axis and batch_axis in mesh.shape
                          and q.shape[0] % mesh.shape[batch_axis] == 0) \
        else None
    spec = P(b_ax, None, axis, None)
    b_local = q.shape[0] // (mesh.shape[b_ax] if b_ax else 1)
    local_q = (b_local, q.shape[1], q.shape[2] // n, q.shape[3])
    if blockwise_supported(local_q, local_q):
        # custom_vjp functions take positional args only; check_vma off
        # because pallas_call out_shapes don't carry vma annotations
        f = shard_map(
            lambda q, k, v: _ring_flash(q, k, v, axis, n, causal, scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        return f(q, k, v)
    f = shard_map(
        functools.partial(ring_attention_shard, axis_name=axis, n_shards=n,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)


def ulysses_attention_shard(q, k, v, axis_name, n_shards, causal=True,
                            scale=None):
    """Per-shard Ulysses body (inside shard_map over `axis_name`).

    Local q,k,v: [B, H, S/n, D].  a2a → [B, H/n, S, D] (all tokens, head
    subset) → plain attention → a2a back.
    """
    def seq_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    d = q.shape[-1]
    scale_ = scale if scale is not None else 1.0 / (d ** 0.5)
    # after the a2a the attention is plain LOCAL self-attention over the
    # full sequence (head subset) — route it through the flash kernel when
    # the shape fits, the same win as single-device attention
    from ..ops.pallas.flash_attention import flash_attention
    o = flash_attention(q, k, v, causal=causal, scale=scale_)
    if o is None:
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale_
        if causal:
            S = s.shape[-1]
            iq = jnp.arange(S)[:, None]
            ik = jnp.arange(S)[None, :]
            s = jnp.where(iq >= ik, s, -1e9)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                       preferred_element_type=jnp.float32).astype(v.dtype)
    return heads_to_seq(o.astype(v.dtype))


def ulysses_attention(mesh, q, k, v, *, axis="cp", causal=True, scale=None):
    n = mesh.shape[axis]
    assert q.shape[1] % n == 0, "num heads must divide cp degree"
    spec = P(None, None, axis, None)
    f = shard_map(
        functools.partial(ulysses_attention_shard, axis_name=axis,
                          n_shards=n, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)  # pallas out_shapes carry no vma annotations
    return f(q, k, v)
